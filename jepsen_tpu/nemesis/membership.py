"""Cluster-membership nemesis: grow/shrink the SUT's member set mid-test.

Reference: jepsen/src/jepsen/nemesis/membership.clj + membership/state.clj.
A user-supplied State object models the cluster's membership view; per-node
view threads poll every ``NODE_VIEW_INTERVAL`` seconds and merge into a
resolved view; ops are generated from the current view, applied via the
State, and completed once the State considers them resolved (fixed-point
resolve loop, membership.clj:95-107,159-210).

Crash safety (doc/robustness.md "Membership and clock-rate faults"): a
reconfiguration is the one fault whose *un-heal* requires remembering
what the cluster looked like. Every membership op is therefore recorded
to the durable fault registry BEFORE it fires — pre-op member set,
affected op, and a serialized *heal spec* — and marked healed only once
the State resolves the op. An op stranded by a SIGKILL (or one whose
invoke outlived its per-op deadline) stays on the books, and the
crash-path replay / ``cli heal`` restores the recorded pre-op member
set through :func:`heal_record`, idempotently.

Threading discipline: ONE lock (``self._lock``) guards ``state`` /
``_pending`` / ``_views``. ``merge_views`` / ``resolve`` /
``resolve_op`` / ``op`` are *model* logic — pure-ish, non-blocking —
and run under the lock (they are called from both the interpreter
scheduler thread, via the generator, and the nemesis worker thread).
``node_view`` and ``invoke`` do real cluster I/O and run OUTSIDE the
lock: a hung ``invoke`` is reaped by the interpreter's per-op deadline
(the worker zombifies, the registry entry stays unhealed for replay),
and a hung ``node_view`` only wedges its own poll thread, which
teardown abandons after a bounded wait.
"""
from __future__ import annotations

import logging
import threading
import time as _time
from importlib import import_module

from jepsen_tpu import generator as gen_mod
from jepsen_tpu import telemetry
from jepsen_tpu.nemesis import Nemesis
from jepsen_tpu.utils import join_noisy

logger = logging.getLogger("jepsen.nemesis.membership")

NODE_VIEW_INTERVAL = 5.0  # seconds (membership.clj:59-61)

# Fixed-point bound: a cyclic resolve_op (state A resolves to B resolves
# back to A) must not spin the resolve pass forever — the loop stops
# here and counts the cap (nemesis_membership_resolve_capped_total).
MAX_RESOLVE_ITERS = 32

# Teardown bound per poll thread: a node_view stuck in remote I/O is
# abandoned (daemon thread) rather than holding teardown hostage.
TEARDOWN_JOIN_S = 10.0


class State:
    """Membership model protocol (membership/state.clj). Implementations
    are free-form records over {"view": ..., "pending": [...]}-style
    state; all methods return a new State (pure) except invoke/teardown.

    Concurrency contract: ``merge_views``/``resolve``/``resolve_op``/
    ``op`` run under the nemesis lock and must be non-blocking model
    logic; ``node_view``/``invoke`` may do cluster I/O and run unlocked
    (possibly concurrently with each other, like Client methods).
    """

    def node_view(self, test: dict, node: str):
        """This node's current view of the cluster (polled, may raise)."""
        raise NotImplementedError

    def merge_views(self, test: dict, views: dict):
        """Collapses {node: view} into one authoritative view; returns
        new State."""
        raise NotImplementedError

    def fs(self) -> set:
        """Op :f values this membership State can perform."""
        return set()

    def op(self, test: dict):
        """Next membership op to try: an op dict or "pending"."""
        return "pending"

    def invoke(self, test: dict, op: dict):
        """Actually performs the op against the cluster. Returns the
        completion value."""
        raise NotImplementedError

    def resolve(self, test: dict):
        """A chance to update internal state; returns new State."""
        return self

    def resolve_op(self, test, pending_pair):
        """(op, completion-value) -> None if still pending, else new
        State with the op resolved."""
        return None

    def teardown(self, test: dict) -> None:
        pass

    # -- crash-safety surface (durable fault registry) -------------------

    def members(self):
        """Snapshot of the current member set — recorded as the PRE-op
        set in the durable fault registry before each reconfiguration.
        None = unknown (the record carries no restorable set)."""
        return None

    def heal_spec(self, test: dict):
        """A JSON-serializable descriptor for restoring a recorded
        pre-op member set OFFLINE (``cli heal`` has no live State):

        * ``{"mechanism": "file", "path": ...}`` — the member set lives
          in a JSON file; :func:`restore_members_file` rewrites it.
        * ``{"mechanism": "import", "module": ..., "fn": ...}`` — the
          named ``fn(test, row)`` restores the set (e.g. the etcd
          suite's member-API healer).

        None = membership reconfigurations are unhealable offline
        (preflight flags the package with NEM005)."""
        return None


HEAL_MECHANISMS = ("file", "import")


class _Pending:
    """One in-flight reconfiguration: the op, its completion value, the
    durable registry id recorded before it fired, and whether the
    invoke outlived its deadline (``no_heal`` — resolution must then
    leave the entry on the books, mirroring the PR-4 late-heal rule)."""

    __slots__ = ("op", "value", "fault_id", "no_heal")

    def __init__(self, op, value, fault_id, no_heal):
        self.op, self.value = op, value
        self.fault_id, self.no_heal = fault_id, no_heal


class PollingGen(gen_mod.Generator):
    """Polls ``fn(test, ctx)`` for the next op each time the interpreter
    asks; PENDING (not exhausted) while fn returns None. Unlike
    ``gen.Fn`` — whose None means *exhausted* — a membership generator
    must stay alive through quiet periods where the State has nothing
    to propose. Inherently stateful (the fn consults live nemesis
    state), so preflight enumeration skips it with GEN005.

    Schedule subtleties, learned the hard way against the interpreter's
    actual polling contract (re-polls before dispatch, first-candidate
    tie-break in ``soonest_op_map``):

    * The emitted op's time is LATCHED to when it first became
      available (``_ready_at``). Re-stamped ``ctx.time`` each poll, the
      op would forever TIE with the client generators' now-stamped ops
      and starve; latched, it goes strictly sooner as the run's clock
      advances and wins. (The interpreter re-stamps the real dispatch
      time, so history ordering is untouched.) For the same reason the
      pacing lives HERE and not in a ``gen.stagger`` wrapper: stagger's
      ``max(op_time, next_time)`` re-stamps an undispatched op back to
      "now" on every poll — its state only advances on dispatch — which
      reintroduces the tie.

    * Dispatch is detected through the generator UPDATE protocol, not
      by guessing from fn's next answer: an offered op may sit through
      many re-polls (busy nemesis thread, lost tie) before dispatching,
      or never dispatch at all. ``update`` sees the dispatched op
      (matched by ``:f`` on the nemesis thread), re-arms the pacing
      interval (uniform 0..2·``interval``), resets the latch, and calls
      the optional ``on_update(event)`` hook — how the during-reconfig
      combos flip their window state only for edges that actually
      landed."""

    def __init__(self, fn, interval: float = 0.0, on_update=None):
        self.fn = fn
        self.interval_nanos = gen_mod.secs_to_nanos(interval)
        self.on_update = on_update
        self._ready_at = None
        self._not_before = None
        self._offered = None  # (f, value) of the op awaiting dispatch

    def op(self, test, ctx):
        if self._not_before is not None and ctx.time < self._not_before:
            return (gen_mod.PENDING, self)  # pacing window
        x = self.fn(test, ctx)
        if x is None:
            self._ready_at = None
            self._offered = None
            return (gen_mod.PENDING, self)
        op = gen_mod.fill_in_op(dict(x), ctx)
        if op is gen_mod.PENDING:
            return (gen_mod.PENDING, self)
        if self._ready_at is None or self._ready_at > op["time"]:
            self._ready_at = op["time"]
        op["time"] = self._ready_at
        self._offered = (op.get("f"), op.get("value"))
        return (op, self)

    def update(self, test, ctx, event):
        if self.on_update is not None:
            try:
                self.on_update(event)
            except Exception:  # noqa: BLE001 — a broken hook can't stall ops
                logger.exception("PollingGen on_update hook failed")
        # match on (f, value), not f alone: nemesis events arrive twice
        # per op (dispatch carries the op's value verbatim, the
        # completion a rewritten value) — a PREVIOUS dispatch's
        # completion must not pass for a dispatch of the current offer
        # and spuriously burn a pacing window
        if self._offered is not None \
                and event.get("process") == gen_mod.NEMESIS \
                and (event.get("f"), event.get("value")) == self._offered:
            # our offered op actually dispatched: unlatch and pace
            self._offered = None
            self._ready_at = None
            if self.interval_nanos:
                self._not_before = ctx.time + int(
                    ctx.rng.random() * 2 * self.interval_nanos)
        return self


class MembershipNemesis(Nemesis):
    """(membership.clj:159-210)"""

    def __init__(self, state: State, poll_interval: float = NODE_VIEW_INTERVAL,
                 max_resolve_iters: int = MAX_RESOLVE_ITERS,
                 teardown_join_s: float = TEARDOWN_JOIN_S):
        self.state = state
        self.poll_interval = poll_interval
        self.max_resolve_iters = max_resolve_iters
        self.teardown_join_s = teardown_join_s
        self._lock = threading.Lock()
        self._views: dict = {}
        self._view_at: dict = {}   # node -> monotonic time of last good view
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._pending: list[_Pending] = []
        self._last_resolve = 0.0

    def fs(self):
        with self._lock:
            state = self.state
        return state.fs()

    def self_recorded_kinds(self):
        # richer records than the interpreter's generic snapshot: the
        # pre-op member set + heal spec, heal-marked at RESOLUTION
        return {"membership"}

    def pending_count(self) -> int:
        """In-flight (unresolved) reconfigurations — the model-aware
        combined generators key fault windows off this."""
        with self._lock:
            return len(self._pending)

    # -- node view polling (membership.clj:143-157) ---------------------
    def _poll_node(self, test, node):  # owner: worker
        while not self._stop.is_set():
            with self._lock:
                state = self.state
            try:
                view = state.node_view(test, node)
                now = _time.monotonic()
                with self._lock:
                    self._views[node] = view
                    self._view_at[node] = now
                self._staleness(node, 0.0)
            except Exception as e:  # noqa: BLE001
                logger.debug("node view %s failed: %r", node, e)
                with self._lock:
                    last = self._view_at.get(node)
                if last is not None:
                    self._staleness(node, _time.monotonic() - last)
            self._stop.wait(self.poll_interval)

    @staticmethod
    def _staleness(node, seconds: float) -> None:
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.gauge("nemesis_membership_view_staleness_seconds",
                      "age of each node's last successful membership view",
                      labels=("node",)).set(seconds, node=str(node))

    def setup(self, test):
        for node in test.get("nodes") or []:
            t = threading.Thread(target=self._poll_node, args=(test, node),
                                 daemon=True,
                                 name=f"membership-view-{node}")
            t.start()
            self._threads.append(t)
        return self

    # -- resolution fixed point (membership.clj:95-107) ------------------
    def _resolve(self, test):  # owner: any
        """One merge + bounded fixed-point resolution pass. Runs on both
        the interpreter scheduler thread (via the generator) and the
        nemesis worker thread — the whole pass holds ``self._lock``,
        which is why the State's model methods must not block."""
        resolved: list[_Pending] = []
        with self._lock:
            self._last_resolve = _time.monotonic()
            views = dict(self._views)
            state = self.state
            try:
                state = state.merge_views(test, views) or state
            except Exception as e:  # noqa: BLE001
                logger.debug("merge_views failed: %r", e)
            iters = 0
            changed = True
            while changed and iters < self.max_resolve_iters:
                iters += 1
                changed = False
                state = state.resolve(test) or state
                still = []
                for pend in self._pending:
                    nxt = state.resolve_op(test, (pend.op, pend.value))
                    if nxt is None:
                        still.append(pend)
                    else:
                        state = nxt
                        resolved.append(pend)
                        changed = True
                self._pending = still
            capped = changed  # the bound fired while still converging
            self.state = state
        for pend in resolved:
            self._on_resolved(test, pend)
        reg = telemetry.get_registry()
        if capped:
            logger.warning("membership resolve fixed point capped at %d "
                           "iteration(s); is resolve_op cyclic?", iters)
            if reg.enabled:
                reg.counter("nemesis_membership_resolve_capped_total",
                            "resolve passes stopped by the fixed-point "
                            "iteration bound").inc()
        if reg.enabled and resolved:
            counter = reg.counter("nemesis_membership_resolves_total",
                                  "membership ops resolved by the State",
                                  labels=("f",))
            for pend in resolved:
                counter.inc(f=str(pend.op.get("f")))

    def maybe_resolve(self, test, min_gap_s: float | None = None) -> None:
        # owner: any
        """Rate-limited :meth:`_resolve` for hot-path callers — the
        generator polls once per scheduler iteration (thousands/s on a
        busy run), but resolution granularity is already bounded by the
        view-poll cadence, so a pass within ``min_gap_s`` (default
        half the poll interval, capped at 1 s) is skipped."""
        gap = min_gap_s if min_gap_s is not None \
            else min(self.poll_interval / 2.0, 1.0)
        if _time.monotonic() - self._last_resolve < gap:
            return
        self._resolve(test)

    def _on_resolved(self, test, pend: _Pending) -> None:  # owner: any
        """Registry bookkeeping for a resolved op: mark its durable
        entry healed — the cluster verifiably converged to the post-op
        configuration — UNLESS the invoke outlived its deadline, in
        which case the entry stays for the replay (the run already
        treats the op as indeterminate)."""
        faults = test.get("_faults")
        if faults is None or pend.fault_id is None:
            return
        if pend.no_heal:
            logger.warning(
                "membership op %r resolved after its deadline; leaving "
                "registry entry %d unhealed for replay",
                pend.op.get("f"), pend.fault_id)
            return
        try:
            faults.mark_healed(fault_id=pend.fault_id, via="resolve")
        except Exception:  # noqa: BLE001
            logger.exception("membership heal-mark failed")

    # durability: record-before-act
    def invoke(self, test, op):  # owner: worker
        self._resolve(test)
        with self._lock:
            state = self.state
        fault_id = self._record(test, state, op)
        try:
            value = state.invoke(test, op)
        except Exception as e:  # noqa: BLE001
            # indeterminate reconfig: the registry entry stays unhealed,
            # so the crash-path replay / `cli heal` restores the
            # recorded pre-op member set
            return {**op, "type": "info", "value": ["error", repr(e)]}
        from jepsen_tpu.generator.interpreter import current_op_reaped
        reaped = current_op_reaped()
        with self._lock:
            self._pending.append(_Pending(op, value, fault_id, reaped))
        self._resolve(test)
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.counter("nemesis_membership_ops_total",
                        "membership reconfiguration ops applied",
                        labels=("f",)).inc(f=str(op.get("f")))
        return {**op, "type": "info", "value": value}

    @staticmethod
    def _record(test, state: State, op) -> int | None:  # owner: worker
        """Durably records the reconfiguration BEFORE it fires: the
        pre-op member set and the heal spec are exactly what a recovery
        needs when the control process dies mid-reconfig."""
        faults = test.get("_faults")
        if faults is None:
            return None
        try:
            pre = state.members()
            record = {"f": op.get("f"), "value": op.get("value"),
                      "pre_members": (sorted(pre, key=str)
                                      if pre is not None else None),
                      "heal": state.heal_spec(test)}
            return faults.record("membership", f=op.get("f"), value=record)
        except Exception:  # noqa: BLE001 — never blocks the reconfig
            logger.exception("membership fault record failed")
            return None

    def teardown(self, test):  # owner: scheduler
        self._stop.set()
        reg = telemetry.get_registry()
        for t in self._threads:
            if not join_noisy(t, f"membership view poll {t.name}",
                              heartbeat_s=2.0,
                              max_wait_s=self.teardown_join_s):
                # daemon thread stuck in node_view I/O: abandon it —
                # teardown must never wedge on a dead node
                if reg.enabled:
                    reg.counter("nemesis_membership_poll_abandoned_total",
                                "view poll threads abandoned at teardown "
                                "(node_view hung past the join bound)"
                                ).inc()
        with self._lock:
            state = self.state
        state.teardown(test)

    # -- preflight (doc/static-analysis.md NEM004/NEM005) ----------------
    def preflight_diags(self, test) -> list:  # owner: scheduler
        """Static package validation, called by preflight's nemesis walk
        — no node contact. Checks the State surface, the poll/resolve
        knobs, and offline healability."""
        from jepsen_tpu.analysis.diagnostics import ERROR, Diagnostic
        out: list = []
        try:
            fs = set(self.state.fs() or ())
        except Exception as e:  # noqa: BLE001
            fs = None
            out.append(Diagnostic(
                "NEM004", ERROR, "nemesis",
                f"membership State.fs() raised: {e!r}"))
        if fs is not None and not fs:
            out.append(Diagnostic(
                "NEM004", ERROR, "nemesis",
                "membership State declares an empty op surface; the "
                "package can never emit an op",
                hint="return the op :f values the State performs from "
                     "State.fs()"))
        for name, v, lo in (("poll_interval", self.poll_interval, 0.0),
                            ("max_resolve_iters", self.max_resolve_iters,
                             1),
                            ("teardown_join_s", self.teardown_join_s,
                             0.0)):
            if isinstance(v, bool) or not isinstance(v, (int, float)) \
                    or v < lo:
                out.append(Diagnostic(
                    "NEM004", ERROR, "nemesis",
                    f"membership package knob {name}={v!r} is not a "
                    f"number >= {lo!r}"))
        try:
            spec = self.state.heal_spec(test)
        except Exception as e:  # noqa: BLE001
            spec = None
            out.append(Diagnostic(
                "NEM004", ERROR, "nemesis",
                f"membership State.heal_spec() raised: {e!r}"))
        if spec is None:
            out.append(Diagnostic(
                "NEM005", ERROR, "nemesis",
                "membership reconfigurations would be unhealable: the "
                "State declares no heal spec, so a crash mid-reconfig "
                "strands the cluster with no offline restore",
                hint="return a {'mechanism': 'file'|'import', ...} spec "
                     "from State.heal_spec(), or add 'NEM005' to "
                     "preflight_allow if that is deliberate"))
        elif not isinstance(spec, dict) \
                or spec.get("mechanism") not in HEAL_MECHANISMS:
            out.append(Diagnostic(
                "NEM005", ERROR, "nemesis",
                f"membership heal spec {spec!r} names no known "
                f"mechanism {HEAL_MECHANISMS}; `cli heal` could not "
                "restore a stranded reconfiguration",
                hint="use {'mechanism': 'file', 'path': ...} or "
                     "{'mechanism': 'import', 'module': ..., 'fn': ...}"))
        return out


def membership_gen(nemesis: MembershipNemesis):
    """Generator polling the State for its next op (membership.clj:212-222).
    Runs on the interpreter thread, concurrently with the nemesis
    worker's invoke — state access goes through the nemesis lock."""

    def next_op(test, ctx):  # owner: scheduler
        nemesis.maybe_resolve(test)
        with nemesis._lock:
            state = nemesis.state
        op = state.op(test)
        if op == "pending" or op is None:
            return None
        return dict(op)

    return next_op


def package(state: State, interval: float = 10.0,
            poll_interval: float = NODE_VIEW_INTERVAL) -> dict:
    """A combined-style package (membership.clj:224-250). The generator
    is a PollingGen with built-in stagger-style pacing: "pending" keeps
    it alive (PENDING), it never exhausts — and preflight enumeration
    skips it with GEN005 rather than consuming live nemesis state."""
    n = MembershipNemesis(state, poll_interval=poll_interval)
    return {
        "nemesis": n,
        "generator": PollingGen(membership_gen(n), interval=interval),
        "final_generator": None,
        "perf": {"name": "membership", "fs": state.fs(),
                 "start": set(state.fs()), "stop": set()},
    }


# ---------------------------------------------------------------------------
# Offline heal: restore a recorded pre-op member set (cli heal / the
# crash-path replay, dispatched from faults.ROW_HEALERS)
# ---------------------------------------------------------------------------

def heal_record(test: dict, row: dict) -> None:
    """Restores ONE membership record's pre-op member set, dispatching
    on its serialized heal spec. Raises
    :class:`jepsen_tpu.nemesis.faults.Unhealable` when the record
    carries no usable spec — wrong bookkeeping is worse than none."""
    from jepsen_tpu.nemesis.faults import Unhealable
    v = row.get("value") if isinstance(row.get("value"), dict) else {}
    spec = v.get("heal")
    if not isinstance(spec, dict):
        raise Unhealable(
            f"membership record {row.get('id')} has no heal spec; the "
            "cluster's member set must be restored manually")
    mech = spec.get("mechanism")
    if mech == "file":
        restore_members_file(test, row)
    elif mech == "import":
        try:
            mod = import_module(str(spec.get("module")))
            fn = getattr(mod, str(spec.get("fn")))
        except (ImportError, AttributeError) as e:
            raise Unhealable(
                f"membership heal target {spec.get('module')}:"
                f"{spec.get('fn')} is not importable: {e}") from e
        fn(test, row)
    else:
        raise Unhealable(
            f"unknown membership heal mechanism {mech!r} "
            f"(known: {HEAL_MECHANISMS})")


def restore_members_file(test: dict, row: dict) -> None:
    """The "file" heal mechanism: atomically rewrites the member-set
    JSON file named by the record's heal spec with the recorded pre-op
    set (``utils.atomic_write_json`` — the restore must be as durable
    as the record that demanded it). Idempotent."""
    from jepsen_tpu.nemesis.faults import Unhealable
    from jepsen_tpu.utils import atomic_write_json
    v = row.get("value") if isinstance(row.get("value"), dict) else {}
    spec = v.get("heal") or {}
    path = spec.get("path")
    pre = v.get("pre_members")
    if not path or pre is None:
        raise Unhealable(
            f"membership record {row.get('id')} lacks a members-file "
            "path or a pre-op member set")
    atomic_write_json(path, sorted(pre, key=str))
    logger.info("restored member set %s -> %s", sorted(pre, key=str), path)
