"""Durable fault registry: exactly-once nemesis heal.

A killed run can leave the cluster partitioned with clocks scrambled —
the nemesis teardown that would have healed it died with the control
process. This module records every injected fault to
``store/<test>/<ts>/faults.jsonl`` *before* injection (fsynced — the
registry must survive the crash it exists for) and marks it healed after
the closing op or the nemesis teardown. What remains unhealed is exactly
what a recovery pass must undo:

* ``core.run`` replays unhealed entries in its crash-path ``finally``
  (full capability: the live test map still holds net/db handles), and
* ``cli heal <store-dir>`` replays them offline for a run whose process
  is gone — net and clock state are restorable from the serialized test
  map alone; process kill/pause heals need the db object and are
  reported as unhealable offline.

Heal actions are idempotent (``iptables -F``, ``tc qdisc del``, reset
clock, ``start!``) and retried with capped-exponential full-jitter
backoff; an entry is marked healed only after its action succeeded, so
replaying the registry twice heals exactly once.

Registry rows: ``{"op": "inject", "id": n, "kind": ..., "f": ...,
"value": ..., "time": ...}`` and ``{"op": "heal", "id": n, "via": ...,
"time": ...}``. The file is append-only jsonl, read with the same
torn-tail-tolerant reader as the history WAL.

Deadline interplay (doc/robustness.md): nemesis ops run under the
interpreter's per-op deadlines too. A fault-*closing* op that outlives
its deadline gets an indeterminate ``:info`` synthesized for it and its
worker zombied; when the real heal eventually completes, the zombied
``NemesisWorker`` deliberately does NOT ``mark_healed`` — the entry
stays on the books so the crash-path replay / ``cli heal`` restores the
network with the idempotent healers below.
"""
from __future__ import annotations

import errno
import json
import logging
import os
import threading
import time
from pathlib import Path
from typing import Any

logger = logging.getLogger("jepsen.nemesis.faults")

FAULTS_NAME = "faults.jsonl"
# rows held in memory while the disk is full (ENOSPC): fault records
# are few and small, but losing one means an unhealable cluster, so
# the bound is generous
ENOSPC_PARK_MAX_ROWS = 1000

# Heal-action dispatch groups. "file" faults (truncate-file, bitflip)
# have no inverse — they're recorded so a recovery knows the damage
# exists, and reported as unhealable. "membership" faults are cluster
# reconfigurations: recorded with the PRE-op member set before firing,
# marked healed once the membership State resolves the op, and — when a
# crash strands an unresolved reconfig — replayed by restoring the
# recorded pre-op member set (nemesis/membership.py heal_record).
# "clock-rate" faults are libfaketime per-node clock-rate windows
# (faketime.py): the record carries the wrapped binary so an offline
# heal can unwrap it.
KINDS = ("net", "netem", "clock", "clock-rate", "process", "pause",
         "file", "membership")

# What a successful nemesis teardown restores ("resumes normal
# operation", nemesis.clj contract): everything EXCEPT file damage,
# which no teardown can undo — those entries stay on the books — and
# membership reconfigurations: State.teardown stops the view polling,
# it does NOT restore the pre-op member set, so an unresolved reconfig
# must survive teardown for the crash-path / `cli heal` replay.
TEARDOWN_HEALS = ("net", "netem", "clock", "clock-rate", "process",
                  "pause")

# Kinds with no heal action at all — recorded as evidence, reported as
# unhealable, and not worth a crash-path replay warning on their own.
UNHEALABLE_KINDS = ("file",)

# Kinds the interpreter's GENERIC pre-fire snapshot must never record:
# a membership record is only actionable with the pre-op member set and
# a heal spec, which only a self-recording nemesis
# (``Nemesis.self_recorded_kinds``, e.g. MembershipNemesis) can supply.
# A generic row would be permanently-unhealed noise — and several
# pre-existing suites (faunadb topology's add-node/remove-node,
# rethinkdb's reconfigure) legitimately use membership-flavored ``:f``
# names with plain nemeses that keep no model at all.
SELF_RECORDED_ONLY = ("membership",)


def classify(f) -> tuple[str | None, str | None]:
    """``(phase, kind)`` for a nemesis op :f — ``("begin", "net")`` for
    an op that opens a fault window, ``("end", "net")`` for one that
    closes it, ``(None, None)`` when the op is not a fault (or is the
    ambiguous bare ``start``/``stop`` pair, which the kill package uses
    as heal/fault in the *opposite* sense from the raw partitioner —
    callers composing those route through f_map'd package names)."""
    if not isinstance(f, str):
        return None, None
    n = f.replace("_", "-")
    table = {
        "start-partition": ("begin", "net"), "partition": ("begin", "net"),
        "snub": ("begin", "net"),
        "stop-partition": ("end", "net"), "heal": ("end", "net"),
        "slow": ("begin", "netem"), "flaky": ("begin", "netem"),
        "start-netem": ("begin", "netem"),
        "fast": ("end", "netem"), "stop-netem": ("end", "netem"),
        "bump": ("begin", "clock"), "strobe": ("begin", "clock"),
        "scramble-clock": ("begin", "clock"),
        "start-clock": ("begin", "clock"),
        "reset": ("end", "clock"), "reset-time": ("end", "clock"),
        "stop-clock": ("end", "clock"),
        "kill": ("begin", "process"),
        "pause": ("begin", "pause"), "resume": ("end", "pause"),
        "start-pause": ("begin", "pause"), "stop-pause": ("end", "pause"),
        "truncate-file": ("begin", "file"), "bitflip": ("begin", "file"),
        # membership reconfigurations (nemesis/membership.py): each op
        # is a one-shot state transition, not a begin/end window pair —
        # it opens as "begin" and is healed by RESOLUTION (the State
        # observing the cluster converge), never by a closing op
        "grow": ("begin", "membership"), "shrink": ("begin", "membership"),
        "join": ("begin", "membership"), "leave": ("begin", "membership"),
        "add-node": ("begin", "membership"),
        "remove-node": ("begin", "membership"),
        "rolling-restart": ("begin", "membership"),
        "reconfigure": ("begin", "membership"),
        # libfaketime clock-rate windows (faketime.py); the explicit
        # rows document the pair — the start-/stop- prefix fallback
        # below would classify them identically
        "start-clock-rate": ("begin", "clock-rate"),
        "stop-clock-rate": ("end", "clock-rate"),
    }
    if n in table:
        return table[n]
    # package convention: start-<x>/stop-<x> open and close an <x>
    # window — but only map to a kind we actually know how to heal
    # (e.g. faunadb's start-partition-replica). An unknown suffix
    # (yugabyte's stop-master is a fault INJECTION, not a heal) must
    # not be guessed at: wrong bookkeeping is worse than none.
    for prefix, phase in (("start-", "begin"), ("stop-", "end")):
        if n.startswith(prefix):
            base = n[len(prefix):]
            if base in KINDS:
                return phase, base
            if "partition" in base:
                return phase, "net"
            return None, None
    # bare "start"/"stop" are genuinely ambiguous (the kill package's
    # heal/restart vs the raw Partitioner's open/close) and are NOT
    # classified; teardown marking and the idempotent replay still
    # cover both cases
    return None, None


class FaultRegistry:  # durability: fsync
    """Append-only durable fault log. Thread-safe: nemesis ops arrive on
    the nemesis worker thread while teardown/replay run on the
    orchestrator thread."""

    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        self._entries: dict[int, dict] = {}
        self._healed: set[int] = set()
        self._next_id = 0
        if self.path.exists():
            self._load()
        self._f = open(self.path, "a", encoding="utf-8")
        # ENOSPC park (doc/robustness.md "Fleet HA"): rows waiting for
        # the disk to drain, retried on the next _append/close. Rows
        # are idempotent on load (keyed by id), so the torn/duplicate
        # lines a failed flush can leave are harmless; the tolerant
        # reader skips them.
        self._parked: list[str] = []
        self._dirty_tail = False

    def _load(self) -> None:
        from jepsen_tpu.journal import read_jsonl_tolerant
        rows, _truncated = read_jsonl_tolerant(self.path)
        for row in rows:
            rid = row.get("id")
            if not isinstance(rid, int):
                continue
            if row.get("op") == "inject":
                self._entries[rid] = row
                self._next_id = max(self._next_id, rid + 1)
            elif row.get("op") == "heal":
                self._healed.add(rid)

    def _append(self, row: dict) -> None:
        from jepsen_tpu.store import _serializable
        line = json.dumps(_serializable(row)) + "\n"
        reopened = self._f.closed
        if reopened:
            # a LATE record — a reaped fault injection landing after the
            # run closed the registry (interpreter zombie thread) — must
            # still reach the durable log: it may be the only evidence
            # the cluster is dirty. Append-only jsonl makes a one-shot
            # reopen safe.
            self._f = open(self.path, "a", encoding="utf-8")
        try:
            # a bare newline terminates whatever partial line a failed
            # flush left (readers skip torn lines); the ENOSPC backlog
            # rides along before the new row
            prefix = ("\n" if self._dirty_tail else "") \
                + "".join(self._parked)
            self._f.write(prefix + line)
            self._f.flush()
            os.fsync(self._f.fileno())
            if self._parked or self._dirty_tail:
                logger.info("fault registry %s recovered from ENOSPC; "
                            "%d parked row(s) flushed", self.path,
                            len(self._parked))
            self._parked = []
            self._dirty_tail = False
        except OSError as e:
            if e.errno != errno.ENOSPC:
                raise
            # disk full is transient: park the row (bounded) for the
            # next _append/close instead of losing the only evidence a
            # fault was injected — a full disk must not make the
            # registry permanently self-disable (doc/robustness.md
            # "Fleet HA")
            self._dirty_tail = True
            if len(self._parked) < ENOSPC_PARK_MAX_ROWS:
                self._parked.append(line)
            logger.warning("fault registry %s hit ENOSPC; row parked "
                           "for retry (%d waiting)", self.path,
                           len(self._parked))
        finally:
            if reopened:
                try:
                    self._f.close()
                except OSError:
                    pass

    def record(self, kind: str, f=None, value: Any = None) -> int:
        """Durably records an injection BEFORE it happens; returns the
        fault id. If the control process dies right after, the entry is
        already on disk for ``cli heal``."""
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            row = {"op": "inject", "id": rid, "kind": kind, "f": f,
                   "value": value, "time": time.time()}
            self._entries[rid] = row
            self._append(row)
        self._count("nemesis_faults_recorded_total", kind)
        # causal trace: the DURABLE registry is the source of truth for
        # fault windows (not the op stream — crash-replayed heals and
        # late re-records only exist here); async slices keyed by fault
        # id so overlapping windows never interleave
        from jepsen_tpu import trace as trace_mod
        tracer = trace_mod.get_tracer()
        if tracer.enabled:
            tracer.window_begin(trace_mod.TRACK_NEMESIS, str(kind),
                                wid=f"fault-{rid}",
                                args={"f": str(f), "id": rid})
        return rid

    def mark_healed(self, fault_id: int | None = None,
                    kind: str | None = None, kinds=None,
                    via: str = "nemesis") -> list[int]:
        """Marks faults healed: one by id, every unhealed fault of a
        kind (or of any kind in ``kinds``), or — all selectors None —
        every unhealed fault. Returns the ids marked."""
        with self._lock:
            if fault_id is not None:
                ids = ([fault_id] if fault_id in self._entries
                       and fault_id not in self._healed else [])
            else:
                wanted = (set(kinds) if kinds is not None
                          else {kind} if kind is not None else None)
                ids = [rid for rid, row in sorted(self._entries.items())
                       if rid not in self._healed
                       and (wanted is None or row.get("kind") in wanted)]
            for rid in ids:
                self._healed.add(rid)
                self._append({"op": "heal", "id": rid, "via": via,
                              "time": time.time()})
        for rid in ids:
            self._count("nemesis_faults_healed_total",
                        self._entries[rid].get("kind"))
        if ids:
            from jepsen_tpu import trace as trace_mod
            tracer = trace_mod.get_tracer()
            if tracer.enabled:
                for rid in ids:
                    tracer.window_end(
                        trace_mod.TRACK_NEMESIS,
                        str(self._entries[rid].get("kind")),
                        wid=f"fault-{rid}", args={"via": via})
        return ids

    def unhealed(self) -> list[dict]:
        with self._lock:
            return [dict(row) for rid, row in sorted(self._entries.items())
                    if rid not in self._healed]

    def close(self) -> None:
        with self._lock:
            if (self._parked or self._dirty_tail) and not self._f.closed:
                # last ENOSPC-drain try before the handle goes away
                try:
                    self._f.write(("\n" if self._dirty_tail else "")
                                  + "".join(self._parked))
                    self._f.flush()
                    os.fsync(self._f.fileno())
                    self._parked = []
                    self._dirty_tail = False
                except OSError:
                    logger.warning("fault registry %s: %d parked row(s) "
                                   "lost at close (disk still full)",
                                   self.path, len(self._parked))
            if not self._f.closed:
                try:
                    self._f.close()
                except OSError:
                    pass

    @staticmethod
    def _count(metric: str, kind) -> None:
        from jepsen_tpu import telemetry
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.counter(metric, "durable fault-registry entries",
                        labels=("kind",)).inc(kind=str(kind))


def load_rows(path) -> list[dict]:
    """Every row of a ``faults.jsonl`` (torn-tail tolerant, like the
    registry's own loader); [] when the file is absent/unreadable. The
    read-only surface the forensics/plotting layers use — no registry
    object, no write handle."""
    from jepsen_tpu.journal import read_jsonl_tolerant
    try:
        rows, _truncated = read_jsonl_tolerant(Path(path))
    except OSError:
        return []
    return [r for r in rows if isinstance(r, dict)]


def pair_rows(rows: list[dict]) -> list[dict]:
    """Inject rows joined with their heal rows: ``[{id, kind, f, value,
    t_wall, healed, via, t_heal_wall}]`` in injection order. Wall-clock
    times (the registry records ``time.time()``); use
    :func:`history_windows` for history-relative overlays."""
    heals: dict = {}
    for r in rows:
        if r.get("op") == "heal":
            heals.setdefault(r.get("id"), r)
    out = []
    for r in rows:
        if r.get("op") != "inject":
            continue
        h = heals.get(r.get("id"))
        out.append({"id": r.get("id"), "kind": r.get("kind"),
                    "f": r.get("f"), "value": r.get("value"),
                    "t_wall": r.get("time"),
                    "healed": h is not None,
                    "via": (h or {}).get("via"),
                    "t_heal_wall": (h or {}).get("time")})
    return out


def history_windows(history: list[dict], rows: list[dict]) -> list[dict]:
    """Fault windows in HISTORY time: each durable inject record matched
    (in order, by ``:f``) to its nemesis op in the history for the start
    edge; the end edge is the next nemesis op classifying as
    ``("end", same kind)``, else open. A window whose heal happened
    OUTSIDE the history — nemesis teardown, the crash-path replay,
    ``cli heal`` — keeps ``end_time: None`` with ``healed``/``via`` set:
    exactly the evidence the registry adds over history-derived
    intervals (crash-replayed heals have no history op to pair with).
    Registry rows with no matching history op (a crash before the
    injection journaled) are skipped."""
    paired = pair_rows(rows)
    queues: dict = {}
    for w in paired:
        queues.setdefault(w.get("f"), []).append(w)
    open_by_kind: dict[str, list[dict]] = {}
    out: list[dict] = []
    for op in history or []:
        if op.get("process") != "nemesis" or op.get("type") != "info":
            continue
        f = op.get("f")
        phase, kind = classify(f)
        if phase == "begin":
            q = queues.get(f)
            rec = q.pop(0) if q else None
            win = {"kind": kind if rec is None else rec.get("kind"),
                   "f": f, "start_time": op.get("time"),
                   "end_time": None,
                   "healed": bool(rec and rec.get("healed")),
                   "via": (rec or {}).get("via"),
                   "record_id": (rec or {}).get("id"),
                   "in_registry": rec is not None}
            out.append(win)
            open_by_kind.setdefault(win["kind"], []).append(win)
        elif phase == "end":
            opened = open_by_kind.get(kind) or []
            if opened:
                win = opened.pop(0)
                win["end_time"] = op.get("time")
    return out


def actionable_unhealed(registry: FaultRegistry) -> tuple[list[dict],
                                                          list[dict]]:
    """Splits the registry's unhealed entries into ``(actionable,
    evidence)`` — *evidence* being :data:`UNHEALABLE_KINDS` rows (file
    damage), which a crash-path replay should report, never retry."""
    pending = registry.unhealed()
    actionable = [r for r in pending
                  if str(r.get("kind")) not in UNHEALABLE_KINDS]
    evidence = [r for r in pending
                if str(r.get("kind")) in UNHEALABLE_KINDS]
    return actionable, evidence


class Unhealable(Exception):
    """This fault kind cannot be healed with the handles available
    (e.g. a process kill from ``cli heal``, where the db object is
    gone, or file damage with no inverse)."""


# ---------------------------------------------------------------------------
# Heal actions — each idempotent over the whole cluster
# ---------------------------------------------------------------------------

def _net_for(test: dict):
    net = test.get("net")
    if net is not None:
        return net
    # offline heal (cli heal): the serialized test map dropped the net
    # object; rebuild the default for the transport
    from jepsen_tpu.net import IPTables, NoopNet
    return NoopNet() if (test.get("ssh") or {}).get("dummy") else IPTables()


def _heal_net(test: dict) -> None:
    _net_for(test).heal(test)


def _heal_netem(test: dict) -> None:
    _net_for(test).fast(test)


def _heal_clock(test: dict) -> None:
    """Resyncs every node's clock, RAISING when no mechanism worked on a
    node — a heal that can't verify its work must not report success
    (the registry marks healed only on a healer's clean return). Tries
    the ntp-quality resyncs first, then the coarse ``date -s`` that a
    control node can always serve."""
    from jepsen_tpu import control
    from jepsen_tpu.control.core import RemoteError
    from jepsen_tpu.utils import real_pmap

    def reset(node):
        def do():
            for cmd in (("ntpdate", "-p", "1", "-b", "pool.ntp.org"),
                        ("chronyc", "-a", "makestep"),
                        ("systemctl", "restart", "systemd-timesyncd"),
                        ("date", "-s", f"@{int(time.time())}")):
                try:
                    control.exec_(*cmd)
                    return
                except RemoteError:
                    continue
            raise RuntimeError(f"no working clock-reset mechanism on "
                               f"{node}")
        control.on(node, test, do)

    real_pmap(reset, list(test.get("nodes") or []))


def _db_heal(test: dict, method: str) -> None:
    from jepsen_tpu import db as db_mod
    from jepsen_tpu.utils import real_pmap
    db = test.get("db")
    want = db_mod.Process if method == "start" else db_mod.Pause
    if db is None or not isinstance(db, want):
        raise Unhealable(
            f"no live db object implementing {method!r}; restart the "
            "cluster's processes manually or re-run from a live test map")
    fn = db.start if method == "start" else db.resume
    real_pmap(lambda n: fn(test, n), list(test.get("nodes") or []))


def _heal_process(test: dict) -> None:
    _db_heal(test, "start")


def _heal_pause(test: dict) -> None:
    _db_heal(test, "resume")


def _heal_file(test: dict) -> None:
    raise Unhealable("file damage (truncate/bitflip) has no inverse; "
                     "the db setup cycle must rebuild the node")


def _heal_membership(test: dict, rows: list[dict]) -> None:
    """Restores each unresolved reconfiguration's recorded pre-op member
    set (nemesis/membership.py heal_record dispatches on the record's
    serialized heal spec, so this works offline from ``cli heal``).
    Rows are applied newest-first so the OLDEST unresolved record's
    pre-op set — the member set before the first stranded reconfig —
    is what the cluster ends on."""
    from jepsen_tpu.nemesis import membership as membership_mod
    for row in sorted(rows, key=lambda r: r.get("id", 0), reverse=True):
        membership_mod.heal_record(test, row)


def _heal_clock_rate(test: dict, rows: list[dict]) -> None:
    """Unwraps every libfaketime-wrapped binary the records name
    (idempotent: faketime.unwrap is a no-op once the .real binary is
    back in place). The binary path rides in the record value because
    an offline heal has no nemesis object to ask."""
    from jepsen_tpu import control, faketime
    from jepsen_tpu.utils import real_pmap
    binaries: dict[str, set] = {}
    for row in rows:
        v = row.get("value") if isinstance(row.get("value"), dict) else {}
        binary = v.get("binary")
        if not binary:
            raise Unhealable(
                "clock-rate record names no binary path; unwrap the "
                "faketime-wrapped binaries manually")
        nodes = list(v.get("rates") or ()) or list(test.get("nodes") or [])
        binaries.setdefault(binary, set()).update(nodes)
    for binary, nodes in sorted(binaries.items()):
        real_pmap(
            lambda node, b=binary: control.on(
                node, test, lambda: faketime.unwrap(b)),
            sorted(nodes))


HEALERS = {
    "net": _heal_net,
    "netem": _heal_netem,
    "clock": _heal_clock,
    "process": _heal_process,
    "pause": _heal_pause,
    "file": _heal_file,
}

# Kinds whose heal depends on WHAT was recorded, not just that
# something of the kind happened: these healers receive the unhealed
# rows (pre-op member sets, wrapped-binary paths) and take precedence
# over the kind-wide HEALERS dispatch in replay_unhealed.
ROW_HEALERS = {
    "membership": _heal_membership,
    "clock-rate": _heal_clock_rate,
}


def replay_unhealed(test: dict, registry: FaultRegistry,
                    tries: int = 4, rng=None) -> dict:
    """Heals every unhealed fault in the registry, grouped by kind (one
    idempotent cluster-wide action heals any number of same-kind
    faults), each action retried with capped-exponential full-jitter
    backoff. Entries are marked healed only after their action
    succeeded — a second replay is exactly a no-op. Returns
    ``{"healed": [...], "unhealable": [...], "failed": [...]}`` id
    lists."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.utils import retry_with_backoff

    out: dict[str, list[int]] = {"healed": [], "unhealable": [],
                                 "failed": []}
    pending = registry.unhealed()
    if not pending:
        return out
    by_kind: dict[str, list[dict]] = {}
    for row in pending:
        by_kind.setdefault(str(row.get("kind")), []).append(row)
    reg = telemetry.get_registry()
    for kind in sorted(by_kind):
        rows = by_kind[kind]
        ids = [r["id"] for r in rows]
        row_healer = ROW_HEALERS.get(kind)
        healer = HEALERS.get(kind)
        try:
            if row_healer is not None:
                action = lambda: row_healer(test, rows)  # noqa: E731
            elif healer is not None:
                action = lambda: healer(test)  # noqa: E731
            else:
                raise Unhealable(f"no healer registered for kind {kind!r}")
            # Unhealable is a terminal verdict, not a flake: no backoff
            retry_with_backoff(action, tries=tries, rng=rng,
                               desc=f"heal {kind}", no_retry=(Unhealable,))
        except Unhealable as e:
            logger.warning("faults %s (kind %s) left unhealed: %s",
                           ids, kind, e)
            out["unhealable"].extend(ids)
            continue
        except Exception:  # noqa: BLE001 — keep healing the other kinds
            logger.exception("heal replay for kind %r failed after %d "
                             "tries", kind, tries)
            out["failed"].extend(ids)
            continue
        registry.mark_healed(kind=kind, via="replay")
        out["healed"].extend(ids)
        if reg.enabled:
            reg.counter("nemesis_heal_replayed_total",
                        "fault heals applied by crash-path/cli replay",
                        labels=("kind",)).inc(len(ids), kind=kind)
            if kind == "membership":
                reg.counter("nemesis_membership_replayed_heals_total",
                            "stranded reconfigurations restored to their "
                            "recorded pre-op member set by replay"
                            ).inc(len(ids))
    return out
