"""Unified runtime telemetry: a Prometheus-style metrics registry.

The span log in :mod:`jepsen_tpu.tracing` answers "what happened, when";
this module answers "how much / how fast / how hot", on every run — not
just when bench.py happens to execute. It is the missing half of the
observability pair Jepsen's own suites ship (dgraph's trace.clj spans go
to Jaeger; its serving stack scrapes Prometheus): a thread-safe registry
of Counters, Gauges, and log-bucketed Histograms with labels, a
``timer()`` context manager, timestamped events (nemesis fault windows),
and exporters for the Prometheus text exposition format
(``metrics.prom``) plus a JSONL snapshot (``metrics.json``) written into
the test's store directory.

Zero-cost disabled mode: the module-level default registry is
:data:`NULL`, whose instrument constructors hand back one shared no-op
instrument. Call sites fetch the registry once (``get_registry()``) and
either test ``reg.enabled`` around hot blocks or just call through —
every method on the null instruments is a constant no-op. ``core.run``
installs a live :class:`Registry` for the duration of a run (unless the
test map sets ``metrics: False``) and restores the previous one after.

Device helpers (``device_memory_stats``, ``matrix_modeled_flops``,
``device_peak_flops``) give the checker and bench.py one shared
vocabulary for memory high-water and roofline accounting.
"""
from __future__ import annotations

import bisect as _bisect
import json
import logging
import math
import os
import tempfile
import threading
import time
from collections import deque
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Iterable

logger = logging.getLogger("jepsen.telemetry")

# Log-spaced latency buckets: 1 µs .. ~275 s in x4 steps (20 bounds plus
# the +Inf overflow). Wide enough for SSH execs and JIT compiles, fine
# enough near the bottom for the interpreter's µs-scale scheduling.
DEFAULT_BUCKETS: tuple = tuple(1e-6 * 4.0 ** i for i in range(20))


def log_buckets(start: float, factor: float, count: int) -> tuple:
    """Explicit log-bucket constructor: ``start * factor**i``."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    return tuple(start * factor ** i for i in range(count))


# ---------------------------------------------------------------------------
# Instruments
# ---------------------------------------------------------------------------

class _Family:
    """A named metric family: children keyed by label-value tuples."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labels: Iterable[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self._children: dict = {}
        self._lock = threading.Lock()

    def _key(self, labels: dict) -> tuple:
        return tuple(str(labels.get(n, "")) for n in self.label_names)

    def _child(self, labels: dict):
        key = self._key(labels)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._new_child())
        return child

    def _new_child(self):
        raise NotImplementedError

    def _rows(self):
        """[(label_values, child)] snapshot, stable order."""
        with self._lock:
            return sorted(self._children.items())

    def clear(self) -> None:
        """Drops every child series. For per-snapshot-rebuilt label
        sets — the live daemon's capped ``{run}`` gauges re-rank which
        runs keep their own series on every poll, and a run that fell
        out of the top-K must stop exporting a stale value."""
        with self._lock:
            self._children.clear()


class Counter(_Family):
    """Monotone sum. ``inc(amount, **labels)``."""

    kind = "counter"

    def _new_child(self):
        return [0.0]

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        child = self._child(labels)
        with self._lock:
            child[0] += amount

    def cell(self, **labels) -> list:
        """The mutable ``[value]`` behind one child, for SINGLE-WRITER
        hot paths (the interpreter's scheduler thread): the caller does
        ``cell[0] += n`` with no lock. Snapshots still see it."""
        return self._child(labels)

    def value(self, **labels) -> float:
        return self._child(labels)[0]


class Gauge(_Family):
    """Point-in-time value. ``set/inc/dec/set_max``."""

    kind = "gauge"

    def _new_child(self):
        return [0.0]

    def set(self, value: float, **labels) -> None:
        self._child(labels)[0] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        child = self._child(labels)
        with self._lock:
            child[0] += amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def set_max(self, value: float, **labels) -> None:
        """High-water update: keeps the max of current and ``value``."""
        child = self._child(labels)
        with self._lock:
            if value > child[0]:
                child[0] = float(value)

    def cell(self, **labels) -> list:
        """Single-writer fast path; see Counter.cell."""
        return self._child(labels)

    def value(self, **labels) -> float:
        return self._child(labels)[0]


class _HistState:
    __slots__ = ("counts", "sum", "count", "min", "max")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative), last=+Inf
        self.sum = 0.0
        self.count = 0
        self.min = math.inf
        self.max = -math.inf


class Histogram(_Family):
    """Log-bucketed distribution. ``observe(v, **labels)``; quantiles are
    estimated by linear interpolation inside the containing bucket."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", labels: Iterable[str] = (),
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labels)
        self.bounds = tuple(sorted(buckets))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")

    def _new_child(self):
        return _HistState(len(self.bounds) + 1)

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        i = _bisect.bisect_left(self.bounds, value)
        child = self._child(labels)
        with self._lock:
            child.counts[i] += 1
            child.sum += value
            child.count += 1
            if value < child.min:
                child.min = value
            if value > child.max:
                child.max = value

    def observer(self, **labels):
        """A SINGLE-WRITER observe closure bound to one child: skips the
        family lock and per-call child lookup (one bisect + five plain
        mutations). The interpreter's scheduler thread records µs-scale
        op latencies through this without measurably slowing the loop."""
        child = self._child(labels)
        bounds = self.bounds
        bl = _bisect.bisect_left

        def observe(value: float) -> None:
            child.counts[bl(bounds, value)] += 1
            child.sum += value
            child.count += 1
            if value < child.min:
                child.min = value
            if value > child.max:
                child.max = value

        return observe

    def quantile(self, q: float, **labels) -> float | None:
        """Bucket-interpolated quantile in [0, 1]; None when empty."""
        child = self._child(labels)
        if child.count == 0:
            return None
        rank = q * child.count
        cum = 0
        for i, c in enumerate(child.counts):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else min(child.min, self.bounds[0])
                hi = self.bounds[i] if i < len(self.bounds) else child.max
                frac = (rank - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return child.max


class _Timer:
    """``with reg.timer("x_seconds"): ...`` — observes elapsed seconds."""

    __slots__ = ("_hist", "_labels", "_t0")

    def __init__(self, hist: Histogram, labels: dict):
        self._hist = hist
        self._labels = labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0, **self._labels)
        return False


# ---------------------------------------------------------------------------
# Registries
# ---------------------------------------------------------------------------

class Registry:
    """Thread-safe get-or-create family store + exporters."""

    enabled = True

    def __init__(self, max_events: int = 4096):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}
        self._events: deque = deque(maxlen=max_events)

    def _family(self, cls, name: str, help: str, labels: Iterable[str],
                **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(name, help, labels, **kw)
                self._families[name] = fam
                return fam
        if not isinstance(fam, cls):
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}")
        if tuple(labels) and fam.label_names != tuple(labels):
            raise ValueError(
                f"metric {name!r} already registered with labels "
                f"{fam.label_names}, not {tuple(labels)}")
        return fam

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> Counter:
        return self._family(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> Gauge:
        return self._family(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (),
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._family(Histogram, name, help, labels, buckets=buckets)

    def timer(self, name: str, help: str = "", **labels) -> _Timer:
        hist = self.histogram(name, help, labels=tuple(labels))
        return _Timer(hist, labels)

    def event(self, name: str, **fields) -> None:
        """Timestamped event row (nemesis fault windows et al.); kept in a
        bounded deque, exported in metrics.json."""
        self._events.append({"type": "event", "name": name,
                             "time": time.time(), "fields": fields})

    # -- export ------------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """One dict per (family, label-set) + one per event — the
        metrics.json rows."""
        out: list[dict] = []
        with self._lock:
            families = sorted(self._families.items())
        for name, fam in families:
            for key, child in fam._rows():
                labels = dict(zip(fam.label_names, key))
                row: dict[str, Any] = {"name": name, "type": fam.kind,
                                       "labels": labels}
                if fam.kind in ("counter", "gauge"):
                    row["value"] = child[0]
                else:
                    row.update({
                        "count": child.count,
                        "sum": round(child.sum, 9),
                        "min": None if child.count == 0 else child.min,
                        "max": None if child.count == 0 else child.max,
                        "buckets": [[le, c] for le, c in
                                    zip(list(fam.bounds) + ["+Inf"],
                                        child.counts) if c],
                    })
                    for q, label in ((0.5, "p50"), (0.95, "p95"),
                                     (0.99, "p99")):
                        v = fam.quantile(q, **labels)
                        if v is not None:
                            row[label] = round(v, 9)
                out.append(row)
        out.extend(self._events)
        return out

    def render_prom(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: list[str] = []
        with self._lock:
            families = sorted(self._families.items())
        for name, fam in families:
            if fam.help:
                lines.append(f"# HELP {name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {name} {fam.kind}")
            for key, child in fam._rows():
                labels = dict(zip(fam.label_names, key))
                if fam.kind in ("counter", "gauge"):
                    lines.append(f"{name}{_fmt_labels(labels)} {_fmt(child[0])}")
                    continue
                cum = 0
                for le, c in zip(list(fam.bounds) + ["+Inf"], child.counts):
                    cum += c
                    le_s = "+Inf" if le == "+Inf" else _fmt(le)
                    lines.append(
                        f"{name}_bucket{_fmt_labels({**labels, 'le': le_s})}"
                        f" {cum}")
                lines.append(f"{name}_sum{_fmt_labels(labels)} {_fmt(child.sum)}")
                lines.append(f"{name}_count{_fmt_labels(labels)} {child.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def export(self, dirpath, prefix: str = "metrics") -> None:
        """<prefix>.prom + <prefix>.json into ``dirpath``, atomically
        (the flusher races web readers; a half-written snapshot must
        never be served). Standalone re-analysis exports under a
        ``metrics-analyze`` prefix so it can't clobber the live run's
        snapshot (core.analyze)."""
        d = Path(dirpath)
        d.mkdir(parents=True, exist_ok=True)
        _atomic_write(d / f"{prefix}.prom", self.render_prom())
        _atomic_write(d / f"{prefix}.json", "".join(
            json.dumps(row, default=str) + "\n" for row in self.snapshot()))


class _NullTimer:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_TIMER = _NullTimer()


def _null_observe(value: float) -> None:
    pass


class _NullInstrument:
    """One shared no-op standing in for every instrument when disabled."""

    __slots__ = ()

    def inc(self, amount: float = 1.0, **labels) -> None:
        pass

    def dec(self, amount: float = 1.0, **labels) -> None:
        pass

    def set(self, value: float, **labels) -> None:
        pass

    def set_max(self, value: float, **labels) -> None:
        pass

    def observe(self, value: float, **labels) -> None:
        pass

    def cell(self, **labels) -> list:
        return [0.0]  # fresh throwaway: writes accumulate nowhere shared

    def observer(self, **labels):
        return _null_observe

    def value(self, **labels) -> float:
        return 0.0

    def quantile(self, q: float, **labels):
        return None

    def clear(self) -> None:
        pass


_NULL_INSTRUMENT = _NullInstrument()


class NullRegistry:
    """The disabled mode: every constructor returns the shared no-op."""

    enabled = False

    def counter(self, name: str, help: str = "", labels=()):
        return _NULL_INSTRUMENT

    def gauge(self, name: str, help: str = "", labels=()):
        return _NULL_INSTRUMENT

    def histogram(self, name: str, help: str = "", labels=(), buckets=()):
        return _NULL_INSTRUMENT

    def timer(self, name: str, help: str = "", **labels):
        return _NULL_TIMER

    def event(self, name: str, **fields) -> None:
        pass

    def snapshot(self) -> list[dict]:
        return []

    def render_prom(self) -> str:
        return ""

    def export(self, dirpath) -> None:
        pass


NULL = NullRegistry()

_REGISTRY: Registry | NullRegistry = NULL
_REGISTRY_LOCK = threading.Lock()


def get_registry() -> Registry | NullRegistry:
    """The currently installed registry (NULL when telemetry is off)."""
    return _REGISTRY


def install(registry: Registry | NullRegistry | None):
    """Swaps the process-global registry; returns the previous one so
    callers can restore it (core.run does)."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        prev = _REGISTRY
        _REGISTRY = registry if registry is not None else NULL
        return prev


@contextmanager
def use(registry: Registry | NullRegistry):
    prev = install(registry)
    try:
        yield registry
    finally:
        install(prev)


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in labels.items())
    return "{" + inner + "}"


def _atomic_write(path: Path, content: str) -> None:
    # unique tmp per writer: the flusher thread and an analyze-time
    # export may race on the same target, and a shared tmp name could
    # publish a torn file — the one thing this helper exists to prevent
    fd, tmp = tempfile.mkstemp(dir=str(path.parent),
                               prefix=path.name + ".", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(content)
            f.flush()
            # fsync before the rename: without it os.replace can publish
            # the durable name with its data still in the page cache, so
            # a power cut leaves a torn/empty snapshot — and analyze
            # REUSES live-status.json written through this helper
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


# ---------------------------------------------------------------------------
# Background flusher
# ---------------------------------------------------------------------------

class Flusher:
    """Periodically exports a registry to a directory while a run is in
    flight, so a crashed run still leaves a recent metrics snapshot.
    ``interval_s <= 0`` skips the thread; ``stop()`` always does one
    final export."""

    def __init__(self, registry: Registry, dirpath, interval_s: float = 10.0):
        self.registry = registry
        self.dirpath = dirpath
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "Flusher":
        if self.interval_s and self.interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="jepsen-telemetry-flusher")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.registry.export(self.dirpath)
            except Exception:  # noqa: BLE001 — flushing must never kill a run
                logger.exception("periodic metrics flush failed")

    def stop(self, final_export: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if final_export:
            try:
                self.registry.export(self.dirpath)
            except Exception:  # noqa: BLE001
                logger.exception("final metrics export failed")


# ---------------------------------------------------------------------------
# Thread-stack forensics
# ---------------------------------------------------------------------------

def dump_thread_stacks(target) -> bool:
    """All-threads stack dump via ``faulthandler`` into ``target`` — a
    path (appended, with a timestamp header) or an open file object with
    a real file descriptor. The interpreter's stall watchdog and the
    tier-1 budget guard both use this so a wedged run/session leaves
    *where every thread was stuck* on disk instead of nothing. Returns
    True on success; never raises."""
    import faulthandler
    try:
        if hasattr(target, "write"):
            faulthandler.dump_traceback(file=target, all_threads=True)
            return True
        p = Path(target)
        p.parent.mkdir(parents=True, exist_ok=True)
        with open(p, "a", encoding="utf-8") as f:
            f.write(f"\n==== thread stacks @ {time.time():.3f} ====\n")
            f.flush()
            faulthandler.dump_traceback(file=f, all_threads=True)
        return True
    except Exception:  # noqa: BLE001 — a diagnostic must never raise
        logger.exception("thread-stack dump failed")
        return False


# ---------------------------------------------------------------------------
# Nemesis fault-window classification
# ---------------------------------------------------------------------------

# Nemesis :f conventions across the packages: start_*/stop_* (partition,
# clock, membership), kill/start and pause/resume (db_specific). "start"
# alone is the *heal* of a kill window.
_FAULT_BEGIN = ("kill", "pause", "partition", "bitflip", "snub")
_FAULT_END = ("start", "resume", "heal")


def fault_phase(f) -> str | None:
    """'begin' / 'end' when the op opens or closes a fault window, else
    None (heuristic over the package :f naming conventions)."""
    if not isinstance(f, str):
        return None
    if f.startswith("start_"):
        return "begin"
    if f.startswith("stop_"):
        return "end"
    if f in _FAULT_BEGIN:
        return "begin"
    if f in _FAULT_END:
        return "end"
    return None


# ---------------------------------------------------------------------------
# Device helpers: memory high-water, roofline accounting, profiler
# ---------------------------------------------------------------------------

def device_memory_stats() -> dict | None:
    """``jax.local_devices()[0].memory_stats()`` or None — CPU backends
    and older runtimes return nothing; that's fine."""
    try:
        import jax
        devs = jax.local_devices()
        if not devs:
            return None
        return devs[0].memory_stats() or None
    except Exception:  # noqa: BLE001 — telemetry never takes a run down
        return None


def device_memory_peak_bytes() -> int | None:
    stats = device_memory_stats()
    if not stats:
        return None
    for key in ("peak_bytes_in_use", "bytes_in_use"):
        if key in stats:
            return int(stats[key])
    return None


def matrix_modeled_flops(n_returns: int, n_slots: int,
                         num_states: int) -> float:
    """Modeled f32 FLOPs issued by the transfer-matrix kernel for
    ``n_returns`` returns: each composes one [MV, MV] operator via
    ~(ceil(log2 S) + 2) dense matmuls (bench.py's roofline accounting,
    shared here so the checker's runtime gauge and bench agree; a LOWER
    bound — the elementwise L build is excluded)."""
    MV = (1 << n_slots) * num_states
    n_sq = 0
    while (1 << n_sq) < n_slots:
        n_sq += 1
    return n_returns * (n_sq + 2) * 2.0 * MV ** 3


def matrix_phase_model(n_returns: int, n_slots: int, num_states: int,
                       n_chunks: int = 1, n_keys: int = 1) -> dict:
    """Modeled FLOP shares of one transfer-matrix dispatch, by phase —
    the analytic companion to the measured host/device split
    (ops.jitlin.last_phase_seconds). Three on-device phases:

    * ``matmul`` — the closure squarings + kill-apply + compose per
      return: (ceil(log2 S) + 2) dense [MV, MV] products.
    * ``lbuild`` — the elementwise L assembly (each of the MV^2 cells
      sums S gated products).
    * ``combine`` — the per-key chunk-product chain: C-1 products per
      key plus the tot0 compose, amortized over the whole dispatch.

    The shares say where a restructure could possibly pay: when
    ``lbuild_frac`` + ``combine_frac`` is already small, the residual
    gap to peak is NOT in those phases — it is fixed per-dispatch
    overhead (host prep + round trip), which the measured phase split
    attributes directly."""
    MV = (1 << n_slots) * num_states
    # the matmul term IS the roofline numerator — shared with
    # checker_roofline_frac so the attribution can never diverge from
    # the fraction it explains
    matmul = matrix_modeled_flops(n_returns, n_slots, num_states)
    lbuild = n_returns * 2.0 * n_slots * MV * MV
    combine = n_keys * n_chunks * 2.0 * MV ** 3
    total = matmul + lbuild + combine
    return {
        "modeled_matmul_frac": round(matmul / total, 4),
        "modeled_lbuild_frac": round(lbuild / total, 6),
        "modeled_combine_frac": round(combine / total, 6),
    }


def combine_modeled_hbm_bytes(n_keys: int, n_chunks: int, mv: int,
                              fused: bool, itemsize: int = 2) -> int:
    """Modeled HBM traffic of the chunk-product combine stage, per
    dispatch (bf16 matrices: itemsize 2). The tree combine's
    ceil(log2 C) levels each read two [MV, MV] products and write one
    per pair; the fused streaming combine (pallas_matrix._build_combine)
    reads each chunk product exactly once, reads tot0, and writes only
    the total — the ratio of the two is the ``combine_fused_reduction``
    bench.py reports, and ``combine_hbm_frac`` divides the active
    model's bytes by wall time and measured HBM bandwidth."""
    cell = mv * mv * itemsize
    if fused:
        return n_keys * (n_chunks + 2) * cell
    total = 0
    c = n_chunks
    while c > 1:
        pairs = c // 2
        total += pairs * 3 * cell       # read 2, write 1 per pair
        c = pairs + (c % 2)
    total += 3 * cell                   # the tot0 compose
    return n_keys * total


_DEVICE_PEAK: dict = {}


def set_device_peak_flops(value: float) -> None:
    """Publishes a measured f32 matmul peak (bench.device_roofline does)
    so runtime roofline gauges have a denominator."""
    _DEVICE_PEAK["f32_matmul_flops"] = float(value)


def device_peak_flops() -> float | None:
    """Measured-or-declared f32 matmul peak: set_device_peak_flops first,
    then the JEPSEN_DEVICE_PEAK_FLOPS env var. None means 'unknown' —
    runtime roofline gauges are skipped, never guessed."""
    if "f32_matmul_flops" in _DEVICE_PEAK:
        return _DEVICE_PEAK["f32_matmul_flops"]
    env = os.environ.get("JEPSEN_DEVICE_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            return None
    return None


@contextmanager
def profiler_trace(dirpath):
    """jax.profiler device trace into ``dirpath`` (--profile); degrades
    to a no-op when the profiler is unavailable."""
    started = False
    try:
        import jax
        Path(dirpath).mkdir(parents=True, exist_ok=True)
        jax.profiler.start_trace(str(dirpath))
        started = True
    except Exception:  # noqa: BLE001
        logger.exception("jax.profiler trace unavailable; continuing")
    try:
        yield
    finally:
        if started:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                logger.exception("profiler stop_trace failed")
