/* strobe-time: oscillate the system wall clock by +/- DELTA_MS every
 * PERIOD_MS for DURATION_S seconds. Compiled with gcc on each DB node at
 * clock-nemesis setup (capability-equivalent to the reference's
 * jepsen/resources/strobe-time.c, deployed by nemesis/time.clj:49).
 *
 * usage: strobe-time DELTA_MS PERIOD_MS DURATION_S
 * exit:  0 on success; 1 on usage error; 2 if settimeofday fails.
 *
 * The sleep between flips uses the MONOTONIC clock so the oscillation
 * rate is unaffected by the wall-clock jumps it is itself causing.
 */
#include <errno.h>
#include <stdio.h>
#include <stdlib.h>
#include <time.h>
#include <sys/time.h>

static int bump(long long delta_ms) {
  struct timeval tv;
  if (gettimeofday(&tv, NULL) != 0) {
    perror("gettimeofday");
    return -1;
  }
  long long usec = (long long)tv.tv_usec + delta_ms * 1000LL;
  long long carry = usec / 1000000LL;
  usec %= 1000000LL;
  if (usec < 0) {
    usec += 1000000LL;
    carry -= 1;
  }
  tv.tv_sec += (time_t)carry;
  tv.tv_usec = (suseconds_t)usec;
  if (settimeofday(&tv, NULL) != 0) {
    perror("settimeofday");
    return -1;
  }
  return 0;
}

static void sleep_ms_monotonic(long long ms) {
  struct timespec ts;
  ts.tv_sec = ms / 1000LL;
  ts.tv_nsec = (ms % 1000LL) * 1000000LL;
  while (nanosleep(&ts, &ts) != 0 && errno == EINTR) {
  }
}

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s DELTA_MS PERIOD_MS DURATION_S\n", argv[0]);
    return 1;
  }
  long long delta_ms = atoll(argv[1]);
  long long period_ms = atoll(argv[2]);
  long long duration_s = atoll(argv[3]);
  if (period_ms <= 0 || duration_s < 0) {
    fprintf(stderr, "period must be > 0, duration >= 0\n");
    return 1;
  }

  struct timespec start, now;
  clock_gettime(CLOCK_MONOTONIC, &start);
  int sign = 1;
  for (;;) {
    clock_gettime(CLOCK_MONOTONIC, &now);
    if (now.tv_sec - start.tv_sec >= duration_s) break;
    if (bump(sign * delta_ms) != 0) return 2;
    sign = -sign;
    sleep_ms_monotonic(period_ms);
  }
  /* leave the clock roughly where we found it: an even number of flips
   * cancels out; if we stopped after an odd flip, undo it. */
  if (sign == -1 && bump(-delta_ms) != 0) return 2;
  return 0;
}
