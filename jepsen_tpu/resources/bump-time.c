/* bump-time: jump the system wall clock by a signed number of
 * milliseconds, once. Compiled with gcc on each DB node at clock-nemesis
 * setup (capability-equivalent to the reference's
 * jepsen/resources/bump-time.c, deployed by nemesis/time.clj:20-39).
 *
 * usage: bump-time DELTA_MS
 * exit:  0 on success; 1 on usage error; 2 if settimeofday fails
 *        (typically: not root).
 */
#include <errno.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/time.h>

int main(int argc, char **argv) {
  if (argc != 2) {
    fprintf(stderr, "usage: %s DELTA_MS\n", argv[0]);
    return 1;
  }
  char *end = NULL;
  long long delta_ms = strtoll(argv[1], &end, 10);
  if (end == argv[1] || *end != '\0') {
    fprintf(stderr, "bad delta: %s\n", argv[1]);
    return 1;
  }

  struct timeval tv;
  if (gettimeofday(&tv, NULL) != 0) {
    perror("gettimeofday");
    return 2;
  }

  long long usec = (long long)tv.tv_usec + delta_ms * 1000LL;
  long long sec_carry = usec / 1000000LL;
  usec %= 1000000LL;
  if (usec < 0) {
    usec += 1000000LL;
    sec_carry -= 1;
  }
  tv.tv_sec += (time_t)sec_carry;
  tv.tv_usec = (suseconds_t)usec;

  if (settimeofday(&tv, NULL) != 0) {
    perror("settimeofday");
    return 2;
  }
  return 0;
}
