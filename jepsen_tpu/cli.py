"""CLI runner (reference: jepsen/src/jepsen/cli.clj).

Subcommands: ``test`` (run + exit by validity), ``analyze`` (re-check a
stored history with fresh checker code — analysis is re-entrant,
cli.clj:399-427), ``serve`` (web UI), ``test-all`` (sweeps). Exit codes
mirror cli.clj:129-139: 0 pass / 1 invalid / 2 unknown / 254 bad args /
255 crash. Node and "--concurrency 3n" parsing per cli.clj:150-202.
"""
from __future__ import annotations

import argparse
import logging
import sys
from typing import Callable

logger = logging.getLogger("jepsen.cli")

EXIT_OK = 0
EXIT_INVALID = 1
EXIT_UNKNOWN = 2
EXIT_BAD_ARGS = 254
EXIT_CRASH = 255


from jepsen_tpu.utils import parse_concurrency  # noqa: E402  (re-export)


def parse_nodes(opts) -> list[str]:
    """Merges --node, --nodes, --nodes-file (cli.clj:167-202)."""
    nodes: list[str] = []
    if getattr(opts, "nodes", None):
        nodes.extend(x for x in opts.nodes.split(",") if x)
    if getattr(opts, "node", None):
        nodes.extend(opts.node)
    if getattr(opts, "nodes_file", None):
        with open(opts.nodes_file) as f:
            nodes.extend(line.strip() for line in f if line.strip())
    return nodes or ["n1", "n2", "n3", "n4", "n5"]


def add_test_opts(p: argparse.ArgumentParser) -> None:
    """Shared test option spec (cli.clj:64-111)."""
    p.add_argument("--nodes", help="comma-separated node list")
    p.add_argument("--node", action="append", help="a node to test (repeatable)")
    p.add_argument("--nodes-file", help="file with one node per line")
    p.add_argument("--username", default="root")
    p.add_argument("--password")
    p.add_argument("--port", type=int)
    p.add_argument("--ssh-private-key", dest="ssh_private_key")
    p.add_argument("--no-ssh", action="store_true",
                   help="use the dummy remote (no cluster needed)")
    p.add_argument("--concurrency", default="1n",
                   help="number of workers; '3n' = 3 per node")
    p.add_argument("--time-limit", type=float, default=60.0)
    p.add_argument("--test-count", type=int, default=1)
    p.add_argument("--leave-db-running", action="store_true")
    p.add_argument("--accelerator", default="auto",
                   choices=["auto", "cpu", "tpu"],
                   help="checker backend (the TPU switch)")
    p.add_argument("--store-dir", default="store")
    # unified telemetry (doc/observability.md): spans, metrics, profiles
    p.add_argument("--trace", action="store_true",
                   help="causal trace: stream a Perfetto trace.json of "
                        "the whole run (workers, nemesis, checker "
                        "ladder, checkpoints) plus the per-client span "
                        "log trace.jsonl")
    p.add_argument("--flight-recorder-events", type=int, default=None,
                   dest="flight_recorder_events",
                   help="flight-recorder ring capacity (default 4096; "
                        "0 disables; the ring dumps to "
                        "flight-recorder.jsonl on stalls and crashes)")
    p.add_argument("--metrics-interval", type=float, default=None,
                   help="seconds between background metrics flushes into "
                        "the store dir (default 10; 0 = final export "
                        "only, negative = metrics off)")
    p.add_argument("--profile", action="store_true",
                   help="capture a jax.profiler device trace of the "
                        "checker phase into the run's profile/ dir")
    # per-op deadline (doc/robustness.md): a hung client invoke becomes
    # a bounded, indeterminate :info instead of wedging the run
    p.add_argument("--op-timeout", type=float, default=None,
                   dest="op_timeout",
                   help="seconds before an in-flight op is reaped to an "
                        "indeterminate :info and its worker replaced "
                        "(default 600; 0 disables; per-op timeout_s and "
                        "JEPSEN_TPU_OP_TIMEOUT_S also apply)")
    # preflight (doc/static-analysis.md): static test-map validation
    # before any node/db contact; the escape hatch restores the old
    # behavior bit-identically
    p.add_argument("--no-preflight", action="store_true",
                   dest="no_preflight",
                   help="skip preflight validation of the test map "
                        "(generator op surface, nemesis healability, "
                        "knob type/range checks)")


def test_opts_to_test(opts, base_test: dict) -> dict:
    nodes = parse_nodes(opts)
    test = dict(base_test)
    test["nodes"] = nodes
    test["concurrency"] = parse_concurrency(opts.concurrency, len(nodes))
    test["time_limit"] = opts.time_limit
    test["leave_db_running"] = bool(opts.leave_db_running)
    test["store_dir"] = opts.store_dir
    test["accelerator"] = opts.accelerator
    # telemetry opts ride along in the test map so every suite gets
    # spans/metrics/profiles with no suite-side code (core.run wires them)
    test["trace"] = bool(getattr(opts, "trace", False) or test.get("trace"))
    interval = getattr(opts, "metrics_interval", None)
    if interval is None:  # flag omitted: the base test's setting wins
        interval = test.get("metrics_interval", 10.0)
    test["metrics_interval"] = max(interval, 0.0)
    if interval < 0:
        test["metrics"] = False
    test["profile"] = bool(getattr(opts, "profile", False)
                           or test.get("profile"))
    if getattr(opts, "flight_recorder_events", None) is not None:
        # 0 disables the always-on flight recorder for this run
        test["flight_recorder_events"] = opts.flight_recorder_events
    if getattr(opts, "op_timeout", None) is not None:
        # 0 disables (the interpreter treats falsy as no deadline)
        test["op_timeout_s"] = opts.op_timeout
    if getattr(opts, "no_preflight", False):
        test["preflight"] = False
    ssh = dict(test.get("ssh") or {})
    ssh.update({
        "username": opts.username,
        "password": opts.password,
        "port": opts.port,
        "private_key_path": opts.ssh_private_key,
        "dummy": bool(opts.no_ssh) or ssh.get("dummy", False),
    })
    test["ssh"] = ssh
    return test


def validity_exit_code(test: dict) -> int:
    valid = (test.get("results") or {}).get("valid?")
    if valid is True:
        return EXIT_OK
    if valid == "unknown":
        return EXIT_UNKNOWN
    return EXIT_INVALID


def single_test_cmd(
    test_fn: Callable[[argparse.Namespace], dict],
    opt_fn: Callable[[argparse.ArgumentParser], None] | None = None,
    name: str = "jepsen-tpu",
) -> Callable[[list[str] | None], int]:
    """Builds a main() with test/analyze/serve subcommands around a
    test-map constructor (cli.clj:352-427 single-test-cmd)."""

    def main(argv: list[str] | None = None) -> int:
        parser = argparse.ArgumentParser(prog=name)
        sub = parser.add_subparsers(dest="command", required=True)

        p_test = sub.add_parser("test", help="run a test")
        add_test_opts(p_test)
        if opt_fn:
            opt_fn(p_test)

        p_an = sub.add_parser("analyze", help="re-check a stored history")
        p_an.add_argument("--test-name")
        p_an.add_argument("--timestamp", help="defaults to latest run")
        p_an.add_argument("--recover", action="store_true",
                          help="recover a crashed run's partial history "
                               "from its write-ahead journal "
                               "(history.wal.jsonl), check it, and mark "
                               "the results incomplete")
        p_an.add_argument("--no-live-reuse", action="store_true",
                          dest="no_live_reuse",
                          help="re-check from scratch even when the live "
                               "checker daemon left a fresh final "
                               "incremental verdict (live-status.json) "
                               "for this run")
        p_an.add_argument("--no-resume-check", action="store_true",
                          dest="no_resume_check",
                          help="re-check from zero even when an "
                               "interrupted check left a valid durable "
                               "checkpoint (check.ckpt) for this run "
                               "(doc/robustness.md)")
        add_test_opts(p_an)  # analyze takes the same opts (cli.clj:399-427)
        if opt_fn:
            opt_fn(p_an)

        p_heal = sub.add_parser(
            "heal", help="replay a crashed run's unhealed faults "
                         "(faults.jsonl) to restore net/clock state")
        p_heal.add_argument("dir", nargs="?",
                            help="store dir, or one run's directory "
                                 "(store/<name>/<timestamp>); defaults "
                                 "to --store-dir's latest run")
        p_heal.add_argument("--test-name")
        p_heal.add_argument("--timestamp", help="defaults to latest run")
        p_heal.add_argument("--store-dir", default="store")

        p_ex = sub.add_parser(
            "explain", help="re-derive anomaly forensics for a stored "
                            "run: localize the first anomaly, shrink a "
                            "minimal witness, write anomaly.json + "
                            "witness-timeline.html "
                            "(doc/observability.md)")
        p_ex.add_argument("dir", nargs="?",
                          help="one run's directory "
                               "(store/<name>/<timestamp>) or a store "
                               "dir; defaults to --store-dir's latest "
                               "run")
        p_ex.add_argument("--test-name")
        p_ex.add_argument("--timestamp", help="defaults to latest run")
        p_ex.add_argument("--store-dir", default="store")
        p_ex.add_argument("--shrink-budget", type=int, default=None,
                          dest="explain_shrink_budget",
                          help="max witness-shrink candidate checks "
                               "(default 128)")
        p_ex.add_argument("--max-witness-ops", type=int, default=None,
                          dest="explain_max_witness_ops",
                          help="stop shrinking once the witness is this "
                               "small (default 16)")

        p_tr = sub.add_parser(
            "trace", help="re-derive a stored run's causal trace from "
                          "its artifacts (WAL/history + faults.jsonl + "
                          "late.jsonl + telemetry events) into a "
                          "Perfetto-loadable trace.json "
                          "(doc/observability.md)")
        p_tr.add_argument("dir", nargs="?",
                          help="one run's directory "
                               "(store/<name>/<timestamp>) or a store "
                               "dir; defaults to --store-dir's latest "
                               "run")
        p_tr.add_argument("--test-name")
        p_tr.add_argument("--timestamp", help="defaults to latest run")
        p_tr.add_argument("--store-dir", default="store")
        p_tr.add_argument("--out", help="target path (default: the "
                                        "run's trace.json, or "
                                        "trace-derived.json when a "
                                        "live trace already exists)")

        p_serve = sub.add_parser("serve", help="serve the web UI")
        p_serve.add_argument("--host", default="0.0.0.0")
        p_serve.add_argument("-p", "--port", type=int, default=8080)
        p_serve.add_argument("--store-dir", default="store")

        p_live = sub.add_parser(
            "live", help="online checker daemon: tail active runs' "
                         "write-ahead journals and serve streaming "
                         "verdicts (doc/observability.md)")
        p_live.add_argument("dirs", nargs="*",
                            help="store root and/or individual run "
                                 "directories (store/<name>/<ts>); "
                                 "defaults to --store-dir")
        p_live.add_argument("--store-dir", default="store")
        p_live.add_argument("--poll", dest="live_poll_s", default=None,
                            help="seconds between WAL polls (default 1)")
        p_live.add_argument("--lag-budget-ops", dest="live_lag_budget_ops",
                            default=None,
                            help="lag budget in ops; beyond it a run's "
                                 "status flags over_lag_budget")
        p_live.add_argument("--max-runs", dest="live_max_runs",
                            default=None,
                            help="admission cap on concurrently tracked "
                                 "runs (default 16)")
        p_live.add_argument("--check-budget", dest="live_check_budget_s",
                            default=None,
                            help="per-poll verdict budget in predicted "
                                 "CPU seconds (cost-model admission)")
        p_live.add_argument("--accelerator", default="auto",
                            choices=["auto", "cpu", "tpu"])
        p_live.add_argument("--once", action="store_true",
                            help="poll until every tracked run "
                                 "finalizes, then exit")
        p_live.add_argument("--timeout", type=float, default=0.0,
                            help="with --once: give up after this many "
                                 "seconds (0 = wait forever)")

        p_ship = sub.add_parser(
            "ship", help="ship a run's WAL to a fleet ingest receiver "
                         "over HTTP, resume-token checked "
                         "(doc/observability.md \"Fleet plane\")")
        p_ship.add_argument("dir", help="one run's directory "
                                        "(store/<name>/<timestamp>)")
        p_ship.add_argument("--to", default=None, action="append",
                            help="receiver base URL; repeat (or comma-"
                                 "separate) for failover targets "
                                 "(default: fleet_receivers knob / "
                                 "JEPSEN_TPU_FLEET_RECEIVERS, else "
                                 "http://127.0.0.1:<fleet_port>)")
        p_ship.add_argument("--poll", dest="ship_poll_s", type=float,
                            default=0.2,
                            help="seconds between WAL polls when idle")
        p_ship.add_argument("--timeout", type=float, default=300.0,
                            help="give up after this many seconds")

        p_fleet = sub.add_parser(
            "fleet", help="fleet daemon: HTTP WAL ingest + pooled live "
                          "checking + /fleet dashboard aggregate "
                          "(doc/observability.md \"Fleet plane\")")
        p_fleet.add_argument("--store-dir", default="store",
                             help="ingest store root (shipped runs land "
                                  "here)")
        p_fleet.add_argument("--host", default="127.0.0.1")
        p_fleet.add_argument("-p", "--port", dest="fleet_port",
                             default=None,
                             help="ingest/status port (default 8091; "
                                  "env twin JEPSEN_TPU_FLEET_PORT)")
        p_fleet.add_argument("--ingest-budget",
                             dest="fleet_ingest_budget_s", default=None,
                             help="per-poll verdict budget in predicted "
                                  "CPU seconds (env twin "
                                  "JEPSEN_TPU_FLEET_INGEST_BUDGET_S)")
        p_fleet.add_argument("--max-runs", dest="fleet_max_runs",
                             default=None,
                             help="admission cap on concurrently "
                                  "tracked runs (env twin "
                                  "JEPSEN_TPU_FLEET_MAX_RUNS)")
        p_fleet.add_argument("--lease-ttl", dest="fleet_lease_ttl_s",
                             default=None,
                             help="run-lease TTL in seconds for leased "
                                  "checking; 0 disables leasing (env "
                                  "twin JEPSEN_TPU_FLEET_LEASE_TTL_S)")
        p_fleet.add_argument("--disk-headroom",
                             dest="fleet_disk_headroom_mb", default=None,
                             help="free-disk floor in MB below which "
                                  "the receiver sheds chunks with 429 "
                                  "(env twin "
                                  "JEPSEN_TPU_FLEET_DISK_HEADROOM_MB)")
        p_fleet.add_argument("--poll", dest="fleet_poll_s", type=float,
                             default=None,
                             help="seconds between pool polls")
        p_fleet.add_argument("--once", action="store_true",
                             help="poll until every tracked run "
                                  "finalizes, then exit")
        p_fleet.add_argument("--timeout", type=float, default=0.0,
                             help="with --once: give up after this "
                                  "many seconds (0 = wait forever)")

        p_chaos = sub.add_parser(
            "fleet-chaos", help="self-chaos harness: producers + "
                                "receiver + a two-host leased pool "
                                "under SIGKILL/SIGSTOP/torn-TCP/ENOSPC "
                                "injection; asserts the HA invariants "
                                "(doc/robustness.md \"Fleet HA\")")
        p_chaos.add_argument("--store-dir", default="store",
                             help="harness workspace; the report lands "
                                  "at <store>/fleet-chaos.json")
        p_chaos.add_argument("--runs", type=int, default=4,
                             help="producer runs to ship under chaos")
        p_chaos.add_argument("--ops", type=int, default=160,
                             help="history ops per run")
        p_chaos.add_argument("--seed", type=int, default=0,
                             help="seeds the chaos schedule and every "
                                  "producer history")
        p_chaos.add_argument("--lease-ttl", dest="fleet_lease_ttl_s",
                             type=float, default=1.0,
                             help="pool hosts' lease TTL (short: more "
                                  "adoption churn)")
        p_chaos.add_argument("--timeout", type=float, default=180.0,
                             help="overall harness deadline in seconds")

        p_hunt = sub.add_parser(
            "hunt", help="coverage-guided nemesis schedule fuzzer: "
                         "thousands of short fake-mode trials verdicted "
                         "through the live fleet path; anomalies ddmin-"
                         "minimize into hunt/<id>/ artifacts "
                         "(doc/robustness.md \"Schedule fuzzing\")")
        p_hunt.add_argument("--store-dir", default="store",
                            help="hunt workspace; artifacts land under "
                                 "<store>/hunt/<id>/")
        p_hunt.add_argument("--trials", dest="fuzz_trials", default=None,
                            help="trial budget (default 400; env twin "
                                 "JEPSEN_TPU_FUZZ_TRIALS)")
        p_hunt.add_argument("--pool-workers", dest="fuzz_pool_workers",
                            default=None,
                            help="trial pool processes; 0/1 = inline "
                                 "(env twin JEPSEN_TPU_FUZZ_POOL_WORKERS)")
        p_hunt.add_argument("--trial-ops", dest="fuzz_trial_ops",
                            default=None,
                            help="client ops per trial (default 120; env "
                                 "twin JEPSEN_TPU_FUZZ_TRIAL_OPS)")
        p_hunt.add_argument("--seed", dest="fuzz_seed", default=None,
                            help="hunt seed: fully determines the search "
                                 "(env twin JEPSEN_TPU_FUZZ_SEED)")
        p_hunt.add_argument("--blind", action="store_true",
                            help="disable coverage guidance (the "
                                 "random-baseline bench.py compares "
                                 "against)")
        p_hunt.add_argument("--no-stop-on-first", action="store_true",
                            help="spend the whole trial budget even "
                                 "after an anomaly lands")
        p_hunt.add_argument("--demo-bug", action="store_true",
                            help="plant the canned interleaving-gated "
                                 "anomaly into every trial's target")
        p_hunt.add_argument("--accelerator", default="cpu",
                            choices=["auto", "cpu", "tpu"])
        p_hunt.add_argument("--replay", metavar="ID", default=None,
                            help="re-run a landed hunt/<ID> artifact and "
                                 "verify the bit-identical reproduction")
        p_hunt.add_argument("--list", action="store_true",
                            help="list landed anomalies and exit")

        p_pre = sub.add_parser(
            "preflight", help="validate the test map without running it "
                              "(doc/static-analysis.md)")
        add_test_opts(p_pre)
        if opt_fn:
            opt_fn(p_pre)
        p_pre.add_argument("--format", choices=["text", "json"],
                           default="text")

        p_lint = sub.add_parser(
            "lint", help="run the concurrency/JAX/native-C invariant "
                         "linter; collects .py and .c/.cpp files "
                         "(doc/static-analysis.md)")
        p_lint.add_argument("paths", nargs="*", default=["jepsen_tpu"])
        p_lint.add_argument("--format", choices=["text", "json"],
                            default="text")
        p_lint.add_argument("--baseline",
                            help="waiver file (default: lint-baseline.txt "
                                 "next to the linted package)")
        p_lint.add_argument("--no-baseline", action="store_true",
                            help="report baselined findings too")
        p_lint.add_argument("--update-baseline", action="store_true",
                            help="rewrite the baseline from the current "
                                 "findings")
        p_lint.add_argument("--rule", action="append", dest="rules",
                            help="restrict to a rule (repeatable; globs "
                                 "allowed: --rule 'jtn-*' runs just the "
                                 "native C rules)")

        p_fuzz = sub.add_parser(
            "fuzz-native",
            help="differential WAL-parser fuzz harness: seeded, "
                 "grammar-aware byte mutants through the native "
                 "ingest_chunk (chunked + whole-buffer) vs the Python "
                 "tolerant parser, byte-exact agreement asserted on "
                 "every exec; runs under the ASan+UBSan build when "
                 "available (doc/static-analysis.md \"Native code\")")
        p_fuzz.add_argument("--execs", type=int, default=100_000,
                            help="mutant executions (default 100000)")
        p_fuzz.add_argument("--seed", type=int, default=0,
                            help="master seed: fully determines the "
                                 "mutant stream")
        p_fuzz.add_argument("--no-san", action="store_true",
                            help="run against the plain -O3 build even "
                                 "when the sanitizer lane is available")
        p_fuzz.add_argument("--store-dir", default="store",
                            help="divergence artifacts land at "
                                 "<store>/fuzz-native/")

        try:
            opts = parser.parse_args(argv)
        except SystemExit as e:
            return EXIT_BAD_ARGS if e.code not in (0, None) else 0

        try:
            if opts.command == "test":
                from jepsen_tpu import core
                from jepsen_tpu.analysis.preflight import PreflightFailed
                code = EXIT_OK
                for i in range(opts.test_count):
                    try:
                        test = test_fn(opts)
                    except (ValueError, KeyError) as e:
                        print(f"bad arguments: {e}", file=sys.stderr)
                        return EXIT_BAD_ARGS
                    try:
                        result = core.run(test)
                    except PreflightFailed as e:
                        for d in e.diagnostics:
                            print(d.render(), file=sys.stderr)
                        print("preflight rejected the test before any "
                              "node was touched (--no-preflight skips)",
                              file=sys.stderr)
                        return EXIT_BAD_ARGS
                    code = validity_exit_code(result)
                    if code != EXIT_OK:
                        break
                return code
            if opts.command == "analyze":
                return analyze_cmd(opts, test_fn)
            if opts.command == "heal":
                return heal_cmd(opts)
            if opts.command == "explain":
                return explain_cmd(opts)
            if opts.command == "trace":
                return trace_cmd(opts)
            if opts.command == "preflight":
                return preflight_cmd(opts, test_fn)
            if opts.command == "lint":
                return lint_cmd(opts)
            if opts.command == "fuzz-native":
                return fuzz_native_cmd(opts)
            if opts.command == "serve":
                from jepsen_tpu.web import serve
                serve(opts.store_dir, opts.host, opts.port)
                return EXIT_OK
            if opts.command == "live":
                return live_cmd(opts)
            if opts.command == "ship":
                return ship_cmd(opts)
            if opts.command == "fleet":
                return fleet_cmd(opts)
            if opts.command == "fleet-chaos":
                return fleet_chaos_cmd(opts)
            if opts.command == "hunt":
                return hunt_cmd(opts)
            return EXIT_BAD_ARGS
        except KeyboardInterrupt:
            return EXIT_CRASH
        except Exception:  # noqa: BLE001
            logger.exception("test crashed")
            return EXIT_CRASH

    return main


def _resolve_run(opts) -> tuple[str, str] | None:
    """(test-name, timestamp) from --test-name/--timestamp, defaulting
    to the latest stored run. None when nothing matches."""
    from jepsen_tpu import store
    if getattr(opts, "test_name", None):
        name = opts.test_name
        if getattr(opts, "timestamp", None):
            return name, opts.timestamp
        runs = store.tests(name, opts.store_dir).get(name) or {}
        if not runs:
            print(f"no stored runs for test {name!r}", file=sys.stderr)
            return None
        return name, sorted(runs)[-1]
    found = store.latest(opts.store_dir)
    if found is None:
        print("no stored tests found", file=sys.stderr)
        return None
    return found[0], found[1]


def live_cmd(opts) -> int:
    """``jepsen-tpu live``: runs the online checker daemon over a store
    root and/or explicit run directories (doc/observability.md, "Live
    checking")."""
    from pathlib import Path

    from jepsen_tpu.live import daemon as live_daemon

    store_root = opts.store_dir
    run_dirs: list = []
    for d in getattr(opts, "dirs", None) or ():
        p = Path(d)
        # a run dir holds (or held) a WAL / history; anything else is a
        # store root (last one wins, mirroring heal_cmd's dir handling)
        if (p / live_daemon.WAL_NAME).exists() or \
                (p / "history.jsonl").exists() or \
                (p / "test.json").exists():
            run_dirs.append(p)
        else:
            store_root = str(p)
    kw = {
        "poll_s": opts.live_poll_s,
        "lag_budget_ops": opts.live_lag_budget_ops,
        "max_runs": opts.live_max_runs,
        "check_budget_s": opts.live_check_budget_s,
        "accelerator": opts.accelerator,
    }
    if getattr(opts, "once", False):
        daemon = live_daemon.LiveDaemon(store_root=store_root,
                                        run_dirs=run_dirs, **kw)
        timeout = opts.timeout if opts.timeout and opts.timeout > 0 \
            else 3600.0
        statuses = daemon.run_until_idle(timeout_s=timeout)
        daemon.stop()
        for label, s in sorted(statuses.items()):
            print(f"{label}: {s['state']} valid_so_far="
                  f"{s['valid_so_far']} first_anomaly_op="
                  f"{s['first_anomaly_op']} lag_ops={s['lag_ops']}")
        worst = EXIT_OK
        for s in statuses.values():
            if s.get("valid_so_far") is False:
                worst = max(worst, EXIT_INVALID)
            elif s.get("valid_so_far") not in (True, False):
                worst = max(worst, EXIT_UNKNOWN)
        return worst
    live_daemon.serve(store_root, run_dirs=run_dirs, **kw)
    return EXIT_OK


def ship_cmd(opts) -> int:
    """``jepsen-tpu ship``: streams one run dir's WAL to a fleet
    ingest receiver, resume-token checked, finalizing with the
    authoritative history once the run completes
    (doc/observability.md "Fleet plane")."""
    from pathlib import Path

    from jepsen_tpu.fleet import (DEFAULT_FLEET_PORT, fleet_knob,
                                  fleet_receivers)
    from jepsen_tpu.fleet.ship import Shipper

    run_dir = Path(opts.dir)
    # --to repeats (or comma-separates) into a failover list; with none
    # given, the fleet_receivers knob/env twin decides, and the local
    # fleet_port receiver is the last resort (doc/robustness.md
    # "Fleet HA")
    bases: list[str] = []
    for item in opts.to or ():
        bases.extend(fleet_receivers(item))
    if not bases:
        bases = fleet_receivers()
    if not bases:
        port = int(fleet_knob("fleet_port", None,
                              DEFAULT_FLEET_PORT, 0.0))
        bases = [f"http://127.0.0.1:{port}"]
    sh = Shipper(run_dir, bases, poll_s=opts.ship_poll_s)
    ok = sh.run(timeout_s=opts.timeout)
    print(f"{sh.key}: shipped {sh.bytes_sent} byte(s) in "
          f"{sh.chunks_sent} chunk(s), {sh.resets} reset(s), "
          f"{sh.failovers} failover(s), finalized={sh.finalized}")
    return EXIT_OK if ok else EXIT_CRASH


def fleet_cmd(opts) -> int:
    """``jepsen-tpu fleet``: the pool side — HTTP WAL ingest, one live
    daemon over the ingest store, mesh heal probes, and the aggregated
    fleet-status plane (doc/observability.md "Fleet plane")."""
    from jepsen_tpu.fleet import scheduler as fleet_scheduler
    from jepsen_tpu.live.daemon import DEFAULT_POLL_S

    kw = {
        "host": opts.host,
        "port": opts.fleet_port,
        "ingest_budget_s": opts.fleet_ingest_budget_s,
        "max_runs": opts.fleet_max_runs,
        "lease_ttl_s": opts.fleet_lease_ttl_s,
        "disk_headroom_mb": opts.fleet_disk_headroom_mb,
        "poll_s": (opts.fleet_poll_s if opts.fleet_poll_s is not None
                   else DEFAULT_POLL_S),
    }
    if getattr(opts, "once", False):
        fd = fleet_scheduler.FleetDaemon(opts.store_dir, **kw)
        timeout = opts.timeout if opts.timeout and opts.timeout > 0 \
            else 3600.0
        payload = fd.run_until_idle(timeout_s=timeout)
        runs = payload.get("runs", {})
        print(f"fleet: {runs.get('final', 0)} run(s) settled, "
              f"{runs.get('invalid', 0)} invalid, worst lag "
              f"{payload.get('worst_lag_ops', 0)} ops")
        return EXIT_INVALID if runs.get("invalid", 0) else EXIT_OK
    fleet_scheduler.serve(opts.store_dir, **kw)
    return EXIT_OK


def fleet_chaos_cmd(opts) -> int:
    """``jepsen-tpu fleet-chaos``: the fleet-HA self-chaos harness
    (doc/robustness.md "Fleet HA"). Exits EXIT_OK only when every
    invariant held — zero double-checked runs, zero lost/duplicated
    WAL bytes, fleet verdicts bit-identical to local analyze."""
    import json as _json

    from jepsen_tpu.fleet.chaos import run_fleet_chaos

    report = run_fleet_chaos(opts.store_dir, runs=opts.runs,
                             n_ops=opts.ops, seed=opts.seed,
                             lease_ttl_s=opts.fleet_lease_ttl_s,
                             timeout_s=opts.timeout)
    print(_json.dumps(report, indent=2))
    return EXIT_OK if report["ok"] else EXIT_INVALID


def hunt_cmd(opts) -> int:
    """``jepsen-tpu hunt``: the coverage-guided schedule fuzzer
    (doc/robustness.md "Schedule fuzzing"). Exit codes mirror ``test``:
    a landed anomaly is EXIT_INVALID; ``--replay`` exits EXIT_OK only
    on a bit-identical reproduction."""
    import json as _json

    from jepsen_tpu.fuzz import hunt as hunt_mod

    if getattr(opts, "list", False):
        rows = hunt_mod.list_hunts(opts.store_dir)
        for r in rows:
            print(f"{r['id']}: seed={r['seed']} n_ops={r['n_ops']} "
                  f"windows={r['windows']}")
        if not rows:
            print("no landed anomalies")
        return EXIT_OK
    if opts.replay:
        try:
            out = hunt_mod.replay(opts.store_dir, opts.replay)
        except (OSError, ValueError) as e:
            print(f"replay failed to load hunt/{opts.replay}: {e}",
                  file=sys.stderr)
            return EXIT_BAD_ARGS
        print(_json.dumps(out, indent=2))
        return (EXIT_OK if out["identical"] and out["reproduced"]
                else EXIT_INVALID)
    hunter = hunt_mod.Hunter(
        opts.store_dir,
        trials=opts.fuzz_trials,
        pool_workers=opts.fuzz_pool_workers,
        trial_ops=opts.fuzz_trial_ops,
        seed=opts.fuzz_seed,
        guided=not getattr(opts, "blind", False),
        bug_spec=(hunt_mod.DEMO_BUG_SPEC
                  if getattr(opts, "demo_bug", False) else None),
        accelerator=opts.accelerator,
        stop_on_first=not getattr(opts, "no_stop_on_first", False))
    summary = hunter.run()
    print(_json.dumps(summary, indent=2))
    for hid in summary.get("hunt_ids", ()):
        print(f"reproduce with: jepsen-tpu hunt --store-dir "
              f"{opts.store_dir} --replay {hid}"
              + (" (--demo-bug artifact)" if hunter.bug_spec else ""))
    return EXIT_INVALID if summary["anomalies"] else EXIT_OK


def analyze_cmd(opts, test_fn) -> int:
    """Re-runs checkers over a stored history (cli.clj:399-427). With
    ``--recover``, a crashed run (no history.jsonl) is rebuilt from its
    write-ahead journal: the partial history is persisted via save_1,
    checked normally, and its results carry ``incomplete: true``
    (doc/robustness.md)."""
    from jepsen_tpu import core, store
    run = _resolve_run(opts)
    if run is None:
        return EXIT_BAD_ARGS
    name, ts = run
    stored = store.load_test(name, ts, opts.store_dir)
    stored["store_dir"] = opts.store_dir
    if getattr(opts, "recover", False):
        from jepsen_tpu import journal as journal_mod
        wal = store.path(stored, journal_mod.WAL_NAME)
        existing = stored.get("history") or []
        if wal.exists():
            ops, truncated = journal_mod.read_wal(wal)
            # a crash DURING save_1 can leave a torn history.jsonl next
            # to the complete journal: the journal wins whenever it
            # holds more ops than what the (tolerant) history load saw
            if len(ops) > len(existing):
                print(f"recovered {len(ops)} op(s) from {wal}"
                      + (" (torn final line dropped)" if truncated
                         else "")
                      + (f"; replacing {len(existing)}-op torn history"
                         if existing else ""))
                stored["history"] = ops
                stored["wal_recovered"] = True
                if truncated:
                    stored["wal_truncated_tail"] = True
                # persist the recovered history so the run is
                # re-analyzable through the normal path from here on
                store.save_1(stored)
            else:
                print(f"history.jsonl already holds {len(existing)} "
                      f"op(s), journal {len(ops)}; nothing to recover")
        elif not existing:
            print(f"no history and no journal at {wal}", file=sys.stderr)
            return EXIT_BAD_ARGS
    # fresh checker from the suite's constructor
    fresh = test_fn(opts)
    stored["checker"] = fresh.get("checker")
    # a live-daemon-tracked run leaves its final incremental verdict in
    # live-status.json; analyze reuses it when fresh (same op count)
    # unless --no-live-reuse re-checks from scratch
    stored["live_reuse"] = not getattr(opts, "no_live_reuse", False)
    # an interrupted check leaves a durable check.ckpt; the checker
    # auto-resumes a valid one unless --no-resume-check opts out
    if getattr(opts, "no_resume_check", False):
        stored["resume_check"] = False
    test = core.analyze(stored)
    core.log_results(test)
    print(f"valid?: {(test.get('results') or {}).get('valid?')}")
    return validity_exit_code(test)


def preflight_cmd(opts, test_fn) -> int:
    """``jepsen-tpu preflight``: builds the test map exactly as ``test``
    would and runs the static checks, printing structured diagnostics.
    Exit 0 when clean (warnings included), EXIT_BAD_ARGS on errors."""
    from jepsen_tpu import core
    from jepsen_tpu.analysis import diagnostics as diag_mod
    from jepsen_tpu.analysis import preflight as preflight_mod
    try:
        test = test_fn(opts)
    except (ValueError, KeyError) as e:
        print(f"bad arguments: {e}", file=sys.stderr)
        return EXIT_BAD_ARGS
    test = core.prepare_test(test)
    diags = preflight_mod.preflight(test)
    if getattr(opts, "format", "text") == "json":
        sys.stdout.write(diag_mod.render_json(diags))
    else:
        for d in diags:
            print(d.render())
    errors = [d for d in diags if d.severity == diag_mod.ERROR]
    if errors:
        print(f"preflight: {len(errors)} error(s), "
              f"{len(diags) - len(errors)} other diagnostic(s)",
              file=sys.stderr)
        return EXIT_BAD_ARGS
    if getattr(opts, "format", "text") == "text":
        print(f"preflight clean ({len(diags)} non-fatal diagnostic(s))"
              if diags else "preflight clean")
    return EXIT_OK


def lint_cmd(opts) -> int:
    """``jepsen-tpu lint [paths...]``: the invariant linter. Exit 0 when
    no non-baselined finding remains."""
    from jepsen_tpu.analysis import lint as lint_mod
    baseline: object = getattr(opts, "baseline", None)
    if getattr(opts, "no_baseline", False):
        baseline = False
    try:
        report = lint_mod.lint_paths(opts.paths, baseline=baseline,
                                     rules=getattr(opts, "rules", None))
    except ValueError as e:
        print(f"lint: {e}", file=sys.stderr)
        return EXIT_BAD_ARGS
    if getattr(opts, "update_baseline", False):
        if getattr(opts, "rules", None):
            # a rule-restricted run only sees that rule's findings — a
            # rewrite from it would silently drop every OTHER rule's
            # waivers (and their why-comments) from the baseline
            print("lint: --update-baseline cannot be combined with "
                  "--rule (it would discard the other rules' waivers); "
                  "run it over the full rule set", file=sys.stderr)
            return EXIT_BAD_ARGS
        from pathlib import Path
        bpath = (Path(opts.baseline) if getattr(opts, "baseline", None)
                 else lint_mod._guess_root(opts.paths)
                 / lint_mod.BASELINE_NAME)
        lint_mod.write_baseline(bpath, report.findings + report.baselined)
        print(f"baseline written: {bpath} "
              f"({len(report.findings) + len(report.baselined)} entries)")
        return EXIT_OK
    if getattr(opts, "format", "text") == "json":
        sys.stdout.write(lint_mod.render_report_json(report))
    else:
        print(lint_mod.render_text(report))
    return EXIT_OK if report.exit_code == 0 else 1


def fuzz_native_cmd(opts) -> int:
    """``jepsen-tpu fuzz-native``: the differential WAL-parser fuzz
    harness (doc/static-analysis.md "Native code"). By default the run
    happens under the ASan+UBSan build: when this process doesn't have
    libasan preloaded (it can't be dlopen'd late — GCC's runtime aborts
    the process), the command re-execs itself once in a child with
    ``columnar_c.san_env()``. Exit: 0 clean, 1 divergence found, 2 when
    no native build is loadable (nothing to differentiate)."""
    import shutil
    import subprocess as sp

    from jepsen_tpu.native import columnar_c

    want_san = not getattr(opts, "no_san", False)
    if want_san and not columnar_c._asan_mapped():
        env = columnar_c.san_env()
        built = False
        if env is not None and shutil.which("g++"):
            try:
                columnar_c.build(san=True)
                built = True
            except Exception:  # noqa: BLE001 — fall through to plain
                logger.warning("sanitizer build failed", exc_info=True)
        if built:
            print("fuzz-native: re-exec under the ASan+UBSan build "
                  "(LD_PRELOAD libasan)")
            sys.stdout.flush()
            cmd = [sys.executable, "-m", "jepsen_tpu.cli", "fuzz-native",
                   "--execs", str(opts.execs), "--seed", str(opts.seed),
                   "--store-dir", opts.store_dir]
            return sp.run(cmd, env=env).returncode
        print("fuzz-native: sanitizer lane unavailable (no g++/libasan "
              "or san build failed); running against the plain -O3 "
              "build", file=sys.stderr)
        from jepsen_tpu.history_ir import ingest
        ingest.fallback_count("san-unavailable")
        want_san = False

    from jepsen_tpu.fuzz import native as fuzz_native
    res = fuzz_native.run_fuzz(opts.execs, seed=opts.seed, san=want_san,
                               store_dir=opts.store_dir, progress=print)
    if res["status"] == "no-native":
        print("fuzz-native: no native build loadable in this process; "
              "nothing to differentiate", file=sys.stderr)
        return EXIT_UNKNOWN
    variant = "san" if res["san"] else "plain"
    print(f"fuzz-native: {res['execs']} execs "
          f"({res['execs_per_s']:,.0f}/s, variant={variant}, "
          f"seed={opts.seed}) — {res['ops_parsed']} ops parsed, "
          f"{res['torn_lines']} torn lines, "
          f"{res['divergences']} divergence(s)")
    cov = ", ".join(f"{k}:{v}" for k, v in
                    sorted(res["operator_coverage"].items()))
    print(f"  operator coverage: {cov}")
    if res["divergences"]:
        for a in res["artifacts"]:
            print(f"  divergence artifact: {a}", file=sys.stderr)
        return EXIT_INVALID
    return EXIT_OK


def explain_cmd(opts) -> int:
    """``jepsen-tpu explain``: offline anomaly forensics for a stored
    run — localization + minimal witness + artifacts, re-derived from
    history.jsonl alone (doc/observability.md "Anomaly forensics").
    Exit codes follow ``validity_exit_code``'s convention: EXIT_OK when
    the run is valid (nothing to explain), EXIT_INVALID when forensics
    were derived and written, EXIT_UNKNOWN for a run explain cannot
    judge (no usable history, or a workload with no forensics),
    EXIT_BAD_ARGS when no run could be resolved at all."""
    from pathlib import Path

    from jepsen_tpu.checker import explain as explain_mod

    run_dir = None
    if getattr(opts, "dir", None):
        d = Path(opts.dir)
        if (d / "history.jsonl").exists() or (d / "test.json").exists():
            run_dir = d  # a single run's directory
        else:
            opts.store_dir = str(d)  # a store dir: fall through to latest
    if run_dir is None:
        run = _resolve_run(opts)
        if run is None:
            return EXIT_BAD_ARGS
        name, ts = run
        run_dir = Path(opts.store_dir) / name / ts
    summary = explain_mod.explain_run(
        run_dir,
        shrink_budget=getattr(opts, "explain_shrink_budget", None),
        max_witness_ops=getattr(opts, "explain_max_witness_ops", None))
    if summary is None:
        print(f"no usable history at {run_dir}", file=sys.stderr)
        return EXIT_UNKNOWN
    if summary.get("valid") is True:
        print(f"{run_dir}: history is valid — nothing to explain")
        return EXIT_OK
    if "unsupported" in summary:
        print(f"{run_dir}: no forensics for workload "
              f"{summary['unsupported']!r} (register and list-append "
              "histories are supported)", file=sys.stderr)
        return EXIT_UNKNOWN
    if "first_anomaly_op" in summary:
        print(f"{run_dir}: first anomaly at op "
              f"{summary['first_anomaly_op']} — witness of "
              f"{summary['witness_ops']} op(s) via {summary['backend']}; "
              f"wrote {', '.join(summary.get('artifacts') or [])}")
    else:
        print(f"{run_dir}: valid?={summary.get('valid')} anomalies="
              f"{summary.get('anomaly_types')}; wrote "
              f"{', '.join(summary.get('artifacts') or [])}")
    return EXIT_INVALID if summary.get("valid") is False else EXIT_UNKNOWN


def trace_cmd(opts) -> int:
    """``jepsen-tpu trace``: offline causal-trace derivation for a
    stored run — old runs become traceable retroactively
    (doc/observability.md "Causal trace"). Prints the summary (span
    counts per track, slowest ops, demotion chain) and the written
    path. Exit 0 on success, EXIT_UNKNOWN when the run has no usable
    op artifact, EXIT_BAD_ARGS when no run resolves."""
    from pathlib import Path

    from jepsen_tpu.journal import WAL_NAME
    from jepsen_tpu.trace.derive import derive_run_trace, summarize_trace

    run_dir = None
    if getattr(opts, "dir", None):
        d = Path(opts.dir)
        if (d / "history.jsonl").exists() or (d / WAL_NAME).exists() \
                or (d / "test.json").exists():
            run_dir = d  # a single run's directory
        else:
            opts.store_dir = str(d)  # a store dir: fall through to latest
    if run_dir is None:
        run = _resolve_run(opts)
        if run is None:
            return EXIT_BAD_ARGS
        name, ts = run
        run_dir = Path(opts.store_dir) / name / ts
    out = derive_run_trace(run_dir, out=getattr(opts, "out", None))
    if out is None:
        print(f"no usable history or journal at {run_dir}",
              file=sys.stderr)
        return EXIT_UNKNOWN
    summary = summarize_trace(out)
    if summary:
        tracks = ", ".join(f"{t}: {n}"
                           for t, n in summary["tracks"].items())
        print(f"{out}: {summary['events']} event(s) across "
              f"{len(summary['tracks'])} track(s) [{tracks}]")
        for o in summary["slowest_ops"]:
            print(f"  slow: {o['name']} ({o['track']}) {o['dur_ms']} ms")
        if summary["demotions"]:
            print("  demotion chain: " + " -> ".join(summary["demotions"]))
    else:
        print(f"{out}: written (no events?)")
    print("load it at https://ui.perfetto.dev (or chrome://tracing)")
    return EXIT_OK


def heal_cmd(opts) -> int:
    """Replays a crashed run's unhealed faults (``cli heal``): reads the
    run's ``faults.jsonl``, applies the idempotent heal for each
    unhealed kind (net partitions flushed, netem cleared, clocks
    reset), and marks entries healed. Process kill/pause faults need
    the live db object and are reported unhealable offline
    (doc/robustness.md)."""
    import json as _json
    from pathlib import Path

    from jepsen_tpu import store
    from jepsen_tpu.nemesis import faults as faults_mod

    run_dir = None
    if getattr(opts, "dir", None):
        d = Path(opts.dir)
        if (d / faults_mod.FAULTS_NAME).exists() or (d / "test.json").exists():
            run_dir = d  # a single run's directory
        else:
            opts.store_dir = str(d)  # a store dir: fall through to latest
    if run_dir is None:
        run = _resolve_run(opts)
        if run is None:
            return EXIT_BAD_ARGS
        name, ts = run
        run_dir = Path(opts.store_dir) / name / ts
    reg_path = run_dir / faults_mod.FAULTS_NAME
    if not reg_path.exists():
        print(f"no fault registry at {reg_path}; nothing to heal")
        return EXIT_OK
    test: dict = {}
    try:
        with open(run_dir / "test.json") as f:
            test = _json.load(f)
    except (OSError, ValueError):
        logger.warning("no readable test.json in %s", run_dir)
    test.setdefault("nodes", [])
    test["store_dir"] = str(run_dir.parent.parent)
    registry = faults_mod.FaultRegistry(reg_path)
    try:
        unhealed = registry.unhealed()
        if not unhealed:
            print("no unhealed faults; cluster is clean")
            return EXIT_OK
        if not test["nodes"]:
            # healing over zero nodes would trivially "succeed" and
            # durably mark the faults healed without touching the
            # cluster — destroying the only record that healing is
            # still needed. Refuse instead.
            print(f"{len(unhealed)} unhealed fault(s) but no node list "
                  f"(missing/corrupt test.json in {run_dir}); refusing "
                  "to heal blind — pass a run dir with an intact "
                  "test.json or heal the cluster manually",
                  file=sys.stderr)
            return EXIT_UNKNOWN
        print(f"replaying {len(unhealed)} unhealed fault(s): "
              + ", ".join(sorted({str(r.get('kind')) for r in unhealed})))
        summary = faults_mod.replay_unhealed(test, registry)
        print(f"healed: {summary['healed']}  "
              f"unhealable: {summary['unhealable']}  "
              f"failed: {summary['failed']}")
        return (EXIT_OK if not summary["unhealable"] and not summary["failed"]
                else EXIT_UNKNOWN)
    finally:
        registry.close()
        from jepsen_tpu import control
        try:
            control.disconnect_all(test)
        except Exception:  # noqa: BLE001
            pass


def test_all_cmd(tests_fn: Callable[[argparse.Namespace], list], name="jepsen-tpu"):
    """Sweep runner (cli.clj:429-515): runs every workload, summarizes.
    Honors the module exit-code contract like single_test_cmd: bad
    arguments → EXIT_BAD_ARGS, a crash mid-sweep → EXIT_CRASH."""

    def main(argv: list[str] | None = None) -> int:
        parser = argparse.ArgumentParser(prog=f"{name} test-all")
        add_test_opts(parser)
        try:
            opts = parser.parse_args(argv)
        except SystemExit:
            return EXIT_BAD_ARGS
        try:
            from jepsen_tpu import core
            from jepsen_tpu.analysis.preflight import PreflightFailed
            worst = EXIT_OK
            # each round rebuilds the test maps — core.run mutates them
            # (cli.clj:429-515 runs every combination test-count times)
            for _ in range(getattr(opts, "test_count", 1) or 1):
                for test in tests_fn(opts):
                    try:
                        result = core.run(test)
                    except PreflightFailed as e:
                        for d in e.errors:
                            print(d.render(), file=sys.stderr)
                        logger.error("%s rejected by preflight",
                                     test.get("name"))
                        worst = max(worst, EXIT_BAD_ARGS)
                        continue
                    code = validity_exit_code(result)
                    worst = max(worst, code if code != EXIT_OK else worst)
                    logger.info("%s: %s", test.get("name"),
                                (result.get("results") or {}).get("valid?"))
            return worst
        except KeyboardInterrupt:
            return EXIT_CRASH
        except Exception:  # noqa: BLE001
            logger.exception("sweep crashed")
            return EXIT_CRASH

    return main


def noop_main(argv: list[str] | None = None) -> int:
    """`python -m jepsen_tpu.cli` — runs the noop test (smoke check)."""
    from jepsen_tpu.fakes import noop_test

    def build(opts):
        return test_opts_to_test(opts, noop_test())

    return single_test_cmd(build)(argv)


if __name__ == "__main__":
    sys.exit(noop_main())
