"""PN-counter workload (reference: the aerospike and yugabyte counter
tests — aerospike/src/aerospike/counter.clj, yugabyte counter clients —
over jepsen's counter checker, checker.clj:737-795).

Clients add random increments (and, when ``negative`` is set,
decrements) to one shared counter while readers poll it; every ok read
must fall inside the [sum-of-acknowledged, sum-of-attempted] window,
with indeterminate adds widening the window forever.

Op shapes: ``{"f": "add", "value": delta}`` and ``{"f": "read",
"value": None → int}``.
"""
from __future__ import annotations

from jepsen_tpu import checker as chk
from jepsen_tpu import generator as gen


def adds(negative: bool = False):
    def add(test, ctx):
        v = 1 + ctx.rng.randint(0, 4)
        if negative and ctx.rng.random() < 0.5:
            v = -v
        return {"f": "add", "value": v}

    return gen.Fn(add)


def reads():
    def read(test, ctx):
        return {"f": "read", "value": None}

    return gen.Fn(read)


def workload(test: dict | None = None, negative: bool = False,
             **_) -> dict:
    return {
        "counter": True,  # fake-client dispatch marker
        "generator": gen.mix([adds(negative), reads()]),
        "checker": chk.counter(),
    }
