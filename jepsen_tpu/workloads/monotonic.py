"""Monotonic-inserts workload (reference:
cockroachdb/src/jepsen/cockroach/monotonic.clj — each transaction reads
the current maximum value and inserts max+1 together with the DB's own
transaction timestamp; a serializable system must yield values whose
order agrees with timestamp order).

Op shapes:
- ``{"f": "inc", "value": None}`` — one read-max-insert-max+1 txn; the
  ok completion's value is the inserted integer.
- ``{"f": "read-all", "value": None → [[val, ts], ...]}`` — final read
  of every row with its commit timestamp (``ts`` compares as a string
  or number, whatever the DB provides).

The checker (monotonic.clj:147-210): order the final read's rows by
timestamp; the values must be strictly increasing (off-order values =
serializability violation), with no duplicates.
"""
from __future__ import annotations

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker


def incs():
    def inc(test, ctx):
        return {"f": "inc", "value": None}

    return gen.Fn(inc)


def final_read():
    def read(test, ctx):
        return {"f": "read-all", "value": None}

    return gen.once(gen.Fn(read))


def non_monotonic(rows: list) -> tuple[list, list]:
    """Classifies adjacent rows of a ts-sorted [(ts, [val, ts]), ...]
    sequence (monotonic.clj:147-154): returns (off_order, ambiguous)
    pair lists. Equal-timestamp neighbours have no knowable order, so
    they are ambiguous regardless of value order — judged before the
    value comparison so the count doesn't depend on the DB's row-return
    order for ties."""
    off_order, ambiguous = [], []
    for (ta, a), (tb, b) in zip(rows, rows[1:]):
        if ta == tb:
            ambiguous.append([a, b])
        elif not a[0] < b[0]:
            off_order.append([a, b])
    return off_order, ambiguous


# every clock-fault op shape: the combined clock package's ClockNemesis
# (reset/bump/strobe) and the legacy coarse ClockScrambler
CLOCK_NEMESIS_FS = {"reset", "bump", "strobe", "scramble-clock"}


def _clock_nemesis_active(history) -> bool:
    return any(not isinstance(op.get("process"), int)
               and op.get("f") in CLOCK_NEMESIS_FS for op in history)


class MonotonicChecker(Checker):
    """Timestamp-order monotonicity (monotonic.clj:147-210), with three
    honesty refinements over a naive sort-and-compare:

    * rows whose timestamp doesn't parse are reported separately and
      force ``valid? "unknown"`` — a data/parsing problem must not
      masquerade as a serializability violation;
    * adjacent rows with EQUAL timestamps have no knowable order, so
      they're counted as ``ambiguous-pairs`` rather than off-order;
    * when the history contains clock-nemesis activity and the client's
      timestamps are wall-clock (``client.logical_ts`` is False — the
      postgres-family default ``clock_timestamp()``; cockroach's HLC sets
      True), off-order pairs are expected even on a healthy serializable
      DB, so the verdict degrades to ``"unknown"`` instead of convicting.
    """

    def name(self):
        return "monotonic"

    def check(self, test, history, opts):
        final = None
        for op in history:
            if op.get("type") == "ok" and op.get("f") == "read-all":
                final = op
        if final is None:
            return {"valid?": "unknown", "error": "no final read"}
        from decimal import Decimal, InvalidOperation

        rows, unparseable = [], []
        for r in final.get("value") or []:
            try:
                row = list(r)
                rows.append((Decimal(str(row[1])), row))
            except (InvalidOperation, TypeError, ValueError, IndexError):
                # any malformed row (short, scalar, unparseable ts) lands
                # here — including ones list() itself can't take
                try:
                    unparseable.append(list(r))
                except TypeError:
                    unparseable.append([r, None])
        rows.sort(key=lambda p: p[0])
        off_order, ambiguous = non_monotonic(rows)
        vals = [r[0] for _, r in rows] + [r[0] for r in unparseable
                                         if r and r[0] is not None]

        def key(v):  # unhashable values must not crash the verdict
            try:
                hash(v)
                return v
            except TypeError:
                return ("__unhashable__", repr(v))

        from collections import Counter
        counts = Counter(key(v) for v in vals)
        dups = sorted((v for v in {key(v): v for v in vals}.values()
                       if counts[key(v)] > 1), key=repr)
        # every acknowledged insert must be present in the final read
        acked = {key(op.get("value")) for op in history
                 if op.get("type") == "ok" and op.get("f") == "inc"}
        lost = sorted(acked - {key(v) for v in vals}, key=repr)
        valid = not off_order and not dups and not lost
        note = None
        if unparseable:
            valid = "unknown" if valid is True else valid
            note = "unparseable timestamps: no ordering verdict"
        if off_order and not dups and not lost and _clock_nemesis_active(
                history) and getattr(test.get("client"), "logical_ts",
                                     None) is False:
            valid = "unknown"
            note = ("wall-clock timestamps under a clock nemesis: "
                    "off-order pairs are not evidence against the DB")
        out = {
            "valid?": valid,
            "row-count": len(rows) + len(unparseable),
            "off-order-pairs": off_order[:10],
            "off-order-count": len(off_order),
            "ambiguous-pairs": ambiguous[:10],
            "ambiguous-count": len(ambiguous),
            "unparseable-ts": unparseable[:10],
            "unparseable-count": len(unparseable),
            "duplicates": dups[:10],
            "lost": lost[:10],
            "lost-count": len(lost),
        }
        if note:
            out["note"] = note
        return out


def checker() -> Checker:
    return MonotonicChecker()


def workload(test: dict | None = None, **_) -> dict:
    return {
        "generator": incs(),
        "final_generator": final_read(),
        "checker": checker(),
    }
