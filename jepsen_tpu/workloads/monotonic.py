"""Monotonic-inserts workload (reference:
cockroachdb/src/jepsen/cockroach/monotonic.clj — each transaction reads
the current maximum value and inserts max+1 together with the DB's own
transaction timestamp; a serializable system must yield values whose
order agrees with timestamp order).

Op shapes:
- ``{"f": "inc", "value": None}`` — one read-max-insert-max+1 txn; the
  ok completion's value is the inserted integer.
- ``{"f": "read-all", "value": None → [[val, ts], ...]}`` — final read
  of every row with its commit timestamp (``ts`` compares as a string
  or number, whatever the DB provides).

The checker (monotonic.clj:147-210): order the final read's rows by
timestamp; the values must be strictly increasing (off-order values =
serializability violation), with no duplicates.
"""
from __future__ import annotations

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker


def incs():
    def inc(test, ctx):
        return {"f": "inc", "value": None}

    return gen.Fn(inc)


def final_read():
    def read(test, ctx):
        return {"f": "read-all", "value": None}

    return gen.once(gen.Fn(read))


def non_monotonic(pairs: list) -> list:
    """Adjacent [val, ts] pairs (sorted by ts) whose values do not
    strictly increase (monotonic.clj:147-154)."""
    bad = []
    for a, b in zip(pairs, pairs[1:]):
        if not a[0] < b[0]:
            bad.append([a, b])
    return bad


class MonotonicChecker(Checker):
    def name(self):
        return "monotonic"

    def check(self, test, history, opts):
        final = None
        for op in history:
            if op.get("type") == "ok" and op.get("f") == "read-all":
                final = op
        if final is None:
            return {"valid?": "unknown", "error": "no final read"}
        from decimal import Decimal, InvalidOperation

        def ts_key(r):
            # timestamps arrive as strings (HLC decimals overflow float
            # precision) or numbers; Decimal compares both exactly
            try:
                return Decimal(str(r[1]))
            except InvalidOperation:
                return Decimal(0)

        rows = [list(r) for r in (final.get("value") or [])]
        rows.sort(key=ts_key)
        off_order = non_monotonic(rows)
        vals = [r[0] for r in rows]
        from collections import Counter
        dups = sorted(v for v, n in Counter(vals).items() if n > 1)
        # every acknowledged insert must be present in the final read
        acked = {op.get("value") for op in history
                 if op.get("type") == "ok" and op.get("f") == "inc"}
        lost = sorted(acked - set(vals))
        return {
            "valid?": not off_order and not dups and not lost,
            "row-count": len(rows),
            "off-order-pairs": off_order[:10],
            "off-order-count": len(off_order),
            "duplicates": dups[:10],
            "lost": lost[:10],
            "lost-count": len(lost),
        }


def checker() -> Checker:
    return MonotonicChecker()


def workload(test: dict | None = None, **_) -> dict:
    return {
        "generator": incs(),
        "final_generator": final_read(),
        "checker": checker(),
    }
