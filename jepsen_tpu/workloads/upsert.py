"""Upsert-uniqueness workload (reference: dgraph/src/jepsen/dgraph/
upsert.clj — concurrent conditional creates of the same key must yield
at most ONE record: two racers both reading "absent" and both creating
is the classic upsert anomaly).

Op shapes:
- ``{"f": "upsert", "value": [k, attempt_id]}`` — create key k if absent
- ``{"f": "read-uids", "value": [k, uids]}`` — every record currently
  holding key k
"""
from __future__ import annotations

import itertools
import threading

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker


def generator(key_rotation: int = 8, attempts_per_key: int = 6):
    """Bursts of concurrent upserts on one key, then a read, then rotate
    to the next key (upsert.clj drives ~n concurrent upserts per key)."""
    lock = threading.Lock()
    counter = itertools.count()
    state = {"key": 0, "left": attempts_per_key}

    def one(test, ctx):
        with lock:
            if state["left"] <= 0:
                state["key"] += 1
                state["left"] = attempts_per_key
                return {"f": "read-uids", "value": [state["key"] - 1, None]}
            state["left"] -= 1
            return {"f": "upsert",
                    "value": [state["key"], next(counter)]}

    return gen.Fn(one)


class UpsertChecker(Checker):
    """Valid iff no read ever observes two records for one key
    (upsert.clj's at-most-one invariant)."""

    def check(self, test, history, opts):
        dups = []
        reads = 0
        for op in history:
            if op.get("type") != "ok" or op.get("f") != "read-uids":
                continue
            reads += 1
            k, uids = op.get("value")
            if uids is not None and len(uids) > 1:
                dups.append({"key": k, "uids": list(uids)})
        return {"valid?": not dups, "read-count": reads,
                "duplicate-count": len(dups), "duplicates": dups[:10]}


def workload(test: dict | None = None, **_) -> dict:
    return {
        "upsert-workload": True,  # fake-mode client dispatch marker
        "generator": generator(),
        "checker": UpsertChecker(),
    }
