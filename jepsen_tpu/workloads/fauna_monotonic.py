"""FaunaDB-style monotonic workload (reference:
faunadb/src/jepsen/faunadb/monotonic.clj — clients observe a single
increment-only register through current reads, temporal (``at``) reads,
and increments; every completion carries the transaction timestamp, so
the history supports both per-session and global timestamp-order
monotonicity checks).

Op shapes (monotonic.clj:8-26):
- ``{"f": "inc", "value": None}`` → ok ``[ts, v]`` — bumped the register
  at time ``ts``; ``v`` is the pre-increment value.
- ``{"f": "read", "value": None}`` → ok ``[ts, v]`` — current read.
- ``{"f": "read-at", "value": [ts|None, None]}`` → ok ``[ts, v]`` — read
  at the (possibly jittered past) timestamp ``ts``.

Checkers:
- ``monotonic`` (monotonic.clj:151-192): within each process, the
  sequence of ok read/inc completions must never go backwards — in
  value OR in timestamp.
- ``timestamp-value`` (monotonic.clj:206-219): globally, sorting ok
  read-at/inc completions by timestamp must yield non-decreasing
  values (the register is increment-only, so a higher timestamp can
  never hold a lower value).
- ``not-found`` (monotonic.clj:334-348): reads guard with explicit
  existence checks, so a not-found failure is itself an anomaly.
- ``timestamp-value-plot`` (monotonic.clj:293-332): renders windows of
  the value-vs-timestamp curve around each non-monotonic spot.
"""
from __future__ import annotations

from typing import Any, Callable

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker, compose


def ts_key(ts) -> tuple:
    """Total-order key over heterogeneous timestamps: numerics sort
    numerically, everything else lexically (stripped ISO-8601 strings
    compare correctly this way — monotonic.clj:51-59 strips the Z for
    exactly this reason)."""
    if isinstance(ts, bool):  # bool is an int subtype; don't let it in
        return (1, 0.0, str(ts))
    if isinstance(ts, (int, float)):
        return (0, float(ts), "")
    return (1, 0.0, str(ts))


def _pair_value(op: dict):
    v = op.get("value")
    if isinstance(v, (list, tuple)) and len(v) == 2:
        return v
    return None


def non_monotonic_pairs_by_process(extractor: Callable, history) -> list:
    """Pairs of ok ops on the same process where ``extractor`` goes
    backwards (monotonic.clj:151-172)."""
    last: dict[Any, dict] = {}
    errs = []
    for op in history:
        if op.get("type") != "ok":
            continue
        p = op.get("process")
        prev = last.get(p)
        if prev is not None:
            a, b = extractor(prev), extractor(op)
            if a is not None and b is not None and not a <= b:
                errs.append([prev, op])
        last[p] = op
    return errs


def non_monotonic_pairs(extractor: Callable, ops: list) -> list:
    """Adjacent pairs where ``extractor`` decreases
    (monotonic.clj:194-204)."""
    errs = []
    for a, b in zip(ops, ops[1:]):
        va, vb = extractor(a), extractor(b)
        if va is not None and vb is not None and not va <= vb:
            errs.append([a, b])
    return errs


def merged_windows(s: int, points: list) -> list:
    """[lower, upper] windows of ``s`` around each point, overlaps
    merged (monotonic.clj:221-243)."""
    if not points:
        return []
    points = sorted(points)
    windows = []
    lower, upper = points[0] - s, points[0] + s
    for p in points[1:]:
        if upper <= p - s:
            windows.append([lower, upper])
            lower = p - s
        upper = p + s
    windows.append([lower, upper])
    return windows


def _val_of(op):
    pair = _pair_value(op)
    return None if pair is None else pair[1]


def _ts_of(op):
    pair = _pair_value(op)
    return None if pair is None else ts_key(pair[0])


class PerProcessMonotonicChecker(Checker):
    """Per-session monotonicity of both values and timestamps
    (monotonic.clj:174-192)."""

    def name(self):
        return "monotonic"

    def check(self, test, history, opts):
        ops = [op for op in history if op.get("f") in ("read", "inc")]
        value_errs = non_monotonic_pairs_by_process(_val_of, ops)
        ts_errs = non_monotonic_pairs_by_process(_ts_of, ops)
        return {
            "valid?": not value_errs and not ts_errs,
            "value-errors": value_errs[:10],
            "value-error-count": len(value_errs),
            "ts-errors": ts_errs[:10],
            "ts-error-count": len(ts_errs),
        }


class TimestampValueChecker(Checker):
    """Global timestamp→value monotonicity over read-at/inc completions
    (monotonic.clj:206-219)."""

    def name(self):
        return "timestamp-value"

    def check(self, test, history, opts):
        ops = sorted(
            (op for op in history
             if op.get("type") == "ok" and op.get("f") in ("read-at", "inc")
             and _pair_value(op) is not None),
            key=_ts_of)
        errs = non_monotonic_pairs(_val_of, ops)
        return {"valid?": not errs, "errors": errs[:10],
                "error-count": len(errs)}


class NotFoundChecker(Checker):
    """Existence-guarded reads must never fail not-found
    (monotonic.clj:334-348)."""

    def name(self):
        return "not-found"

    def check(self, test, history, opts):
        def is_nf(op):
            err = op.get("error")
            if err == "not-found":
                return True
            return isinstance(err, (list, tuple)) and "not-found" in err

        errs = [op for op in history
                if op.get("type") == "fail" and is_nf(op)]
        return {
            "valid?": not errs,
            "invoke-count": sum(op.get("type") == "invoke"
                                for op in history),
            "error-count": len(errs),
            "first": errs[0] if errs else None,
            "last": errs[-1] if errs else None,
        }


class TimestampValuePlotter(Checker):
    """Plots value-vs-timestamp windows around non-monotonic spots
    (monotonic.clj:293-332). Always valid — a render, not a verdict."""

    WINDOW = 32

    def name(self):
        return "timestamp-value-plot"

    def check(self, test, history, opts):
        ops = sorted(
            (op for op in history
             if op.get("type") == "ok" and op.get("f") == "read-at"
             and _pair_value(op) is not None),
            key=_ts_of)
        # non-monotonic "spots": positions where a process's view of the
        # value went backwards (monotonic.clj:308-323)
        last: dict[Any, dict] = {}
        spots = []
        for i, op in enumerate(ops):
            p = op.get("process")
            prev = last.get(p)
            if prev is not None:
                a, b = _val_of(prev), _val_of(op)
                if a is not None and b is not None and not a <= b:
                    spots.append(i)
            last[p] = op
        for i, (lo, hi) in enumerate(merged_windows(self.WINDOW, spots)):
            window = ops[max(lo, 0): hi]  # slice end clamps itself
            self._plot(test, opts, i, window)
        return {"valid?": True, "spot-count": len(spots)}

    def _plot(self, test, opts, index, window):
        if not window:
            return
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        from jepsen_tpu import store

        by_process: dict[Any, list] = {}
        for pos, op in enumerate(window):
            by_process.setdefault(op.get("process"), []).append(
                (pos, _val_of(op)))
        fig, ax = plt.subplots(figsize=(8, 4))
        for p, pts in sorted(by_process.items(), key=lambda kv: str(kv[0])):
            ax.plot([x for x, _ in pts], [y for _, y in pts], "-x",
                    ms=4, label=str(p))
        ax.set_xlabel("read (timestamp order)")
        ax.set_ylabel("register value")
        ax.set_title(f"{test.get('name', 'test')} sequential {index}")
        ax.legend(loc="upper left", fontsize=7)
        d = opts.get("subdirectory")
        fig.savefig(store.path_mk(test, *filter(None, [
            d, f"sequential-{index}.png"])), bbox_inches="tight")
        plt.close(fig)


def generator():
    """Uniform mix of incs, current reads, and temporal reads
    (monotonic.clj:350-366)."""
    return gen.mix([
        gen.Fn(lambda test, ctx: {"f": "inc", "value": None}),
        gen.Fn(lambda test, ctx: {"f": "read", "value": None}),
        gen.Fn(lambda test, ctx: {"f": "read-at", "value": [None, None]}),
    ])


def checker() -> Checker:
    return compose({
        "monotonic": PerProcessMonotonicChecker(),
        "timestamp-value": TimestampValueChecker(),
        "not-found": NotFoundChecker(),
        "timestamp-value-plot": TimestampValuePlotter(),
    })


def workload(test: dict | None = None, **_) -> dict:
    return {
        "fauna_monotonic": True,
        "generator": generator(),
        "checker": checker(),
    }
