"""FaunaDB-style multi-register monotonic workload (reference:
faunadb/src/jepsen/faunadb/multimonotonic.clj — increment-only
registers, one writer per register doing blind writes for throughput,
readers snapshotting random register subsets with the transaction
timestamp; checkers hunt reads that flow backwards).

Op shapes (multimonotonic.clj:85-105):
- ``{"f": "write", "value": {k: v}}`` — blind-write register ``k`` to
  ``v`` (single writer per key; values strictly increase).
- ``{"f": "read", "value": [k, ...]}`` → ok value
  ``{"ts": read_ts, "registers": {k: {"value": v, "ts": ts_k}, ...}}``.

Checkers:
- ``ts-order`` (multimonotonic.clj:255-272): order reads by their
  transaction timestamp and play a state machine forward, tracking the
  maximum observed value per register; a read showing a *lower* value
  than an earlier-timestamped read is an internal consistency
  violation.
- ``read-skew`` (multimonotonic.clj:274-312): the reference describes
  the cycle-detection algorithm in its docstring but ships a stub that
  always passes; here it is implemented for real. Reads are vertices;
  for each register, edges connect each read to the reads observing the
  next-higher value of that register (the transitive reduction of <_k
  over observed values); a strongly connected component with more than
  one read is a set of mutually-unorderable snapshots — read skew.
"""
from __future__ import annotations

import threading
from typing import Any

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker, compose
from jepsen_tpu.workloads.fauna_monotonic import ts_key


def generator(concurrency: int = 5):
    """Half the threads write (each blind-writing a key derived from its
    process id, so a crashed process starts a fresh key), half read
    random nonempty subsets of the active keys
    (multimonotonic.clj:314-341)."""
    lock = threading.Lock()
    last_vals: dict[Any, int] = {}  # key -> last written value
    active: list = []               # insertion-ordered distinct keys

    def write(test, ctx):
        p = ctx.some_free_process()
        if p is None:  # no free reserved thread: let fill_in_op pend
            return {"f": "write", "value": None}
        k = p
        with lock:
            v = last_vals.get(k, -1) + 1
            last_vals[k] = v
            if k not in active:
                active.append(k)
        return {"f": "write", "value": {k: v}, "process": p}

    def read(test, ctx):
        with lock:
            ks = list(active)
        if not ks:
            ks = [0]
        n = ctx.rng.randint(1, len(ks))
        return {"f": "read", "value": sorted(ctx.rng.sample(ks, n))}

    writers = max(1, concurrency // 2)
    return gen.reserve(writers, gen.Fn(write), gen.Fn(read))


# ---------------------------------------------------------------------------
# ts-order checker
# ---------------------------------------------------------------------------

def read_state(op: dict) -> dict:
    """Register key -> observed value for a read completion
    (multimonotonic.clj:244-248)."""
    regs = (op.get("value") or {}).get("registers") or {}
    return {k: r.get("value") for k, r in regs.items()
            if isinstance(r, dict)}


def op_observation(op: dict, k) -> dict:
    """What ``op`` observed for register ``k``
    (multimonotonic.clj:163-177)."""
    value = op.get("value") or {}
    reg = (value.get("registers") or {}).get(k) or {}
    return {"read-ts": value.get("ts"), "ts": reg.get("ts"),
            "value": reg.get("value"), "op-index": op.get("index")}


def nonmonotonic_states(ops: list) -> list:
    """Plays reads forward, tracking the highest observation per key;
    errors where a read's value undercuts the inferred lower bound
    (multimonotonic.clj:179-242)."""
    inferred: dict[Any, dict] = {}  # key -> highest observation
    errors = []
    for op in ops:
        state = read_state(op)
        bad = {}
        for k, v in state.items():
            prev = inferred.get(k)
            if prev is not None and v is not None \
                    and prev["value"] is not None and v < prev["value"]:
                bad[k] = [prev, op_observation(op, k)]
        if bad:
            errors.append({
                "inferred": {k: inferred[k]["value"] for k in state
                             if k in inferred},
                "observed": state,
                "op": op,
                "errors": bad,
            })
        for k, v in state.items():
            prev = inferred.get(k)
            if v is not None and (prev is None or prev["value"] is None
                                  or prev["value"] < v):
                inferred[k] = op_observation(op, k)
    return errors


class TsOrderChecker(Checker):
    """(multimonotonic.clj:255-272)"""

    def name(self):
        return "ts-order"

    def check(self, test, history, opts):
        reads = sorted(
            (op for op in history
             if op.get("type") == "ok" and op.get("f") == "read"
             and isinstance(op.get("value"), dict)
             and op["value"].get("ts") is not None),
            key=lambda op: ts_key(op["value"]["ts"]))
        errs = nonmonotonic_states(reads)
        return {"valid?": not errs, "errors": errs[:10],
                "error-count": len(errs)}


# ---------------------------------------------------------------------------
# read-skew checker (the reference's docstring algorithm, implemented)
# ---------------------------------------------------------------------------

def skew_edges(reads: list) -> tuple[int, list[tuple[int, int]]]:
    """(n_nodes, edges) over read snapshots: for each register, group
    reads by observed value and chain each value class to the next
    higher one *through a synthetic gate node* — reads(class i) → gate_i
    → reads(class i+1) — so reachability matches the per-register value
    order in O(reads) edges instead of a per-class cross product. Gates
    only point forward, so same-value reads never form a spurious
    cycle; any SCC holding >1 READ certifies incompatible orders — read
    skew (multimonotonic.clj:283-299)."""
    edges = []
    by_key: dict[Any, dict] = {}
    for i, op in enumerate(reads):
        for k, v in read_state(op).items():
            if v is not None:
                by_key.setdefault(k, {}).setdefault(v, []).append(i)
    n = len(reads)
    for classes in by_key.values():
        vals = sorted(classes)
        for lo, hi in zip(vals, vals[1:]):
            gate = n
            n += 1
            edges.extend((a, gate) for a in classes[lo])
            edges.extend((gate, b) for b in classes[hi])
    return n, edges


class ReadSkewChecker(Checker):
    """SCC detection over the union of per-register value orders; uses
    the shared Tarjan (ops/scc.py) the Elle path rides."""

    def name(self):
        return "read-skew"

    def check(self, test, history, opts):
        reads = [op for op in history
                 if op.get("type") == "ok" and op.get("f") == "read"
                 and isinstance(op.get("value"), dict)]
        n, edges = skew_edges(reads)
        if not edges:
            return {"valid?": True, "read-count": len(reads)}
        from jepsen_tpu.ops.scc import tarjan_scc
        sccs = []
        for c in tarjan_scc(n, edges):
            members = [i for i in c if i < len(reads)]  # drop gate nodes
            if len(members) > 1:
                sccs.append(members)
        return {
            "valid?": not sccs,
            "read-count": len(reads),
            "skew-component-count": len(sccs),
            "skewed-reads": [[reads[i] for i in c[:4]] for c in sccs[:3]],
        }


def checker() -> Checker:
    return compose({
        "ts-order": TsOrderChecker(),
        "read-skew": ReadSkewChecker(),
    })


def workload(test: dict | None = None, **_) -> dict:
    conc = int((test or {}).get("concurrency", 5))
    return {
        "fauna_multimonotonic": True,
        "generator": generator(conc),
        "checker": checker(),
    }
