"""List-append transactional workload: thin wrapper over the Elle-style
checker (reference: jepsen/src/jepsen/tests/cycle/append.clj — a thin
wrapper over elle.list-append/check + gen, append.clj:11-27).
"""
from __future__ import annotations

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker
from jepsen_tpu.elle import list_append


class AppendChecker(Checker):
    def __init__(self, accelerator: str = "auto",
                 consistency_models=("strict-serializable",)):
        self.accelerator = accelerator
        self.consistency_models = consistency_models

    def name(self):
        return "elle-list-append"

    def check(self, test, history, opts):
        from jepsen_tpu import history_ir
        result = list_append.check(
            history,
            accelerator=opts.get("accelerator", self.accelerator),
            consistency_models=opts.get("consistency_models",
                                        self.consistency_models),
            ir=history_ir.of(test, history))
        # invalid check: leave human-readable per-anomaly explanation
        # files under store/<test>/<ts>/elle/ (the reference passes
        # elle :directory per test, append.clj:17-22)
        from jepsen_tpu.elle import artifacts
        artifacts.write_for_test(test, result, opts, history=history)
        return result


def checker(**kw) -> Checker:
    return AppendChecker(**kw)


def generator(**kw):
    return gen.Fn(list_append.gen(**kw))


def workload(test: dict | None = None, accelerator: str = "auto",
             consistency_models=("strict-serializable",), **gen_kw) -> dict:
    return {
        "generator": generator(**gen_kw),
        "checker": checker(accelerator=accelerator,
                           consistency_models=consistency_models),
    }
