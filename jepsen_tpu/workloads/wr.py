"""Read-write-register transactional workload: thin wrapper over the
Elle-style rw-register checker (reference:
jepsen/src/jepsen/tests/cycle/wr.clj).
"""
from __future__ import annotations

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker
from jepsen_tpu.elle import rw_register


class WrChecker(Checker):
    def __init__(self, accelerator: str = "auto",
                 consistency_models=("strict-serializable",)):
        self.accelerator = accelerator
        self.consistency_models = consistency_models

    def name(self):
        return "elle-rw-register"

    def check(self, test, history, opts):
        from jepsen_tpu import history_ir
        result = rw_register.check(
            history,
            accelerator=opts.get("accelerator", self.accelerator),
            consistency_models=opts.get("consistency_models",
                                        self.consistency_models),
            ir=history_ir.of(test, history))
        # same artifact surface as the list-append checker: per-anomaly
        # explanation files in the run's elle/ directory when invalid
        from jepsen_tpu.elle import artifacts
        artifacts.write_for_test(test, result, opts, history=history)
        return result


def checker(**kw) -> Checker:
    return WrChecker(**kw)


def generator(**kw):
    return gen.Fn(rw_register.gen(**kw))


def workload(test: dict | None = None, accelerator: str = "auto",
             consistency_models=("strict-serializable",), **gen_kw) -> dict:
    return {
        "generator": generator(**gen_kw),
        "checker": checker(accelerator=accelerator,
                           consistency_models=consistency_models),
    }
