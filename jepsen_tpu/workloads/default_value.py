"""Default-value DDL workload (reference:
yugabyte/src/yugabyte/default_value.clj — stress non-transactional DDL
against concurrent DML: create/drop a table while inserting rows and
reading them back, looking for rows where a column with a default of 0
surfaces as null instead).

Op shapes:
- ``{"f": "create-table"}`` / ``{"f": "drop-table"}``
- ``{"f": "insert"}`` — insert a fresh row (the column under test takes
  its default)
- ``{"f": "read", "value": [row...]}`` — full-table read; each row is a
  dict of column values
"""
from __future__ import annotations

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker


def r(test, ctx):
    return {"f": "read", "value": None}


def i(test, ctx):
    return {"f": "insert", "value": None}


def create_table(test, ctx):
    return {"f": "create-table", "value": None}


def drop_table(test, ctx):
    return {"f": "drop-table", "value": None}


def generator():
    """One guaranteed create-table, then DDL churn interleaved with 50
    read/insert pairs per mix slot (default_value.clj:19-26;
    add/drop-column held back there too because the DB under test lacked
    column defaults — create/drop table stands in). The deterministic
    leading create means even short runs exercise DML against a live
    table instead of failing everything until the mix happens to create."""
    fns = [gen.Fn(create_table), gen.Fn(drop_table)]
    fns += [gen.Fn(r), gen.Fn(i)] * 25
    churn = gen.stagger(0.01, gen.mix(fns))
    return gen.then(churn, gen.once(gen.Fn(create_table)))


def bad_row(row) -> bool:
    """A row with a null column value (default_value.clj:28-33)."""
    return isinstance(row, dict) and any(v is None for v in row.values())


class DefaultValueChecker(Checker):
    """Flags ok reads containing a null-column row
    (default_value.clj:45-60)."""

    def check(self, test, history, opts):
        reads = [op for op in history
                 if op.get("type") == "ok" and op.get("f") == "read"]
        bad = []
        for op in reads:
            rows = [row for row in (op.get("value") or []) if bad_row(row)]
            if rows:
                bad.append({"op": op, "bad-rows": rows})
        return {
            "valid?": not bad,
            "read-count": len(reads),
            "bad-read-count": len(bad),
            "bad-reads": bad[:10],
        }


def workload(test: dict | None = None, **_) -> dict:
    return {
        "ddl-table": True,  # fake-mode client dispatch marker
        "generator": generator(),
        "checker": DefaultValueChecker(),
    }
