"""Monotonic-key cycle workload (reference:
tidb/src/tidb/monotonic.clj:1-110 — a pool of increment-only registers;
``inc`` bumps one key in a read-write transaction, ``read`` snapshots
the whole pool. The orders implied by each key's values must be
mutually consistent AND consistent with realtime: no transaction may
observe key x advance but key y retreat, and a transaction that
finished before another began can never depend on it).

Op shapes:
- ``{"f": "inc", "value": k}`` → ok value ``{k: v'}`` — the written
  value.
- ``{"f": "read", "value": {k: None, ...}}`` → ok value ``{k: v}`` with
  ``-1`` for keys never written (monotonic.clj:19-27).

The checker is the generic cycle kit over the monotonic-key dependency
graph combined with realtime precedence (the reference's
``cycle/combine monotonic-key-graph realtime-graph``): for each key,
observations are ordered by observed value, each value class linked to
the next; a cycle in the union (including through realtime edges) is an
anomaly. Value-class links are all-pairs per adjacent class — histories
here are bounded by the generator, so the quadratic corner stays small.
"""
from __future__ import annotations

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker
from jepsen_tpu.workloads import cycle as cycle_kit

DEFAULT_KEY_COUNT = 8  # monotonic.clj:103


def generator(key_count: int = DEFAULT_KEY_COUNT):
    """Uniform mix of single-key incs and whole-pool reads
    (monotonic.clj:90-99)."""
    def inc(test, ctx):
        return {"f": "inc", "value": ctx.rng.randrange(key_count)}

    def read(test, ctx):
        return {"f": "read", "value": {k: None for k in range(key_count)}}

    return gen.mix([gen.Fn(inc), gen.Fn(read)])


def observations(op: dict) -> dict:
    """Key -> observed value for an ok completion; -1 (never written)
    observations are skipped — they order nothing."""
    v = op.get("value")
    if not isinstance(v, dict):
        return {}
    return {k: x for k, x in v.items()
            if isinstance(x, int) and x >= 0}


def monotonic_key_graph(history: list):
    """(Graph, txns): per-key value order as WW edges between adjacent
    value classes (elle.core's monotonic-key-graph shape)."""
    from jepsen_tpu.elle import WW, Graph

    txns = [op for op in history
            if op.get("type") == "ok" and isinstance(op.get("value"), dict)]
    by_key: dict = {}
    for i, op in enumerate(txns):
        for k, val in observations(op).items():
            by_key.setdefault(k, {}).setdefault(val, []).append(i)
    g = Graph(len(txns))
    for classes in by_key.values():
        vals = sorted(classes)
        for lo, hi in zip(vals, vals[1:]):
            for a in classes[lo]:
                for b in classes[hi]:
                    g.add(a, b, WW)
    return g, txns


def analyzer(history: list):
    """monotonic-key graph + realtime precedence (monotonic.clj:105-108
    ``cycle/combine monotonic-key-graph realtime-graph``)."""
    from jepsen_tpu import elle

    g, txns = monotonic_key_graph(history)
    elle.add_timing_edges(g, history, txns, process=False)
    return g, txns


def checker() -> Checker:
    return cycle_kit.checker(analyzer,
                             consistency_models=("strict-serializable",))


def workload(test: dict | None = None,
             key_count: int = DEFAULT_KEY_COUNT, **_) -> dict:
    return {
        "monotonic-key": True,
        "generator": generator(key_count),
        "checker": checker(),
    }
