"""Crate dirty-read workload (reference:
crate/src/jepsen/crate/dirty_read.clj — hunts reads of rows from
transactions that never committed).

Distinct from the elasticsearch probe (workloads/dirty_read.py): here
the generator itself aims every read at the write currently in flight
on the reader's *own* node (rw-gen, dirty_read.clj:197-226) — probing
whether an uncommitted insert is visible in the instant before a crash
— and node disagreement in the final strong reads is a validity
condition, not just a statistic (dirty_read.clj:178-180).

Op shapes:
- ``{"f": "write", "value": id}`` — insert a unique integer row
- ``{"f": "read", "value": id}`` — point-read that id; found → ok,
  absent → fail
- ``{"f": "refresh"}`` — per-thread table refresh before the final
  reads
- ``{"f": "strong-read", "value": [ids...]}`` — one full scan per
  thread in the final phase

The first ``writers`` client threads write; the rest read. The write
counter and the per-node in-flight table are carried *functionally* in
the generator state, so polls discarded by composing generators
(any_gen races the nemesis) never burn a value — the reference's
mutable atoms (dirty_read.clj:202-205) rely on op emission being
dispatch, which does not hold on this framework's pure protocol.

Sizing: like the reference (whose in-flight vector also starts all
zero), a node only gets live in-flight targets once some writer thread
lands on it — readers on writer-less nodes keep probing id 0. Run with
concurrency >= 3x the node count (the reference's typical ``-c 3n``)
so ``writers = concurrency // 3`` covers every node.
"""
from __future__ import annotations

from dataclasses import dataclass, replace

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker


@dataclass(frozen=True)
class RWGen(gen.Generator):
    """While writer threads insert fresh ids (recording each as their
    node's in-flight write), reader threads point-read the id most
    recently in flight on their own node (dirty_read.clj:197-226)."""

    writers: int = 1
    counter: int = 0
    in_flight: tuple = ()

    def op(self, test, ctx):
        p = ctx.some_free_process()
        thread = None if p is None else ctx.thread_of(p)
        # clients-wrapped in production; PENDING on the nemesis
        # sentinel for bare-context polls (a client op bound to the
        # nemesis worker would misdispatch)
        if p is None or not isinstance(thread, int):
            return (gen.PENDING, self)
        nodes = test.get("nodes") or ["n1"]
        in_flight = self.in_flight or (0,) * len(nodes)
        # the node a worker talks to is bound by THREAD id (the
        # interpreter's nodes[thread % n] binding survives process
        # renumbering after crashes) — keying on process id would drift
        # off the worker's real node after the first crashed op
        node_i = thread % len(nodes)
        if thread < self.writers:
            v = self.counter
            nxt = replace(
                self, counter=v + 1,
                in_flight=tuple(v if i == node_i else x
                                for i, x in enumerate(in_flight)))
            return ({"type": "invoke", "f": "write", "value": v,
                     "process": p, "time": ctx.time}, nxt)
        return ({"type": "invoke", "f": "read",
                 "value": in_flight[node_i],
                 "process": p, "time": ctx.time},
                replace(self, in_flight=in_flight))

    def update(self, test, ctx, event):
        return self


def generator(writers: int):
    return gen.stagger(0.1, RWGen(writers=writers))


def final_generator(quiesce_s: float = 10.0):
    """Per-thread refresh, quiescence, then one strong read per thread
    (dirty_read.clj:259-264). ``phases`` barriers each step so no
    strong read can start while a refresh is still in flight."""
    return gen.phases(
        gen.each_thread(gen.once(gen.Fn(
            lambda test, ctx: {"f": "refresh", "value": None}))),
        gen.sleep(quiesce_s),
        gen.each_thread(gen.once(gen.Fn(
            lambda test, ctx: {"f": "strong-read", "value": None}))),
    )


class CrateDirtyReadChecker(Checker):
    """dirty = ok point-reads no strong read corroborates; lost = acked
    writes absent from every strong read; valid additionally requires
    every node's strong read to agree (dirty_read.clj:143-193)."""

    def check(self, test, history, opts):
        writes, reads, strong = set(), set(), []
        for op in history:
            if op.get("type") != "ok":
                continue
            f = op.get("f")
            if f == "write":
                writes.add(op.get("value"))
            elif f == "read":
                reads.add(op.get("value"))
            elif f == "strong-read":
                strong.append(set(op.get("value") or ()))
        if not strong:
            return {"valid?": "unknown", "error": "no strong reads"}
        on_all = set.intersection(*strong)
        on_some = set.union(*strong)
        not_on_all = on_some - on_all
        unchecked = on_some - reads
        dirty = reads - on_some
        lost = writes - on_some
        some_lost = writes - on_all
        nodes_agree = on_all == on_some
        result = {
            "valid?": bool(nodes_agree and not dirty and not lost),
            "nodes-agree?": nodes_agree,
            "read-count": len(reads),
            "on-all-count": len(on_all),
            "on-some-count": len(on_some),
            "unchecked-count": len(unchecked),
            "not-on-all-count": len(not_on_all),
            "not-on-all": sorted(not_on_all)[:10],
            "dirty-count": len(dirty), "dirty": sorted(dirty)[:10],
            "lost-count": len(lost), "lost": sorted(lost)[:10],
            "some-lost-count": len(some_lost),
            "some-lost": sorted(some_lost)[:10],
        }
        # the reference asserts one strong read per worker
        # (dirty_read.clj:176); degrade to unknown instead of crashing
        if len(strong) != int(test.get("concurrency", len(strong))):
            result["valid?"] = "unknown"
            result["error"] = ["strong-read-count", len(strong),
                               "concurrency", test.get("concurrency")]
        return result


def workload(test: dict | None = None, quiesce_s: float = 10.0,
             **_) -> dict:
    test = test or {}
    concurrency = int(test.get("concurrency", 5))
    writers = max(1, concurrency // 3)
    return {
        "dirty-read": True,  # client dispatch marker
        # reads AIM at in-flight writes and legitimately fail en masse;
        # the reference composes only {dirty-read, perf} here
        # (dirty_read.clj:245-247). Exempt ONLY reads from the stats
        # gate — writes failing wholesale must still convict
        "stats_ungated_fs": ("read",),
        "generator": generator(writers),
        "final_generator": final_generator(quiesce_s),
        "checker": CrateDirtyReadChecker(),
    }
