"""Bank-transfer-via-two-phase-commit workload (reference:
mongodb-smartos/src/jepsen/mongodb_smartos/transfer.clj — models the
MongoDB "perform two-phase commits" tutorial: each transfer is a
multi-step txn-document dance, full reads snapshot every account, and
``partial-read`` reads only accounts with no transaction in flight).

Op shapes (transfer.clj:148-173, 223-241):
- ``{"f": "read", "value": None}`` → ok ``{acct: balance, ...}`` over
  ALL accounts (no synchronization — may catch mid-transfer states).
- ``{"f": "partial-read", "value": None}`` → ok ``{acct: balance}``
  over accounts with empty pending-txn lists only.
- ``{"f": "transfer", "value": {"from": a, "to": b, "amount": m}}``.

The checker is the reference's knossos model check (transfer.clj:190-222
``Accounts``): the history must be linearizable against an account-map
model where a full read sees exactly the current balances, a partial
read's entries each match the model, and transfers move ``amount``
between accounts. Runs on the shared linearizable checker's WGL oracle.
"""
from __future__ import annotations

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker
from jepsen_tpu.models import Inconsistent, Model, inconsistent
from jepsen_tpu.utils import int_keyed

DEFAULT_ACCOUNTS = 2
DEFAULT_BALANCE = 10
MAX_TRANSFER = 5


class Accounts(Model):
    """Account-map model (transfer.clj:190-212). Balances may go
    negative — the reference model places no floor; the invariant under
    test is read consistency, not solvency."""

    def __init__(self, balances: dict):
        self.balances = dict(balances)

    def step(self, op):
        f, v = op.get("f"), op.get("value")
        if f == "read":
            if v == self.balances:
                return self
            return inconsistent(f"can't read {v} from {self.balances}")
        if f == "partial-read":
            for acct, bal in (v or {}).items():
                if self.balances.get(acct) != bal:
                    return inconsistent(
                        f"{v} isn't consistent with {self.balances}")
            return self
        if f == "transfer":
            frm, to, amount = v["from"], v["to"], v["amount"]
            nxt = dict(self.balances)
            nxt[frm] = nxt.get(frm, 0) - amount
            nxt[to] = nxt.get(to, 0) + amount
            return Accounts(nxt)
        return inconsistent(f"unknown op {f}")

    def __eq__(self, other):
        return isinstance(other, Accounts) and \
            self.balances == other.balances

    def __hash__(self):
        return hash(tuple(sorted(self.balances.items())))

    def __repr__(self):
        return f"Accounts({self.balances})"


def _norm_op(op: dict) -> dict:
    """JSON round-trips (store.jsonl → analyze re-check) stringify dict
    keys; integer account ids come back as digit strings and would
    falsely convict every stored read against the int-keyed model."""
    v = op.get("value")
    if op.get("f") in ("read", "partial-read") and isinstance(v, dict):
        return {**op, "value": int_keyed(v)}
    return op


class TransferChecker(Checker):
    """Linearizability against the Accounts model via the shared WGL
    oracle (transfer.clj's knossos check)."""

    def __init__(self, accounts: list, starting_balance: int):
        self.init = {a: starting_balance for a in accounts}

    def name(self):
        return "transfer"

    def check(self, test, history, opts):
        from jepsen_tpu.checker.linear_cpu import wgl
        client_ops = [_norm_op(op) for op in history
                      if isinstance(op.get("process"), int)]
        res = wgl(client_ops, Accounts(self.init))
        out = {"valid?": res.valid if res.valid == "unknown"
               else bool(res.valid),
               "op-count": len(client_ops),
               "algorithm": res.algorithm}
        if res.valid is False:
            out["failed-op-index"] = res.failed_op_index
            out["final-configs"] = res.final_configs
        return out


def generator(accounts: list, max_transfer: int = MAX_TRANSFER):
    def transfer(test, ctx):
        frm = ctx.rng.choice(accounts)
        to = ctx.rng.choice([a for a in accounts if a != frm] or [frm])
        return {"f": "transfer",
                "value": {"from": frm, "to": to,
                          "amount": ctx.rng.randint(1, max_transfer)}}

    return gen.mix([
        gen.Fn(lambda test, ctx: {"f": "read", "value": None}),
        gen.Fn(lambda test, ctx: {"f": "partial-read", "value": None}),
        gen.Fn(transfer),
    ])


def workload(test: dict | None = None,
             n_accounts: int = DEFAULT_ACCOUNTS,
             starting_balance: int = DEFAULT_BALANCE, **_) -> dict:
    accounts = list(range(n_accounts))
    return {
        "transfer": True,
        "transfer_accounts": accounts,
        "starting_balance": starting_balance,
        "generator": generator(accounts),
        "checker": TransferChecker(accounts, starting_balance),
    }
