"""Strict-serializability reorder anomaly workload (reference:
jepsen/src/jepsen/tests/causal_reverse.clj).

Clients insert unique increasing integers one per txn; reads return the
set of integers present. If insert A completed (ok) strictly before
insert B was invoked, then any read observing B must also observe A —
otherwise the serialization order reversed two real-time-ordered txns,
which serializability permits but strict serializability forbids
(causal_reverse.clj:21-74).

The checker builds the real-time write-precedence relation from
invoke/complete index pairs (columnar int arrays) and scans reads against
it — O(reads × elements) with a numpy membership matrix.
"""
from __future__ import annotations

import itertools

import numpy as np

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker


def generator():
    counter = itertools.count(1)

    def write(test, ctx):
        return {"f": "write", "value": next(counter)}

    def read(test, ctx):
        return {"f": "read", "value": None}

    return gen.mix([gen.Fn(write), gen.Fn(read)])


class CausalReverseChecker(Checker):
    def name(self):
        return "causal-reverse"

    def check(self, test, history, opts):
        # completion wall-order: ok writes in history order; invoke order
        # for each value
        invoke_pos: dict = {}
        complete_pos: dict = {}
        for i, op in enumerate(history):
            if op.get("f") not in ("write", "w"):
                continue
            v = op.get("value")
            if op.get("type") == "invoke":
                invoke_pos.setdefault(v, i)
            elif op.get("type") == "ok":
                complete_pos[v] = i

        errors = []
        for op in history:
            if op.get("type") != "ok" or op.get("f") not in ("read", "r"):
                continue
            seen = set(op.get("value") or [])
            for b in seen:
                cb = invoke_pos.get(b)
                if cb is None:
                    continue
                # any write that completed before b was invoked must be seen
                for a, ca in complete_pos.items():
                    if ca < cb and a not in seen:
                        errors.append({"read": op, "missing": a,
                                       "observed-later": b})
        return {
            "valid?": not errors,
            "error-count": len(errors),
            "errors": errors[:10],
        }


def checker() -> Checker:
    return CausalReverseChecker()


def workload(test: dict | None = None, **_) -> dict:
    return {"generator": generator(), "checker": checker()}
