"""Sequential-consistency workload (reference:
cockroachdb/src/jepsen/cockroach/sequential.clj — a writer inserts a
key's subkeys in client order across distinct transactions; readers
read them in *reverse* order, so observing a later subkey obliges every
earlier subkey to be visible: a nil after a non-nil in the reversed
read is a sequential-consistency violation).

Op shapes:
- ``{"f": "write", "value": k}`` — insert subkeys ``k_0 .. k_{m-1}``
  in order, one transaction each.
- ``{"f": "read", "value": k → [k, [newest .. oldest]]}`` — read the
  subkeys reversed; each element is the subkey string or None.
"""
from __future__ import annotations

import threading

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker

DEFAULT_KEY_COUNT = 5


def subkeys(key_count: int, k) -> list[str]:
    """``k_0 .. k_{m-1}`` (sequential.clj:50-52)."""
    return [f"{k}_{i}" for i in range(key_count)]


def generator(writer_count: int = 2, buffer_factor: int = 2):
    """n reserved writer threads emitting sequential keys; everyone else
    reads a recently-written key (sequential.clj:105-133)."""
    lock = threading.Lock()
    last_written: list = []
    counter = [0]

    def write(test, ctx):
        with lock:
            k = counter[0]
            counter[0] += 1
            last_written.append(k)
            if len(last_written) > buffer_factor * writer_count:
                last_written.pop(0)
        return {"f": "write", "value": k}

    def read(test, ctx):
        with lock:
            # before any write lands, read key 0 — its subkeys don't
            # exist yet, so the read is an (all-nil) no-op for the checker
            k = ctx.rng.choice(last_written) if last_written else 0
        return {"f": "read", "value": k}

    return gen.reserve(writer_count, gen.Fn(write), gen.Fn(read))


def trailing_nil(coll) -> bool:
    """A nil after a non-nil element (sequential.clj:135-138) — the
    reversed read saw a later subkey but missed an earlier one."""
    seen_non_nil = False
    for x in coll:
        if x is not None:
            seen_non_nil = True
        elif seen_non_nil:
            return True
    return False


class SequentialChecker(Checker):
    def name(self):
        return "sequential"

    def check(self, test, history, opts):
        bad_reads = []
        read_count = 0
        for op in history:
            if op.get("type") != "ok" or op.get("f") != "read":
                continue
            read_count += 1
            v = op.get("value")
            if not isinstance(v, (list, tuple)) or len(v) != 2:
                continue
            _k, elements = v
            if trailing_nil(elements or []):
                bad_reads.append(op)
        return {
            "valid?": not bad_reads,
            "read-count": read_count,
            "bad-read-count": len(bad_reads),
            "bad-reads": bad_reads[:10],
        }


def checker() -> Checker:
    return SequentialChecker()


def workload(test: dict | None = None,
             key_count: int = DEFAULT_KEY_COUNT, **_) -> dict:
    return {
        "key-count": key_count,
        "generator": generator(),
        "checker": checker(),
    }
