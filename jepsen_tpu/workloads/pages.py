"""Pagination-isolation workload (reference:
faunadb/src/jepsen/faunadb/pages.clj — groups of elements insert
together in one transaction; concurrent reads page through the
collection cursor by cursor, and every read must be expressible as a
union of COMPLETE groups. A page boundary slicing a group in half is
the pagination-isolation anomaly this hunts).

Op shapes (independent-lifted [k, v] values):
- ``{"f": "add", "value": [k, [elements...]]}`` — one txn inserts the
  whole group
- ``{"f": "read", "value": [k, [elements...]]}`` — the key's elements,
  gathered across pages
"""
from __future__ import annotations

import itertools
import threading

from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker import Checker


def generator(n_groups: int = 5, per_key_limit: int = 40,
              group_min: int = 2, group_max: int = 4):
    lock = threading.Lock()
    counter = itertools.count()

    def add(test, ctx):
        n = ctx.rng.randint(group_min, group_max)
        with lock:
            group = [next(counter) for _ in range(n)]
        return {"f": "add", "value": group}

    def read(test, ctx):
        return {"f": "read", "value": None}

    def key_gen(k):
        return gen.limit(per_key_limit,
                         gen.mix([gen.Fn(add), gen.Fn(read)]))

    return independent.concurrent_generator(n_groups, itertools.count(),
                                            key_gen)


def read_errors(group_of: dict, read: set) -> list:
    """Errors for any read not expressible as a union of complete groups
    (pages.clj:68-91 read-errs): repeatedly pick an element, check its
    whole group is present, and cross the group off."""
    errs = []
    read = set(read)
    while read:
        e = next(iter(read))
        group = group_of.get(e)
        if group is None:
            errs.append({"unexpected": e})
            read.discard(e)
            continue
        missing = group - read
        if missing:
            errs.append({"expected": sorted(group),
                         "found": sorted(read & group)})
        read -= group
    return errs


class PagesChecker(Checker):
    """Index each element to its add-group (invoked adds minus definite
    fails — an indeterminate add may appear); every ok read must
    decompose into complete groups, without duplicates
    (pages.clj:93-145)."""

    def check(self, test, history, opts):
        invoked: dict = {}
        failed: set = set()
        for op in history:
            if op.get("f") != "add":
                continue
            group = tuple(op.get("value") or ())
            if op.get("type") == "invoke":
                invoked[group] = set(group)
            elif op.get("type") == "fail":
                failed.add(group)
        group_of: dict = {}
        for group, els in invoked.items():
            if group in failed:
                continue
            for e in els:
                group_of[e] = els
        errs = []
        reads = 0
        for op in history:
            if op.get("type") != "ok" or op.get("f") != "read":
                continue
            reads += 1
            vals = list(op.get("value") or ())
            if len(vals) != len(set(vals)):
                errs.append({"op-errors": ["duplicate-items"],
                             "read": sorted(vals)[:20]})
                continue
            e = read_errors(group_of, set(vals))
            if e:
                errs.append({"op-errors": e[:5]})
        return {"valid?": not errs, "ok-read-count": reads,
                "error-count": len(errs), "errors": errs[:10]}


def workload(test: dict | None = None, **_) -> dict:
    test = test or {}
    n = len(test.get("nodes") or []) or 5
    return {
        "pages": True,  # client dispatch marker
        "generator": generator(n_groups=n),
        "checker": independent.checker(PagesChecker()),
    }
