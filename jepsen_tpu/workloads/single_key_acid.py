"""Single-key ACID workload (reference:
yugabyte/src/yugabyte/single_key_acid.clj — concurrent reads, writes and
UPDATE-IF (cas) against independent single rows, verified linearizable).

Per key group of 2N workers, the first N write/cas and the last N read
(gen.reserve), mirroring the reference's worker split. The model is a
CAS register initialized to 0 (single_key_acid.clj:40
model/cas-register 0), checked per key on the batched device kernel.
"""
from __future__ import annotations

import itertools

from jepsen_tpu import checker as chk
from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.models import CASRegister


def r(test, ctx):
    return {"f": "read", "value": None}


def w(test, ctx):
    return {"f": "write", "value": ctx.rng.randint(0, 4)}


def cas(test, ctx):
    return {"f": "cas",
            "value": [ctx.rng.randint(0, 4), ctx.rng.randint(0, 4)]}


def workload(test: dict | None = None, per_key_limit: int = 40,
             process_limit: int | None = 20, accelerator: str = "auto",
             **_) -> dict:
    test = test or {}
    n = len(test.get("nodes") or []) or 5
    group = 2 * n  # single_key_acid.clj:33 concurrent-generator (* 2 n)

    def key_gen(k):
        # first n workers write/cas (1:2 mix), the rest read
        g = gen.reserve(n, gen.mix([gen.Fn(w), gen.Fn(cas), gen.Fn(cas)]),
                        gen.Fn(r))
        g = gen.limit(per_key_limit, g)
        if process_limit is not None:
            g = gen.process_limit(process_limit, g)
        return g

    return {
        "generator": independent.concurrent_generator(
            group, itertools.count(), key_gen),
        "checker": independent.checker(chk.compose({
            "linear": linearizable(model=CASRegister(0),
                                   accelerator=accelerator),
            "timeline": chk.timeline_html(),
        })),
    }
