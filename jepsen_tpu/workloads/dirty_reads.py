"""Dirty-reads workload (reference: galera/src/jepsen/galera/dirty_reads.clj
and percona/src/jepsen/percona/dirty_reads.clj).

Writers compete to set *every* row of an n-row table to one unique
value inside a single transaction; readers concurrently read all rows.
A reader observing the value of a **failed** (aborted) write transaction
is a dirty read — the anomaly this workload exists to catch
(dirty_reads.clj:73-96). Reads whose rows are not all equal are reported
as ``inconsistent-reads`` (fractured snapshots) but, as in the
reference, only dirty reads invalidate the run.

Op shapes: ``{"f": "write", "value": x}`` (set all rows to x) and
``{"f": "read", "value": None → [x0 ... xn-1]}``.
"""
from __future__ import annotations

import itertools

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker

DEFAULT_ROWS = 4


def reads():
    def read(test, ctx):
        return {"f": "read", "value": None}

    return gen.Fn(read)


def writes():
    """Unique, monotonically-increasing write values (dirty_reads.clj:100-105)
    so a failed write's value can be attributed unambiguously."""
    counter = itertools.count()

    def write(test, ctx):
        return {"f": "write", "value": next(counter)}

    return gen.Fn(write)


class DirtyReadsChecker(Checker):
    """Failed writes' values must never appear in an ok read
    (dirty_reads.clj:73-96)."""

    def name(self):
        return "dirty-reads"

    def check(self, test, history, opts):
        failed_writes = {op.get("value") for op in history
                         if op.get("type") == "fail"
                         and op.get("f") == "write"}
        ok_reads = [op.get("value") or [] for op in history
                    if op.get("type") == "ok" and op.get("f") == "read"]
        inconsistent = [r for r in ok_reads if len(set(r)) > 1]
        dirty = [r for r in ok_reads
                 if any(x in failed_writes for x in r)]
        return {
            "valid?": not dirty,
            "read-count": len(ok_reads),
            "failed-write-count": len(failed_writes),
            "inconsistent-reads": inconsistent[:10],
            "inconsistent-count": len(inconsistent),
            "dirty-reads": dirty[:10],
            "dirty-count": len(dirty),
        }


def checker() -> Checker:
    return DirtyReadsChecker()


def workload(test: dict | None = None, rows: int = DEFAULT_ROWS,
             **_) -> dict:
    return {
        "dirty-rows": rows,
        "generator": gen.mix([reads(), writes()]),
        "checker": checker(),
    }
