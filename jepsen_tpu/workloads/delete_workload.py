"""Indexed create/delete visibility workload (reference:
dgraph/src/jepsen/dgraph/delete.clj:1-104 — upsert an indexed record,
delete it, and read through the index; a stale index shows ghost
records or malformed rows).

Per-key op shapes (independent-lifted, delete.clj:18-20):
- ``{"f": "upsert", "value": [k, None]}`` — create the record for ``k``
  unless present (ok, or fail ``present``).
- ``{"f": "delete", "value": [k, None]}`` — delete ``k``'s record if
  present (ok, or fail ``not-found``).
- ``{"f": "read", "value": [k, records]}`` — index lookup; each record
  is a ``{"uid": ..., "key": k}`` dict.

The checker (delete.clj:66-87): every ok read must find either nothing
or exactly one record carrying exactly a uid and the right key —
anything else (two records, a record missing fields, a wrong key) is a
stale- or corrupt-index anomaly.
"""
from __future__ import annotations

from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker import Checker

KEY_CONCURRENCY_FACTOR = 2  # delete.clj:92 (2 * node count)
OPS_PER_KEY = 1000          # delete.clj:95


def per_key_gen(k):
    """Mix of read/upsert/delete on one key (delete.clj:90-96)."""
    mix = gen.mix([
        gen.Fn(lambda test, ctx: {"f": "read", "value": None}),
        gen.Fn(lambda test, ctx: {"f": "upsert", "value": None}),
        gen.Fn(lambda test, ctx: {"f": "delete", "value": None}),
    ])
    return gen.limit(OPS_PER_KEY, mix)


def bad_read(k, op: dict):
    """Why an ok read's value is anomalous, or None (delete.clj:70-85)."""
    records = op.get("value")
    records = records[1] if independent.is_tuple_value(records) else records
    records = records or []
    if len(records) == 0:
        return None
    if len(records) > 1:
        return "multiple-records"
    rec = records[0]
    if not isinstance(rec, dict) or set(rec.keys()) != {"uid", "key"}:
        return "malformed-record"
    if rec.get("key") != k:
        return "wrong-key"
    return None


class DeleteChecker(Checker):
    """(delete.clj:66-87); runs under the independent lift, so each
    check sees one key's subhistory."""

    def name(self):
        return "deletes"

    def check(self, test, history, opts):
        k = opts.get("history-key")
        bad = []
        for op in history:
            if op.get("type") == "ok" and op.get("f") == "read":
                why = bad_read(k, op)
                if why:
                    bad.append({"why": why, "op": op})
        return {"valid?": not bad, "bad-reads": bad[:10],
                "bad-read-count": len(bad)}


def checker() -> Checker:
    return independent.checker(DeleteChecker())


def workload(test: dict | None = None, **_) -> dict:
    t = test or {}
    # the reference sizes groups at 2x node count (delete.clj:92); a
    # group can never exceed the actual client-thread count or the
    # concurrent generator forms zero groups and emits nothing
    n = max(1, min(KEY_CONCURRENCY_FACTOR * len(t.get("nodes") or [1]),
                   int(t.get("concurrency", 5))))
    return {
        "delete-workload": True,
        "generator": independent.concurrent_generator(
            n, _naturals(), per_key_gen),
        "checker": checker(),
    }


def _naturals():
    i = 0
    while True:
        yield i
        i += 1
