"""Queue enqueue/dequeue workload (reference: the rabbitmq suite's queue
test — rabbitmq/src/jepsen/system/rabbitmq.clj — checked with
jepsen/src/jepsen/checker.clj:628-687 ``total-queue`` after
``expand-queue-drain-ops`` :594-626).

Clients enqueue unique integers and dequeue concurrently; the final
phase drains every node's queue so the total-queue multiset algebra
(what goes in must come out) is decidable. Dequeues of an empty queue
must complete as ``fail`` with ``value None``.
"""
from __future__ import annotations

import itertools

from jepsen_tpu import checker as chk
from jepsen_tpu import generator as gen


def enqueues():
    counter = itertools.count()

    def enqueue(test, ctx):
        return {"f": "enqueue", "value": next(counter)}

    return gen.Fn(enqueue)


def dequeues():
    def dequeue(test, ctx):
        return {"f": "dequeue", "value": None}

    return gen.Fn(dequeue)


def drains():
    """One drain per thread; clients loop dequeue-until-empty and report
    the drained elements as the op's value."""
    def drain(test, ctx):
        return {"f": "drain", "value": None}

    return gen.each_thread(gen.once(gen.Fn(drain)))


def workload(test: dict | None = None, **_) -> dict:
    return {
        "generator": gen.mix([enqueues(), dequeues()]),
        "final_generator": drains(),
        "checker": chk.total_queue(),
    }
