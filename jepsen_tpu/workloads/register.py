"""Linearizable-register workload (reference:
jepsen/src/jepsen/tests/linearizable_register.clj).

Per-key r/w/cas mix over an unbounded rotating key space via
jepsen_tpu.independent, checked per key with the linearizability checker —
the vmapped-per-key TPU path (BASELINE config 3). History-length
discipline mirrors the reference: per-key op limit (default 20) and
process limit keep each sub-history tractable for exact search, while the
batched device kernel handles far longer keys when selected.
"""
from __future__ import annotations

import itertools

from jepsen_tpu import checker as chk
from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.models import CASRegister


def r(test, ctx):
    return {"f": "read", "value": None}


def w(test, ctx):
    return {"f": "write", "value": ctx.rng.randint(0, 4)}


def cas(test, ctx):
    return {"f": "cas", "value": [ctx.rng.randint(0, 4), ctx.rng.randint(0, 4)]}


def workload(test: dict | None = None, per_key_limit: int = 20,
             process_limit: int | None = 20, accelerator: str = "auto",
             ops: tuple = ("r", "w", "cas"), **_) -> dict:
    """``ops`` selects the op mix — clients whose transport can't
    express CAS (hazelcast's REST map API) run the r/w subset against
    the same linearizable-register checker."""
    test = test or {}
    n = test.get("concurrency", 5)
    group = max(2, min(10, n))
    fns = {"r": gen.Fn(r), "w": gen.Fn(w), "cas": gen.Fn(cas)}

    def key_gen(k):
        g = gen.mix([fns[o] for o in ops])
        g = gen.limit(per_key_limit, g)
        if process_limit is not None:
            g = gen.process_limit(process_limit, g)
        return g

    return {
        "generator": independent.concurrent_generator(
            group, itertools.count(), key_gen),
        # per-key linear + timeline composition, exactly the reference's
        # (independent/checker (checker/compose {:linear ... :timeline
        # (timeline/html)})) (linearizable_register.clj:30-41)
        "checker": independent.checker(chk.compose({
            "linear": linearizable(model=CASRegister(),
                                   accelerator=accelerator),
            "timeline": chk.timeline_html(),
        })),
    }
