"""Adya G2 (write-skew / anti-dependency cycle) workload (reference:
jepsen/src/jepsen/tests/adya.clj).

Each txn targets a key pair; it reads both cells of the pair and, iff both
are empty, inserts its unique id into ONE of them. Under serializability
at most one insert per pair can succeed (the second txn must observe the
first's insert); two successful inserts into the same pair demonstrate a
predicate anti-dependency cycle — G2 (adya.clj:12-87).
"""
from __future__ import annotations

import itertools
from collections import defaultdict

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker


def generator(n_pairs_hint: int = 0):
    """Emits {"f": "insert", "value": [pair-id, unique-id, which-cell]};
    two txns race per pair. Clients must read both cells and only insert
    when both are empty, reporting :fail otherwise."""
    pair_counter = itertools.count()
    uid = itertools.count(1)
    state: dict = {"open": {}}  # pair -> remaining cell

    def one(test, ctx):
        open_pairs = state["open"]
        if open_pairs and ctx.rng.random() < 0.5:
            pair, cell = open_pairs.popitem()
        else:
            pair = next(pair_counter)
            cell = "a" if ctx.rng.random() < 0.5 else "b"
            open_pairs[pair] = "b" if cell == "a" else "a"
        return {"f": "insert", "value": [pair, next(uid), cell]}

    return gen.Fn(one)


class G2Checker(Checker):
    """Two ok inserts into one pair = G2 (adya.clj:61-87)."""

    def name(self):
        return "adya-g2"

    def check(self, test, history, opts):
        by_pair: dict = defaultdict(list)
        for op in history:
            if op.get("type") == "ok" and op.get("f") == "insert":
                pair, _uid, _cell = op.get("value")
                by_pair[pair].append(op)
        skews = [{"pair": p, "inserts": ops}
                 for p, ops in by_pair.items() if len(ops) > 1]
        return {
            "valid?": not skews,
            "pair-count": len(by_pair),
            "g2-count": len(skews),
            "anomalies": skews[:10],
        }


def checker() -> Checker:
    return G2Checker()


def workload(test: dict | None = None, **_) -> dict:
    return {"generator": generator(), "checker": checker()}
