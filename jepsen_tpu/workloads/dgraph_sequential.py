"""Per-process monotonic register workload (reference:
dgraph/src/jepsen/dgraph/sequential.clj — snapshot isolation permits
arbitrarily stale reads; restricting transactions to read-only or
write-your-read-set makes the history serializable, and then each
process must observe each register's value monotonically. A process
that sees a register's value go DOWN proves the system is not
sequentially consistent).

Per-key op shapes (independent-lifted, sequential.clj:232-235; keys are
drawn from a fixed pool of 8):
- ``{"f": "inc", "value": [k, None]}`` → ok ``[k, v']`` — one
  read-increment-write transaction; ``v'`` is the written value.
- ``{"f": "read", "value": [k, None]}`` → ok ``[k, v]`` (0 when the
  register doesn't exist yet).

The checker (sequential.clj:107-136): within each process, the ok
values for a key never decrease.
"""
from __future__ import annotations

from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker import Checker

KEY_POOL = 8  # sequential.clj:232-235


def generator(key_pool: int = KEY_POOL):
    def inc(test, ctx):
        return {"f": "inc",
                "value": independent.tuple_value(
                    ctx.rng.randrange(key_pool), None)}

    def read(test, ctx):
        return {"f": "read",
                "value": independent.tuple_value(
                    ctx.rng.randrange(key_pool), None)}

    return gen.mix([gen.Fn(inc), gen.Fn(read)])


def non_monotonic_pairs(history: list) -> list:
    """Same-process ok pairs where the observed value decreased
    (sequential.clj:107-126)."""
    last: dict = {}
    errs = []
    for op in history:
        if op.get("type") != "ok":
            continue
        v = op.get("value")
        if independent.is_tuple_value(v):
            v = v[1]
        if not isinstance(v, int):
            continue
        p = op.get("process")
        prev = last.get(p)
        if prev is not None and prev[1] > v:
            errs.append([prev[0], op])
        last[p] = (op, v)
    return errs


class SequentialChecker(Checker):
    """(sequential.clj:128-136); runs under the independent lift."""

    def name(self):
        return "sequential"

    def check(self, test, history, opts):
        errs = non_monotonic_pairs(history)
        return {"valid?": not errs, "non-monotonic": errs[:10],
                "non-monotonic-count": len(errs)}


def checker() -> Checker:
    return independent.checker(SequentialChecker())


def workload(test: dict | None = None, **_) -> dict:
    return {
        "dgraph-sequential": True,
        "generator": generator(),
        "checker": checker(),
    }
