"""Distributed mutex workload (reference: the rabbitmq suite's
Semaphore client, rabbitmq/src/jepsen/rabbitmq.clj:178-255 — a
one-message queue as a lock: holding the message is holding the mutex).

Each thread alternates acquire and release; the checker is
linearizability against the knossos mutex model (acquire of a held
lock / release of a free lock are inconsistent). A failed acquire
(lock busy) completes ``fail`` and is invisible to the model.
"""
from __future__ import annotations

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.models import Mutex


def generator():
    return gen.each_thread(gen.cycle(gen.Seq([
        {"f": "acquire", "value": None},
        {"f": "release", "value": None},
    ])))


def workload(test: dict | None = None, accelerator: str = "auto",
             **_) -> dict:
    return {
        "generator": generator(),
        "checker": linearizable(model=Mutex(), accelerator=accelerator),
    }
