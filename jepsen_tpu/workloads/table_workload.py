"""Table-creation visibility workload (reference:
tidb/src/tidb/table.clj — a DDL race probe: create a table, then insert
into it; a "table doesn't exist" failure for a table whose creation
already acknowledged is a schema-visibility violation).

Op shapes:
- ``{"f": "create-table", "value": table_id}``
- ``{"f": "insert", "value": [table_id, k]}`` — fails with error
  ["doesnt-exist", ...] when the server can't see the table

The generator only inserts into tables whose create-table op has
completed ok (table.clj:62-69 tracks last-created-table the same way),
so every doesnt-exist failure indicts the DB, not the workload.
"""
from __future__ import annotations

import threading

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker


def generator():
    lock = threading.Lock()
    state = {"last_created": None, "next": 0, "row": 0}

    def one(test, ctx):
        with lock:
            last = state["last_created"]
            if last is not None and ctx.rng.random() < 0.8:
                # fresh row key per insert, so every insert probes the
                # table's visibility (a fixed key would duplicate-key
                # away all but the first probe on a real DB)
                state["row"] += 1
                return {"f": "insert", "value": [last, state["row"]]}
            state["next"] += 1
            return {"f": "create-table", "value": state["next"]}

    def on_update(g, test, ctx, event):
        if event.get("type") == "ok" and event.get("f") == "create-table":
            with lock:
                cur = state["last_created"]
                v = event.get("value")
                state["last_created"] = v if cur is None else max(cur, v)
        return g

    return gen.on_update(on_update, gen.Fn(one))


class TableChecker(Checker):
    """Valid iff no insert failed with doesnt-exist (table.clj:70-79)."""

    def check(self, test, history, opts):
        bad = [op for op in history
               if op.get("type") == "fail"
               and (op.get("error") or [None])[0] == "doesnt-exist"]
        return {"valid?": not bad, "missing-table-count": len(bad),
                "missing-table": bad[:10]}


def workload(test: dict | None = None, **_) -> dict:
    return {
        "table-workload": True,  # fake-mode client dispatch marker
        "generator": generator(),
        "checker": TableChecker(),
    }
