"""Multi-key ACID workload (reference:
yugabyte/src/yugabyte/multi_key_acid.clj — transactional read/write
batches over a composite-key table, verified linearizable against a
multi-register model).

Per independent key group: read txns over a random nonempty subset of
the 3-key range, write txns assigning random values to a random subset.
The checker is the linearizability search against
models.MultiRegister — whose int encoding ((V+1)^K = 216 states at the
workload shape) rides the dense-table device kernel, so the per-key
histories batch onto the TPU like the register workload's.
"""
from __future__ import annotations

import itertools

from jepsen_tpu import checker as chk
from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.models import MultiRegister

KEY_RANGE = 3      # multi_key_acid.clj:40 key-range
VAL_RANGE = 5      # multi_key_acid.clj:41 rand-val


def _subset(rng):
    n = rng.randint(1, KEY_RANGE)
    return sorted(rng.sample(range(KEY_RANGE), n))


def r(test, ctx):
    """Read a random nonempty subset of keys (multi_key_acid.clj:43-48)."""
    return {"f": "txn",
            "value": [["r", k, None] for k in _subset(ctx.rng)]}


def w(test, ctx):
    """Write a random nonempty subset of keys (multi_key_acid.clj:50-54)."""
    return {"f": "txn",
            "value": [["w", k, ctx.rng.randint(0, VAL_RANGE - 1)]
                      for k in _subset(ctx.rng)]}


def workload(test: dict | None = None, per_key_limit: int = 20,
             process_limit: int | None = 20, accelerator: str = "auto",
             **_) -> dict:
    test = test or {}
    n = len(test.get("nodes") or []) or 5
    group = 2 * n  # multi_key_acid.clj:59 concurrent-generator (* 2 n)

    def key_gen(k):
        g = gen.reserve(n, gen.Fn(r), gen.Fn(w))
        g = gen.limit(per_key_limit, g)
        if process_limit is not None:
            g = gen.process_limit(process_limit, g)
        return g

    return {
        "txn-mode": "multi",  # fake-mode client dispatch marker
        "generator": independent.concurrent_generator(
            group, itertools.count(), key_gen),
        "checker": independent.checker(chk.compose({
            "linear": linearizable(model=MultiRegister(),
                                   accelerator=accelerator,
                                   multi_shape=(KEY_RANGE, VAL_RANGE)),
            "timeline": chk.timeline_html(),
        })),
    }
