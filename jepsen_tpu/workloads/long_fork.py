"""Long-fork anomaly workload (reference:
jepsen/src/jepsen/tests/long_fork.clj).

Forbidden under snapshot isolation, long fork is the "parallel snapshot
isolation" anomaly: writes w1, w2 to different keys, and two reads such
that one observes w1 but not w2 and the other observes w2 but not w1 —
the reads sit on incomparable forks of history.

Keys come in groups of ``group_size``; each key is written exactly once
(value 1) by a single-write txn; read txns read a whole group. The checker
compares every pair of reads in a group: presence vectors must be totally
ordered (reference read-compare, long_fork.clj:158+). The pairwise compare
is a data-parallel boolean-matrix scan; on large histories it runs as a
vectorized numpy comparison (device offload unnecessary at this size).
"""
from __future__ import annotations

from collections import defaultdict

import numpy as np

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker
from jepsen_tpu.txn import _hk


def group_of(k: int, group_size: int) -> int:
    return k // group_size


def group_keys(g: int, group_size: int) -> list[int]:
    return list(range(g * group_size, (g + 1) * group_size))


def generator(group_size: int = 3):
    """Writes each key once; reads a whole group as one txn
    (long_fork.clj:117-156)."""
    state = {"writes_left": [], "next_group": 0}

    def one(test, ctx):
        if not state["writes_left"] and ctx.rng.random() < 0.5:
            state["writes_left"] = group_keys(state["next_group"], group_size)
            state["next_group"] += 1
        if state["writes_left"] and ctx.rng.random() < 0.7:
            k = state["writes_left"].pop(0)
            return {"f": "txn", "value": [["w", k, 1]]}
        # read a group that has (at least partially) been written
        g = ctx.rng.randrange(max(1, state["next_group"]))
        return {"f": "txn",
                "value": [["r", k, None] for k in group_keys(g, group_size)]}

    return gen.Fn(one)


class LongForkChecker(Checker):
    """Pairwise read-comparability per group (long_fork.clj:311-325)."""

    def __init__(self, group_size: int = 3):
        self.group_size = group_size

    def name(self):
        return "long-fork"

    def check(self, test, history, opts):
        reads_by_group: dict[int, list[tuple[dict, tuple]]] = defaultdict(list)
        early_read_errors = []
        # early/late accounting (long_fork.clj:290-321): a read observing
        # nothing yet (all nil) or everything (all written) can't witness
        # a fork — report how much of the read budget was wasted on them
        reads_count = early_reads = late_reads = 0
        for op in history:
            if op.get("type") != "ok" or op.get("f") != "txn":
                continue
            mops = op.get("value") or []
            rs = [m for m in mops if m[0] == "r"]
            if not rs or len(rs) != len(mops):
                continue  # write txn
            reads_count += 1
            if all(m[2] is None for m in rs):
                early_reads += 1
            elif all(m[2] is not None for m in rs):
                late_reads += 1
            keys = sorted(_hk(m[1]) for m in rs)
            g = group_of(keys[0], self.group_size)
            if keys != group_keys(g, self.group_size):
                early_read_errors.append({"op": op, "error": "bad-key-group"})
                continue
            vec = tuple(m[2] if m[2] is not None else 0
                        for m in sorted(rs, key=lambda m: _hk(m[1])))
            reads_by_group[g].append((op, vec))

        forks = []
        for g, reads in reads_by_group.items():
            if len(reads) < 2:
                continue
            mat = np.asarray([v for _, v in reads], dtype=np.int8)
            # r_i <= r_j elementwise, as a [R, R] boolean matrix
            le = (mat[:, None, :] <= mat[None, :, :]).all(axis=2)
            incomparable = ~(le | le.T)
            ii, jj = np.nonzero(np.triu(incomparable, k=1))
            for i, j in zip(ii.tolist(), jj.tolist()):
                forks.append({"group": g,
                              "reads": [reads[i][0], reads[j][0]]})
        return {
            "valid?": not (forks or early_read_errors),
            "forks": forks[:10],
            "fork-count": len(forks),
            "read-errors": early_read_errors[:10],
            "reads-count": reads_count,
            "early-read-count": early_reads,
            "late-read-count": late_reads,
        }


def checker(group_size: int = 3) -> Checker:
    return LongForkChecker(group_size)


def workload(test: dict | None = None, group_size: int = 3, **_) -> dict:
    return {"generator": generator(group_size),
            "checker": checker(group_size)}
