"""Version-divergence workload (reference:
crate/src/jepsen/crate/version_divergence.clj — writes a stream of
unique integers to per-key rows while faults run; every observed
``_version`` of a row must identify exactly ONE value, so two reads
seeing different values at the same version prove the replicas diverged
under one version number).

Op shapes (independent-lifted [k, v] values):
- ``{"f": "write", "value": [k, unique_int]}``
- ``{"f": "read",  "value": [k, [value, version]]}`` — value+row-version
  as the store reports them (None when the row doesn't exist yet)
"""
from __future__ import annotations

import itertools
import threading

from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker import Checker


def generator(n_groups: int = 5, per_key_limit: int = 60):
    lock = threading.Lock()
    counter = itertools.count()

    def write(test, ctx):
        with lock:
            return {"f": "write", "value": next(counter)}

    def read(test, ctx):
        return {"f": "read", "value": None}

    def key_gen(k):
        return gen.limit(per_key_limit,
                         gen.mix([gen.Fn(read), gen.Fn(write)]))

    return independent.concurrent_generator(n_groups, itertools.count(),
                                            key_gen)


class VersionDivergenceChecker(Checker):
    """Groups ok reads by row version: a version carrying two distinct
    values is divergence (version_divergence.clj:97-108)."""

    def check(self, test, history, opts):
        by_version: dict = {}
        reads = 0
        for op in history:
            if op.get("type") != "ok" or op.get("f") != "read":
                continue
            val = op.get("value")
            if not val or val[1] is None:
                continue  # row absent: no version to judge
            reads += 1
            v, version = val
            by_version.setdefault(version, set()).add(v)
        multis = {ver: sorted(vals) for ver, vals in by_version.items()
                  if len(vals) > 1}
        return {"valid?": not multis, "read-count": reads,
                "divergent-count": len(multis),
                "multis": dict(itertools.islice(multis.items(), 10))}


def workload(test: dict | None = None, **_) -> dict:
    test = test or {}
    n = len(test.get("nodes") or []) or 5
    return {
        "version-divergence": True,  # client dispatch marker
        "generator": generator(n_groups=n),
        "checker": independent.checker(VersionDivergenceChecker()),
    }
