"""CP-subsystem workload kits (reference: the hazelcast suite's
workloads map, hazelcast/src/jepsen/hazelcast.clj:668-775 — locks over
the CP FencedLock in four model strengths, a CP counting semaphore,
unique-id generation over AtomicLongs, and a CAS register over a CP
AtomicLong).

Generators mirror the reference's shapes: lock threads cycle
acquire→release (twice each for the reentrant variants,
hazelcast.clj:698-706), id threads emit ``generate`` ops, cas threads
mix read/write/cas. Ownership in the models is the op's process (see
models.OwnerMutex for why that replaces the reference's uid→client side
map); fenced clients return the fencing token as the ok-acquire value,
which the fence-aware models check for monotonicity.
"""
from __future__ import annotations

import random

from jepsen_tpu import checker as chk
from jepsen_tpu import generator as gen
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.models import (AcquiredPermits, CASRegister, FencedMutex,
                               Mutex, OwnerMutex, ReentrantFencedMutex,
                               ReentrantMutex)

NUM_PERMITS = 2         # hazelcast.clj:55 num-permits
MAX_HOLDS = 2           # hazelcast.clj:53 reentrant-lock-acquire-count


def _lock_gen(acquires: int = 1, dt: float = 0.5):
    ops = ([{"f": "acquire", "value": None}] * acquires
           + [{"f": "release", "value": None}] * acquires)
    return gen.each_thread(gen.stagger(dt, gen.cycle(gen.Seq(ops))))


_MODELS = {
    "lock": lambda: Mutex(),
    "cp-lock": lambda: OwnerMutex(),
    "reentrant-cp-lock": lambda: ReentrantMutex(max_holds=MAX_HOLDS),
    "fenced-lock": lambda: FencedMutex(),
    "reentrant-fenced-lock":
        lambda: ReentrantFencedMutex(max_holds=MAX_HOLDS),
}


def lock_workload(test: dict | None = None, accelerator: str = "auto",
                  flavor: str = "cp-lock", **_) -> dict:
    """acquire/release against one named lock, checked linearizable
    against the flavor's mutex model (hazelcast.clj:668-733):

    - ``lock`` — plain knossos mutex (the AP lock test's model)
    - ``cp-lock`` — owner-aware, non-reentrant
    - ``reentrant-cp-lock`` — owner-aware, ≤2 holds (double
      acquire/release cycles)
    - ``fenced-lock`` / ``reentrant-fenced-lock`` — additionally check
      fencing-token monotonicity from the ok-acquire values
    """
    acquires = 2 if flavor.startswith("reentrant") else 1
    model = _MODELS[flavor]()
    return {
        "generator": _lock_gen(acquires),
        "checker": linearizable(model=model, accelerator=accelerator),
        "stats_ungated_fs": ("acquire",),   # busy-lock acquires fail
    }


def semaphore_workload(test: dict | None = None, accelerator: str = "auto",
                       **_) -> dict:
    """CP counting semaphore: concurrent acquire/release against
    NUM_PERMITS permits, checked against the acquired-permits model
    (hazelcast.clj:735-744)."""
    return {
        "generator": _lock_gen(1),
        "checker": linearizable(model=AcquiredPermits(permits=NUM_PERMITS),
                                accelerator=accelerator),
        "stats_ungated_fs": ("acquire",),
    }


def ids_workload(test: dict | None = None, accelerator: str = "auto",
                 **_) -> dict:
    """Unique-id generation (hazelcast.clj:745-752,766-775
    cp-id-gen-long / atomic-long-ids): every thread asks for fresh ids;
    the checker asserts global uniqueness of acknowledged ids."""
    return {
        "generator": gen.each_thread(gen.stagger(
            0.5, gen.cycle(gen.Seq([{"f": "generate", "value": None}])))),
        "checker": chk.unique_ids(),
    }


def cas_long_workload(test: dict | None = None, accelerator: str = "auto",
                      **_) -> dict:
    """CAS register over a CP AtomicLong (hazelcast.clj:753-765
    cp-cas-long): read / write / cas mix, linearizable against a CAS
    register that starts at 0 (a fresh AtomicLong reads 0)."""
    rng = random.Random()

    def w(test=None, ctx=None):
        return {"f": "write", "value": rng.randint(0, 4)}

    def c(test=None, ctx=None):
        return {"f": "cas", "value": [rng.randint(0, 4), rng.randint(0, 4)]}

    return {
        "generator": gen.each_thread(gen.stagger(0.5, gen.cycle(gen.mix([
            {"f": "read", "value": None}, gen.Fn(w), gen.Fn(c)])))),
        "checker": linearizable(model=CASRegister(0),
                                accelerator=accelerator),
        "stats_ungated_fs": ("cas",),
    }
