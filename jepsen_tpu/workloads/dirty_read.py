"""Dirty-read workload (reference:
elasticsearch/src/jepsen/elasticsearch/dirty_read.clj — hunts reads of
documents that never became durable: any id observed by a point read
but absent from every node's final strong read was a dirty read, and
any acknowledged write absent from the final reads was lost).

Op shapes:
- ``{"f": "write", "value": id}`` — index a unique document
- ``{"f": "read", "value": id}`` — point-read that id; found → ok,
  absent → fail (not an anomaly by itself)
- ``{"f": "refresh"}`` — force visibility before the final phase
- ``{"f": "strong-read", "value": [ids...]}`` — one full read per
  thread in the final phase
"""
from __future__ import annotations

import itertools
import threading

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker


def generator():
    lock = threading.Lock()
    counter = itertools.count()
    issued = [0]

    def write(test, ctx):
        with lock:
            v = next(counter)
            issued[0] = v + 1
            return {"f": "write", "value": v}

    def read(test, ctx):
        with lock:
            hi = issued[0]
        if hi == 0:
            return {"f": "write", "value": 0}
        return {"f": "read", "value": ctx.rng.randrange(hi)}

    return gen.mix([gen.Fn(write), gen.Fn(read)])


def final_generator():
    # phases BARRIERS between the refresh and the strong reads — Seq
    # would hand out strong-reads while the refresh is still in flight,
    # and pre-refresh reads would see a smaller index and fabricate
    # node disagreement
    return gen.phases(
        gen.once(gen.Fn(lambda test, ctx: {"f": "refresh", "value": None})),
        gen.each_thread(gen.once(gen.Fn(
            lambda test, ctx: {"f": "strong-read", "value": None}))),
    )


class DirtyReadChecker(Checker):
    """dirty = point-read ids no strong read ever saw; lost = acked
    writes no strong read ever saw; nodes agree when every strong read
    returned the same set (dirty_read.clj:106-150)."""

    def check(self, test, history, opts):
        writes, reads, strong = set(), set(), []
        for op in history:
            if op.get("type") != "ok":
                continue
            f = op.get("f")
            if f == "write":
                writes.add(op.get("value"))
            elif f == "read":
                reads.add(op.get("value"))
            elif f == "strong-read":
                strong.append(set(op.get("value") or ()))
        if not strong:
            return {"valid?": "unknown", "error": "no strong reads"}
        on_all = set.intersection(*strong)
        on_some = set.union(*strong)
        dirty = reads - on_some
        lost = writes - on_some
        # node disagreement is REPORTED, not a validity condition: an
        # indeterminate write landing between two strong reads is benign
        # visibility skew, while dirty/lost elements are real anomalies
        return {
            "valid?": not dirty and not lost,
            "nodes-agree?": on_all == on_some,
            "read-count": len(reads),
            "write-count": len(writes),
            "strong-read-count": len(strong),
            "dirty-count": len(dirty), "dirty": sorted(dirty)[:10],
            "lost-count": len(lost), "lost": sorted(lost)[:10],
            "not-on-all-count": len(on_some - on_all),
        }


def workload(test: dict | None = None, **_) -> dict:
    return {
        "dirty-read": True,  # client dispatch marker
        "generator": generator(),
        "final_generator": final_generator(),
        "checker": DirtyReadChecker(),
    }
