"""Ledger workload (reference: stolon/src/jepsen/stolon/ledger.clj —
a concrete double-spend probe for G2-item anomalies: each transfer is a
row; withdrawals require the account's row-sum to stay non-negative, so
two concurrent withdrawals that each read a sufficient balance and both
commit demonstrate write skew in monetary form).

Op shape (ledger.clj:117-132):
- ``{"f": "transfer", "value": [account, amount, id]}`` — deposit when
  ``amount`` > 0 (inserted unconditionally), withdrawal when < 0
  (inserted only if the other rows' sum + amount ≥ 0, else fail).
  ``id`` is a generator-assigned unique row key (the reference draws it
  from a client-side atom; hoisting it into the op value keeps the op
  deterministic and the client stateless).

The checker takes the charitable interpretation (ledger.clj:139-153):
deposits count when ok OR indeterminate, withdrawals only when ok; an
account whose balance under that reading is negative proves a
double-spend. (The reference's published checker flags any *nonzero*
balance, which convicts every healthy deposit — the non-negativity
bound is the sound invariant its docstring describes, so that is what
is enforced here.)

Generators: ``rand`` — small random transfers per account, 16 ops each
(ledger.clj:166-172); ``double-spend`` — fund an account with 10, then
race 2^k withdrawals of 9 (ledger.clj:155-164, the headline attack).
"""
from __future__ import annotations

import itertools
import threading

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker

OPS_PER_ACCOUNT = 16  # rand-gen's per-account limit (ledger.clj:171)


def rand_gen():
    """Per-account bursts of small transfers in [-3, 1]
    (ledger.clj:166-172)."""
    lock = threading.Lock()
    ids = itertools.count()
    state = {"account": 0, "left": OPS_PER_ACCOUNT}

    def transfer(test, ctx):
        with lock:
            if state["left"] == 0:
                state["account"] += 1
                state["left"] = OPS_PER_ACCOUNT
            state["left"] -= 1
            account = state["account"]
            row_id = next(ids)
        return {"f": "transfer",
                "value": [account, ctx.rng.randint(-3, 1), row_id]}

    return gen.Fn(transfer)


def double_spend_gen():
    """Fund each account with 10, then race up to 2^4 = 16 withdrawals
    of 9 (ledger.clj:155-164's ``(Math/pow 2 (rand-int 5))``) — at most
    one may commit."""
    lock = threading.Lock()
    ids = itertools.count()
    state = {"account": -1, "left": 0}

    def transfer(test, ctx):
        with lock:
            if state["left"] == 0:
                state["account"] += 1
                state["left"] = 2 ** ctx.rng.randint(0, 4)
                fund = True
            else:
                state["left"] -= 1
                fund = False
            account = state["account"]
            row_id = next(ids)
        amount = 10 if fund else -9
        return {"f": "transfer", "value": [account, amount, row_id]}

    return gen.Fn(transfer)


def check_account(ops: list):
    """Charitable balance for one account's ops (ledger.clj:139-153):
    deposits ok+info, withdrawals ok only; negative proves the probe."""
    balance = 0
    for op in ops:
        amount = op["value"][1]
        if amount > 0 and op.get("type") in ("ok", "info"):
            balance += amount
        elif amount < 0 and op.get("type") == "ok":
            balance += amount
    return balance


class LedgerChecker(Checker):
    def name(self):
        return "ledger"

    def check(self, test, history, opts):
        by_account: dict = {}
        for op in history:
            v = op.get("value")
            if op.get("f") == "transfer" and op.get("type") in ("ok", "info") \
                    and isinstance(v, (list, tuple)) and len(v) >= 2:
                by_account.setdefault(v[0], []).append(op)
        errs = []
        for account, ops in sorted(by_account.items(), key=lambda kv: str(kv[0])):
            balance = check_account(ops)
            if balance < 0:
                errs.append({"account": account, "balance": balance})
        return {"valid?": not errs,
                "account-count": len(by_account),
                "errors": errs}


def checker() -> Checker:
    return LedgerChecker()


def workload(test: dict | None = None, style: str = "rand", **_) -> dict:
    style = (test or {}).get("ledger_style", style)
    return {
        "ledger": True,
        "generator": (double_spend_gen() if style == "double-spend"
                      else rand_gen()),
        "checker": checker(),
    }
