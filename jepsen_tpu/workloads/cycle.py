"""Generic cycle-detection checker from a custom dependency analyzer
(reference: jepsen/src/jepsen/tests/cycle.clj:9-16, the thin adapter over
elle.core/check).

``checker(analyze_fn)`` wraps a function that derives a typed dependency
graph from a history — the extension point for bespoke consistency
models whose dependencies aren't list-append or rw-register shaped. The
analyzer returns ``(graph, txns)``: a :class:`jepsen_tpu.elle.Graph`
over transaction indices plus the transaction ops those indices name
(used to render cycle exemplars). Cycles are classified by edge type
exactly like the txn checkers (G0/G1c/G-single/G2, realtime/process
stages when the analyzer adds timing edges).
"""
from __future__ import annotations

from jepsen_tpu import elle
from jepsen_tpu.checker import Checker


class CycleChecker(Checker):
    def __init__(self, analyze_fn,
                 consistency_models=("strict-serializable",)):
        self.analyze_fn = analyze_fn
        self.consistency_models = consistency_models

    def name(self):
        return "cycle"

    def check(self, test, history, opts):
        graph, txns = self.analyze_fn(history)
        anomalies = elle.check_cycles(
            graph, accelerator=opts.get(
                "accelerator", test.get("accelerator", "auto")))
        result = elle.result_map(
            anomalies, txns,
            consistency_models=self.consistency_models)
        result["edge-count"] = len(graph.edges)
        return result


def checker(analyze_fn, consistency_models=("strict-serializable",)) -> Checker:
    return CycleChecker(analyze_fn, consistency_models=consistency_models)
