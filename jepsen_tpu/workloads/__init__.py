"""Reusable workload kits: generator + checker (+ final-generator) bundles.

Capability-equivalent to the reference's jepsen.tests.* namespaces
(jepsen/src/jepsen/tests/, SURVEY.md §2.2). A workload is a plain dict:

    {"generator": ..., "checker": ..., "final_generator": ...?, ...}

merged into a test map by suites; the "test = data" property is preserved
(SURVEY.md §5.6).
"""
from __future__ import annotations

from jepsen_tpu.workloads import (  # noqa: F401
    adya,
    append,
    bank,
    causal,
    causal_reverse,
    long_fork,
    register,
    set_workload,
    wr,
)

__all__ = [
    "adya", "append", "bank", "causal", "causal_reverse", "long_fork",
    "register", "set_workload", "wr",
]
