"""Comments workload (reference:
cockroachdb/src/jepsen/cockroach/comments.clj — the sequential-id /
visibility probe for strict serializability: if T1 < T2 in realtime but
T2 is visible without T1, later readers see comment threads with holes).

Concurrent blind writes of increasing ids per independent key, spread
across shard-split tables on a real cluster; reads return every visible
id for the key. The checker replays the history tracking, for each
write's invocation, the set of writes already completed — if a read
sees write w but misses some write that completed before w was even
invoked, strict serializability is violated.

Op shapes (independent-lifted [k, v] values):
- ``{"f": "write", "value": [k, id]}`` — blind insert of ``id``
- ``{"f": "read",  "value": [k, sorted-ids]}``
"""
from __future__ import annotations

import itertools
import threading

from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker import Checker


def generator(n_groups: int = 5, per_key_limit: int = 60):
    def read(test, ctx):
        return {"f": "read", "value": None}

    def key_gen(k):
        lock = threading.Lock()
        counter = [0]

        def write(test, ctx):
            with lock:
                n = counter[0]
                counter[0] += 1
            return {"f": "write", "value": n}

        return gen.limit(per_key_limit,
                         gen.stagger(0.01,
                                     gen.mix([gen.Fn(read), gen.Fn(write)])))

    return independent.concurrent_generator(n_groups, itertools.count(),
                                            key_gen)


class CommentsChecker(Checker):
    """First-order write-precedence replay (comments.clj:93-141): a read
    seeing write w must see every write completed before w's invocation."""

    def check(self, test, history, opts):
        completed: set = set()
        expected: dict = {}   # write id -> frozenset completed at invoke
        for op in history:
            if op.get("f") != "write":
                continue
            v = op.get("value")
            if op.get("type") == "invoke":
                expected[v] = frozenset(completed)
            elif op.get("type") == "ok":
                completed.add(v)
        errors = []
        reads = 0
        for op in history:
            if op.get("type") != "ok" or op.get("f") != "read":
                continue
            reads += 1
            seen = set(op.get("value") or ())
            our_expected: set = set()
            for w in seen:
                our_expected |= expected.get(w, frozenset())
            missing = our_expected - seen
            if missing:
                errors.append({"op": {k: v for k, v in op.items()
                                      if k != "value"},
                               "missing": sorted(missing),
                               "expected-count": len(our_expected)})
        return {"valid?": not errors, "errors": errors[:10],
                "read-count": reads}


def workload(test: dict | None = None, **_) -> dict:
    test = test or {}
    n = len(test.get("nodes") or []) or 5
    return {
        "comments": True,  # fake-mode client dispatch marker
        "generator": generator(n_groups=n),
        "checker": independent.checker(CommentsChecker()),
    }
