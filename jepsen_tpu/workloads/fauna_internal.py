"""FaunaDB-style internal-consistency workload (reference:
faunadb/src/jepsen/faunadb/internal.clj — probes whether a single
transaction observes its *own* effects coherently: a query that reads a
set, inserts into it, and reads it again must see the insert in the
second read and not the first, whether the three steps are composed via
let bindings, object literals, or arrays).

Op shapes (internal.clj:71-133):
- ``{"f": "reset", "value": None}`` — delete every cat of both types.
- ``{"f": "create-tabby-let" | "create-tabby-obj" | "create-tabby-arr",
  "value": id}`` → ok value ``{"tabbies-0": [names before],
  "tabby": name, "tabbies-1": [names after]}`` — one transaction that
  reads the tabby set, creates cat ``id`` as a tabby, reads again; the
  three result positions are composed through a let / object literal /
  array respectively, exercising each composition form's evaluation
  order.
- ``{"f": "change-type", "value": None}`` → ok value
  ``[name|None, tabbies_after, calicos_after]`` — one transaction that
  retypes the first tabby to calico and re-reads both sets.

The checker (internal.clj:140-206) is purely per-op: a created tabby
present *before* its create, or missing *after* it, or a retyped cat
still in the old set / missing from the new one, is an internal
consistency error.
"""
from __future__ import annotations

import threading

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker

CREATE_FS = ("create-tabby-let", "create-tabby-obj", "create-tabby-arr")


def op_errors(op: dict) -> list[dict]:
    """Internal-consistency errors evidenced by one ok completion
    (internal.clj:140-191)."""
    f, v = op.get("f"), op.get("value")
    errs = []
    if f in CREATE_FS and isinstance(v, dict):
        name = v.get("tabby")
        if name in (v.get("tabbies-0") or []):
            errs.append({"type": "present-before-create", "name": name,
                         "op": op})
        if name not in (v.get("tabbies-1") or []):
            errs.append({"type": "missing-after-create", "name": name,
                         "op": op})
    elif f == "change-type" and isinstance(v, (list, tuple)) and len(v) == 3:
        name, tabbies, calicos = v
        if name is not None:
            if name in (tabbies or []):
                errs.append({"type": "present-after-change", "name": name,
                             "op": op})
            if name not in (calicos or []):
                errs.append({"type": "missing-after-change", "name": name,
                             "op": op})
    return errs


class InternalChecker(Checker):
    """(internal.clj:193-206)"""

    def name(self):
        return "internal"

    def check(self, test, history, opts):
        errors = []
        for op in history:
            if op.get("type") == "ok":
                errors.extend(op_errors(op))
        return {
            "valid?": not errors,
            "error-count": len(errors),
            "error-types": sorted({e["type"] for e in errors}),
            "errors": errors[:10],
        }


def generator():
    """Uniform mix of resets, type changes, and the three create
    composition forms, ids unique across the run (internal.clj:208-228)."""
    lock = threading.Lock()
    counter = [0]

    def create(f):
        def fn(test, ctx):
            with lock:
                i = counter[0]
                counter[0] += 1
            return {"f": f, "value": i}
        return gen.Fn(fn)

    return gen.mix([
        gen.Fn(lambda test, ctx: {"f": "reset", "value": None}),
        gen.Fn(lambda test, ctx: {"f": "change-type", "value": None}),
        *[create(f) for f in CREATE_FS],
    ])


def checker() -> Checker:
    return InternalChecker()


def workload(test: dict | None = None, **_) -> dict:
    return {
        "fauna_internal": True,
        "generator": generator(),
        "checker": checker(),
    }
