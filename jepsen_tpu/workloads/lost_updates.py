"""Lost-updates workload (reference:
crate/src/jepsen/crate/lost_updates.clj — a map of keys to integer sets
maintained by read-modify-write with the store's optimistic ``_version``
check; every acknowledged add must appear in the key's final read, so a
write that silently clobbers a concurrent one surfaces as a lost
element).

Op shapes (independent-lifted [k, v] values):
- ``{"f": "add", "value": [k, element]}`` — RMW the key's element list
  under a version guard (clients retry conflicts; exhausted retries
  fail the op)
- ``{"f": "read", "value": [k, elements]}`` — the key's current set
"""
from __future__ import annotations

import itertools
import threading

from jepsen_tpu import checker as chk
from jepsen_tpu import generator as gen
from jepsen_tpu import independent


def generator(n_groups: int = 5, adds_per_key: int = 30):
    lock = threading.Lock()
    counter = itertools.count()

    def add(test, ctx):
        with lock:
            return {"f": "add", "value": next(counter)}

    def read(test, ctx):
        return {"f": "read", "value": None}

    def key_gen(k):
        # every thread in the group races RMW adds, then (after the
        # group drains — gen.phases barriers) each takes one final read
        # of the key (the reference's phases + each/once shape,
        # lost_updates.clj:130-136)
        return gen.phases(gen.limit(adds_per_key, gen.Fn(add)),
                          gen.each_thread(gen.once(gen.Fn(read))))

    return independent.concurrent_generator(n_groups, itertools.count(),
                                            key_gen)


def workload(test: dict | None = None, **_) -> dict:
    test = test or {}
    n = len(test.get("nodes") or []) or 5
    return {
        "lost-updates": True,  # client dispatch marker
        "generator": generator(n_groups=n),
        "checker": independent.checker(chk.set_checker()),
    }
