"""Set add/read workload (reference checkers: jepsen/src/jepsen/checker.clj
240-291 `set` and 461-592 `set-full`).

Clients add unique integers to a set; reads return the whole set. The
quick checker compares the final read against attempted adds; set-full
tracks every element's visibility window across all reads.
"""
from __future__ import annotations

import itertools

from jepsen_tpu import checker as chk
from jepsen_tpu import generator as gen


def adds():
    """Infinite unique-element add ops."""
    counter = itertools.count()

    def add(test, ctx):
        return {"f": "add", "value": next(counter)}

    return gen.Fn(add)


def reads(final: bool = False):
    def read(test, ctx):
        return {"f": "read", "value": None}

    # final reads: one per thread; the composing suite applies the
    # clients-only restriction (suites.compose_test owns that wrap)
    if final:
        return gen.each_thread(gen.once(gen.Fn(read)))
    return gen.Fn(read)


def workload(test: dict | None = None, full: bool = False,
             linearizable: bool = False, accelerator: str = "cpu",
             **_) -> dict:
    return {
        "generator": adds() if full is False else gen.mix([adds(), reads()]),
        "final_generator": reads(final=True),
        "checker": (chk.set_full(linearizable=linearizable,
                                 accelerator=accelerator)
                    if full else chk.set_checker()),
    }
