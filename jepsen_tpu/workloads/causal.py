"""Causal-consistency register workload (reference:
jepsen/src/jepsen/tests/causal.clj).

A single register written with sequential values 1..n, where each write is
causally ordered after the previous one (write i+1 is issued only after
write i is visible). The CausalRegister model accepts a write only when it
extends the causal chain (value = current + 1) and reads that return the
current value or the distinguished initial 0. Checking is plain
linearizability search over this model — causal order violations surface
as model inconsistency (causal.clj:12-31,88-112).
"""
from __future__ import annotations

from dataclasses import dataclass

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.models import Model, inconsistent


@dataclass(frozen=True)
class CausalRegister(Model):
    """Register over the causal chain 0 -> 1 -> 2 -> ... (causal.clj:33-84)."""

    value: int = 0

    def step(self, op):
        f, v = op.get("f"), op.get("value")
        if f in ("write", "w"):
            if v == self.value + 1:
                return CausalRegister(v)
            return inconsistent(
                f"write {v!r} does not extend causal chain at {self.value}")
        if f in ("read", "r"):
            if v is None or v == self.value:
                return self
            return inconsistent(f"read {v!r} at register {self.value}")
        return inconsistent(f"unknown f {f!r}")


def generator(n_writes: int = 10):
    """Sequential causally-chained writes interleaved with reads."""
    writes = gen.Seq([{"f": "write", "value": i + 1} for i in range(n_writes)])

    def read(test, ctx):
        return {"f": "read", "value": None}

    return gen.any_gen(writes, gen.Fn(read))


def workload(test: dict | None = None, n_writes: int = 10, **_) -> dict:
    return {
        "generator": generator(n_writes),
        "checker": linearizable(model=CausalRegister()),
        "model": CausalRegister(),
    }
