"""Pause-to-lose-writes workload (reference:
aerospike/src/aerospike/pause.clj — pause a node holding masterships
while clients keep blind-appending; the paused node traps in-flight
writes in memory, a new master is promoted and takes later writes, and
when the old master resumes it applies its trapped writes with a
far-future local clock, clobbering everything acknowledged since. The
probe is a per-key append set: a lost acknowledged element is the
anomaly).

A state machine shared by the client generator, the nemesis generator,
and the completion stream coordinates the phases (pause.clj:173-208):

- ``healthy``: clients append to the current key block; after
  ``pause-healthy-delay`` seconds the nemesis pauses the master set →
- ``paused``: appends continue (they fail against the paused master
  until an election promotes a peer); the FIRST acknowledged append →
- ``wait``: all client ops stop for ``pause-delay`` seconds (so the
  trapped write's local timestamp lands beyond every acknowledged
  one), then the nemesis resumes the node, fresh masters and a fresh
  key block are chosen, and the loop returns to ``healthy``.

The reference drives this with blocking sleeps inside an old-style
per-thread generator; here the same machine rides the pure-generator
protocol — phase waits are PENDING polls, delays are future-timed ops
the interpreter sleeps toward, and the paused→wait edge fires in
``update`` when an append completion arrives (pause.clj's client-side
``swap!``).

Checker: the per-key set checker under the independent lift
(pause.clj:212-214) — every acknowledged element must be in its key's
final read.
"""
from __future__ import annotations

import threading

from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker import set_checker
from jepsen_tpu.generator import PENDING, Generator, fill_in_op

DEFAULT_HEALTHY_DELAY_S = 5.0   # pause.clj:17-19
DEFAULT_PAUSE_DELAY_S = 30.0    # pause.clj:21-23
MASTERS_LIMIT = 1               # pause.clj:25-27


class MachineState:
    """The shared phase machine (pause.clj:29-38 next-healthy)."""

    def __init__(self, rng=None):
        import random as _random
        self.lock = threading.Lock()
        self.rng = rng or _random.Random()
        self.phase = "init"
        self.masters: list = []
        self.keys: list = []
        self.next_key = 0
        self.next_value = 0
        self.phase_at = 0  # history time (ns) of the last transition

    def next_healthy(self, test, now: int) -> None:
        """Pick new masters and a fresh key block (pause.clj:29-38)."""
        nodes = list(test.get("nodes") or ["n1"])
        self.masters = self.rng.sample(nodes, min(MASTERS_LIMIT, len(nodes)))
        per = max(1, int(test.get("concurrency", 5)) // len(nodes))
        self.keys = list(range(self.next_key, self.next_key + per))
        self.next_key += per
        self.phase = "healthy"
        self.phase_at = now


def _delay_ns(test, key: str, default_s: float) -> int:
    return int(float(test.get(key, default_s)) * 1e9)


class PauseClientGen(Generator):
    """Appends to this phase's key block; PENDING through ``wait``;
    flips paused→wait on the first acknowledged append
    (pause.clj:162-171, 92-97)."""

    def __init__(self, state: MachineState):
        self.state = state

    def op(self, test, ctx):
        s = self.state
        with s.lock:
            if s.phase == "init":
                s.next_healthy(test, ctx.time)
            if s.phase == "wait" or not s.keys:
                return (PENDING, self)
            if s.phase == "healthy" and ctx.time < s.phase_at:
                # the resume op that opened this phase is future-timed;
                # appending before it fires would break the wait window
                return (PENDING, self)
            p = ctx.some_free_process()
            # clients-wrapped in production; guard the nemesis sentinel
            # for bare-context polls
            if p is None or not isinstance(p, int):
                return (PENDING, self)
            k = s.keys[p % len(s.keys)]
            v = s.next_value
            s.next_value += 1
        return ({"type": "invoke", "f": "add", "process": p,
                 "time": ctx.time,
                 "value": independent.tuple_value(k, v)}, self)

    def update(self, test, ctx, event):
        if event.get("type") == "ok" and event.get("f") == "add":
            s = self.state
            with s.lock:
                # only adds acknowledged AFTER the pause actually fired
                # count — the pause op itself is future-timed, and an
                # ack from the pre-pause window must not end the phase
                if s.phase == "paused" \
                        and (event.get("time") or ctx.time) >= s.phase_at:
                    s.phase = "wait"
                    s.phase_at = event.get("time") or ctx.time
        return self


class PauseNemesisGen(Generator):
    """healthy → (after healthy-delay) pause op; paused → PENDING until
    the clients flip to wait; wait → (after pause-delay) resume op with
    a fresh key block (pause.clj:145-160).

    ``op`` is PURE — composing generators (any_gen) poll candidates and
    discard the losers, so a state transition at emission time would
    fire on polls that never dispatch. Transitions ride ``update``,
    which only ever sees dispatched events; phase guards make the
    invocation/completion double-delivery idempotent."""

    def __init__(self, state: MachineState):
        self.state = state

    def op(self, test, ctx):
        s = self.state
        with s.lock:
            if s.phase == "init":
                s.next_healthy(test, ctx.time)
            if s.phase == "healthy":
                t = s.phase_at + _delay_ns(test, "pause-healthy-delay",
                                           DEFAULT_HEALTHY_DELAY_S)
                op = fill_in_op({"type": "info", "f": "pause",
                                 "value": list(s.masters),
                                 "time": max(ctx.time, t)}, ctx)
                return (PENDING, self) if op is PENDING else (op, self)
            if s.phase == "wait":
                t = s.phase_at + _delay_ns(test, "pause-delay",
                                           DEFAULT_PAUSE_DELAY_S)
                op = fill_in_op({"type": "info", "f": "resume",
                                 "value": list(s.masters),
                                 "time": max(ctx.time, t)}, ctx)
                return (PENDING, self) if op is PENDING else (op, self)
            return (PENDING, self)

    def update(self, test, ctx, event):
        s = self.state
        f = event.get("f")
        with s.lock:
            if f == "pause" and s.phase == "healthy":
                s.phase = "paused"
                s.phase_at = event.get("time") or ctx.time
            elif f == "resume" and s.phase == "wait":
                s.next_healthy(test, event.get("time") or ctx.time)
        return self


def final_reads(state: MachineState):
    """One read per key ever used (pause.clj:215-224). A one-shot Fn —
    ``gen.once`` would cap the whole Seq at a single op, and a bare Fn
    would rebuild the Seq forever."""
    done: list = []

    def build(test, ctx):
        if done:
            return None
        done.append(True)
        with state.lock:
            n = state.next_key
        return gen.Seq([{"f": "read",
                         "value": independent.tuple_value(k, None)}
                        for k in range(n)])

    return gen.Fn(build)


def workload(test: dict | None = None, state: MachineState | None = None,
             **_) -> dict:
    """The workload half; the suite pairs it with the pause fault
    package sharing the same MachineState (pause.clj:173-233
    workload+nemesis)."""
    state = state or MachineState()
    return {
        "pause-workload": True,
        "pause_state": state,
        "generator": PauseClientGen(state),
        "final_generator": final_reads(state),
        "checker": independent.checker(set_checker()),
    }
