"""Bank workload (reference: jepsen/src/jepsen/tests/bank.clj).

Accounts hold balances; transfers move money between them; every read of
all accounts must sum to the invariant total (snapshot-isolation test).
With ``negative_balances`` false, no read may show a negative balance.
The sum scan is a columnar O(n) reduction.
"""
from __future__ import annotations

import logging

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import Checker
from jepsen_tpu.utils import int_keyed

logger = logging.getLogger("jepsen.workloads.bank")


def read_op(test, ctx):
    return {"f": "read", "value": None}


def transfer(test, ctx):
    accts = test.get("accounts", list(range(8)))
    frm, to = ctx.rng.sample(list(accts), 2)
    return {"f": "transfer",
            "value": {"from": frm, "to": to,
                      "amount": 1 + ctx.rng.randint(0, test.get("max-transfer", 5) - 1)}}


def generator():
    return gen.mix([gen.Fn(read_op), gen.Fn(transfer)])


class BankChecker(Checker):
    """All ok reads sum to total-amount; optionally no negative balances
    (bank.clj:57-121)."""

    def __init__(self, negative_balances: bool = False):
        self.negative_balances = negative_balances

    def name(self):
        return "bank"

    def check(self, test, history, opts):
        total = test.get("total-amount", 0)
        accounts = set(test.get("accounts", list(range(8))))
        bad_reads = []
        read_count = 0
        for op in history:
            if op.get("type") != "ok" or op.get("f") != "read":
                continue
            read_count += 1
            # stored histories stringify account keys (store.jsonl →
            # analyze); normalize or every re-check sees phantom
            # "unexpected accounts"
            balances = int_keyed(op.get("value") or {})
            errs = []
            extra = set(balances) - accounts
            if extra:
                errs.append({"error": "unexpected-accounts",
                             "accounts": sorted(extra, key=str)})
            s = sum(balances.values())
            if s != total:
                errs.append({"error": "wrong-total", "total": s,
                             "expected": total})
            if not self.negative_balances:
                neg = {a: b for a, b in balances.items() if b < 0}
                if neg:
                    errs.append({"error": "negative-balance", "accounts": neg})
            if errs:
                bad_reads.append({"op": op, "errors": errs})
        return {
            "valid?": not bad_reads,
            "read-count": read_count,
            "error-count": len(bad_reads),
            "first-error": bad_reads[0] if bad_reads else None,
            "bad-reads": bad_reads[:10],
        }


class BankPlotter(Checker):
    """Balance-over-time plot (bank.clj:143-177 plotter): the total of
    all accounts seen by each ok read, one series per node (process mod
    node-count), with nemesis activity shaded. A healthy run draws one
    flat line at total-amount; anomalies show up as excursions."""

    def name(self):
        return "plot"

    def check(self, test, history, opts):
        try:
            points_by_node: dict[str, list[tuple[float, float]]] = {}
            nodes = test.get("nodes") or []
            for op in history:
                if op.get("type") != "ok" or op.get("f") != "read":
                    continue
                balances = op.get("value")
                if not isinstance(balances, dict):
                    continue
                p = op.get("process")
                node = (str(nodes[p % len(nodes)])
                        if nodes and isinstance(p, int) else str(p))
                total = sum(v for v in balances.values() if v is not None)
                points_by_node.setdefault(node, []).append(
                    (op.get("time", 0) / 1e9, total))
            if not points_by_node:
                return {"valid?": True}
            from jepsen_tpu import store
            from jepsen_tpu.checker.perf_plots import _figure, _shade_nemesis
            plt, fig, ax = _figure()
            _shade_nemesis(ax, history)
            for node, pts in sorted(points_by_node.items()):
                xs = [x for x, _ in pts]
                ys = [y for _, y in pts]
                ax.plot(xs, ys, "x", ms=4, label=node)
            ax.set_xlabel("time (s)")
            ax.set_ylabel("Total of all accounts")
            ax.set_title(f"{test.get('name', 'test')} bank")
            ax.legend(loc="upper right", fontsize=8)
            d = opts.get("subdirectory")
            path = store.path_mk(test, *filter(None, [d, "bank.png"]))
            fig.savefig(path, bbox_inches="tight")
            plt.close(fig)
            return {"valid?": True, "plot": str(path)}
        except Exception:  # noqa: BLE001  plotting must not fail the test
            logger.exception("bank plot failed")
            return {"valid?": True}


def checker(negative_balances: bool = False) -> Checker:
    return BankChecker(negative_balances)


def plotter() -> Checker:
    return BankPlotter()


def workload(test: dict | None = None, negative_balances: bool = False,
             **_) -> dict:
    """Test bundle (bank.clj:179-192): supplies accounts/total defaults;
    the checker composes the snapshot-isolation check with the
    balance-over-time plot exactly like the reference's
    ``{:SI (checker) :plot (plotter)}``."""
    from jepsen_tpu import checker as chk

    accounts = list(range(8))
    return {
        "accounts": accounts,
        # clients initialize each account to 10; reads must preserve the sum
        "total-amount": 10 * len(accounts),
        "max-transfer": 5,
        "generator": generator(),
        "checker": chk.compose({"SI": checker(negative_balances),
                                "plot": plotter()}),
    }
