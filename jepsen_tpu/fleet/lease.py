"""Leased checking with fencing: at most one pool host per run.

The fleet plane's pool can hold many checker hosts over one shared
ingest store. Without coordination two hosts would both admit the same
run, burn double the accelerator time, and race each other's
``live-session.ckpt`` / ``live-status.json`` writes. This module is the
coordination: a per-run **lease file** (``check.lease`` next to the
run's WAL) that a host must hold before checking, written with the same
tmp+flush+fsync+rename discipline as every other durable artifact.

Lease schema (one JSON document)::

    {"version": 1, "host": "<host-id>", "epoch": 7,
     "acquired_at": <wall>, "renewed_at": <wall>, "ttl_s": 10.0}

* **epoch** — monotonically increasing takeover counter. Every claim of
  a free or expired lease bumps it; renewal by the holder keeps it. The
  epoch is the fencing token: a host checkpoints and publishes status
  only while the on-disk lease still names *its* ``(host, epoch)``.
* **TTL + heartbeat** — the holder renews every poll; a lease whose
  ``renewed_at + ttl_s`` is in the past is up for adoption. A SIGKILLed
  or partitioned checker therefore blocks its runs for at most one TTL.
* **fencing** — :meth:`LeaseStore.guard` re-reads the lease immediately
  before every durable write (restart snapshot, live-status, check
  checkpoint, final publication). A host that lost its lease — paused
  past the TTL, partitioned from the store — sees a foreign or newer
  epoch, drops the write, and abandons the tracker. Its stale state can
  never overwrite the adopter's progress, so a run converges to exactly
  one final verdict even across a kill/partition/un-pause of its
  checker (doc/robustness.md "Fleet HA").

Claims are last-writer-wins on ``os.replace`` with a read-back verify:
two hosts racing for an expired lease both write, but the read-back
elects exactly one winner and the loser reports the claim as failed.
The guard re-read before every durable write bounds any residual
overlap to in-memory work — wasted CPU, never a conflicting artifact.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import time
from pathlib import Path

from jepsen_tpu import telemetry
from jepsen_tpu.utils import atomic_write_json

logger = logging.getLogger(__name__)

LEASE_NAME = "check.lease"
LEASE_VERSION = 1
DEFAULT_LEASE_TTL_S = 10.0


def default_host_id() -> str:
    """A host identity unique per checker process: a pool is typically
    one process per host, but two processes on one box must still fence
    each other."""
    return f"{socket.gethostname()}:{os.getpid()}"


class LeaseStore:  # durability: fsync (via utils.atomic_write_json)
    """Per-run lease files under a store root, for one host identity.

    Touched only by the owning daemon's scheduler poll thread; cross-
    *host* mutual exclusion is the lease protocol itself (fsync-atomic
    writes + read-back + fencing re-reads), not an in-process lock."""

    def __init__(self, store_root, host_id: str | None = None,
                 ttl_s: float = DEFAULT_LEASE_TTL_S,
                 registry: telemetry.Registry | None = None,
                 time_fn=time.time):
        self.store_root = Path(store_root)
        self.host_id = host_id if host_id else default_host_id()
        self.ttl_s = float(ttl_s)
        self.registry = registry if registry is not None \
            else telemetry.get_registry()
        # wall time, not monotonic: expiry is compared across hosts
        self._time = time_fn
        # run-dir-str -> epoch we hold (our own view; the file decides)
        self.held: dict[str, int] = {}

    # -- file plumbing ----------------------------------------------------

    def lease_path(self, run_dir) -> Path:
        return Path(run_dir) / LEASE_NAME

    def read(self, run_dir) -> dict | None:
        """The on-disk lease document, or None (missing/torn)."""
        try:
            with open(self.lease_path(run_dir), encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) \
            and doc.get("version") == LEASE_VERSION else None

    def _expired(self, doc: dict) -> bool:
        try:
            horizon = float(doc.get("renewed_at", 0)) \
                + float(doc.get("ttl_s", self.ttl_s))
        except (TypeError, ValueError):
            return True  # a garbled lease never blocks adoption
        return self._time() > horizon

    def _write(self, run_dir, epoch: int, acquired_at: float) -> bool:
        doc = {"version": LEASE_VERSION, "host": self.host_id,
               "epoch": int(epoch), "acquired_at": acquired_at,
               "renewed_at": self._time(), "ttl_s": self.ttl_s}
        try:
            atomic_write_json(self.lease_path(run_dir), doc)
        except OSError:
            logger.exception("lease write failed for %s", run_dir)
            return False
        return True

    # -- protocol ---------------------------------------------------------

    def acquire(self, run_dir) -> int | None:
        """Claims the run; returns the fencing epoch, or None when
        another live holder owns it (or the claim raced and lost)."""
        cur = self.read(run_dir)
        now = self._time()
        if cur is not None and cur.get("host") == self.host_id \
                and not self._expired(cur):
            # already ours: a renewal, not a takeover
            epoch = int(cur.get("epoch", 0))
            if self._write(run_dir, epoch,
                           float(cur.get("acquired_at", now))):
                self.held[str(run_dir)] = epoch
                return epoch
            return None
        if cur is not None and not self._expired(cur):
            return None  # a live foreign holder
        epoch = int(cur.get("epoch", 0)) + 1 if cur is not None else 1
        if not self._write(run_dir, epoch, now):
            return None
        # read-back verify: last-writer-wins elects exactly one claimant
        back = self.read(run_dir)
        if back is None or back.get("host") != self.host_id \
                or int(back.get("epoch", -1)) != epoch:
            logger.info("lease claim for %s lost the race to %r",
                        run_dir, back and back.get("host"))
            return None
        self.held[str(run_dir)] = epoch
        self.registry.counter(
            "fleet_lease_acquired_total",
            "run leases claimed by this pool host (first claims and "
            "takeovers of expired leases)").inc()
        return epoch

    def renew(self, run_dir, epoch: int) -> bool:
        """Heartbeat: pushes ``renewed_at`` forward while the on-disk
        lease still names our ``(host, epoch)``. False = lease lost —
        the caller must fence itself and abandon the run."""
        cur = self.read(run_dir)
        if cur is None or cur.get("host") != self.host_id \
                or int(cur.get("epoch", -1)) != int(epoch):
            self._lost(run_dir)
            return False
        if self._expired(cur):
            # expired but not yet adopted: renewing would resurrect a
            # lease another host may be mid-claim on — treat as lost
            self._lost(run_dir)
            return False
        if not self._write(run_dir, int(epoch),
                           float(cur.get("acquired_at", self._time()))):
            return False
        self.registry.counter(
            "fleet_lease_renewals_total",
            "lease heartbeat renewals by the holding host").inc()
        return True

    def guard(self, run_dir, epoch: int) -> bool:
        """The fencing check: may a write stamped ``epoch`` proceed?
        Re-reads the lease; a foreign or newer epoch means this host
        was deposed and the write must be dropped."""
        cur = self.read(run_dir)
        ok = (cur is not None and cur.get("host") == self.host_id
              and int(cur.get("epoch", -1)) == int(epoch))
        if not ok:
            self.registry.counter(
                "fleet_lease_fenced_writes_total",
                "durable writes rejected because the writer's lease "
                "epoch went stale (the host was deposed)").inc()
        return ok

    def release(self, run_dir, epoch: int) -> None:
        """Drops the lease (run finalized / daemon shutting down) —
        only when still ours at ``epoch``; a deposed host must not
        unlink its successor's lease."""
        self.held.pop(str(run_dir), None)
        cur = self.read(run_dir)
        if cur is None or cur.get("host") != self.host_id \
                or int(cur.get("epoch", -1)) != int(epoch):
            return
        try:
            self.lease_path(run_dir).unlink(missing_ok=True)
        except OSError:
            logger.exception("couldn't release lease for %s", run_dir)

    def _lost(self, run_dir) -> None:
        if self.held.pop(str(run_dir), None) is not None:
            self.registry.counter(
                "fleet_lease_lost_total",
                "leases this host held and lost (TTL expiry while "
                "paused/partitioned, or a takeover)").inc()
            logger.warning("lease lost for %s; fencing and abandoning "
                           "the run", run_dir)
