"""Fleet observability plane: many producers, one checker pool.

The single-run story (core.run writes a WAL, the live daemon tails it,
``analyze`` settles it post hoc) assumes the run was born on the host
that checks it. At fleet scale it wasn't: runs are born on many control
hosts and a shared accelerator-backed pool does the checking. This
package is the bridge (doc/observability.md "Fleet plane"):

* :mod:`.ingest` — the HTTP WAL-shipping receiver. Producers POST
  chunked WAL bytes with the tailer's prefix-sha256 offset as a resume
  token; the receiver verifies the prefix hash before every append, so
  a diverged or replayed shipment is rejected with the current token
  instead of silently absorbed.
* :mod:`.ship` — the producer-side client (``jepsen-tpu ship``),
  riding :class:`jepsen_tpu.journal.WalTailer` so it ships exactly the
  newline-terminated prefix a local checker would have consumed.
* :mod:`.scheduler` — the pool daemon: one
  :class:`jepsen_tpu.live.daemon.LiveDaemon` over the ingest store
  (admission-budgeted, most-lagged-first, per-run breakers), plus the
  elastic mesh's heal path (``parallel.regrow_mesh``).
* :mod:`.status` — the aggregated ``fleet-status.json`` + fleet-level
  Prometheus export behind the ``/fleet`` dashboard.

Knobs follow the live-daemon convention: tolerant coercion here so the
daemon comes up on a half-garbled config, strictness in preflight
(KNB001/KNB002), and a ``JEPSEN_TPU_*`` env twin per knob.
"""
from __future__ import annotations

import os

from jepsen_tpu.live.daemon import coerce_knob

DEFAULT_FLEET_PORT = 8091
DEFAULT_FLEET_INGEST_BUDGET_S = 0.5
DEFAULT_FLEET_MAX_RUNS = 64
# HA knobs (doc/robustness.md "Fleet HA"): run-lease TTL for the pool's
# leased checking (0 disables leasing — the single-host mode), and the
# receiver's free-disk floor below which it sheds chunks with 429
DEFAULT_FLEET_LEASE_TTL_S = 10.0
DEFAULT_FLEET_DISK_HEADROOM_MB = 64.0

# (knob, default, floor) — mirrored by preflight's KNB rows and the
# env twins below; doc/observability.md "Fleet plane" documents each
FLEET_KNOBS = (
    ("fleet_port", DEFAULT_FLEET_PORT, 0.0),
    ("fleet_ingest_budget_s", DEFAULT_FLEET_INGEST_BUDGET_S, 0.0),
    ("fleet_max_runs", DEFAULT_FLEET_MAX_RUNS, 1.0),
    ("fleet_lease_ttl_s", DEFAULT_FLEET_LEASE_TTL_S, 0.0),
    ("fleet_disk_headroom_mb", DEFAULT_FLEET_DISK_HEADROOM_MB, 0.0),
)


def fleet_knob(name: str, value, default: float, lo: float) -> float:
    """Tolerant fleet-knob coercion with an env twin: an explicit
    ``value`` wins, else ``JEPSEN_TPU_<NAME>`` is consulted, else the
    default. Garbage in either logs a warning and falls back — the
    fleet daemon must come up; preflight is where garbage is an
    error."""
    if value is None:
        value = os.environ.get("JEPSEN_TPU_" + name.upper())
    return coerce_knob(name, value, default, lo)


def fleet_receivers(value=None) -> list[str]:
    """The shipper's receiver endpoint list (``fleet_receivers``): an
    explicit value wins (an iterable of base URLs, or one comma-
    separated string), else the ``JEPSEN_TPU_FLEET_RECEIVERS`` env
    twin, else empty. Tolerant like every fleet knob — blank entries
    drop, garbage (a non-string, non-iterable value) logs a warning
    and reads as unset; preflight (KNB001) is where garbage errors."""
    import logging
    if value is None:
        value = os.environ.get("JEPSEN_TPU_FLEET_RECEIVERS")
    if value is None:
        return []
    if isinstance(value, str):
        parts = value.split(",")
    else:
        try:
            parts = [str(v) for v in value]
        except TypeError:
            logging.getLogger(__name__).warning(
                "fleet knob fleet_receivers=%r is not a URL list; "
                "ignoring", value)
            return []
    return [p.strip().rstrip("/") for p in parts if p and p.strip()]
