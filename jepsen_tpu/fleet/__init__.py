"""Fleet observability plane: many producers, one checker pool.

The single-run story (core.run writes a WAL, the live daemon tails it,
``analyze`` settles it post hoc) assumes the run was born on the host
that checks it. At fleet scale it wasn't: runs are born on many control
hosts and a shared accelerator-backed pool does the checking. This
package is the bridge (doc/observability.md "Fleet plane"):

* :mod:`.ingest` — the HTTP WAL-shipping receiver. Producers POST
  chunked WAL bytes with the tailer's prefix-sha256 offset as a resume
  token; the receiver verifies the prefix hash before every append, so
  a diverged or replayed shipment is rejected with the current token
  instead of silently absorbed.
* :mod:`.ship` — the producer-side client (``jepsen-tpu ship``),
  riding :class:`jepsen_tpu.journal.WalTailer` so it ships exactly the
  newline-terminated prefix a local checker would have consumed.
* :mod:`.scheduler` — the pool daemon: one
  :class:`jepsen_tpu.live.daemon.LiveDaemon` over the ingest store
  (admission-budgeted, most-lagged-first, per-run breakers), plus the
  elastic mesh's heal path (``parallel.regrow_mesh``).
* :mod:`.status` — the aggregated ``fleet-status.json`` + fleet-level
  Prometheus export behind the ``/fleet`` dashboard.

Knobs follow the live-daemon convention: tolerant coercion here so the
daemon comes up on a half-garbled config, strictness in preflight
(KNB001/KNB002), and a ``JEPSEN_TPU_*`` env twin per knob.
"""
from __future__ import annotations

import os

from jepsen_tpu.live.daemon import coerce_knob

DEFAULT_FLEET_PORT = 8091
DEFAULT_FLEET_INGEST_BUDGET_S = 0.5
DEFAULT_FLEET_MAX_RUNS = 64

# (knob, default, floor) — mirrored by preflight's KNB rows and the
# env twins below; doc/observability.md "Fleet plane" documents each
FLEET_KNOBS = (
    ("fleet_port", DEFAULT_FLEET_PORT, 0.0),
    ("fleet_ingest_budget_s", DEFAULT_FLEET_INGEST_BUDGET_S, 0.0),
    ("fleet_max_runs", DEFAULT_FLEET_MAX_RUNS, 1.0),
)


def fleet_knob(name: str, value, default: float, lo: float) -> float:
    """Tolerant fleet-knob coercion with an env twin: an explicit
    ``value`` wins, else ``JEPSEN_TPU_<NAME>`` is consulted, else the
    default. Garbage in either logs a warning and falls back — the
    fleet daemon must come up; preflight is where garbage is an
    error."""
    if value is None:
        value = os.environ.get("JEPSEN_TPU_" + name.upper())
    return coerce_knob(name, value, default, lo)
