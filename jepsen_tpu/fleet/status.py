"""The fleet status plane: one aggregated, atomically-published view.

``fleet-status.json`` is the dashboard's (and any curl's) single read:
per-run ``live_*`` state rolled up across the pool — runs active /
deferred / invalid, the worst checker lag and who owns it, breaker
trips, the elastic mesh's current width, ingest throughput — plus a
top-K-by-lag run table whose rows link straight into each run's
existing artifacts (live-status.json, anomaly explains, the witness
timeline, the causal trace). Published with the same tmp+fsync+rename
discipline as every other status file (telemetry._atomic_write), so a
reader never sees a torn fleet view.
"""
from __future__ import annotations

import json
import logging
import time
from pathlib import Path

from jepsen_tpu import telemetry

logger = logging.getLogger(__name__)

FLEET_STATUS_NAME = "fleet-status.json"
TOP_RUNS = 10

# artifacts a run row links to, when present in its run dir
_LINKABLE = ("live-status.json", "anomaly.json", "witness-timeline.html",
             "trace.json", "history.jsonl")


def _counter_total(snap: list[dict], name: str) -> float:
    return sum(s.get("value", 0.0) for s in snap if s["name"] == name)


def _mesh_view() -> dict:
    from jepsen_tpu import parallel
    failed = sorted(parallel.failed_device_ids())
    width = 0
    try:
        import jax
        width = parallel._pow2_floor(
            max(1, len(jax.devices()) - len(failed)))
    except Exception:  # noqa: BLE001 — no accelerator runtime is a fine fleet state
        pass
    return {"width": width, "failed_devices": failed}


class FleetStatus:
    """Accumulates cross-poll state (throughput deltas) and writes the
    aggregate. One instance per fleet daemon, touched only by the
    scheduler poll loop — no locking needed."""

    def __init__(self, store_root, registry: telemetry.Registry):
        self.store_root = Path(store_root)
        self.registry = registry
        self.polls = 0
        self._prev_bytes = 0.0
        self._prev_t = time.monotonic()
        # labels seen reaching "final": trackers pop once settled, so
        # the dashboard's finals count must outlive them
        self._finals_seen: set[str] = set()
        self._invalid_seen: set[str] = set()

    def _run_row(self, label: str, st: dict) -> dict:
        run_dir = self.store_root / label
        links = {a: label + "/" + a for a in _LINKABLE
                 if (run_dir / a).exists()}
        return {
            "name": label.split("/", 1)[0],
            "timestamp": label.split("/", 1)[-1],
            "state": st.get("state"),
            "valid_so_far": st.get("valid_so_far"),
            "lag_ops": st.get("lag_ops", 0),
            "lag_s": st.get("lag_s", 0.0),
            "first_anomaly_op": st.get("first_anomaly_op"),
            "breaker_open": st.get("state") == "error",
            "links": links,
        }

    def write(self, statuses: dict, ingest_by_run: dict,
              ha: dict | None = None) -> dict:
        """Aggregates this poll's per-run statuses + ingest cursors and
        atomically publishes fleet-status.json; returns the payload.
        ``ha`` is the scheduler's HA view (host id, leasing state) —
        counter totals for the lease/shed/degraded story ride along so
        the dashboard reads one file. A failed publish sets
        ``degraded_write`` in the returned payload instead of raising:
        status is a non-verdict surface (doc/robustness.md
        "Fleet HA")."""
        self.polls += 1
        snap = self.registry.snapshot()
        now = time.monotonic()
        bytes_total = _counter_total(snap, "fleet_ingest_bytes_total")
        dt = max(1e-9, now - self._prev_t)
        bytes_per_s = max(0.0, bytes_total - self._prev_bytes) / dt
        self._prev_bytes, self._prev_t = bytes_total, now

        sts = list(statuses.items())
        for k, st in sts:
            if st.get("state") == "final":
                self._finals_seen.add(k)
            if st.get("valid_so_far") is False:
                self._invalid_seen.add(k)
        worst = max(sts, key=lambda kv: kv[1].get("lag_ops", 0),
                    default=None)
        ranked = sorted(sts, key=lambda kv: kv[1].get("lag_ops", 0),
                        reverse=True)[:TOP_RUNS]
        payload = {
            "version": 1,
            "updated": time.time(),
            "polls": self.polls,
            "runs": {
                "tracked": len(sts),
                "active": sum(1 for _, st in sts
                              if st.get("state") != "final"),
                "invalid": len(self._invalid_seen),
                "final": len(self._finals_seen),
                "breaker_open": sum(1 for _, st in sts
                                    if st.get("state") == "error"),
                "deferred_total": _counter_total(
                    snap, "live_admission_deferred_total"),
            },
            "worst_lag_ops": (worst[1].get("lag_ops", 0)
                              if worst else 0),
            "worst_lag_run": worst[0] if worst else None,
            "mesh": {
                **_mesh_view(),
                "shrinks": _counter_total(snap, "mesh_shrink_total"),
                "regrows": _counter_total(snap, "mesh_regrow_total"),
            },
            "ingest": {
                "bytes_total": bytes_total,
                "bytes_per_s": bytes_per_s,
                "chunks_total": _counter_total(
                    snap, "fleet_ingest_chunks_total"),
                "rejected_total": _counter_total(
                    snap, "fleet_ingest_rejected_total"),
                "shed_total": _counter_total(
                    snap, "fleet_ingest_shed_total"),
                "runs": len(ingest_by_run),
            },
            "ha": {
                **(ha or {}),
                "lease_acquired": _counter_total(
                    snap, "fleet_lease_acquired_total"),
                "lease_lost": _counter_total(
                    snap, "fleet_lease_lost_total"),
                "fenced_writes": _counter_total(
                    snap, "fleet_lease_fenced_writes_total"),
                "degraded_total": _counter_total(
                    snap, "fleet_degraded_total"),
            },
            "top_runs": [self._run_row(k, st) for k, st in ranked],
        }
        try:
            telemetry._atomic_write(
                self.store_root / FLEET_STATUS_NAME,
                json.dumps(payload, indent=1))
        except OSError:
            logger.exception("fleet-status.json write failed")
            payload["degraded_write"] = True
        return payload


def load_fleet_status(store_root) -> dict | None:
    try:
        with open(Path(store_root) / FLEET_STATUS_NAME,
                  encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
