"""The pool scheduler: one daemon over every shipped run.

Composition, not reinvention: the ingest receiver lands shipped WALs
in the exact store layout core.run writes locally, so the pool's
checker IS a :class:`jepsen_tpu.live.daemon.LiveDaemon` over the
ingest store — discovery, admission (``CostModel.admission_budget_ops``
spent most-lagged-first), per-run circuit breakers, restart snapshots
and the capped metric export all apply to fleet runs unchanged. What
this module adds on top, per poll:

* the **mesh heal path** — when devices previously shrunk away
  (``parallel.shrink_mesh``) may have recovered, re-probe and regrow
  (``parallel.regrow_mesh``, ``mesh_regrow_total{from,to}``), on a
  backoff so a flapping device can't turn every poll into a probe
  storm;
* the **status plane** — one aggregated, atomic ``fleet-status.json``
  plus the fleet-level Prometheus export (``fleet-metrics.prom``);
* the **HA plane** (doc/robustness.md "Fleet HA") — a
  :class:`jepsen_tpu.fleet.lease.LeaseStore` handed to the live daemon
  so two pool hosts over one shared ingest store check each run
  exactly once (fencing keeps a deposed host's stale writes out);
  receiver backpressure (free-disk floor + an aggregate-lag pressure
  hook feeding 429s); and **degraded mode** — a failing status write
  or metrics export is counted (``fleet_degraded_total{surface}``) and
  survived, never allowed to stall the verdict path.
"""
from __future__ import annotations

import logging
import threading
import time

from jepsen_tpu import telemetry
from jepsen_tpu.fleet import (
    DEFAULT_FLEET_DISK_HEADROOM_MB, DEFAULT_FLEET_INGEST_BUDGET_S,
    DEFAULT_FLEET_LEASE_TTL_S, DEFAULT_FLEET_MAX_RUNS,
    DEFAULT_FLEET_PORT, fleet_knob,
)
from jepsen_tpu.fleet.ingest import RETRY_AFTER_S, IngestServer
from jepsen_tpu.fleet.lease import LeaseStore, default_host_id
from jepsen_tpu.fleet.status import FleetStatus
from jepsen_tpu.live.daemon import DEFAULT_POLL_S, LiveDaemon
from jepsen_tpu.utils import join_noisy

logger = logging.getLogger(__name__)

REGROW_BACKOFF_S = 5.0
# aggregate-lag pressure: shed new chunks once total checker lag
# exceeds this many per-run lag budgets — the pool is drowning and
# absorbing more WAL only digs the hole (doc/robustness.md "Fleet HA")
LAG_SHED_BUDGETS = 4.0


class FleetDaemon:
    """Ingest receiver + live checker pool + status plane, one knob
    set (``fleet_port``, ``fleet_ingest_budget_s``, ``fleet_max_runs``,
    ``fleet_lease_ttl_s``, ``fleet_disk_headroom_mb`` — each with a
    ``JEPSEN_TPU_FLEET_*`` env twin)."""

    def __init__(self, store_root, host: str = "127.0.0.1",
                 port=None, ingest_budget_s=None, max_runs=None,
                 lease_ttl_s=None, disk_headroom_mb=None,
                 host_id: str | None = None,
                 poll_s=DEFAULT_POLL_S, accelerator: str = "auto",
                 registry: telemetry.Registry | None = None,
                 regrow_backoff_s: float = REGROW_BACKOFF_S,
                 on_final=None, fault_hook=None):
        self.registry = registry if registry is not None \
            else telemetry.Registry()
        self.store_root = store_root
        port = int(fleet_knob("fleet_port", port,
                              DEFAULT_FLEET_PORT, 0.0))
        budget = fleet_knob("fleet_ingest_budget_s", ingest_budget_s,
                            DEFAULT_FLEET_INGEST_BUDGET_S, 0.0)
        max_runs = int(fleet_knob("fleet_max_runs", max_runs,
                                  DEFAULT_FLEET_MAX_RUNS, 1.0))
        ttl = fleet_knob("fleet_lease_ttl_s", lease_ttl_s,
                         DEFAULT_FLEET_LEASE_TTL_S, 0.0)
        headroom = fleet_knob("fleet_disk_headroom_mb",
                              disk_headroom_mb,
                              DEFAULT_FLEET_DISK_HEADROOM_MB, 0.0)
        self.host_id = host_id or default_host_id()
        # ttl 0 disables leasing: the single-pool-host mode, where
        # fencing would only cost fsyncs
        self.lease_store = None if ttl <= 0 else LeaseStore(
            store_root, host_id=self.host_id, ttl_s=ttl,
            registry=self.registry)
        # aggregate-lag pressure for the receiver: poll_once updates
        # the wait; the ingest thread only reads it (atomic attr read)
        self._shed_wait: float | None = None
        self.ingest = IngestServer(store_root, host=host, port=port,
                                   registry=self.registry,
                                   disk_headroom_mb=headroom,
                                   pressure=lambda: self._shed_wait,
                                   fault_hook=fault_hook)
        self.daemon = LiveDaemon(store_root=store_root,
                                 poll_s=poll_s, max_runs=max_runs,
                                 check_budget_s=budget,
                                 accelerator=accelerator,
                                 registry=self.registry,
                                 on_final=on_final,
                                 lease_store=self.lease_store)
        self.status = FleetStatus(store_root, self.registry)
        self.regrow_backoff_s = regrow_backoff_s
        self._regrow_last = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self.ingest.port

    def _maybe_regrow(self) -> None:
        """Re-probes shrunk-away devices on a backoff; a heal regrows
        the mesh for every session the pool checks."""
        from jepsen_tpu import parallel
        if not parallel.failed_device_ids():
            return
        now = time.monotonic()
        if now - self._regrow_last < self.regrow_backoff_s:
            return
        self._regrow_last = now
        parallel.regrow_mesh()

    def _degraded(self, surface: str) -> None:
        """Counts a non-verdict surface failing — the fleet keeps
        checking; the dashboard shows it's flying on instruments."""
        self.registry.counter(
            "fleet_degraded_total",
            "non-verdict surfaces (status write, metrics export) that "
            "failed a poll; verdicts kept flowing",
            labels=("surface",)).inc(surface=surface)

    def _update_pressure(self, statuses: dict) -> None:
        """Refreshes the receiver's aggregate-lag shed signal from this
        poll's statuses: once total lag across tracked runs exceeds
        LAG_SHED_BUDGETS per-run budgets, new chunks get a 429 until
        the pool catches up."""
        budget = self.daemon.lag_budget_ops * LAG_SHED_BUDGETS
        if budget <= 0:
            self._shed_wait = None
            return
        agg = sum(st.get("lag_ops", 0) or 0
                  for st in statuses.values())
        self._shed_wait = RETRY_AFTER_S if agg > budget else None

    def poll_once(self) -> dict:  # owner: scheduler
        """One fleet poll: check every tracked run (the live daemon's
        own poll), then heal, then publish the aggregate. Publication
        failures degrade, they don't stall verdicts."""
        statuses = self.daemon.poll_once()
        self._update_pressure(statuses)
        self._maybe_regrow()
        ha = {
            "host": self.host_id,
            "leasing": self.lease_store is not None,
            "lease_ttl_s": (self.lease_store.ttl_s
                            if self.lease_store else 0.0),
            "leases_held": (len(self.lease_store.held)
                            if self.lease_store else 0),
            "shedding": self._shed_wait is not None,
        }
        payload = self.status.write(statuses,
                                    self.ingest.ingest_stats(), ha=ha)
        if payload.get("degraded_write"):
            self._degraded("status")
        try:
            self.registry.export(self.status.store_root,
                                 prefix="fleet-metrics")
        except OSError:
            logger.exception("fleet metrics export failed")
            self._degraded("metrics-export")
        return payload

    # -- lifecycle ------------------------------------------------------

    def _loop(self) -> None:  # owner: scheduler
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the pool must survive anything
                logger.exception("fleet poll failed")
            rest = self.daemon.poll_s - (time.monotonic() - t0)
            if rest > 0:
                self._stop.wait(rest)

    def start(self) -> "FleetDaemon":
        self.ingest.start()
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="jepsen-fleet-poller")
            self._thread.start()
        logger.info("fleet daemon up: ingest on :%d, polling every "
                    "%.3gs", self.port, self.daemon.poll_s)
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            join_noisy(t, "fleet daemon poller", heartbeat_s=5.0)
            self._thread = None
        self.ingest.stop()

    def run_until_idle(self, timeout_s: float = 60.0) -> dict:
        """Foreground helper (tests, ``--once``): the ingest plane
        stays up while the pool polls until every tracked run
        finalized (or the deadline passes); returns the last
        fleet-status payload."""
        self.ingest.start()
        deadline = time.monotonic() + timeout_s
        payload: dict = {}
        try:
            while time.monotonic() < deadline:
                payload = self.poll_once()
                if self.status.polls > 1 and not self.daemon.trackers:
                    break
                time.sleep(min(self.daemon.poll_s,
                               max(0.0,
                                   deadline - time.monotonic())))
        finally:
            self.ingest.stop()
        return payload


def serve(store_root, **kw) -> None:
    """``jepsen-tpu fleet``: runs the fleet daemon in the foreground
    until interrupted."""
    fd = FleetDaemon(store_root, **kw)
    fd.start()
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        fd.stop()
