"""Self-chaos harness: the fleet plane under its own faults.

``jepsen-tpu fleet-chaos`` turns the nemesis discipline on the fleet
plane itself (doc/robustness.md "Fleet HA"): N producers write + ship
runs against a receiver and a two-host checker pool — all real OS
processes — while the conductor

* SIGKILLs the receiver mid-stream and restarts it on the same port
  (shippers fail over / back off, the resume token re-syncs);
* SIGSTOPs the active pool host past its lease TTL (its peer adopts
  the runs from the restart snapshots; the un-paused host must fence)
  and later SIGKILLs a pool host outright;
* tears TCP shipments mid-chunk (a short body the receiver must
  reject, never absorb);
* injects ENOSPC into the receiver's WAL appends via a flag file
  (chunks bounce with 429, the WAL stays uncorrupted).

Then it asserts the invariants the HA design promises:

1. **zero double-checked runs** — across every pool host's finals log,
   each run was finalized exactly once;
2. **zero lost or duplicated WAL bytes** — the receiver's per-run WAL
   is byte-identical to the producer's local WAL;
3. **verdict parity** — every surviving run's fleet verdict equals a
   local post-hoc ``analyze`` of the producer's own history, bit for
   bit.

The harness reuses the schedule-fuzzer's trial discipline (seeded
histories, planted anomalies) and writes a ``fleet-chaos.json`` report
into the store root. Child processes re-enter this module via
``python -m jepsen_tpu.fleet.chaos <role>``.
"""
from __future__ import annotations

import argparse
import errno
import json
import logging
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

logger = logging.getLogger(__name__)

ENOSPC_FLAG = ".chaos-enospc"
REPORT_NAME = "fleet-chaos.json"
READY_TIMEOUT_S = 60.0
# harness-speed override for the receiver's ENOSPC park window: the
# production 5s default would serialize the whole chaos budget behind
# one injected fault
CHILD_ENOSPC_PARK_S = 0.3


def _planted_history(n_ops: int, seed: int, plant: bool
                     ) -> tuple[list[dict], int | None]:
    """A deterministic register history via the fuzz trial machinery;
    ``plant`` corrupts one acked read so the run's only correct verdict
    is invalid — verdict-parity checks need both polarities."""
    from jepsen_tpu.fuzz.schedule import Schedule
    from jepsen_tpu.fuzz.trial import run_trial
    history = run_trial(Schedule(seed=seed, n_ops=n_ops, concurrency=3))
    planted = None
    if plant:
        for i, op in enumerate(history):
            if i > n_ops // 2 and op.get("type") == "ok" \
                    and op.get("f") == "read" \
                    and op.get("value") is not None:
                op["value"] = op["value"] + 10_000
                planted = i
                break
    return history, planted


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- child roles (python -m jepsen_tpu.fleet.chaos <role> ...) ----------

def _receiver_child(opts) -> None:
    """The ingest receiver as a killable process. ENOSPC injection is a
    flag file so it survives receiver restarts: while
    ``<store>/.chaos-enospc`` exists, every WAL append raises ENOSPC
    and the receiver must shed instead of corrupting."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet import ingest as ingest_mod
    ingest_mod.ENOSPC_PARK_S = CHILD_ENOSPC_PARK_S
    store = Path(opts.store)
    flag = store / ENOSPC_FLAG

    def fault_hook(key, body):
        if flag.exists():
            raise OSError(errno.ENOSPC, "chaos: injected disk full")

    srv = ingest_mod.IngestServer(store, port=opts.port,
                                  registry=telemetry.Registry(),
                                  fault_hook=fault_hook)
    srv.start()
    print(f"READY {srv.port}", flush=True)
    while True:  # killed by the conductor, never exits on its own
        time.sleep(0.5)


def _pool_child(opts) -> None:
    """One leased pool host as a stoppable/killable process. Every
    finalize is appended (fsynced) to ``finals-<host>.jsonl`` — the
    double-check invariant's evidence — stamped with the lease epoch
    the verdict was published under."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.lease import LeaseStore
    from jepsen_tpu.live.daemon import LiveDaemon
    store = Path(opts.store)
    finals = store / f"finals-{opts.host_id}.jsonl"

    def on_final(tr, results):
        row = {"key": tr.label, "host": opts.host_id,
               "epoch": (tr.lease or {}).get("epoch"),
               "valid": tr.last_verdict.get("valid_so_far"),
               "first_anomaly_op":
                   tr.last_verdict.get("first_anomaly_op"),
               "time": time.time()}
        with open(finals, "a", encoding="utf-8") as f:  # durability: fsync
            f.write(json.dumps(row) + "\n")
            f.flush()
            os.fsync(f.fileno())

    lease_store = LeaseStore(store, host_id=opts.host_id,
                             ttl_s=opts.ttl,
                             registry=telemetry.Registry())
    daemon = LiveDaemon(store_root=store, poll_s=opts.poll,
                        check_budget_s=30.0, accelerator="cpu",
                        registry=telemetry.Registry(),
                        on_final=on_final, lease_store=lease_store)
    print("READY 0", flush=True)
    while True:  # killed/stopped by the conductor
        daemon.poll_once()
        time.sleep(opts.poll)


def _child_main(argv) -> int:
    ap = argparse.ArgumentParser(prog="jepsen_tpu.fleet.chaos")
    sub = ap.add_subparsers(dest="role", required=True)
    pr = sub.add_parser("receiver")
    pr.add_argument("--store", required=True)
    pr.add_argument("--port", type=int, default=0)
    pp = sub.add_parser("pool")
    pp.add_argument("--store", required=True)
    pp.add_argument("--host-id", required=True)
    pp.add_argument("--ttl", type=float, default=1.0)
    pp.add_argument("--poll", type=float, default=0.05)
    opts = ap.parse_args(argv)
    if opts.role == "receiver":
        _receiver_child(opts)
    else:
        _pool_child(opts)
    return 0


# -- the conductor ------------------------------------------------------

class _Child:
    """One spawned role process + its READY handshake."""

    def __init__(self, store: Path, role: str, args: list[str],
                 log_name: str):
        self.store = store
        self.role = role
        self.args = args
        self.log_path = store / log_name
        self.proc: subprocess.Popen | None = None
        self.port = 0
        self.stopped = False

    def spawn(self) -> "_Child":
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        log = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "jepsen_tpu.fleet.chaos",
             self.role] + self.args,
            stdout=subprocess.PIPE, stderr=log, env=env, text=True)
        log.close()
        line: list[str] = []

        def read():  # blocking: rpc — child stdout, bounded by join below
            line.append(self.proc.stdout.readline())

        t = threading.Thread(target=read, daemon=True)
        t.start()
        t.join(READY_TIMEOUT_S)
        if not line or not line[0].startswith("READY"):
            self.proc.kill()
            raise RuntimeError(
                f"chaos {self.role} child never came up "
                f"(see {self.log_path})")
        self.port = int(line[0].split()[1])
        self.stopped = False
        return self

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)

    def pause(self) -> None:
        os.kill(self.proc.pid, signal.SIGSTOP)
        self.stopped = True

    def resume(self) -> None:
        if self.stopped and self.proc.poll() is None:
            os.kill(self.proc.pid, signal.SIGCONT)
        self.stopped = False


def _torn_tcp(port: int, key: str) -> None:
    """Half a POST body, then a hard close: the receiver's short read
    must reject the chunk, never absorb the fragment."""
    body = b'{"torn": true}\n' * 16
    zero = "0" * 64
    head = (f"POST /wal/{key} HTTP/1.1\r\nHost: chaos\r\n"
            f"X-Jepsen-Offset: 0\r\nX-Jepsen-Prefix-Sha: {zero}\r\n"
            f"X-Jepsen-Chunk-Sha: {zero}\r\n"
            f"Content-Length: {len(body)}\r\n\r\n").encode()
    try:
        s = socket.create_connection(("127.0.0.1", port), timeout=2.0)
        s.sendall(head + body[: len(body) // 2])
        s.close()
    except OSError:
        pass  # receiver mid-restart: the tear landed even harder


def run_fleet_chaos(store_root, runs: int = 4, n_ops: int = 160,
                    seed: int = 0, lease_ttl_s: float = 1.0,
                    timeout_s: float = 180.0) -> dict:
    """The full harness; returns (and persists) the invariant report.
    ``ok`` is True only when every invariant held."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.ship import Shipper
    from jepsen_tpu.journal import WAL_NAME, Journal

    rng = random.Random(seed)
    root = Path(store_root)
    fleet = root / "fleet-store"
    src = root / "src"
    fleet.mkdir(parents=True, exist_ok=True)
    src.mkdir(parents=True, exist_ok=True)
    port = _free_port()

    receiver = _Child(fleet, "receiver",
                      ["--store", str(fleet), "--port", str(port)],
                      "chaos-receiver.log").spawn()
    pools = [
        _Child(fleet, "pool",
               ["--store", str(fleet), "--host-id", f"pool{i}",
                "--ttl", str(lease_ttl_s)],
               f"chaos-pool{i}.log").spawn()
        for i in (0, 1)
    ]

    cases: dict[str, tuple[list[dict], int | None]] = {}
    threads: list[threading.Thread] = []
    shippers: list[Shipper] = []
    dead_base = f"http://127.0.0.1:{_free_port()}"
    stats = {"receiver_kills": 0, "pool_kills": 0, "pool_stops": 0,
             "torn_tcp": 0, "enospc_windows": 0}

    def producer(run_dir: Path, history: list[dict]) -> None:
        j = Journal(run_dir / WAL_NAME, fsync_interval_s=-1)
        for op in history:
            j.append(op)
            time.sleep(0.002)
        j.close()
        with open(run_dir / "history.jsonl", "w",
                  encoding="utf-8") as f:
            for op in history:
                f.write(json.dumps(op) + "\n")

    try:
        for i in range(runs):
            key = f"c{i:02d}/0"
            rd = src / key
            rd.mkdir(parents=True, exist_ok=True)
            history, planted = _planted_history(
                n_ops, seed=seed * 1000 + i, plant=(i % 2 == 1))
            cases[key] = (history, planted)
            tp = threading.Thread(target=producer, args=(rd, history),
                                  daemon=True)
            # odd runs lead with a dead endpoint: every exchange
            # exercises the failover rotation before reaching the real
            # receiver
            bases = ([dead_base, f"http://127.0.0.1:{port}"]
                     if i % 2 else [f"http://127.0.0.1:{port}"])
            sh = Shipper(rd, bases, poll_s=0.02,
                         registry=telemetry.Registry(),
                         rng=random.Random(rng.getrandbits(32)))
            ts = threading.Thread(
                target=lambda sh=sh: sh.run(timeout_s=timeout_s),
                daemon=True)
            tp.start()
            ts.start()
            threads.extend([tp, ts])
            shippers.append(sh)

        # -- the chaos schedule, while producers ship -------------------
        time.sleep(0.4)
        _torn_tcp(port, "c00/0")
        stats["torn_tcp"] += 1

        (fleet / ENOSPC_FLAG).touch()  # receiver WAL appends now ENOSPC
        stats["enospc_windows"] += 1
        time.sleep(0.5)
        (fleet / ENOSPC_FLAG).unlink(missing_ok=True)

        receiver.kill()  # SIGKILL mid-stream
        stats["receiver_kills"] += 1
        time.sleep(0.3)
        _torn_tcp(port, "c01/0")  # tear against the dead port too
        stats["torn_tcp"] += 1
        receiver.spawn()  # same port + store: cursors rebuild from disk

        # pause one pool host past its TTL: the peer adopts from the
        # restart snapshots; the un-paused host must fence, not
        # double-publish
        pools[0].pause()
        stats["pool_stops"] += 1
        time.sleep(max(2.5 * lease_ttl_s, 1.0))
        pools[0].resume()

        time.sleep(0.5)
        pools[1].kill()  # hard kill: its leases expire, pool0 adopts
        stats["pool_kills"] += 1

        for t in threads:
            t.join(timeout_s)

        # every run settled: a final live-status on the fleet side
        from jepsen_tpu.live.daemon import load_live_status
        deadline = time.monotonic() + timeout_s
        pending = set(cases)
        while pending and time.monotonic() < deadline:
            for key in sorted(pending):
                st = load_live_status(fleet / key)
                if st is not None and st.get("state") == "final":
                    pending.discard(key)
            time.sleep(0.2)
    finally:
        receiver.kill()
        for p in pools:
            p.resume()
            p.kill()

    # -- invariants -----------------------------------------------------
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.journal import read_jsonl_tolerant
    from jepsen_tpu.live.daemon import load_live_status

    finals: dict[str, list[dict]] = {}
    for f in sorted(fleet.glob("finals-*.jsonl")):
        rows, _ = read_jsonl_tolerant(f)
        for row in rows:
            finals.setdefault(str(row.get("key")), []).append(row)

    double_checked = sorted(k for k, rows in finals.items()
                            if len(rows) > 1)
    unsettled = sorted(pending)
    wal_mismatch: list[str] = []
    verdict_mismatch: list[str] = []
    for key, (history, planted) in cases.items():
        if key in unsettled:
            continue
        local_wal = (src / key / "history.wal.jsonl").read_bytes()
        fleet_wal_p = fleet / key / "history.wal.jsonl"
        fleet_wal = (fleet_wal_p.read_bytes()
                     if fleet_wal_p.exists() else b"")
        if fleet_wal != local_wal:
            wal_mismatch.append(key)
        st = load_live_status(fleet / key) or {}
        local = LinearizableChecker(accelerator="cpu").check(
            {}, history, {})
        if st.get("valid_so_far") is not local["valid?"] or (
                planted is not None
                and st.get("first_anomaly_op") != planted):
            verdict_mismatch.append(key)

    report = {
        "version": 1,
        "runs": len(cases),
        "settled": len(cases) - len(unsettled),
        "unsettled": unsettled,
        "double_checked": double_checked,
        "wal_mismatch": wal_mismatch,
        "verdict_mismatch": verdict_mismatch,
        "finals_hosts": {k: [r.get("host") for r in rows]
                         for k, rows in sorted(finals.items())},
        "chaos": stats,
        "ok": not (double_checked or wal_mismatch
                   or verdict_mismatch or unsettled),
    }
    telemetry._atomic_write(root / REPORT_NAME,
                            json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(_child_main(sys.argv[1:]))
