"""The producer-side WAL shipper (``jepsen-tpu ship <run-dir>``).

Rides :class:`jepsen_tpu.journal.WalTailer` — the same cursor the live
daemon tails with locally — so what goes on the wire is exactly the
newline-terminated prefix a local checker would have consumed: torn
final lines stay home until their newline lands.

Recovery is a ladder, cheapest rung first (doc/observability.md "Fleet
plane"):

1. normal append — POST at the tailer's own ``(offset, prefix_sha)``;
2. on 409 the receiver's current token comes back — a **fresh** local
   tailer ``seek()``\\ s to it (hash-verified against the local WAL), so
   a shipper restart or a receiver that is ahead/behind fast-forwards
   without re-sending what already landed;
3. when that seek fails — the local WAL no longer hash-matches what
   the receiver holds (mid-file rewrite, a new run reusing the dir) —
   the only honest move is an explicit offset-0 ``X-Jepsen-Reset`` and
   a full re-ship. Divergence costs a re-send, never a wrong byte.
"""
from __future__ import annotations

import hashlib
import json
import logging
import time
import urllib.error
import urllib.request
from pathlib import Path

from jepsen_tpu.journal import WAL_NAME, WalTailer

logger = logging.getLogger(__name__)

DEFAULT_POLL_S = 0.2
HTTP_TIMEOUT_S = 10.0

_EMPTY_SHA = hashlib.sha256().hexdigest()


class Shipper:
    """Ships one run dir's WAL to an ingest receiver."""

    def __init__(self, run_dir, base_url: str,
                 poll_s: float = DEFAULT_POLL_S):
        self.run_dir = Path(run_dir)
        self.base = base_url.rstrip("/")
        self.key = (self.run_dir.parent.name + "/" + self.run_dir.name)
        self.poll_s = poll_s
        self.tailer = WalTailer(self.run_dir / WAL_NAME)
        self.chunks_sent = 0
        self.bytes_sent = 0
        self.resets = 0
        self.finalized = False

    # -- wire -----------------------------------------------------------

    def _request(self, method: str, path: str, body: bytes = b"",
                 headers: dict | None = None):  # blocking: rpc
        """One HTTP exchange; returns (status, body) or None when the
        receiver is unreachable (the caller's loop retries)."""
        req = urllib.request.Request(self.base + path, data=body,
                                     headers=headers or {},
                                     method=method)
        try:
            with urllib.request.urlopen(
                    req, timeout=HTTP_TIMEOUT_S) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            logger.warning("ship %s: receiver unreachable (%s)",
                           self.key, e)
            return None

    # -- recovery ladder ------------------------------------------------

    def _recover(self, token: dict) -> bool:
        """Repositions at the receiver's token, or resets the receiver
        to 0 when the local WAL diverged from what it holds. Returns
        False only when the receiver is unreachable."""
        fresh = WalTailer(self.run_dir / WAL_NAME)
        offset = int(token.get("offset", 0))
        if offset > 0 and fresh.seek(
                offset, prefix_sha=token.get("prefix_sha")):
            logger.info("ship %s: resumed at receiver offset %d",
                        self.key, offset)
            self.tailer = fresh
            return True
        # local prefix doesn't hash to what the receiver absorbed:
        # re-ingest from zero, explicitly
        got = self._request(
            "POST", "/wal/" + self.key,
            headers={"X-Jepsen-Offset": "0",
                     "X-Jepsen-Prefix-Sha": _EMPTY_SHA,
                     "X-Jepsen-Chunk-Sha": _EMPTY_SHA,
                     "X-Jepsen-Reset": "1"})
        if got is None:
            return False
        self.resets += 1
        self.tailer = WalTailer(self.run_dir / WAL_NAME)
        logger.warning("ship %s: local WAL diverged from receiver; "
                       "reset and re-shipping from 0", self.key)
        return True

    def sync(self) -> bool:
        """Adopts the receiver's current cursor before the first ship —
        a restarted shipper continues instead of colliding."""
        got = self._request("GET", "/wal/" + self.key)
        if got is None or got[0] != 200:
            return False
        token = json.loads(got[1])
        if int(token.get("offset", 0)) == 0:
            return True  # both sides at zero already
        return self._recover(token)

    # -- shipping -------------------------------------------------------

    def step(self) -> int:
        """Ships one WAL poll's worth of complete lines. Returns bytes
        shipped (0: nothing new, or receiver unreachable)."""
        pre_off = self.tailer.offset
        pre_sha = self.tailer.prefix_sha()
        body = self.tailer.poll_bytes()
        if not body:
            return 0
        got = self._request(
            "POST", "/wal/" + self.key, body=body,
            headers={"X-Jepsen-Offset": str(pre_off),
                     "X-Jepsen-Prefix-Sha": pre_sha,
                     "X-Jepsen-Chunk-Sha": self.tailer.prefix_sha()})
        if got is None:
            # undo nothing: the tailer advanced, but recovery re-syncs
            # it from the receiver's token on the next step
            self.tailer = WalTailer(self.run_dir / WAL_NAME)
            self.sync()
            return 0
        status, resp = got
        if status == 204:
            self.chunks_sent += 1
            self.bytes_sent += len(body)
            return len(body)
        if status == 409:
            try:
                token = json.loads(resp)
            except ValueError:
                token = {}
            self._recover(token)
            return 0
        logger.warning("ship %s: receiver said %s", self.key, status)
        return 0

    def _final_path(self) -> Path:
        return self.run_dir / "history.jsonl"

    def finalize(self) -> bool:
        """Ships the authoritative history.jsonl once the run is over
        and the WAL is fully drained."""
        try:
            body = self._final_path().read_bytes()
        except OSError:
            return False
        got = self._request(
            "POST", "/final/" + self.key, body=body,
            headers={"X-Jepsen-Sha256":
                     hashlib.sha256(body).hexdigest()})
        if got is not None and got[0] == 204:
            self.finalized = True
            return True
        return False

    def run(self, timeout_s: float = 300.0) -> bool:
        """Ships until the run completes (history.jsonl shipped) or the
        deadline passes. Returns True when fully shipped + finalized."""
        deadline = time.monotonic() + timeout_s
        self.sync()
        while time.monotonic() < deadline:
            shipped = self.step()
            if shipped:
                continue  # drain hot WALs without sleeping
            if self._final_path().exists():
                # run is over; one last drain for the WAL tail, then
                # ship the authoritative history
                while self.step():
                    pass
                if self.finalize():
                    return True
            time.sleep(self.poll_s)
        return False
