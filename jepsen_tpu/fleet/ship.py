"""The producer-side WAL shipper (``jepsen-tpu ship <run-dir>``).

Rides :class:`jepsen_tpu.journal.WalTailer` — the same cursor the live
daemon tails with locally — so what goes on the wire is exactly the
newline-terminated prefix a local checker would have consumed: torn
final lines stay home until their newline lands.

Recovery is a ladder, cheapest rung first (doc/observability.md "Fleet
plane"):

1. normal append — POST at the tailer's own ``(offset, prefix_sha)``;
2. on 409 the receiver's current token comes back — a **fresh** local
   tailer ``seek()``\\ s to it (hash-verified against the local WAL), so
   a shipper restart or a receiver that is ahead/behind fast-forwards
   without re-sending what already landed;
3. when that seek fails — the local WAL no longer hash-matches what
   the receiver holds (mid-file rewrite, a new run reusing the dir) —
   the only honest move is an explicit offset-0 ``X-Jepsen-Reset`` and
   a full re-ship. Divergence costs a re-send, never a wrong byte.

HA legs (doc/robustness.md "Fleet HA"): the shipper takes a **list**
of receiver endpoints and fails over to the next on every unreachable
exchange — the prefix-sha resume token makes cross-receiver replay
safe by construction (the new receiver's cursor says exactly what it
holds; the ladder above does the rest). While every endpoint is down,
retries ride :func:`jepsen_tpu.utils.backoff_delay` — capped
exponential full jitter, so a rebooting receiver isn't met by a
thundering herd of fixed-cadence shippers. A 429 + Retry-After (the
receiver shedding load honestly) is obeyed verbatim. Every re-sync is
counted (``fleet_ship_resyncs_total{reason}``) so a flapping receiver
is visible in the metrics, not silent.
"""
from __future__ import annotations

import hashlib
import json
import logging
import random
import time
import urllib.error
import urllib.request
from pathlib import Path

from jepsen_tpu import telemetry
from jepsen_tpu.journal import WAL_NAME, WalTailer
from jepsen_tpu.utils import backoff_delay

logger = logging.getLogger(__name__)

DEFAULT_POLL_S = 0.2
HTTP_TIMEOUT_S = 10.0
BACKOFF_BASE_S = 0.05
BACKOFF_CAP_S = 5.0

_EMPTY_SHA = hashlib.sha256().hexdigest()


class Shipper:
    """Ships one run dir's WAL to an ingest receiver (or a failover
    list of them)."""

    def __init__(self, run_dir, base_url, poll_s: float = DEFAULT_POLL_S,
                 registry: telemetry.Registry | None = None,
                 rng: random.Random | None = None):
        self.run_dir = Path(run_dir)
        if isinstance(base_url, str):
            bases = [base_url]
        else:
            bases = list(base_url)
        if not bases:
            raise ValueError("Shipper needs at least one receiver URL")
        self.bases = [b.rstrip("/") for b in bases]
        self._base_i = 0
        self.key = (self.run_dir.parent.name + "/" + self.run_dir.name)
        self.poll_s = poll_s
        self.registry = registry if registry is not None \
            else telemetry.get_registry()
        # rng: seeds the backoff jitter for deterministic tests
        self.rng = rng
        self.tailer = WalTailer(self.run_dir / WAL_NAME)
        self.chunks_sent = 0
        self.bytes_sent = 0
        self.resets = 0
        self.failovers = 0
        self.finalized = False
        self.sealed = False  # receiver says the run is already final
        # consecutive unreachable/shed exchanges: the backoff ladder's
        # rung, reset to 0 by any successful exchange
        self._attempt = 0
        # monotonic deadline a 429's Retry-After told us to wait until
        self._retry_at = 0.0

    @property
    def base(self) -> str:
        return self.bases[self._base_i]

    def _resync(self, reason: str) -> None:
        self.registry.counter(
            "fleet_ship_resyncs_total",
            "shipper cursor re-syncs, by cause (failover, 409 "
            "recovery, divergence reset, shed backoff)",
            labels=("reason",)).inc(reason=reason)

    # -- wire -----------------------------------------------------------

    def _request(self, method: str, path: str, body: bytes = b"",
                 headers: dict | None = None):  # blocking: rpc
        """One HTTP exchange against the current endpoint; returns
        (status, body, headers) or None when it is unreachable (the
        caller fails over / backs off)."""
        req = urllib.request.Request(self.base + path, data=body,
                                     headers=headers or {},
                                     method=method)
        try:
            with urllib.request.urlopen(
                    req, timeout=HTTP_TIMEOUT_S) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers or {})
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            logger.warning("ship %s: receiver %s unreachable (%s)",
                           self.key, self.base, e)
            return None

    def _failover(self) -> None:
        """Rotates to the next receiver endpoint (no-op with one). The
        resume-token handshake on the next exchange re-syncs the cursor
        against whatever the new receiver actually holds."""
        if len(self.bases) > 1:
            self._base_i = (self._base_i + 1) % len(self.bases)
            self.failovers += 1
            logger.warning("ship %s: failing over to %s", self.key,
                           self.base)
        self._resync("failover")

    def _on_shed(self, resp_body: bytes, headers: dict) -> None:
        """Obeys a 429's Retry-After verbatim: the receiver is shedding
        honestly and told us exactly when to come back."""
        wait = None
        try:
            wait = float(headers.get("Retry-After", ""))
        except (TypeError, ValueError):
            try:
                wait = float(json.loads(resp_body).get("retry_after"))
            except (TypeError, ValueError):
                pass
        if wait is None or wait < 0:
            wait = backoff_delay(self._attempt, BACKOFF_BASE_S,
                                 BACKOFF_CAP_S, self.rng)
        self._retry_at = time.monotonic() + wait
        self._attempt += 1
        self._resync("shed")
        logger.info("ship %s: receiver shedding; retrying in %.3gs",
                    self.key, wait)

    # -- recovery ladder ------------------------------------------------

    def _recover(self, token: dict) -> bool:
        """Repositions at the receiver's token, or resets the receiver
        to 0 when the local WAL diverged from what it holds. Returns
        False only when the receiver is unreachable."""
        if token.get("reason") == "finalized":
            # the receiver already holds the authoritative history for
            # this run (a finals race we lost, or a re-ship of a done
            # run): the WAL is sealed, nothing left to ship
            self.sealed = True
            return True
        fresh = WalTailer(self.run_dir / WAL_NAME)
        offset = int(token.get("offset", 0))
        if offset > 0 and fresh.seek(
                offset, prefix_sha=token.get("prefix_sha")):
            logger.info("ship %s: resumed at receiver offset %d",
                        self.key, offset)
            self.tailer = fresh
            self._resync("recover")
            return True
        if offset == 0:
            # the receiver holds nothing (a failover target's fresh
            # store): just restart the local cursor, no reset needed
            self.tailer = fresh
            self._resync("recover")
            return True
        # local prefix doesn't hash to what the receiver absorbed:
        # re-ingest from zero, explicitly
        got = self._request(
            "POST", "/wal/" + self.key,
            headers={"X-Jepsen-Offset": "0",
                     "X-Jepsen-Prefix-Sha": _EMPTY_SHA,
                     "X-Jepsen-Chunk-Sha": _EMPTY_SHA,
                     "X-Jepsen-Reset": "1"})
        if got is None:
            return False
        self.resets += 1
        self._resync("reset")
        self.tailer = WalTailer(self.run_dir / WAL_NAME)
        logger.warning("ship %s: local WAL diverged from receiver; "
                       "reset and re-shipping from 0", self.key)
        return True

    def sync(self) -> bool:
        """Adopts the receiver's current cursor before the first ship —
        a restarted shipper continues instead of colliding."""
        got = self._request("GET", "/wal/" + self.key)
        if got is None or got[0] != 200:
            return False
        token = json.loads(got[1])
        if int(token.get("offset", 0)) == 0:
            self.tailer = WalTailer(self.run_dir / WAL_NAME)
            return True  # receiver at zero: ship from the top
        return self._recover(token)

    # -- shipping -------------------------------------------------------

    def step(self) -> int:
        """Ships one WAL poll's worth of complete lines. Returns bytes
        shipped (0: nothing new, receiver unreachable/shedding, or the
        run is sealed)."""
        if self.sealed or time.monotonic() < self._retry_at:
            return 0
        pre_off = self.tailer.offset
        pre_sha = self.tailer.prefix_sha()
        body = self.tailer.poll_bytes()
        if not body:
            return 0
        got = self._request(
            "POST", "/wal/" + self.key, body=body,
            headers={"X-Jepsen-Offset": str(pre_off),
                     "X-Jepsen-Prefix-Sha": pre_sha,
                     "X-Jepsen-Chunk-Sha": self.tailer.prefix_sha()})
        if got is None:
            # the tailer advanced past bytes the receiver never saw:
            # fail over, and re-sync from the (new) receiver's token
            self._attempt += 1
            self._failover()
            self.tailer = WalTailer(self.run_dir / WAL_NAME)
            self.sync()
            return 0
        status, resp, headers = got
        if status == 204:
            self._attempt = 0
            self.chunks_sent += 1
            self.bytes_sent += len(body)
            return len(body)
        if status == 429:
            # un-absorbed: rewind to re-poll the same bytes later
            self._on_shed(resp, headers)
            fresh = WalTailer(self.run_dir / WAL_NAME)
            if not fresh.seek(pre_off, prefix_sha=pre_sha):
                fresh = WalTailer(self.run_dir / WAL_NAME)
            self.tailer = fresh
            return 0
        if status == 409:
            try:
                token = json.loads(resp)
            except ValueError:
                token = {}
            self._recover(token)
            return 0
        logger.warning("ship %s: receiver said %s", self.key, status)
        return 0

    def _final_path(self) -> Path:
        return self.run_dir / "history.jsonl"

    def finalize(self) -> bool:
        """Ships the authoritative history.jsonl once the run is over
        and the WAL is fully drained."""
        try:
            body = self._final_path().read_bytes()
        except OSError:
            return False
        got = self._request(
            "POST", "/final/" + self.key, body=body,
            headers={"X-Jepsen-Sha256":
                     hashlib.sha256(body).hexdigest()})
        if got is None:
            self._attempt += 1
            self._failover()
            return False
        status, resp, headers = got
        if status == 204:
            self.finalized = True
            return True
        if status == 429:
            self._on_shed(resp, headers)
        elif status == 409:
            # finals race lost: someone else's (byte-different) final
            # is installed — ours will never land, stop trying
            self.sealed = True
            logger.warning("ship %s: final conflicts with an installed "
                           "history; receiver's wins", self.key)
        return False

    def _idle_delay(self) -> float:
        """The loop's sleep: poll cadence when healthy, the jittered
        backoff ladder while the receiver is unreachable or shedding."""
        if self._attempt == 0:
            return self.poll_s
        wait = backoff_delay(self._attempt - 1, BACKOFF_BASE_S,
                             BACKOFF_CAP_S, self.rng)
        until_retry = self._retry_at - time.monotonic()
        return max(wait, until_retry, 0.0)

    def run(self, timeout_s: float = 300.0) -> bool:
        """Ships until the run completes (history.jsonl shipped) or the
        deadline passes. Returns True when fully shipped + finalized."""
        deadline = time.monotonic() + timeout_s
        self.sync()
        while time.monotonic() < deadline:
            shipped = self.step()
            if shipped:
                continue  # drain hot WALs without sleeping
            if self.sealed:
                return True
            if self._final_path().exists():
                # run is over; one last drain for the WAL tail, then
                # ship the authoritative history
                while self.step():
                    pass
                if self.finalize() or self.sealed:
                    return True
            time.sleep(min(self._idle_delay(),
                           max(0.0, deadline - time.monotonic())))
        return False
