"""The ingest plane: an HTTP receiver for shipped WAL bytes.

The protocol is the WAL streamer's divergence-checked resume contract
(doc/robustness.md), lifted onto the wire. The resume token IS the
tailer's cursor: ``(offset, prefix_sha256)``. Every ``POST /wal`` names
the offset it believes it is appending at and the sha256 of every byte
before it; the receiver accepts only when both match its own cursor, so

* a **replayed** chunk (stale offset) bounces with 409 + the current
  token — the shipper fast-forwards, nothing is double-absorbed;
* a **diverged** shipment (same offset, different prefix hash — the
  producer's WAL was rewritten, or a different run reuses the name)
  bounces the same way, and the shipper's only way back in is an
  explicit offset-0 reset;
* a **gap** (offset beyond the receiver's) bounces so a shipper that
  lost its receiver (receiver restart, wiped store) re-ships from the
  receiver's real cursor instead of leaving a hole.

The chunk itself carries ``X-Jepsen-Chunk-Sha`` — the running digest
*after* the append — verified before any byte hits disk, so a corrupt
body is dropped with no cursor movement.

Accepted bytes land in ``<store>/<name>/<ts>/history.wal.jsonl`` — the
exact layout core.run writes locally — so the live daemon's discovery,
tailing, snapshots and verdicts work unchanged on shipped runs, and
``analyze`` on the receiver's copy is bit-identical to the producer's.
"""
from __future__ import annotations

import errno
import hashlib
import json
import logging
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from jepsen_tpu import telemetry
from jepsen_tpu.history_ir import ingest as ingest_mod
from jepsen_tpu.journal import WAL_NAME
from jepsen_tpu.utils import join_noisy

logger = logging.getLogger(__name__)

# one path segment: excludes "", ".", "..", hidden names and separators
_SEGMENT = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")

MAX_CHUNK_BYTES = 32 << 20  # absurdly large for one WAL poll

# honest load shedding (doc/robustness.md "Fleet HA"): the Retry-After
# a 429 carries, and how long an ENOSPC'd run stays parked before the
# next append re-probes the disk
RETRY_AFTER_S = 1.0
ENOSPC_PARK_S = 5.0


def disk_free_mb(path) -> float | None:
    """Free megabytes on ``path``'s filesystem, or None when the probe
    itself fails (the caller must not shed on a broken probe)."""
    try:
        st = os.statvfs(str(path))
    except (OSError, AttributeError):
        return None
    return st.f_bavail * st.f_frsize / (1 << 20)


def _atomic_write_bytes(path: Path, body: bytes) -> None:
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as f:
        f.write(body)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class _IngestHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # a whole fleet reconnecting at once (receiver restart, network
    # partition healing) is the normal case, not a burst to shed: the
    # stdlib's 5-deep listen backlog RSTs the stragglers and every one
    # of them walks the recovery ladder
    request_queue_size = 128


class IngestServer:
    """Receives shipped WALs into a local store root.

    Per-run cursor state lives in ``_runs[name/ts] = {"offset", "sha",
    "bytes"}`` under one lock — verification + append are serialized,
    which is what makes the accept/reject decision race-free when two
    shippers (a producer restart overlapping its predecessor) target
    the same run. A cursor missing from ``_runs`` (receiver restart)
    is rebuilt by hashing the WAL already on disk, so shippers resume
    against a restarted receiver without re-sending history.

    Verified chunks are handed STRAIGHT to the native ingest spine
    (history_ir.ingest.parse_wal_chunk) while the bytes are still in
    memory — a co-located consumer registered via ``feed`` gets the
    parsed op dicts without ever re-reading the file the tailer path
    would have to. A per-run carry buffer stitches lines split across
    chunk boundaries; its cursor advances exactly as the tailer's
    would, so a consumer that later falls back to disk-tailing resumes
    at the same op."""

    def __init__(self, store_root, host: str = "127.0.0.1",
                 port: int = 0,
                 registry: telemetry.Registry | None = None,
                 feed=None, disk_headroom_mb: float = 0.0,
                 pressure=None, fault_hook=None):
        self.store_root = Path(store_root)
        self.registry = registry if registry is not None \
            else telemetry.get_registry()
        # feed(key, ops): parsed-op push for a co-located consumer
        self.feed = feed
        # honest backpressure (doc/robustness.md "Fleet HA"):
        # disk_headroom_mb > 0 sheds chunks with 429 + Retry-After when
        # the store's filesystem drops below that free space;
        # pressure() -> seconds | None is the pool's aggregate-lag hook
        # (non-None = shed, telling shippers how long to back off)
        self.disk_headroom_mb = float(disk_headroom_mb or 0.0)
        self.pressure = pressure
        # fault_hook(key, body): test seam — called right before the
        # WAL append so the chaos harness can inject ENOSPC (an OSError
        # it raises takes the exact same park-and-bounce path a real
        # disk-full does)
        self.fault_hook = fault_hook
        self._runs: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._httpd = _IngestHTTPServer((host, port),
                                        self._make_handler())
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # -- cursor state ---------------------------------------------------

    def _wal_path(self, key: str) -> Path:
        return self.store_root / key / WAL_NAME

    def _cursor(self, key: str) -> dict:
        """The run's cursor, creating it from the on-disk WAL when this
        receiver has never seen the run (fresh run OR receiver
        restart). Caller holds ``_lock``."""
        st = self._runs.get(key)
        if st is None:
            st = {"offset": 0, "sha": hashlib.sha256(), "bytes": 0,
                  "carry": b"", "ops": 0, "torn": 0}
            p = self._wal_path(key)
            try:
                with open(p, "rb") as f:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        st["sha"].update(chunk)
                        st["offset"] += len(chunk)
            except OSError:
                pass  # no WAL yet: cursor starts at 0
            # a receiver restart must also remember the run was sealed:
            # an installed history.jsonl IS the final (finals-race 409s
            # survive the restart)
            fp = self.store_root / key / "history.jsonl"
            try:
                final_body = fp.read_bytes()
            except OSError:
                final_body = None
            if final_body is not None:
                st["final"] = True
                st["final_sha"] = hashlib.sha256(
                    final_body).hexdigest()
            self._runs[key] = st
        return st

    def _reject(self, reason: str) -> None:
        self.registry.counter(
            "fleet_ingest_rejected_total",
            "shipped chunks bounced by resume-token verification",
            labels=("reason",)).inc(reason=reason)

    def _shed(self, reason: str, retry_after_s: float) -> dict:
        """A 429 verdict: the chunk is bounced un-absorbed (no cursor
        movement, no disk write) with an honest Retry-After."""
        self.registry.counter(
            "fleet_ingest_shed_total",
            "chunks shed with 429 + Retry-After under pressure",
            labels=("reason",)).inc(reason=reason)
        return {"shed": reason, "retry_after": retry_after_s}

    def overload(self):  # -> dict | None
        """The receiver-wide shed verdict, or None when healthy: disk
        headroom below the floor, or the pool's aggregate-lag hook
        asking for backoff. Checked before any per-chunk work."""
        if self.disk_headroom_mb > 0:
            free = disk_free_mb(self.store_root)
            if free is not None and free < self.disk_headroom_mb:
                return self._shed("headroom", RETRY_AFTER_S)
        if self.pressure is not None:
            try:
                wait = self.pressure()
            except Exception:  # noqa: BLE001 — a broken hook must not shed
                logger.exception("fleet ingest: pressure hook failed")
                wait = None
            if wait is not None:
                return self._shed("lag", float(wait))
        return None

    # -- protocol ops (handler threads) ---------------------------------

    def token(self, key: str) -> dict:  # owner: worker
        with self._lock:
            st = self._cursor(key)
            return {"offset": st["offset"],
                    "prefix_sha": st["sha"].hexdigest()}

    def append_chunk(self, key: str, offset: int, prefix_sha: str,
                     chunk_sha: str, body: bytes,
                     reset: bool = False):  # owner: worker
        """Verifies the resume token + chunk digest and appends.
        Returns None on accept, or the current-token dict the shipper
        needs to recover (409 payload)."""
        with self._lock:
            st = self._cursor(key)
            if st.get("final"):
                # finals race: once the authoritative history.jsonl is
                # installed the run's WAL is sealed — a late chunk gets
                # 409 so the loser knows, and the history stays the one
                # digest-valid document
                self._reject("finalized")
                out = {"offset": st["offset"],
                       "prefix_sha": st["sha"].hexdigest()}
                out["reason"] = "finalized"
                return out
            parked = st.get("parked_until", 0.0) - time.monotonic()
            if parked > 0:
                # ENOSPC park: bounce without touching the disk until
                # the park lapses (then the append itself re-probes)
                return self._shed("enospc", parked)
            if reset:
                if offset != 0:
                    self._reject("bad-reset")
                    return {"offset": st["offset"],
                            "prefix_sha": st["sha"].hexdigest()}
                # explicit re-ingest-from-zero: the producer's WAL was
                # rewritten out from under its shipper (seek() failed
                # locally) — truncate and start over
                p = self._wal_path(key)
                p.parent.mkdir(parents=True, exist_ok=True)
                with open(p, "wb"):
                    pass
                st["offset"] = 0
                st["sha"] = hashlib.sha256()
                st["carry"] = b""
                st["ops"] = 0
                st["torn"] = 0
                logger.warning("fleet ingest: %s reset to offset 0",
                               key)
            if offset != st["offset"]:
                self._reject("stale-token" if offset < st["offset"]
                             else "gap")
                return {"offset": st["offset"],
                        "prefix_sha": st["sha"].hexdigest()}
            if prefix_sha != st["sha"].hexdigest():
                self._reject("diverged")
                return {"offset": st["offset"],
                        "prefix_sha": st["sha"].hexdigest()}
            sha = st["sha"].copy()
            sha.update(body)
            if chunk_sha != sha.hexdigest():
                # corrupt in flight: no cursor movement, no disk write
                self._reject("bad-chunk")
                return {"offset": st["offset"],
                        "prefix_sha": st["sha"].hexdigest()}
            p = self._wal_path(key)
            p.parent.mkdir(parents=True, exist_ok=True)
            try:
                if self.fault_hook is not None:
                    self.fault_hook(key, body)
                with open(p, "ab") as f:
                    f.write(body)
                    f.flush()
            except OSError as e:
                # roll back any partial append so the on-disk WAL still
                # ends exactly at the advertised cursor — a half-landed
                # chunk must bounce, never corrupt
                try:
                    if p.exists() and p.stat().st_size > st["offset"]:
                        os.truncate(p, st["offset"])
                except OSError:
                    logger.exception("fleet ingest: couldn't roll back "
                                     "partial append for %s", key)
                if e.errno == errno.ENOSPC:
                    # disk full is a weather condition, not a fatal
                    # fault: park the run and shed honestly; the park's
                    # lapse re-probes by just trying the next append
                    st["parked_until"] = time.monotonic() + ENOSPC_PARK_S
                    logger.warning("fleet ingest: ENOSPC appending %s; "
                                   "parked %.3gs", key, ENOSPC_PARK_S)
                    return self._shed("enospc", ENOSPC_PARK_S)
                logger.exception("fleet ingest: append failed for %s",
                                 key)
                return self._shed("io-error", RETRY_AFTER_S)
            st["parked_until"] = 0.0
            st["sha"] = sha
            st["offset"] += len(body)
            st["bytes"] += len(body)
            self._feed_chunk(key, st, body)
            self.registry.counter(
                "fleet_ingest_bytes_total",
                "WAL bytes accepted over the ingest plane"
                ).inc(len(body))
            self.registry.counter(
                "fleet_ingest_chunks_total",
                "WAL chunks accepted over the ingest plane").inc()
            return None

    def _feed_chunk(self, key: str, st: dict, body: bytes) -> None:
        """Parses the just-verified bytes through the native ingest
        spine while they're still in memory. The carry buffer holds the
        unterminated tail a chunk boundary split, so every op parses
        exactly once and in order; parsed counts feed the status plane
        and the optional ``feed`` consumer gets the op dicts directly
        (no disk re-read). Counts restart with the process — a
        late-attaching consumer seeds itself from the on-disk WAL.
        Caller holds ``_lock``."""
        buf = st["carry"] + body
        try:
            with ingest_mod.ingest_burst():
                ops, consumed, torn, _trunc = ingest_mod.parse_wal_chunk(
                    buf, final=False)
        except Exception:  # noqa: BLE001 — parse never bounces a chunk
            logger.exception("fleet ingest: post-append parse failed "
                             "for %s", key)
            st["carry"] = b""
            return
        st["carry"] = buf[consumed:]
        st["ops"] += len(ops)
        st["torn"] += torn
        if ops:
            self.registry.counter(
                "fleet_ingest_ops_total",
                "ops parsed straight off verified ingest chunks").inc(
                len(ops))
        if self.feed is not None and ops:
            try:
                self.feed(key, ops)
            except Exception:  # noqa: BLE001 — consumer bugs stay local
                logger.exception("fleet ingest: feed consumer failed "
                                 "for %s", key)

    def finalize_run(self, key: str, sha256: str,
                     body: bytes) -> str:  # owner: worker
        """Atomically installs the authoritative ``history.jsonl`` —
        the producer's run is over. Digest-checked like every other
        byte on this wire. Returns ``"ok"`` (installed, or an
        idempotent byte-identical replay), ``"conflict"`` (already
        finalized with DIFFERENT bytes — the 409 loser of the finals
        race), ``"bad"`` (digest mismatch), or ``"shed"`` (disk
        refused; retry later). Serialized under the run lock so a
        final racing a late chunk resolves deterministically."""
        if hashlib.sha256(body).hexdigest() != sha256:
            self._reject("bad-chunk")
            return "bad"
        with self._lock:
            st = self._cursor(key)
            if st.get("final"):
                if st.get("final_sha") == sha256:
                    return "ok"  # idempotent re-send of the same final
                self._reject("finalized")
                return "conflict"
            d = self.store_root / key
            try:
                d.mkdir(parents=True, exist_ok=True)
                _atomic_write_bytes(d / "history.jsonl", body)
            except OSError as e:
                if e.errno == errno.ENOSPC:
                    st["parked_until"] = (time.monotonic()
                                          + ENOSPC_PARK_S)
                    self._shed("enospc", ENOSPC_PARK_S)
                    return "shed"
                logger.exception("fleet ingest: final install failed "
                                 "for %s", key)
                return "shed"
            st["final"] = True
            st["final_sha"] = sha256
            return "ok"

    def ingest_stats(self) -> dict:
        """(bytes-by-run, total) snapshot for the status plane."""
        with self._lock:
            return {k: st["bytes"] for k, st in self._runs.items()}

    def parse_stats(self) -> dict:
        """Per-run ``{"ops", "torn"}`` parsed straight off verified
        chunks (this process's lifetime)."""
        with self._lock:
            return {k: {"ops": st["ops"], "torn": st["torn"]}
                    for k, st in self._runs.items()}

    # -- http plumbing --------------------------------------------------

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: N802
                logger.debug("ingest: " + fmt, *args)

            def _send(self, code: int, body: bytes = b"",
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                if body:
                    self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _run_key(self) -> str | None:
                parts = self.path.split("/")
                # "/wal/<name>/<ts>" -> ["", "wal", name, ts]
                if len(parts) != 4:
                    return None
                name, ts = parts[2], parts[3]
                if not (_SEGMENT.match(name) and _SEGMENT.match(ts)):
                    return None
                return name + "/" + ts

            def _body(self) -> bytes | None:
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    return None
                if n < 0 or n > MAX_CHUNK_BYTES:
                    return None
                return self.rfile.read(n)

            def do_GET(self) -> None:  # noqa: N802  # owner: worker
                if self.path.startswith("/wal/"):
                    key = self._run_key()
                    if key is None:
                        self._send(404)
                        return
                    self._send(200, json.dumps(
                        server.token(key)).encode())
                elif self.path == "/fleet-status.json":
                    try:
                        data = (server.store_root
                                / "fleet-status.json").read_bytes()
                    except OSError:
                        self._send(404)
                        return
                    self._send(200, data)
                elif self.path == "/metrics":
                    self._send(200,
                               server.registry.render_prom().encode(),
                               ctype="text/plain; version=0.0.4")
                else:
                    self._send(404)

            def do_POST(self) -> None:  # noqa: N802  # owner: worker
                key = self._run_key()
                body = self._body()
                if key is None or body is None:
                    self._send(400)
                    return
                h = self.headers
                if self.path.startswith("/wal/"):
                    try:
                        offset = int(h.get("X-Jepsen-Offset", ""))
                    except ValueError:
                        self._send(400)
                        return
                    current = server.overload()
                    if current is None:
                        current = server.append_chunk(
                            key, offset,
                            h.get("X-Jepsen-Prefix-Sha", ""),
                            h.get("X-Jepsen-Chunk-Sha", ""), body,
                            reset=h.get("X-Jepsen-Reset") == "1")
                    if current is None:
                        self._send(204)
                    elif "shed" in current:
                        self._send_retry_after(current)
                    else:
                        self._send(409,
                                   json.dumps(current).encode())
                elif self.path.startswith("/final/"):
                    got = server.finalize_run(
                        key, h.get("X-Jepsen-Sha256", ""), body)
                    if got == "ok":
                        self._send(204)
                    elif got == "conflict":
                        self._send(409, json.dumps(
                            {"reason": "finalized"}).encode())
                    elif got == "shed":
                        self._send_retry_after(
                            {"shed": "enospc",
                             "retry_after": RETRY_AFTER_S})
                    else:
                        self._send(400)
                else:
                    self._send(404)

            def _send_retry_after(self, verdict: dict) -> None:
                body = json.dumps(verdict).encode()
                self.send_response(429)
                self.send_header("Retry-After", "%.3f" % max(
                    0.0, float(verdict.get("retry_after", 0.0))))
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        return Handler

    def start(self) -> "IngestServer":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="fleet-ingest", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread is not None:
            join_noisy(self._thread, "fleet ingest server",
                       max_wait_s=10.0)
            self._thread = None
        self._httpd.server_close()
