"""The ingest plane: an HTTP receiver for shipped WAL bytes.

The protocol is the WAL streamer's divergence-checked resume contract
(doc/robustness.md), lifted onto the wire. The resume token IS the
tailer's cursor: ``(offset, prefix_sha256)``. Every ``POST /wal`` names
the offset it believes it is appending at and the sha256 of every byte
before it; the receiver accepts only when both match its own cursor, so

* a **replayed** chunk (stale offset) bounces with 409 + the current
  token — the shipper fast-forwards, nothing is double-absorbed;
* a **diverged** shipment (same offset, different prefix hash — the
  producer's WAL was rewritten, or a different run reuses the name)
  bounces the same way, and the shipper's only way back in is an
  explicit offset-0 reset;
* a **gap** (offset beyond the receiver's) bounces so a shipper that
  lost its receiver (receiver restart, wiped store) re-ships from the
  receiver's real cursor instead of leaving a hole.

The chunk itself carries ``X-Jepsen-Chunk-Sha`` — the running digest
*after* the append — verified before any byte hits disk, so a corrupt
body is dropped with no cursor movement.

Accepted bytes land in ``<store>/<name>/<ts>/history.wal.jsonl`` — the
exact layout core.run writes locally — so the live daemon's discovery,
tailing, snapshots and verdicts work unchanged on shipped runs, and
``analyze`` on the receiver's copy is bit-identical to the producer's.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from jepsen_tpu import telemetry
from jepsen_tpu.history_ir import ingest as ingest_mod
from jepsen_tpu.journal import WAL_NAME
from jepsen_tpu.utils import join_noisy

logger = logging.getLogger(__name__)

# one path segment: excludes "", ".", "..", hidden names and separators
_SEGMENT = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")

MAX_CHUNK_BYTES = 32 << 20  # absurdly large for one WAL poll


def _atomic_write_bytes(path: Path, body: bytes) -> None:
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as f:
        f.write(body)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class _IngestHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # a whole fleet reconnecting at once (receiver restart, network
    # partition healing) is the normal case, not a burst to shed: the
    # stdlib's 5-deep listen backlog RSTs the stragglers and every one
    # of them walks the recovery ladder
    request_queue_size = 128


class IngestServer:
    """Receives shipped WALs into a local store root.

    Per-run cursor state lives in ``_runs[name/ts] = {"offset", "sha",
    "bytes"}`` under one lock — verification + append are serialized,
    which is what makes the accept/reject decision race-free when two
    shippers (a producer restart overlapping its predecessor) target
    the same run. A cursor missing from ``_runs`` (receiver restart)
    is rebuilt by hashing the WAL already on disk, so shippers resume
    against a restarted receiver without re-sending history.

    Verified chunks are handed STRAIGHT to the native ingest spine
    (history_ir.ingest.parse_wal_chunk) while the bytes are still in
    memory — a co-located consumer registered via ``feed`` gets the
    parsed op dicts without ever re-reading the file the tailer path
    would have to. A per-run carry buffer stitches lines split across
    chunk boundaries; its cursor advances exactly as the tailer's
    would, so a consumer that later falls back to disk-tailing resumes
    at the same op."""

    def __init__(self, store_root, host: str = "127.0.0.1",
                 port: int = 0,
                 registry: telemetry.Registry | None = None,
                 feed=None):
        self.store_root = Path(store_root)
        self.registry = registry if registry is not None \
            else telemetry.get_registry()
        # feed(key, ops): parsed-op push for a co-located consumer
        self.feed = feed
        self._runs: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._httpd = _IngestHTTPServer((host, port),
                                        self._make_handler())
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # -- cursor state ---------------------------------------------------

    def _wal_path(self, key: str) -> Path:
        return self.store_root / key / WAL_NAME

    def _cursor(self, key: str) -> dict:
        """The run's cursor, creating it from the on-disk WAL when this
        receiver has never seen the run (fresh run OR receiver
        restart). Caller holds ``_lock``."""
        st = self._runs.get(key)
        if st is None:
            st = {"offset": 0, "sha": hashlib.sha256(), "bytes": 0,
                  "carry": b"", "ops": 0, "torn": 0}
            p = self._wal_path(key)
            try:
                with open(p, "rb") as f:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        st["sha"].update(chunk)
                        st["offset"] += len(chunk)
            except OSError:
                pass  # no WAL yet: cursor starts at 0
            self._runs[key] = st
        return st

    def _reject(self, reason: str) -> None:
        self.registry.counter(
            "fleet_ingest_rejected_total",
            "shipped chunks bounced by resume-token verification",
            labels=("reason",)).inc(reason=reason)

    # -- protocol ops (handler threads) ---------------------------------

    def token(self, key: str) -> dict:  # owner: worker
        with self._lock:
            st = self._cursor(key)
            return {"offset": st["offset"],
                    "prefix_sha": st["sha"].hexdigest()}

    def append_chunk(self, key: str, offset: int, prefix_sha: str,
                     chunk_sha: str, body: bytes,
                     reset: bool = False):  # owner: worker
        """Verifies the resume token + chunk digest and appends.
        Returns None on accept, or the current-token dict the shipper
        needs to recover (409 payload)."""
        with self._lock:
            st = self._cursor(key)
            if reset:
                if offset != 0:
                    self._reject("bad-reset")
                    return {"offset": st["offset"],
                            "prefix_sha": st["sha"].hexdigest()}
                # explicit re-ingest-from-zero: the producer's WAL was
                # rewritten out from under its shipper (seek() failed
                # locally) — truncate and start over
                p = self._wal_path(key)
                p.parent.mkdir(parents=True, exist_ok=True)
                with open(p, "wb"):
                    pass
                st["offset"] = 0
                st["sha"] = hashlib.sha256()
                st["carry"] = b""
                st["ops"] = 0
                st["torn"] = 0
                logger.warning("fleet ingest: %s reset to offset 0",
                               key)
            if offset != st["offset"]:
                self._reject("stale-token" if offset < st["offset"]
                             else "gap")
                return {"offset": st["offset"],
                        "prefix_sha": st["sha"].hexdigest()}
            if prefix_sha != st["sha"].hexdigest():
                self._reject("diverged")
                return {"offset": st["offset"],
                        "prefix_sha": st["sha"].hexdigest()}
            sha = st["sha"].copy()
            sha.update(body)
            if chunk_sha != sha.hexdigest():
                # corrupt in flight: no cursor movement, no disk write
                self._reject("bad-chunk")
                return {"offset": st["offset"],
                        "prefix_sha": st["sha"].hexdigest()}
            p = self._wal_path(key)
            p.parent.mkdir(parents=True, exist_ok=True)
            with open(p, "ab") as f:
                f.write(body)
                f.flush()
            st["sha"] = sha
            st["offset"] += len(body)
            st["bytes"] += len(body)
            self._feed_chunk(key, st, body)
            self.registry.counter(
                "fleet_ingest_bytes_total",
                "WAL bytes accepted over the ingest plane"
                ).inc(len(body))
            self.registry.counter(
                "fleet_ingest_chunks_total",
                "WAL chunks accepted over the ingest plane").inc()
            return None

    def _feed_chunk(self, key: str, st: dict, body: bytes) -> None:
        """Parses the just-verified bytes through the native ingest
        spine while they're still in memory. The carry buffer holds the
        unterminated tail a chunk boundary split, so every op parses
        exactly once and in order; parsed counts feed the status plane
        and the optional ``feed`` consumer gets the op dicts directly
        (no disk re-read). Counts restart with the process — a
        late-attaching consumer seeds itself from the on-disk WAL.
        Caller holds ``_lock``."""
        buf = st["carry"] + body
        try:
            with ingest_mod.ingest_burst():
                ops, consumed, torn, _trunc = ingest_mod.parse_wal_chunk(
                    buf, final=False)
        except Exception:  # noqa: BLE001 — parse never bounces a chunk
            logger.exception("fleet ingest: post-append parse failed "
                             "for %s", key)
            st["carry"] = b""
            return
        st["carry"] = buf[consumed:]
        st["ops"] += len(ops)
        st["torn"] += torn
        if ops:
            self.registry.counter(
                "fleet_ingest_ops_total",
                "ops parsed straight off verified ingest chunks").inc(
                len(ops))
        if self.feed is not None and ops:
            try:
                self.feed(key, ops)
            except Exception:  # noqa: BLE001 — consumer bugs stay local
                logger.exception("fleet ingest: feed consumer failed "
                                 "for %s", key)

    def finalize_run(self, key: str, sha256: str,
                     body: bytes) -> bool:  # owner: worker
        """Atomically installs the authoritative ``history.jsonl`` —
        the producer's run is over. Digest-checked like every other
        byte on this wire."""
        if hashlib.sha256(body).hexdigest() != sha256:
            self._reject("bad-chunk")
            return False
        d = self.store_root / key
        d.mkdir(parents=True, exist_ok=True)
        _atomic_write_bytes(d / "history.jsonl", body)
        return True

    def ingest_stats(self) -> dict:
        """(bytes-by-run, total) snapshot for the status plane."""
        with self._lock:
            return {k: st["bytes"] for k, st in self._runs.items()}

    def parse_stats(self) -> dict:
        """Per-run ``{"ops", "torn"}`` parsed straight off verified
        chunks (this process's lifetime)."""
        with self._lock:
            return {k: {"ops": st["ops"], "torn": st["torn"]}
                    for k, st in self._runs.items()}

    # -- http plumbing --------------------------------------------------

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: N802
                logger.debug("ingest: " + fmt, *args)

            def _send(self, code: int, body: bytes = b"",
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                if body:
                    self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _run_key(self) -> str | None:
                parts = self.path.split("/")
                # "/wal/<name>/<ts>" -> ["", "wal", name, ts]
                if len(parts) != 4:
                    return None
                name, ts = parts[2], parts[3]
                if not (_SEGMENT.match(name) and _SEGMENT.match(ts)):
                    return None
                return name + "/" + ts

            def _body(self) -> bytes | None:
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    return None
                if n < 0 or n > MAX_CHUNK_BYTES:
                    return None
                return self.rfile.read(n)

            def do_GET(self) -> None:  # noqa: N802  # owner: worker
                if self.path.startswith("/wal/"):
                    key = self._run_key()
                    if key is None:
                        self._send(404)
                        return
                    self._send(200, json.dumps(
                        server.token(key)).encode())
                elif self.path == "/fleet-status.json":
                    try:
                        data = (server.store_root
                                / "fleet-status.json").read_bytes()
                    except OSError:
                        self._send(404)
                        return
                    self._send(200, data)
                elif self.path == "/metrics":
                    self._send(200,
                               server.registry.render_prom().encode(),
                               ctype="text/plain; version=0.0.4")
                else:
                    self._send(404)

            def do_POST(self) -> None:  # noqa: N802  # owner: worker
                key = self._run_key()
                body = self._body()
                if key is None or body is None:
                    self._send(400)
                    return
                h = self.headers
                if self.path.startswith("/wal/"):
                    try:
                        offset = int(h.get("X-Jepsen-Offset", ""))
                    except ValueError:
                        self._send(400)
                        return
                    current = server.append_chunk(
                        key, offset,
                        h.get("X-Jepsen-Prefix-Sha", ""),
                        h.get("X-Jepsen-Chunk-Sha", ""), body,
                        reset=h.get("X-Jepsen-Reset") == "1")
                    if current is None:
                        self._send(204)
                    else:
                        self._send(409,
                                   json.dumps(current).encode())
                elif self.path.startswith("/final/"):
                    if server.finalize_run(
                            key, h.get("X-Jepsen-Sha256", ""), body):
                        self._send(204)
                    else:
                        self._send(400)
                else:
                    self._send(404)

        return Handler

    def start(self) -> "IngestServer":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="fleet-ingest", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread is not None:
            join_noisy(self._thread, "fleet ingest server",
                       max_wait_s=10.0)
            self._thread = None
        self._httpd.server_close()
