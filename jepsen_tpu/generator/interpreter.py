"""Threaded interpreter: bridges the pure generator to real client threads.

Reference: jepsen/src/jepsen/generator/interpreter.clj. One thread per
client worker plus one for the nemesis; each worker has a size-1 in-queue,
all share a completion queue; a single scheduler thread alternates between
polling completions and asking the generator for ops (interpreter.clj:
181-310). Crashed ops (:info) renumber the worker's process and force a
client reopen unless the client is reusable (:33-67, :142-157). Pseudo-ops
(:sleep/:log) are handled in-worker and excluded from history (:172-179).
"""
from __future__ import annotations

import logging
import queue
import threading
import time as _time
from typing import Any

from jepsen_tpu import client as client_mod, telemetry
from jepsen_tpu.generator import (
    NEMESIS, PENDING, Context, as_gen, context, friendly_exceptions, validate,
)
from jepsen_tpu.utils import (
    relative_time_nanos, relative_time_origin, with_relative_time,
)

logger = logging.getLogger("jepsen.interpreter")

# Max time between generator re-polls when pending, µs (interpreter.clj:166-170)
MAX_PENDING_INTERVAL_S = 0.001


class _Exit:
    pass


_EXIT = _Exit()


class Worker:
    """One sequential execution context (interpreter.clj:19-31)."""

    def open(self, test: dict, worker_id) -> "Worker":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def close(self, test: dict) -> None:
        pass


class ClientWorker(Worker):
    """Wraps a Client; reopens it when its process crashes
    (interpreter.clj:33-67)."""

    def __init__(self, node: str, client: client_mod.Client | None = None,
                 process=None):
        self.node = node
        self.client = client
        self.process = process

    def open(self, test, worker_id):
        return self

    def _ensure_client(self, test, process):
        if self.client is not None and (
            self.process == process or getattr(self.client, "reusable", False)
        ):
            self.process = process
            return self.client
        if self.client is not None:
            try:
                self.client.close(test)
            except Exception:  # noqa: BLE001
                logger.exception("error closing client for reopen")
            self.client = None
        self.client = test["client"].open(test, self.node)
        self.process = process
        return self.client

    def invoke(self, test, op):
        try:
            c = self._ensure_client(test, op.get("process"))
        except Exception as e:  # noqa: BLE001
            logger.exception("client open failed")
            return {**op, "type": "fail", "error": ["no-client", repr(e)]}
        try:
            return c.invoke(test, op)
        except Exception as e:  # noqa: BLE001
            logger.exception("client op crashed")
            # indeterminate: the op may or may not have happened
            # (interpreter.clj:142-157)
            return {**op, "type": "info", "error": ["indeterminate", repr(e)]}

    def close(self, test):
        if self.client is not None:
            self.client.close(test)
            self.client = None


class NemesisWorker(Worker):
    """Applies ops via the test's nemesis (interpreter.clj:69-76).

    When the test carries a durable fault registry (``test['_faults']``,
    installed by core.run), fault-opening ops are recorded to
    ``faults.jsonl`` BEFORE injection and fault-closing ops mark their
    kind healed after they complete cleanly — the exactly-once-heal
    ledger a crashed run's recovery replays (doc/robustness.md)."""

    def invoke(self, test, op):
        reg = telemetry.get_registry()
        if reg.enabled:
            f = str(op.get("f"))
            reg.counter("nemesis_ops_total", "nemesis ops applied",
                        labels=("f",)).inc(f=f)
            phase = telemetry.fault_phase(op.get("f"))
            if phase is not None:
                reg.event("nemesis-fault", f=f, phase=phase,
                          value=repr(op.get("value")))
                gauge = reg.gauge("nemesis_fault_active",
                                  "open fault windows (begin - end events)")
                gauge.inc() if phase == "begin" else gauge.dec()
        nemesis = test.get("nemesis")
        faults = test.get("_faults") if nemesis is not None else None
        fault_phase = fault_kind = None
        if faults is not None:
            from jepsen_tpu.nemesis.faults import classify
            fault_phase, fault_kind = classify(op.get("f"))
            if fault_phase == "begin":
                try:
                    faults.record(fault_kind, f=op.get("f"),
                                  value=op.get("value"))
                except Exception:  # noqa: BLE001 — never blocks injection
                    logger.exception("fault registry record failed")
        try:
            if nemesis is None:
                return {**op, "type": "info"}
            completion = nemesis.invoke(test, op)
            if completion is None:
                completion = {**op}
            completion.setdefault("type", "info")
            if (faults is not None and fault_phase == "end"
                    and completion.get("error") is None):
                try:
                    faults.mark_healed(kind=fault_kind, via="nemesis")
                except Exception:  # noqa: BLE001
                    logger.exception("fault registry heal-mark failed")
            return completion
        except Exception as e:  # noqa: BLE001
            logger.exception("nemesis op crashed")
            return {**op, "type": "info", "error": ["indeterminate", repr(e)]}


def goes_in_history(op: dict) -> bool:
    """:sleep and :log pseudo-ops are invisible (interpreter.clj:172-179)."""
    return op.get("type") not in ("sleep", "log")


def _spawn_worker(test: dict, worker_id, completions: queue.Queue):
    """Worker thread + its in-queue (interpreter.clj:99-164)."""
    in_q: queue.Queue = queue.Queue(maxsize=1)
    if worker_id == NEMESIS:
        worker: Worker = NemesisWorker()
    else:
        nodes = test.get("nodes") or [None]
        worker = ClientWorker(nodes[worker_id % len(nodes)])

    def run():
        threading.current_thread().name = f"jepsen-worker-{worker_id}"
        while True:
            op = in_q.get()
            if op is _EXIT:
                completions.put((worker_id, _EXIT))
                return
            typ = op.get("type")
            if typ == "sleep":
                _time.sleep(op.get("value") or 0)
                completion = {**op}
            elif typ == "log":
                logger.info("%s", op.get("value"))
                completion = {**op}
            else:
                completion = worker.invoke(test, op)
            completions.put((worker_id, completion))

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return {"id": worker_id, "in": in_q, "thread": t, "worker": worker}


def run(test: dict) -> list[dict]:
    """Runs the test's generator to completion, returning the history
    (interpreter.clj:181-310). Must be called inside
    utils.with_relative_time (core.run does this); establishes one if not.
    """
    if relative_time_origin() is None:
        with with_relative_time():
            return run(test)

    gen = friendly_exceptions(validate(as_gen(test.get("generator"))))
    ctx = context(test)
    completions: queue.Queue = queue.Queue()
    workers = {w["id"]: w for w in (
        _spawn_worker(test, wid, completions) for wid in ctx.workers
    )}
    history: list[dict] = []
    # write-ahead journal (core.run installs it): every history-bound op
    # — invocations at dispatch, completions as they arrive — lands in
    # history.wal.jsonl the moment it enters the in-memory history, so a
    # killed run leaves a replayable prefix (doc/robustness.md)
    journal = test.get("_journal")

    # telemetry: instruments fetched ONCE before the loop, then driven
    # through the single-writer fast paths (cell/observer — only this
    # scheduler thread mutates them, so no per-op lock). When disabled
    # the per-op cost is a single boolean check (metrics_on).
    reg = telemetry.get_registry()
    metrics_on = reg.enabled
    m_latency = reg.histogram(
        "interpreter_op_latency_seconds",
        "invoke -> completion latency by op :f", labels=("f",))
    inflight_cell = reg.gauge(
        "interpreter_in_flight_ops",
        "ops dispatched, not yet completed").cell()
    qdepth_cell = reg.gauge(
        "interpreter_completion_queue_depth",
        "completions waiting for the scheduler (sampled every 128th)").cell()
    m_ops = reg.counter("interpreter_ops_total",
                        "ops dispatched to workers", labels=("f",))
    m_crash = reg.counter(
        "interpreter_crashed_ops_total",
        "client ops that crashed to :info (process renumbered)",
        labels=("f",))
    lat_obs: dict = {}       # f -> bound observe closure
    ops_cells: dict = {}     # f -> counter cell
    invoke_at: dict = {}     # thread -> dispatch time (relative nanos)
    inflight_n = 0
    completion_i = 0

    def thread_of(process):
        return NEMESIS if process == NEMESIS else ctx.thread_of(process)

    def process_completion(completion) -> Any:
        """Re-stamps time, frees the thread, updates the generator, and
        renumbers crashed processes (interpreter.clj:216-241). Returns the
        freed thread id."""
        nonlocal ctx, gen, inflight_n, completion_i
        now = relative_time_nanos()
        completion = {**completion, "time": now}
        ctx = ctx.with_time(now)
        thread = thread_of(completion.get("process"))
        if goes_in_history(completion):
            history.append(completion)
            if journal is not None:
                journal.append(completion)
            if metrics_on:
                t0 = invoke_at.pop(thread, None)
                if t0 is not None:
                    f = completion.get("f")
                    obs = lat_obs.get(f)
                    if obs is None:
                        obs = lat_obs[f] = m_latency.observer(f=str(f))
                    obs((now - t0) / 1e9)
                inflight_n -= 1
                inflight_cell[0] = inflight_n
                completion_i += 1
                if not completion_i & 127:  # qsize() locks: sample rarely
                    qdepth_cell[0] = completions.qsize()
                if (completion.get("type") == "info"
                        and completion.get("process") != NEMESIS):
                    m_crash.inc(f=str(completion.get("f")))
            if gen is not None:
                gen = gen.update(test, ctx, completion)
            if (completion.get("type") == "info"
                    and completion.get("process") != NEMESIS):
                ctx = ctx.with_next_process(thread)
        ctx = ctx.free_thread(thread)
        return thread

    try:
        # main scheduling loop (interpreter.clj:206-292)
        while True:
            # 1. drain any ready completion
            try:
                _, completion = completions.get_nowait()
                process_completion(completion)
                continue
            except queue.Empty:
                pass
            # 2. ask the generator
            now = relative_time_nanos()
            ctx = ctx.with_time(now)
            res = gen.op(test, ctx) if gen is not None else None
            if res is None:
                break  # exhausted -> drain
            op, gen2 = res
            if op is PENDING:
                gen = gen2
                # nothing soon: block briefly on completions
                # (max-pending-interval, interpreter.clj:166-170,264)
                try:
                    _, completion = completions.get(timeout=MAX_PENDING_INTERVAL_S)
                    process_completion(completion)
                except queue.Empty:
                    pass
                continue
            if op["time"] > now:
                # future-dated: wait for its time, but a completion may
                # change the schedule — reconsult the (old) generator
                # (interpreter.clj:268-275)
                try:
                    _, completion = completions.get(timeout=(op["time"] - now) / 1e9)
                    process_completion(completion)
                    continue
                except queue.Empty:
                    pass
            # dispatch
            gen = gen2
            now = relative_time_nanos()
            op = {**op, "time": now}
            thread = thread_of(op.get("process"))
            workers[thread]["in"].put(op)
            ctx = ctx.busy_thread(thread).with_time(now)
            if goes_in_history(op):
                history.append(op)
                if journal is not None:
                    journal.append(op)
                if metrics_on:
                    invoke_at[thread] = now
                    inflight_n += 1
                    inflight_cell[0] = inflight_n
                    f = op.get("f")
                    cell = ops_cells.get(f)
                    if cell is None:
                        cell = ops_cells[f] = m_ops.cell(f=str(f))
                    cell[0] += 1
                if gen is not None:
                    gen = gen.update(test, ctx, op)

        # drain: free workers exit now; busy workers exit after completing
        # (interpreter.clj:250-261)
        pending_exits = set(workers)
        for t in ctx.free_threads:
            workers[t]["in"].put(_EXIT)
        while pending_exits:
            wid, completion = completions.get()
            if completion is _EXIT:
                pending_exits.discard(wid)
                continue
            thread = process_completion(completion)
            workers[thread]["in"].put(_EXIT)
    finally:
        # abnormal shutdown: make sure worker threads die and clients close
        # (interpreter.clj:294-309)
        for w in workers.values():
            try:
                w["in"].put_nowait(_EXIT)
            except queue.Full:
                pass
        for w in workers.values():
            try:
                if isinstance(w["worker"], ClientWorker):
                    w["worker"].close(test)
            except Exception:  # noqa: BLE001
                pass
    return history
