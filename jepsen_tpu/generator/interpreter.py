"""Threaded interpreter: bridges the pure generator to real client threads.

Reference: jepsen/src/jepsen/generator/interpreter.clj. One thread per
client worker plus one for the nemesis; each worker has a size-1 in-queue,
all share a completion queue; a single scheduler thread alternates between
polling completions and asking the generator for ops (interpreter.clj:
181-310). Crashed ops (:info) renumber the worker's process and force a
client reopen unless the client is reusable (:33-67, :142-157). Pseudo-ops
(:sleep/:log) are handled in-worker and excluded from history (:172-179).

Deadlines and reaping (doc/robustness.md): the reference blocks forever
on a client that never returns — one hung ``Client.invoke`` wedges the
whole run. Here every history-bound op carries a deadline (``op
['timeout_s']`` → ``test['op_timeout_s']`` → ``JEPSEN_TPU_OP_TIMEOUT_S``,
``None``/``0`` disables) and the scheduler's wait points clamp to the
earliest one. On expiry the scheduler synthesizes an indeterminate
``{:type :info, :error [op-timeout ...]}`` completion — journaled and
process-renumbered like any crash — marks the worker *zombie*, and spawns
a replacement thread under the same worker id with a bumped generation.
A zombie's late completion (stale generation) is quarantined to the
run's ``late.jsonl``, never appended to history; the zombie's client is
closed by the zombie's own thread when it finally unblocks, never
concurrently by the scheduler. The drain phase runs under its own
deadline (``JEPSEN_TPU_DRAIN_S``), and a stall detector
(``JEPSEN_TPU_STALL_S``) dumps all thread stacks into the store dir when
neither a dispatch nor a completion happens for too long.
"""
from __future__ import annotations

import logging
import os
import queue
import threading
import time as _time
from typing import Any

from jepsen_tpu import client as client_mod, telemetry
from jepsen_tpu import journal as journal_mod
from jepsen_tpu import trace as trace_mod
from jepsen_tpu.generator import (
    NEMESIS, PENDING, Context, as_gen, context, friendly_exceptions, validate,
)
from jepsen_tpu.utils import (
    relative_time_nanos, relative_time_origin, with_relative_time,
)

logger = logging.getLogger("jepsen.interpreter")

# Max time between generator re-polls when pending, µs (interpreter.clj:166-170)
MAX_PENDING_INTERVAL_S = 0.001

# Completions processed (and WAL records coalesced) per scheduler drain
# chunk; the ``sched_batch_ops`` test knob / ``JEPSEN_TPU_SCHED_BATCH``
# env twin override, ``0``/``None`` restores per-op hops + per-op WAL
# appends (doc/performance.md "Host ingest spine").
DEFAULT_SCHED_BATCH_OPS = 256

# Deadline defaults (doc/robustness.md). The op timeout is deliberately
# generous: it exists to unwedge a run, not to police slow databases —
# a synthesized :info is indeterminate, and flooding a history with
# them tells the checker nothing.
DEFAULT_OP_TIMEOUT_S = 600.0
DEFAULT_DRAIN_TIMEOUT_S = 60.0
DEFAULT_STALL_S = 300.0
# How often the drain loop wakes to re-check its deadlines when no
# completion arrives, and how long the shutdown path waits for worker
# threads (and thus their self-closed clients) before abandoning them.
DRAIN_POLL_S = 0.5
SHUTDOWN_JOIN_S = 5.0

STALL_DUMP_NAME = "stall-threads.txt"

_UNSET = object()


def _knob(test: dict, key: str, env: str, default: float) -> float | None:
    """Resolves a timeout knob: test map → environment → default.
    ``None``/``0`` (from any layer) disables and returns None."""
    v = test.get(key, _UNSET)
    if v is _UNSET:
        e = os.environ.get(env)
        if e is None or e == "":
            v = default
        else:
            v = e
    if not v:
        return None
    try:
        v = float(v)
    except (TypeError, ValueError):
        logger.warning("unparsable %s=%r; using default %s", key, v, default)
        v = default
    return float(v) if v else None


def _coerce_timeout(v, fallback: float | None) -> float | None:
    """A per-op ``timeout_s`` override, tolerantly: falsy (incl. "0")
    disables, garbage degrades to ``fallback`` with a warning — a bad
    op field must not kill the scheduler."""
    if not v:
        return None
    try:
        return float(v) or None
    except (TypeError, ValueError):
        logger.warning("unparsable op timeout_s=%r; using %s", v, fallback)
        return fallback


class _Exit:
    pass


_EXIT = _Exit()


class _SchedBus:
    """Chunked completion bus between workers and the scheduler.

    Workers stage compact ``(worker_id, generation, payload)`` tuples;
    the scheduler drains the staged run (up to ``max_chunk``) in ONE
    lock round instead of one queue hop per completion — the
    scheduler-side analog of the batched trace emission, and the thing
    that lets the WAL coalesce a whole chunk into one write+fsync.
    Arrival order is preserved exactly (stages append under the lock),
    so history order, generator updates, and the late-quarantine
    bookkeeping see the same schedule a per-op queue.Queue would; with
    ``max_chunk=1`` the bus IS that per-op queue.
    """

    def __init__(self, max_chunk: int = DEFAULT_SCHED_BATCH_OPS):
        self.max_chunk = max(int(max_chunk), 1)
        self._cv = threading.Condition(threading.Lock())
        self._staged: list = []

    def put(self, item) -> None:  # owner: worker
        with self._cv:
            self._staged.append(item)
            self._cv.notify()

    def qsize(self) -> int:  # owner: scheduler (sampled metric only)
        with self._cv:
            return len(self._staged)

    def drain_nowait(self) -> list:  # owner: scheduler
        # racy truthiness peek: a miss only delays one poll round, and
        # the hot loop skips a lock acquisition on every empty pass
        if not self._staged:
            return []
        with self._cv:
            return self._take()

    def drain(self, timeout: float) -> list:  # owner: scheduler
        """Blocks up to ``timeout`` for the first staged tuple; an empty
        list is the queue.Empty analog (the wait genuinely timed out —
        wait_for rides out spurious wakeups, and a notify always leaves
        something staged for _take)."""
        with self._cv:
            self._cv.wait_for(lambda: self._staged, timeout)
            return self._take()

    def _take(self) -> list:
        staged = self._staged
        if len(staged) <= self.max_chunk:
            self._staged = []
            return staged
        chunk = staged[:self.max_chunk]
        del staged[:self.max_chunk]
        return chunk


class Worker:
    """One sequential execution context (interpreter.clj:19-31)."""

    def open(self, test: dict, worker_id) -> "Worker":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def close(self, test: dict) -> None:
        pass


class ClientWorker(Worker):
    """Wraps a Client; reopens it when its process crashes
    (interpreter.clj:33-67)."""

    def __init__(self, node: str, client: client_mod.Client | None = None,
                 process=None):
        self.node = node
        self.client = client
        self.process = process

    def open(self, test, worker_id):
        return self

    def _ensure_client(self, test, process):
        if self.client is not None and (
            self.process == process or getattr(self.client, "reusable", False)
        ):
            self.process = process
            return self.client
        if self.client is not None:
            try:
                self.client.close(test)
            except Exception:  # noqa: BLE001
                logger.exception("error closing client for reopen")
            self.client = None
        self.client = test["client"].open(test, self.node)
        self.process = process
        return self.client

    def invoke(self, test, op):  # owner: worker
        try:
            c = self._ensure_client(test, op.get("process"))
        except Exception as e:  # noqa: BLE001
            logger.exception("client open failed")
            return {**op, "type": "fail", "error": ["no-client", repr(e)]}
        try:
            return c.invoke(test, op)
        except Exception as e:  # noqa: BLE001
            logger.exception("client op crashed")
            # indeterminate: the op may or may not have happened
            # (interpreter.clj:142-157)
            return {**op, "type": "info", "error": ["indeterminate", repr(e)]}

    def close(self, test):
        if self.client is not None:
            self.client.close(test)
            self.client = None


class NemesisWorker(Worker):
    """Applies ops via the test's nemesis (interpreter.clj:69-76).

    When the test carries a durable fault registry (``test['_faults']``,
    installed by core.run), fault-opening ops are recorded to
    ``faults.jsonl`` BEFORE injection and fault-closing ops mark their
    kind healed after they complete cleanly — the exactly-once-heal
    ledger a crashed run's recovery replays (doc/robustness.md).

    Unlike clients (reopened per process), the nemesis OBJECT is shared:
    after a deadline reap, the replacement worker invokes the same
    ``test['nemesis']`` while the zombie may still be blocked inside it.
    Nemeses must tolerate that — per-call transports and idempotent
    heal actions (the existing package contract) already do."""

    # Set by _spawn_worker: when the scheduler reaps this worker at a
    # deadline, a fault-closing op that later completes must NOT mark
    # its kind healed — the synthesized :info already stands and the
    # entry stays on the books for the crash-path / cli-heal replay.
    zombied: threading.Event | None = None

    # the fault row must be on disk before the injection fires; the
    # durability-protocol lint rule holds this method to that order
    # durability: record-before-act
    def invoke(self, test, op):  # owner: worker
        reg = telemetry.get_registry()
        if reg.enabled:
            f = str(op.get("f"))
            reg.counter("nemesis_ops_total", "nemesis ops applied",
                        labels=("f",)).inc(f=f)
            phase = telemetry.fault_phase(op.get("f"))
            if phase is not None:
                reg.event("nemesis-fault", f=f, phase=phase,
                          value=repr(op.get("value")))
                gauge = reg.gauge("nemesis_fault_active",
                                  "open fault windows (begin - end events)")
                gauge.inc() if phase == "begin" else gauge.dec()
        nemesis = test.get("nemesis")
        faults = test.get("_faults") if nemesis is not None else None
        fault_phase = fault_kind = None
        if faults is not None:
            from jepsen_tpu.nemesis import self_recorded_kinds
            from jepsen_tpu.nemesis.faults import (
                SELF_RECORDED_ONLY, classify,
            )
            fault_phase, fault_kind = classify(op.get("f"))
            if fault_kind is not None \
                    and (fault_kind in SELF_RECORDED_ONLY
                         or fault_kind in self_recorded_kinds(nemesis)):
                # the nemesis keeps its own (richer) registry books for
                # this kind — e.g. membership records the pre-op member
                # set and heal-marks at resolution; a generic record
                # here would double-book an entry nothing ever heals.
                # SELF_RECORDED_ONLY kinds are ALSO skipped for plain
                # nemeses (faunadb topology, rethinkdb reconfigure):
                # without a model there is no pre-op set to restore,
                # and an unhealable row is worse than none
                fault_phase = fault_kind = None
            if fault_phase == "begin":
                try:
                    faults.record(fault_kind, f=op.get("f"),
                                  value=op.get("value"))
                except Exception:  # noqa: BLE001 — never blocks injection
                    logger.exception("fault registry record failed")
        try:
            if nemesis is None:
                return {**op, "type": "info"}
            completion = nemesis.invoke(test, op)
            if completion is None:
                completion = {**op}
            completion.setdefault("type", "info")
            if (faults is not None and fault_phase == "begin"
                    and completion.get("error") is None
                    and self.zombied is not None
                    and self.zombied.is_set()):
                # the injection landed AFTER this worker was reaped: a
                # same-kind closing op may already have marked the
                # pre-recorded entry healed, so put the fault back on
                # the books — the replay / `cli heal` must know the
                # late injection exists
                try:
                    faults.record(fault_kind, f=op.get("f"),
                                  value=op.get("value"))
                    logger.warning(
                        "fault-opening op %r completed after its "
                        "deadline; re-recorded kind %r as unhealed",
                        op.get("f"), fault_kind)
                except Exception:  # noqa: BLE001
                    logger.exception("late fault re-record failed")
            if (faults is not None and fault_phase == "end"
                    and completion.get("error") is None):
                if self.zombied is not None and self.zombied.is_set():
                    # this closing op outlived its deadline: the run
                    # already recorded an indeterminate :info for it, so
                    # the entry must stay unhealed — core.run's
                    # crash-path replay / `cli heal` restores the network
                    logger.warning(
                        "fault-closing op %r completed after its "
                        "deadline; leaving kind %r unhealed for replay",
                        op.get("f"), fault_kind)
                else:
                    try:
                        faults.mark_healed(kind=fault_kind, via="nemesis")
                    except Exception:  # noqa: BLE001
                        logger.exception("fault registry heal-mark failed")
            return completion
        except Exception as e:  # noqa: BLE001
            logger.exception("nemesis op crashed")
            return {**op, "type": "info", "error": ["indeterminate", repr(e)]}


def goes_in_history(op: dict) -> bool:
    """:sleep and :log pseudo-ops are invisible (interpreter.clj:172-179)."""
    return op.get("type") not in ("sleep", "log")


# Per-worker-thread state, installed by _spawn_worker's run() so code
# called FROM a worker (clients, nemeses) can learn its fate without a
# worker handle. Off-worker threads see nothing.
_worker_tls = threading.local()


def current_worker_zombie():
    """The calling thread's zombie event (None off-worker) — helpers
    that hop threads (``utils.timeout``) hand it to their child via
    :func:`adopt_worker_zombie` so :func:`current_op_reaped` keeps
    answering for the logical op, not the physical thread."""
    return getattr(_worker_tls, "zombied", None)


def adopt_worker_zombie(event) -> None:
    if event is not None:
        _worker_tls.zombied = event


def current_op_reaped() -> bool:
    """True when the calling thread is an interpreter worker whose
    in-flight op was reaped at its deadline (the worker is zombied and a
    synthesized indeterminate ``:info`` already stands in the history).
    Client/nemesis code consults this to keep late side effects off the
    books — e.g. the membership nemesis leaves its registry entry
    unhealed so the crash-path / ``cli heal`` replay restores the
    pre-op member set (doc/robustness.md)."""
    ev = getattr(_worker_tls, "zombied", None)
    return ev is not None and ev.is_set()


def _spawn_worker(test: dict, worker_id, completions: "_SchedBus",
                  generation: int = 0):
    """Worker thread + its in-queue (interpreter.clj:99-164).

    Every completion is tagged with this worker's ``generation`` so the
    scheduler can tell a live worker's result from a reaped zombie's
    late one. The worker owns its client's lifecycle: it closes the
    client from its own thread on ``_EXIT`` — and, when zombied, after
    its one outstanding op finally unblocks — so a close can never race
    a mid-``invoke`` use of the same client object."""
    in_q: queue.Queue = queue.Queue(maxsize=1)
    if worker_id == NEMESIS:
        worker: Worker = NemesisWorker()
    else:
        nodes = test.get("nodes") or [None]
        worker = ClientWorker(nodes[worker_id % len(nodes)])
    zombied = threading.Event()
    if isinstance(worker, NemesisWorker):
        worker.zombied = zombied

    def close_own_client():  # owner: worker
        if isinstance(worker, ClientWorker):
            try:
                worker.close(test)
            except Exception:  # noqa: BLE001
                logger.exception("worker %s client close failed", worker_id)

    def run():  # owner: worker
        threading.current_thread().name = (
            f"jepsen-worker-{worker_id}"
            + (f".{generation}" if generation else ""))
        _worker_tls.zombied = zombied
        while True:
            op = in_q.get()
            if op is _EXIT:
                # close-before-ack: when the scheduler sees this exit
                # marker, the client is already released (a hung close
                # is therefore caught by the drain deadline)
                close_own_client()
                completions.put((worker_id, generation, _EXIT))
                return
            typ = op.get("type")
            if typ == "sleep":
                _time.sleep(op.get("value") or 0)
                completion = {**op}
            elif typ == "log":
                logger.info("%s", op.get("value"))
                completion = {**op}
            else:
                completion = worker.invoke(test, op)
            completions.put((worker_id, generation, completion))
            if zombied.is_set():
                # reaped mid-op: the completion above will be
                # quarantined (stale generation); close our own client
                # and die — a replacement already took this worker id
                close_own_client()
                return

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return {"id": worker_id, "in": in_q, "thread": t, "worker": worker,
            "gen": generation, "zombied": zombied}


class _StallWatchdog:
    """Detects a wedged run: history-bound ops in flight, yet neither a
    dispatch nor a completion for ``stall_s`` seconds. Fires once per
    stall episode: a telemetry event + counter, a warning, and an
    all-threads stack dump into the store dir (``stall-threads.txt``) so
    the wedge is diagnosable post-mortem. Re-arms only after activity
    resumes. ``inflight_probe`` gates firing: a schedule that is merely
    *quiet* (nothing in flight — a long :sleep, future-dated ops spaced
    far apart) is not a stall."""

    def __init__(self, test: dict, stall_s: float | None, activity: list,
                 inflight_probe=None):
        self.test = test
        self.stall_s = stall_s
        self.activity = activity
        self.inflight_probe = inflight_probe or (lambda: True)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "_StallWatchdog":
        if self.stall_s:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="jepsen-stall-watchdog")
            self._thread.start()
        return self

    def _loop(self) -> None:  # owner: any
        fired_at = None
        poll = min(max(self.stall_s / 4.0, 0.05), 5.0)
        while not self._stop.wait(poll):
            if not self.inflight_probe():
                fired_at = None
                continue  # quiet schedule, not a stall
            last = self.activity[0]
            if _time.monotonic() - last < self.stall_s:
                fired_at = None
                continue
            if fired_at == last:
                continue  # this episode is already on the record
            fired_at = last
            self._fire(_time.monotonic() - last)

    def _fire(self, idle_s: float) -> None:
        logger.warning("interpreter stalled: no dispatch or completion "
                       "for %.1fs; dumping thread stacks", idle_s)
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.counter(
                "interpreter_stalls_total",
                "stall-detector trips (no dispatch or completion for "
                "JEPSEN_TPU_STALL_S)").inc()
            reg.event("interpreter-stall", idle_s=round(idle_s, 3))
        tracer = trace_mod.get_tracer()
        tracer.instant(trace_mod.TRACK_SCHEDULER, "stall",
                       args={"idle_s": round(idle_s, 3)})
        try:
            from jepsen_tpu import store
            target = store.path_mk(self.test, STALL_DUMP_NAME)
        except Exception:  # noqa: BLE001 — bare test map, no store coords
            logger.debug("no store dir for stall dump", exc_info=True)
            return
        telemetry.dump_thread_stacks(target)
        # a wedge is exactly what the flight recorder exists for: the
        # last ~N events of causal context land next to the stack dump
        try:
            from jepsen_tpu import store
            tracer.dump_flight(
                store.path_mk(self.test, trace_mod.FLIGHT_NAME),
                reason="stall")
        except Exception:  # noqa: BLE001 — diagnostics never raise
            logger.debug("no store dir for flight dump", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def run(test: dict) -> list[dict]:  # owner: scheduler
    """Runs the test's generator to completion, returning the history
    (interpreter.clj:181-310). Must be called inside
    utils.with_relative_time (core.run does this); establishes one if not.
    """
    if relative_time_origin() is None:
        with with_relative_time():
            return run(test)

    gen = friendly_exceptions(validate(as_gen(test.get("generator"))))
    ctx = context(test)
    # chunked scheduler (doc/performance.md "Host ingest spine"): the
    # knob caps both the completions processed per bus drain and the
    # WAL records coalesced per flush; 0/None restores per-op behavior
    sched_batch_f = _knob(test, "sched_batch_ops", "JEPSEN_TPU_SCHED_BATCH",
                          DEFAULT_SCHED_BATCH_OPS)
    sched_batch = int(sched_batch_f) if sched_batch_f else 1
    completions = _SchedBus(max_chunk=sched_batch)
    workers = {w["id"]: w for w in (
        _spawn_worker(test, wid, completions) for wid in ctx.workers
    )}
    history: list[dict] = []
    # write-ahead journal (core.run installs it): every history-bound op
    # — invocations at dispatch, completions as they arrive — lands in
    # history.wal.jsonl before the scheduler next goes to sleep, so a
    # killed run leaves a replayable prefix (doc/robustness.md). Within
    # one drain chunk the records stage in wal_stage and land as ONE
    # write(+interval fsync) via Journal.append_many — bytes identical
    # to per-op appends, syscalls per chunk instead of per op.
    journal = test.get("_journal")
    wal_stage: list = []

    def wal_push(rec) -> None:  # owner: scheduler
        if journal is None:
            return
        if sched_batch <= 1:
            journal.append(rec)
            return
        wal_stage.append(rec)
        if len(wal_stage) >= sched_batch:
            wal_flush()

    def wal_flush() -> None:  # owner: scheduler
        """Coalesced WAL landing — called at every point the scheduler
        can block or exit, so the durability contract stays "everything
        before the scheduler sleeps is on disk"."""
        if wal_stage:
            journal.append_many(wal_stage)
            wal_stage.clear()

    # deadline knobs (doc/robustness.md): the test map wins, then the
    # environment, then the generous defaults; None/0 disables
    default_timeout_s = _knob(test, "op_timeout_s",
                              "JEPSEN_TPU_OP_TIMEOUT_S",
                              DEFAULT_OP_TIMEOUT_S)
    drain_timeout_s = _knob(test, "drain_timeout_s", "JEPSEN_TPU_DRAIN_S",
                            DEFAULT_DRAIN_TIMEOUT_S)
    stall_s = _knob(test, "stall_s", "JEPSEN_TPU_STALL_S", DEFAULT_STALL_S)

    # telemetry: instruments fetched ONCE before the loop, then driven
    # through the single-writer fast paths (cell/observer — only this
    # scheduler thread mutates them, so no per-op lock). When disabled
    # the per-op cost is a single boolean check (metrics_on).
    reg = telemetry.get_registry()
    metrics_on = reg.enabled
    # causal trace (doc/observability.md "Causal trace"): every
    # history-bound op opens a slice on its worker's track at dispatch,
    # keyed by the stable trace id — a pure function of the op's
    # (process, invoke-time), which client spans, the WAL record, reap
    # forensics, and the checker's explain localization all share. The
    # hot path appends one raw tuple per event through op_sink() (the
    # telemetry cell() analog); track names, ids, and wall timestamps
    # are derived at sink-drain/dump time from the op dicts + the
    # one-shot clock origin below.
    tracer = trace_mod.get_tracer()
    tracing_on = tracer.enabled
    op_trace = None
    if tracing_on:
        tracer.set_op_origin(_time.time_ns() // 1000
                             - relative_time_nanos() // 1000)
        op_trace = tracer.op_sink()
        tracer.instant(trace_mod.TRACK_SCHEDULER, "interpreter-start",
                       args={"workers": len(ctx.workers)})
    OP_B, OP_X = trace_mod.OP_BEGIN, trace_mod.OP_COMPLETE
    m_latency = reg.histogram(
        "interpreter_op_latency_seconds",
        "invoke -> completion latency by op :f", labels=("f",))
    inflight_cell = reg.gauge(
        "interpreter_in_flight_ops",
        "ops dispatched, not yet completed").cell()
    qdepth_cell = reg.gauge(
        "interpreter_completion_queue_depth",
        "completions waiting for the scheduler (sampled every 128th)").cell()
    m_ops = reg.counter("interpreter_ops_total",
                        "ops dispatched to workers", labels=("f",))
    m_crash = reg.counter(
        "interpreter_crashed_ops_total",
        "client ops that crashed to :info (process renumbered)",
        labels=("f",))
    m_timeouts = reg.counter(
        "interpreter_op_timeouts_total",
        "in-flight ops reaped at their deadline (:info synthesized)",
        labels=("f",))
    zombies_gauge = reg.gauge(
        "interpreter_zombie_workers",
        "deadline-reaped workers whose late completion has not arrived "
        "yet (drain/shutdown abandons are counted separately)")
    m_late = reg.counter(
        "interpreter_late_completions_total",
        "stale-generation completions quarantined to late.jsonl")
    m_abandoned = reg.counter(
        "interpreter_abandoned_workers_total",
        "workers abandoned at shutdown (still busy past the drain/join "
        "bounds)")
    lat_obs: dict = {}       # f -> bound observe closure
    ops_cells: dict = {}     # f -> counter cell
    invoke_at: dict = {}     # thread -> dispatch time (relative nanos)
    inflight: dict = {}      # thread -> its in-flight history-bound op
    deadlines: dict = {}     # thread -> (deadline rel-nanos, timeout_s)
    zombies: list = []       # reaped records (their threads self-close)
    inflight_n = 0
    completion_i = 0

    # late.jsonl: core.run installs a ForensicLog; a standalone run
    # builds its own lazily when the test map has store coordinates
    late_log = test.get("_late")
    own_late = False
    activity = [_time.monotonic()]
    # the probe reads the scheduler-owned dict without a lock: a racy
    # truthiness check is fine for a detector that only ever logs
    watchdog = _StallWatchdog(test, stall_s, activity,
                              inflight_probe=lambda: bool(invoke_at)).start()

    def thread_of(process):
        return NEMESIS if process == NEMESIS else ctx.thread_of(process)

    def process_completion(completion) -> Any:  # owner: scheduler
        """Re-stamps time, frees the thread, updates the generator, and
        renumbers crashed processes (interpreter.clj:216-241). Returns the
        freed thread id."""
        nonlocal ctx, gen, inflight_n, completion_i
        now = relative_time_nanos()
        completion = {**completion, "time": now}
        ctx = ctx.with_time(now)
        thread = thread_of(completion.get("process"))
        if goes_in_history(completion):
            history.append(completion)
            wal_push(completion)
            # dispatch-time tracking is unconditional: the deadline layer
            # needs it whether or not metrics are on
            t0 = invoke_at.pop(thread, None)
            inflight.pop(thread, None)
            deadlines.pop(thread, None)
            if op_trace is not None:
                op_trace((OP_X, thread, completion, t0))
            if metrics_on:
                if t0 is not None:
                    f = completion.get("f")
                    obs = lat_obs.get(f)
                    if obs is None:
                        obs = lat_obs[f] = m_latency.observer(f=str(f))
                    obs((now - t0) / 1e9)
                inflight_n -= 1
                inflight_cell[0] = inflight_n
                completion_i += 1
                if not completion_i & 127:  # qsize() locks: sample rarely
                    qdepth_cell[0] = completions.qsize()
                if (completion.get("type") == "info"
                        and completion.get("process") != NEMESIS):
                    m_crash.inc(f=str(completion.get("f")))
            if gen is not None:
                gen = gen.update(test, ctx, completion)
            if (completion.get("type") == "info"
                    and completion.get("process") != NEMESIS):
                ctx = ctx.with_next_process(thread)
        ctx = ctx.free_thread(thread)
        return thread

    def quarantine(wid, payload) -> None:  # owner: scheduler
        """A stale-generation completion: the zombie finally unblocked.
        Its synthesized :info already stands in the history, so this one
        is written to the late.jsonl forensic artifact instead — never
        appended to history, never journaled."""
        nonlocal late_log, own_late
        if metrics_on:
            zombies_gauge.dec()
        if not goes_in_history(payload):
            return
        if metrics_on:
            m_late.inc()
        if tracing_on:
            tracer.instant(
                trace_mod.TRACK_SCHEDULER, "late-completion",
                args={"worker": wid, "f": str(payload.get("f")),
                      "trace_id": trace_mod.trace_id_for(
                          payload.get("process"), payload.get("time"))})
        logger.info("quarantined late completion from zombie worker %s "
                    "(f=%r)", wid, payload.get("f"))
        if late_log is None and not own_late:
            own_late = True  # only try to build one once
            try:
                late_log = journal_mod.ForensicLog(
                    journal_mod.late_path(test))
            except Exception:  # noqa: BLE001 — bare test map, no store
                logger.debug("no store dir for late.jsonl", exc_info=True)
        if late_log is not None:
            # invoke_time preserves the dispatch stamp the re-stamped
            # "time" clobbers: it is the trace id's second input, so
            # offline derivation can join this row to its dispatch
            # slice (jepsen_tpu/trace/derive.py)
            late_log.append({**payload, "late": True, "worker": wid,
                             "invoke_time": payload.get("time"),
                             "time": relative_time_nanos()})

    def on_item(item) -> None:  # owner: scheduler
        """Routes one completion-queue item: current-generation
        completions advance the run; stale ones are quarantined; stale
        exit markers (a zombie dying) are dropped."""
        wid, gen_, payload = item
        activity[0] = _time.monotonic()
        if gen_ != workers[wid]["gen"]:
            if payload is not _EXIT:
                quarantine(wid, payload)
            return
        if payload is _EXIT:
            return  # only drain/shutdown send exits to live workers
        process_completion(payload)

    def zombify(w) -> None:  # owner: scheduler
        """The one way a worker is given up on: mark it, leave an exit
        marker so a racing completion can't strand it on a dead queue,
        and put it on the books. The zombie closes its own client and
        exits when it unblocks."""
        w["zombied"].set()
        try:
            w["in"].put_nowait(_EXIT)
        except queue.Full:
            pass
        zombies.append(w)

    def reap(thread, error) -> None:  # owner: scheduler
        """Deadline expiry: zombifies ``thread``'s worker, synthesizes
        the indeterminate :info completion for its in-flight op (which
        journals and renumbers like any crash), and spawns a replacement
        under a bumped generation. The zombie's client is closed by the
        zombie's own thread when it unblocks — never here. Deadlines are
        registered only for history-bound ops, so the in-flight op is
        always present (pseudo-ops never reap)."""
        w = workers[thread]
        zombify(w)
        op = inflight[thread]
        deadlines.pop(thread, None)
        workers[thread] = _spawn_worker(test, thread, completions,
                                        generation=w["gen"] + 1)
        if metrics_on:
            m_timeouts.inc(f=str(op.get("f")))
            zombies_gauge.inc()
        if tracing_on:
            # the reap instant carries the op's trace id, so the
            # synthesized :info (which ends the dispatch slice below)
            # links back to the original dispatch causally
            tracer.instant(
                trace_mod.TRACK_SCHEDULER, "op-timeout",
                args={"worker": thread, "f": str(op.get("f")),
                      "replacement_gen": w["gen"] + 1,
                      "trace_id": trace_mod.trace_id_for(
                          op.get("process"), op.get("time"))})
        logger.warning(
            "op deadline expired on worker %s (f=%r); synthesizing :info "
            "and spawning replacement generation %d", thread, op.get("f"),
            w["gen"] + 1)
        process_completion({**op, "type": "info", "error": error})

    def expire_deadlines(now_ns) -> list:  # owner: scheduler
        """Reaps every thread whose per-op deadline has passed; returns
        the reaped thread ids."""
        expired = [(t, s) for t, (d, s) in list(deadlines.items())
                   if d <= now_ns]
        for t, timeout_s in expired:
            reap(t, ["op-timeout", timeout_s])
        return [t for t, _ in expired]

    def earliest_deadline_wait(now_ns) -> float | None:  # owner: scheduler
        if not deadlines:
            return None
        ddl = min(d for d, _ in deadlines.values())
        return max((ddl - now_ns) / 1e9, 0.0)

    try:
        # main scheduling loop (interpreter.clj:206-292)
        while True:
            # 1. drain any ready completions — BEFORE the deadline check:
            # a completion that already arrived beat its deadline and
            # must never be falsely reaped. Chunked: the old loop only
            # ever reached expire_deadlines with an EMPTY queue (the
            # get_nowait/continue spin), so handling the whole chunk and
            # continuing is order-identical to one-at-a-time.
            chunk = completions.drain_nowait()
            if chunk:
                for item in chunk:
                    on_item(item)
                continue
            now = relative_time_nanos()
            if deadlines and expire_deadlines(now):
                continue
            # 2. ask the generator
            ctx = ctx.with_time(now)
            res = gen.op(test, ctx) if gen is not None else None
            if res is None:
                break  # exhausted -> drain
            op, gen2 = res
            ddl_wait = earliest_deadline_wait(now)
            if op is PENDING:
                gen = gen2
                # nothing soon: block briefly on completions
                # (max-pending-interval, interpreter.clj:166-170,264)
                wait_s = MAX_PENDING_INTERVAL_S
                if ddl_wait is not None:
                    wait_s = min(wait_s, ddl_wait)
                wal_flush()  # land staged records before sleeping
                for item in completions.drain(wait_s):
                    on_item(item)
                continue
            if op["time"] > now:
                # future-dated: wait for its time, but a completion may
                # change the schedule — reconsult the (old) generator
                # (interpreter.clj:268-275); an in-flight deadline may
                # fire first, so never sleep past it
                full_wait = (op["time"] - now) / 1e9
                wait_s = full_wait
                if ddl_wait is not None:
                    wait_s = min(wait_s, ddl_wait)
                wal_flush()  # land staged records before sleeping
                chunk = completions.drain(wait_s)
                if chunk:
                    for item in chunk:
                        on_item(item)
                    continue
                if wait_s < full_wait:
                    continue  # woke for a deadline, not the op time
            # dispatch
            gen = gen2
            now = relative_time_nanos()
            op = {**op, "time": now}
            thread = thread_of(op.get("process"))
            workers[thread]["in"].put(op)
            ctx = ctx.busy_thread(thread).with_time(now)
            activity[0] = _time.monotonic()
            if goes_in_history(op):
                history.append(op)
                wal_push(op)
                invoke_at[thread] = now
                inflight[thread] = op
                if op_trace is not None:
                    op_trace((OP_B, thread, op))
                timeout_s = op.get("timeout_s", _UNSET)
                if timeout_s is _UNSET:
                    timeout_s = default_timeout_s
                else:
                    timeout_s = _coerce_timeout(timeout_s,
                                                default_timeout_s)
                if timeout_s:
                    deadlines[thread] = (now + int(timeout_s * 1e9),
                                         timeout_s)
                if metrics_on:
                    inflight_n += 1
                    inflight_cell[0] = inflight_n
                    f = op.get("f")
                    cell = ops_cells.get(f)
                    if cell is None:
                        cell = ops_cells[f] = m_ops.cell(f=str(f))
                    cell[0] += 1
                if gen is not None:
                    gen = gen.update(test, ctx, op)

        # drain: free workers exit now; busy workers exit after completing
        # (interpreter.clj:250-261). The whole phase runs under its own
        # deadline so one stuck op or hung client close can't wedge
        # teardown — the run must always reach its checker.
        drain_deadline = (_time.monotonic() + drain_timeout_s
                          if drain_timeout_s else None)
        if tracing_on:
            tracer.instant(trace_mod.TRACK_SCHEDULER, "drain-begin",
                           args={"busy": len(ctx.workers)
                                 - len(ctx.free_threads)})
        pending_exits = set(workers)
        reaped_in_drain: set = set()
        wal_flush()  # main loop is done; land anything still staged
        for t in ctx.free_threads:
            workers[t]["in"].put(_EXIT)
        while pending_exits:
            now = relative_time_nanos()
            wait_s = DRAIN_POLL_S
            ddl_wait = earliest_deadline_wait(now)
            if ddl_wait is not None:
                wait_s = min(wait_s, ddl_wait)
            if drain_deadline is not None:
                wait_s = min(wait_s,
                             max(drain_deadline - _time.monotonic(), 0.0))
            wal_flush()  # land staged records before sleeping
            chunk = completions.drain(wait_s)
            if not chunk:
                just_reaped = expire_deadlines(relative_time_nanos())
                reaped_in_drain.update(just_reaped)
                for t in just_reaped:
                    # the replacement worker is idle: release it
                    workers[t]["in"].put(_EXIT)
                if (drain_deadline is not None
                        and _time.monotonic() >= drain_deadline):
                    # drain deadline: synthesize :info for whatever is
                    # still stuck, abandon the stragglers, proceed
                    for swid in sorted(pending_exits, key=str):
                        if swid in reaped_in_drain:
                            # a per-op deadline already handled it
                            # during this drain: the fresh replacement
                            # is exiting cleanly, not stuck — don't
                            # zombify or count it
                            continue
                        w = workers[swid]
                        if not w["thread"].is_alive():
                            continue
                        zombify(w)
                        sop = inflight.get(swid)
                        deadlines.pop(swid, None)
                        if metrics_on:
                            # the abandon counter, not the zombie gauge:
                            # same-generation abandons have no stale
                            # completion to decrement on, so the gauge
                            # would drift for a thread that does return
                            m_abandoned.inc()
                        logger.warning(
                            "drain deadline expired; abandoning worker "
                            "%s (%s)", swid,
                            f"f={sop.get('f')!r}" if sop is not None
                            else "no history-bound op in flight")
                        if tracing_on:
                            tracer.instant(
                                trace_mod.TRACK_SCHEDULER,
                                "worker-abandoned",
                                args={"worker": swid,
                                      "phase": "drain-deadline"})
                        if sop is not None:
                            process_completion(
                                {**sop, "type": "info",
                                 "error": ["op-timeout", "drain-deadline"]})
                    break
                continue
            activity[0] = _time.monotonic()
            for wid, gen_, payload in chunk:
                if gen_ != workers[wid]["gen"]:
                    if payload is not _EXIT:
                        quarantine(wid, payload)
                    continue
                if payload is _EXIT:
                    pending_exits.discard(wid)
                    continue
                thread = process_completion(payload)
                workers[thread]["in"].put(_EXIT)
    finally:
        watchdog.stop()
        try:
            wal_flush()  # never leak staged WAL records on any exit path
        except Exception:
            logger.exception("final WAL flush failed")
        # shutdown: every live worker gets an exit marker; one too busy
        # to take it is abandoned EXPLICITLY below — zombie-marked,
        # counted, logged — never silently leaked (interpreter.clj:294-309)
        for w in workers.values():
            if not w["thread"].is_alive():
                continue
            try:
                w["in"].put_nowait(_EXIT)
            except queue.Full:
                pass
        join_deadline = _time.monotonic() + SHUTDOWN_JOIN_S
        for w in workers.values():
            if w["zombied"].is_set():
                continue  # a known zombie: never wait on a hung thread
            w["thread"].join(
                timeout=max(join_deadline - _time.monotonic(), 0.0))
        for w in workers.values():
            if w["thread"].is_alive():
                if not w["zombied"].is_set():
                    # still mid-op after the bounded join: make the
                    # abandonment explicit; the worker closes its own
                    # client when it unblocks
                    zombify(w)
                    if metrics_on:
                        m_abandoned.inc()
                    if tracing_on:
                        tracer.instant(trace_mod.TRACK_SCHEDULER,
                                       "worker-abandoned",
                                       args={"worker": w["id"],
                                             "phase": "shutdown"})
                    logger.warning(
                        "worker %s still busy at shutdown; abandoned "
                        "(its client closes on its own thread when it "
                        "unblocks)", w["id"])
                continue
            # the thread exited, so it already closed its own client;
            # this is a safety net for a thread that died some other
            # way — with the thread gone, the close cannot race an
            # in-flight invoke
            try:
                if isinstance(w["worker"], ClientWorker):
                    w["worker"].close(test)
            except Exception:  # noqa: BLE001
                pass
        if zombies:
            logger.info("run finished with %d zombie/abandoned worker(s) "
                        "on the books", len(zombies))
        if own_late and late_log is not None:
            late_log.close()
    return history
