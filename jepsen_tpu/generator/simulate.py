"""Deterministic generator simulation — the unit-test backbone.

Equivalent capability to jepsen.generator.test (shipped in the reference's
src/ as a public testing kit, jepsen/src/jepsen/generator/test.clj): a
simulated scheduler with model workers of fixed latency, so generator
behavior is asserted as exact op/time/process sequences without threads or a
cluster (SURVEY.md §4 tier 1).
"""
from __future__ import annotations

import random
import time as _time
import types as _types
from heapq import heappop as _heappop, heappush as _heappush
from typing import Callable

from jepsen_tpu import generator as gen_mod
from jepsen_tpu.generator import (
    NEMESIS, PENDING, Context, as_gen, context, next_process,
)
from jepsen_tpu.utils import ms_to_nanos

DEFAULT_TEST = {"concurrency": 2}

# sentinels for the native scheduler lane plumbing (see _lane_attempt):
# _AUTO = resolve the lane from the ingest dispatch layer per call;
# _NO_INJECT = no half-finished step handed back by a lane bail
_AUTO = object()
_NO_INJECT = object()


def _native_lane():
    """The probed-and-trusted C scheduler loop (columnar_ext.c
    sim_lane), or None. Resolved per simulate() call — the ingest
    knob/probe latch (history_ir/ingest.py) owns the verdict, so
    ``ingest_native=0`` / a probe divergence turn this off exactly like
    the rest of the native plane."""
    try:
        from jepsen_tpu.history_ir import ingest
        return ingest.sim_lane()
    except Exception:  # noqa: BLE001 — the lane is an optimization only
        return None


def default_context(test: dict | None = None, seed: int = 0) -> Context:
    """Two client workers plus nemesis, deterministic rng
    (generator/test.clj:8-24, with-fixed-rand seeding :32-40)."""
    return context(test or DEFAULT_TEST, rng=random.Random(seed))


class StepClock:
    """A virtual wall clock derived from its OWN call count: each read
    advances ``step_s`` seconds. Injected into :func:`simulate` as
    ``clock``, it makes ``max_wall_s`` a pure step-count cap — the same
    seed truncates at the same op under any machine load, which is the
    reproducibility contract schedule fuzzing is built on
    (doc/robustness.md "Schedule fuzzing")."""

    def __init__(self, step_s: float = 1e-6):
        self.step_s = step_s
        self.reads = 0

    def __call__(self) -> float:
        t = self.reads * self.step_s
        self.reads += 1
        return t


def simulate(
    test: dict,
    gen,
    complete_fn: Callable[[Context, dict], dict | None],
    ctx: Context | None = None,
    limit: int = 100_000,
    *,
    seed: int = 0,
    max_wall_s: float | None = None,
    stats: dict | None = None,
    clock: Callable[[], float] | None = None,
    _lane=_AUTO,
) -> list[dict]:
    """Simulates gen against model workers.

    complete_fn(ctx, invoke_op) -> completion op (type ok/fail/info, with
    :time set to when the worker would finish) or None for ops that never
    complete. Pseudo-ops (:sleep/:log) occupy their thread for their
    duration but do not enter the returned history.

    Returns the full history: invokes and completions interleaved in time
    order, with generator updates and crashed-process renumbering applied
    exactly as the threaded interpreter would.

    Termination is guaranteed: ``limit`` caps scheduler steps (each step
    either emits an op, applies a completion, or breaks), ``seed`` makes
    the default context's rng injectable (deterministic enumeration —
    preflight and exact-sequence tests depend on it), and ``max_wall_s``
    adds a hard wall-clock cap for generators whose per-op cost is
    unbounded (preflight must never hang on a pathological generator);
    on expiry the history collected so far is returned. A generator
    stuck at :pending with nothing in flight is a deadlock and breaks
    immediately rather than spinning.

    ``clock`` makes the wall-cap clock injectable (default
    ``time.monotonic``). The real clock means the same seed can
    truncate at DIFFERENT ops under different machine load — fine for
    preflight's never-hang cap, fatal for seed ⇒ schedule
    reproducibility. Callers that need exact replay pass a virtual
    clock (:class:`StepClock`), making the cap a deterministic
    function of scheduler steps alone.

    Pass a dict as ``stats`` to learn HOW the simulation ended:
    ``steps`` taken, and ``step_limited`` / ``wall_limited`` flags —
    callers that must distinguish "generator exhausted" from "cap hit"
    (preflight's GEN003 truncation diagnostic) read these instead of
    guessing from history length.
    """
    ctx = ctx or default_context(test, seed=seed)
    g = as_gen(gen)
    history: list[dict] = []
    # completions waiting for their time, as a (time, seq, op) heap:
    # the soonest is peeked on EVERY step but removed only when it
    # applies, so O(1) peek beats the old per-step linear min(). ``seq``
    # (monotone insertion order) breaks time ties exactly the way the
    # old first-match scan did — and keeps the un-comparable op dicts
    # out of the tuple comparison.
    pending: list[tuple] = []
    pending_seq = 0
    if stats is None:
        stats = {}
    stats.update(steps=0, step_limited=False, wall_limited=False)

    if clock is None:
        clock = _time.monotonic
    deadline = (clock() + max_wall_s
                if max_wall_s is not None else None)
    steps = 0
    inject = _NO_INJECT
    try:
        # the stock Limit(Fn)/stock-completer/stock-rng shape runs its
        # whole loop in C when the native ingest plane is trusted —
        # bit-identical by the sim_lane contract (history dicts, rng
        # entropy, step counts), with a mid-step bail handing the
        # consumed f() result back through ``inject``
        if deadline is None and g is not None:
            lane = _native_lane() if _lane is _AUTO else _lane
            if lane is not None:
                _lsteps = [0]
                try:
                    out = _lane_attempt(test, g, ctx, complete_fn, limit,
                                        history, pending, lane, _lsteps)
                finally:
                    # on any exit — f() raising included — the lane has
                    # folded its progress back; the twin would have
                    # counted those steps too
                    steps = _lsteps[0]
                if out is not None:
                    status, pending_seq, g, ctx, bail_x = out
                    if status == 1:
                        stats["step_limited"] = True
                        return history
                    if status == 0:
                        return history
                    # status 3: f() already ran for this step — finish
                    # the step's tail here and continue pure-Python
                    inject = g.op_tail(g.gen.op_tail(test, ctx, bail_x))
        while True:
            if inject is not _NO_INJECT:
                # a lane bail mid-step: the limit check, step count and
                # g.op consult already happened natively — resume at
                # the res-handling point with the handed-back result
                res = inject
                inject = _NO_INJECT
                comp = pending[0][2] if pending else None
            else:
                if steps >= limit:
                    stats["step_limited"] = True
                    break
                steps += 1
                if deadline is not None and clock() >= deadline:
                    stats["wall_limited"] = True
                    break
                comp = pending[0][2] if pending else None
                res = g.op(test, ctx) if g is not None else None
            if res is None:
                if comp is None:
                    break
                g, ctx, _ = _apply_completion(test, g, ctx, comp, history)
                _heappop(pending)
                continue
            op, g_next = res
            if op is PENDING:
                if comp is None:
                    # Nothing will ever free a thread or advance time:
                    # deadlock.
                    break
                g, ctx, _ = _apply_completion(test, g, ctx, comp, history)
                _heappop(pending)
                continue
            if comp is not None and pending[0][0] <= op["time"]:
                # the completion happens first: apply it (updating the
                # generator — an until_ok/on_update must see it) and
                # reconsult; the op we were offered came from the
                # pre-completion generator state and is NOT dispatched
                g, ctx, _ = _apply_completion(test, g, ctx, comp, history)
                _heappop(pending)
                continue
            # dispatch the op
            g = g_next
            if op["time"] > ctx.time:
                ctx = ctx.with_time(op["time"])
            thread = (NEMESIS if op["process"] == NEMESIS
                      else ctx.thread_of(op["process"]))
            ctx = ctx.busy_thread(thread)
            if op["type"] in ("sleep", "log"):
                dt = op["value"] if op["type"] == "sleep" else 0
                completion = dict(op)
                completion["time"] = (op["time"]
                                      + ms_to_nanos(dt * 1000 if dt else 0))
                completion["type"] = "__free__"
                _heappush(pending,
                          (completion["time"], pending_seq, completion))
                pending_seq += 1
                if g is not None:
                    g = g.update(test, ctx, op)
                continue
            history.append(op)
            if g is not None:
                g = g.update(test, ctx, op)
            completion = complete_fn(ctx, op)
            if completion is not None:
                _heappush(pending,
                          (completion["time"], pending_seq, completion))
                pending_seq += 1
    finally:
        stats["steps"] = steps
    return history


def _apply_completion(test, g, ctx, comp, history):
    if comp["time"] > ctx.time:
        ctx = ctx.with_time(comp["time"])
    thread = NEMESIS if comp["process"] == NEMESIS else ctx.thread_of(comp["process"])
    ctx = ctx.free_thread(thread)
    if comp["type"] == "__free__":
        return g, ctx, False
    if comp["type"] == "info" and comp["process"] != NEMESIS:
        # crashed: worker gets a fresh process id (generator.clj:519-527)
        ctx = ctx.with_next_process(thread)
    history.append(comp)
    if g is not None:
        g = g.update(test, ctx, comp)
    return g, ctx, False


def _lane_attempt(test, g, ctx, complete_fn, limit, history, pending,
                  lane, steps_out):
    """Runs the scheduler's hot loop natively when every moving part is
    the stock shape (columnar_ext.c sim_lane's contract): Limit(Fn)
    with a zero-arity plain function, a ``_sim_kind``-marked ok/fail
    completer, a stock random.Random, <= 62 threads with unique
    process ids. Returns None when ineligible — the caller runs the
    pure loop from untouched state — else ``(status, pending_seq, g,
    ctx, bail_x)`` with the shared ``history``/``pending`` lists
    already advanced and ``steps_out[0]`` holding the steps taken
    (set even when the lane propagates an exception from f()).
    """
    kind = getattr(complete_fn, "_sim_kind", None)
    if (kind is None or kind[0] not in ("ok", "fail")
            or type(kind[1]) is not int or kind[1] < 0):
        return None
    if (type(g) is not gen_mod.Limit
            or type(g.gen) is not gen_mod.Fn):
        return None
    remaining = g.remaining
    if (type(remaining) is not int or abs(remaining) > 2 ** 60
            or type(limit) is not int or not 0 <= limit <= 2 ** 60):
        return None
    fn_gen = g.gen
    f = fn_gen.f
    style = fn_gen.__dict__.get("_style")
    if style is None:
        if type(f) is not _types.FunctionType:
            return None
        code = f.__code__
        if code.co_argcount != 0 or (code.co_flags & 0x04):
            return None
        # f(test, ctx) would TypeError("...positional argument...") and
        # Fn.op's probe would settle on f(): memoize that verdict the
        # same way the probe does
        object.__setattr__(fn_gen, "_style", 0)
    elif style != 0:
        return None
    rng = ctx.rng
    if type(rng) is not random.Random:
        return None
    time0 = ctx.time
    if type(time0) is not int or not 0 <= time0 <= 2 ** 60:
        return None
    workers = ctx.workers
    n = len(workers)
    if not 1 <= n <= 62:
        return None
    try:
        # bit i of the lane's free mask = the i-th thread in sorted
        # order, so subset sort order == ascending bit order
        ts = sorted(workers, key=gen_mod._thread_sort_key)
        procs = [workers[t] for t in ts]
        if len(set(procs)) != n:
            return None  # thread_of needs unique process ids
        pos = {t: i for i, t in enumerate(ts)}
        freemask = 0
        for t in ctx.free_threads:
            freemask |= 1 << pos[t]
    except (TypeError, KeyError):
        return None
    st = rng.getstate()
    if st[0] != 3 or len(st[1]) != 625:
        return None
    S = {"f": f, "remaining": remaining, "limit": limit, "steps": 0,
         "time": time0, "procs": procs, "free": freemask,
         "history": history, "typ": kind[0], "latency": kind[1],
         "mt": st[1], "seq": 0}
    try:
        status = lane(S)
    finally:
        # the lane writes back over the keys it read on EVERY exit
        # (errors included), so folding up is unconditional; a call
        # that died before loading state folds back as a no-op
        steps_out[0] = S["steps"]
        rng.setstate((3, S["mt"], st[2]))
        pending.extend(S.get("pending", ()))
    fm = S["free"]
    fs = frozenset(t for i, t in enumerate(ts) if fm >> i & 1)
    c = Context.__new__(Context)
    d = c.__dict__
    d["time"] = S["time"]
    d["free_threads"] = fs
    d["workers"] = workers
    d["rng"] = rng
    g2 = gen_mod._mk_limit(S["remaining"], fn_gen)
    return (status, S["seq"], g2, c, S.pop("bail_x", None))


def _lane_probe(lane) -> bool:
    """Canned differential for ingest._probe: the native scheduler lane
    vs the pure twin across latencies (pre-emption), seeds (MT
    write-back), concurrencies (PENDING pressure) and a bail-heavy
    generator (mid-step handoff). True iff histories, stats AND the
    rng's end state all match."""
    def mk():
        c = {"n": 0}
        def f():
            c["n"] += 1
            return {"f": "write", "value": c["n"] % 5}
        return gen_mod.limit(40, gen_mod.Fn(f))

    def mk_bail():
        c = {"n": 0}
        def f():
            c["n"] += 1
            if c["n"] > 30:
                return None
            if c["n"] % 7 == 0:
                # explicit process key: outside the lane's dict shape,
                # forces the consumed-x bail handoff
                return {"f": "read", "value": None, "process": None}
            return {"f": "w", "value": c["n"]}
        return gen_mod.limit(25, gen_mod.Fn(f))

    def fp(h):
        # key INSERTION order is part of the bit-identity contract
        # (json/repr of history dicts see it), so == isn't enough
        return [list(op.items()) for op in h]

    try:
        for mk_gen in (mk, mk_bail):
            for typ, latency in (("ok", 0), ("ok", 7), ("fail", 3)):
                for seed in (0, 7):
                    for conc in (1, 2, 5):
                        test = {"concurrency": conc}
                        r1, r2 = random.Random(seed), random.Random(seed)
                        s1: dict = {}
                        s2: dict = {}
                        h1 = simulate(test, mk_gen(),
                                      _completer(typ, latency),
                                      context(test, rng=r1),
                                      stats=s1, _lane=None)
                        h2 = simulate(test, mk_gen(),
                                      _completer(typ, latency),
                                      context(test, rng=r2),
                                      stats=s2, _lane=lane)
                        if (fp(h1) != fp(h2) or s1 != s2
                                or r1.getstate() != r2.getstate()):
                            return False
        return True
    except Exception:  # noqa: BLE001 — a crashing lane condemns native
        return False


def _completer(typ: str, latency_nanos: int):
    def complete(ctx: Context, op: dict):
        comp = dict(op)
        comp["type"] = typ
        comp["time"] = op["time"] + latency_nanos
        return comp
    # the native scheduler lane recognizes this stock completer by its
    # (type, latency) signature instead of decompiling the closure
    complete._sim_kind = (typ, latency_nanos)
    return complete


def quick(test: dict, gen, ctx: Context | None = None, **caps) -> list[dict]:
    """Zero-latency :ok completions — the fastest way to see what a
    generator emits (generator/test.clj quick). ``caps`` pass through to
    :func:`simulate` (``seed``/``limit``/``max_wall_s`` — preflight's
    bounded enumeration rides this)."""
    return simulate(test, gen, _completer("ok", 0), ctx, **caps)


def perfect(test: dict, gen, ctx: Context | None = None, latency_ms: float = 10.0, **caps) -> list[dict]:
    """Fixed-latency :ok completions (generator/test.clj perfect)."""
    return simulate(test, gen, _completer("ok", ms_to_nanos(latency_ms)), ctx, **caps)


def perfect_info(test: dict, gen, ctx: Context | None = None, latency_ms: float = 10.0, **caps) -> list[dict]:
    """Fixed-latency :info (crashed) completions — exercises process
    renumbering (generator/test.clj perfect-info)."""
    return simulate(test, gen, _completer("info", ms_to_nanos(latency_ms)), ctx, **caps)


def invocations(history: list[dict]) -> list[dict]:
    return [op for op in history if op.get("type") == "invoke"]
