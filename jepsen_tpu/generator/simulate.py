"""Deterministic generator simulation — the unit-test backbone.

Equivalent capability to jepsen.generator.test (shipped in the reference's
src/ as a public testing kit, jepsen/src/jepsen/generator/test.clj): a
simulated scheduler with model workers of fixed latency, so generator
behavior is asserted as exact op/time/process sequences without threads or a
cluster (SURVEY.md §4 tier 1).
"""
from __future__ import annotations

import random
import time as _time
from typing import Callable

from jepsen_tpu import generator as gen_mod
from jepsen_tpu.generator import (
    NEMESIS, PENDING, Context, as_gen, context, next_process,
)
from jepsen_tpu.utils import ms_to_nanos

DEFAULT_TEST = {"concurrency": 2}


def default_context(test: dict | None = None, seed: int = 0) -> Context:
    """Two client workers plus nemesis, deterministic rng
    (generator/test.clj:8-24, with-fixed-rand seeding :32-40)."""
    return context(test or DEFAULT_TEST, rng=random.Random(seed))


def simulate(
    test: dict,
    gen,
    complete_fn: Callable[[Context, dict], dict | None],
    ctx: Context | None = None,
    limit: int = 100_000,
    *,
    seed: int = 0,
    max_wall_s: float | None = None,
    stats: dict | None = None,
) -> list[dict]:
    """Simulates gen against model workers.

    complete_fn(ctx, invoke_op) -> completion op (type ok/fail/info, with
    :time set to when the worker would finish) or None for ops that never
    complete. Pseudo-ops (:sleep/:log) occupy their thread for their
    duration but do not enter the returned history.

    Returns the full history: invokes and completions interleaved in time
    order, with generator updates and crashed-process renumbering applied
    exactly as the threaded interpreter would.

    Termination is guaranteed: ``limit`` caps scheduler steps (each step
    either emits an op, applies a completion, or breaks), ``seed`` makes
    the default context's rng injectable (deterministic enumeration —
    preflight and exact-sequence tests depend on it), and ``max_wall_s``
    adds a hard wall-clock cap for generators whose per-op cost is
    unbounded (preflight must never hang on a pathological generator);
    on expiry the history collected so far is returned. A generator
    stuck at :pending with nothing in flight is a deadlock and breaks
    immediately rather than spinning.

    Pass a dict as ``stats`` to learn HOW the simulation ended:
    ``steps`` taken, and ``step_limited`` / ``wall_limited`` flags —
    callers that must distinguish "generator exhausted" from "cap hit"
    (preflight's GEN003 truncation diagnostic) read these instead of
    guessing from history length.
    """
    ctx = ctx or default_context(test, seed=seed)
    g = as_gen(gen)
    history: list[dict] = []
    pending: list[dict] = []  # completion ops waiting for their time
    if stats is None:
        stats = {}
    stats.update(steps=0, step_limited=False, wall_limited=False)

    def soonest_pending():
        if not pending:
            return None
        return min(pending, key=lambda o: o["time"])

    deadline = (_time.monotonic() + max_wall_s
                if max_wall_s is not None else None)
    steps = 0
    while True:
        if steps >= limit:
            stats["step_limited"] = True
            break
        steps += 1
        stats["steps"] = steps
        if deadline is not None and _time.monotonic() >= deadline:
            stats["wall_limited"] = True
            break
        comp = soonest_pending()
        res = g.op(test, ctx) if g is not None else None
        if res is None:
            if comp is None:
                break
            g2, ctx, done = _apply_completion(test, g, ctx, comp, history)
            pending.remove(comp)
            g = g2
            continue
        op, g_next = res
        if op is PENDING:
            if comp is None:
                # Nothing will ever free a thread or advance time: deadlock.
                break
            g2, ctx, _ = _apply_completion(test, g, ctx, comp, history)
            pending.remove(comp)
            g = g2
            continue
        if comp is not None and comp["time"] <= op["time"]:
            # the completion happens first: apply it (updating the
            # generator — an until_ok/on_update must see it) and
            # reconsult; the op we were offered came from the
            # pre-completion generator state and is NOT dispatched
            g, ctx, _ = _apply_completion(test, g, ctx, comp, history)
            pending.remove(comp)
            continue
        # dispatch the op
        g = g_next
        ctx = ctx.with_time(max(ctx.time, op["time"]))
        thread = NEMESIS if op["process"] == NEMESIS else ctx.thread_of(op["process"])
        ctx = ctx.busy_thread(thread)
        if op["type"] in ("sleep", "log"):
            dt = op["value"] if op["type"] == "sleep" else 0
            completion = dict(op)
            completion["time"] = op["time"] + ms_to_nanos(dt * 1000 if dt else 0)
            completion["type"] = "__free__"
            pending.append(completion)
            if g is not None:
                g = g.update(test, ctx, op)
            continue
        history.append(op)
        if g is not None:
            g = g.update(test, ctx, op)
        completion = complete_fn(ctx, op)
        if completion is not None:
            pending.append(completion)
    return history


def _apply_completion(test, g, ctx, comp, history):
    ctx = ctx.with_time(max(ctx.time, comp["time"]))
    thread = NEMESIS if comp["process"] == NEMESIS else ctx.thread_of(comp["process"])
    ctx = ctx.free_thread(thread)
    if comp["type"] == "__free__":
        return g, ctx, False
    if comp["type"] == "info" and comp["process"] != NEMESIS:
        # crashed: worker gets a fresh process id (generator.clj:519-527)
        ctx = ctx.with_next_process(thread)
    history.append(comp)
    if g is not None:
        g = g.update(test, ctx, comp)
    return g, ctx, False


def _completer(typ: str, latency_nanos: int):
    def complete(ctx: Context, op: dict):
        comp = dict(op)
        comp["type"] = typ
        comp["time"] = op["time"] + latency_nanos
        return comp
    return complete


def quick(test: dict, gen, ctx: Context | None = None, **caps) -> list[dict]:
    """Zero-latency :ok completions — the fastest way to see what a
    generator emits (generator/test.clj quick). ``caps`` pass through to
    :func:`simulate` (``seed``/``limit``/``max_wall_s`` — preflight's
    bounded enumeration rides this)."""
    return simulate(test, gen, _completer("ok", 0), ctx, **caps)


def perfect(test: dict, gen, ctx: Context | None = None, latency_ms: float = 10.0, **caps) -> list[dict]:
    """Fixed-latency :ok completions (generator/test.clj perfect)."""
    return simulate(test, gen, _completer("ok", ms_to_nanos(latency_ms)), ctx, **caps)


def perfect_info(test: dict, gen, ctx: Context | None = None, latency_ms: float = 10.0, **caps) -> list[dict]:
    """Fixed-latency :info (crashed) completions — exercises process
    renumbering (generator/test.clj perfect-info)."""
    return simulate(test, gen, _completer("info", ms_to_nanos(latency_ms)), ctx, **caps)


def invocations(history: list[dict]) -> list[dict]:
    return [op for op in history if op.get("type") == "invoke"]
