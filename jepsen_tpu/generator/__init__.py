"""Purely-functional op scheduling DSL (reference: jepsen/src/jepsen/generator.clj).

A *generator* is an immutable value with two operations::

    gen.op(test, ctx)      -> None                      (exhausted)
                            | (PENDING, gen')           (nothing soon)
                            | (op_dict, gen')           (op to run at op["time"])

    gen.update(test, ctx, event) -> gen'                (react to history event)

(protocol at generator.clj:382-390). Plain data act as generators
(generator.clj:545-620): a dict emits exactly one op; a list emits each
element in order; a callable is invoked as ``f(test, ctx)`` or ``f()`` each
time and stays in place until it returns None; None is exhausted.

The *context* models logical time (relative nanos), the set of free threads,
and the thread->process map (generator.clj:453-464). All scheduling decisions
are pure: the interpreter (generator/interpreter.py) and the deterministic
simulator (generator/simulate.py) both drive the same protocol, which is what
makes the reference's exact-output unit-test strategy (SURVEY.md §4 tier 1)
possible here.
"""
from __future__ import annotations

import logging
import random as _random
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Iterable, Sequence

from jepsen_tpu.utils import secs_to_nanos

logger = logging.getLogger("jepsen.generator")

NEMESIS = "nemesis"


class _Pending:
    __slots__ = ()

    def __repr__(self):
        return "PENDING"


PENDING = _Pending()


# ---------------------------------------------------------------------------
# Context
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Context:
    """Scheduling context: logical time, free threads, thread->process map.

    Threads are identified by ints 0..n-1 plus the string "nemesis". A
    *process* is the logical client identity; when a process crashes (:info),
    its thread gets a fresh process id = old + concurrency
    (generator.clj:519-527).
    """

    time: int = 0
    free_threads: frozenset = frozenset()
    workers: dict = field(default_factory=dict)  # thread -> process (treat as immutable)
    rng: _random.Random = field(default_factory=lambda: _random.Random(), compare=False, repr=False)

    # -- queries ----------------------------------------------------------
    def all_threads(self):
        return self.workers.keys()

    def thread_count(self) -> int:
        return len(self.workers)

    def process_of(self, thread):
        return self.workers[thread]

    def thread_of(self, process):
        # fast path: until a process crashes, workers[t] == t, so a
        # process that maps to itself IS its thread (process ids are
        # unique across the map, so no other thread can claim it)
        w = self.workers
        if process is not None and w.get(process) == process:
            return process
        for t, p in w.items():
            if p == process:
                return t
        return None

    def free_processes(self) -> list:
        return [self.workers[t] for t in self.free_threads]

    def some_free_process(self):
        """Fair uniform choice among free threads' processes
        (generator.clj:480-487; fairness rationale 438-449)."""
        if not self.free_threads:
            return None
        # the sorted view is memoized by the free-thread SET value —
        # contexts churn every step but cycle through few distinct sets,
        # and the per-poll sort was the scheduler loop's second-hottest
        # cost (the reference leans on Bifurcan's ordered sets here)
        ts = _FREE_SORT_CACHE.get(self.free_threads)
        if ts is None:
            if len(_FREE_SORT_CACHE) > 4096:
                _FREE_SORT_CACHE.clear()
            ts = sorted(self.free_threads, key=_thread_sort_key)
            _FREE_SORT_CACHE[self.free_threads] = ts
        # rng._randbelow is the exact draw randrange()/choice() bottom
        # out in (Random dispatches it per-instance, so subclasses that
        # override random() keep their variant) — byte-identical entropy
        # consumption, so deterministic enumeration (preflight,
        # exact-sequence tests) sees the same schedule, minus two frames
        # of argument plumbing on the hottest call of the scheduler
        return self.workers[ts[self.rng._randbelow(len(ts))]]

    # -- functional updates (direct __dict__ construction: the generated
    # frozen-dataclass __init__ routes every field through
    # object.__setattr__, ~3x the cost of plain dict stores, and these
    # three run on every scheduler step) --------------------------------
    def with_time(self, time: int) -> "Context":
        c = Context.__new__(Context)
        d = c.__dict__
        d["time"] = time
        d["free_threads"] = self.free_threads
        d["workers"] = self.workers
        d["rng"] = self.rng
        return c

    def busy_thread(self, thread) -> "Context":
        # free-thread sets cycle through a tiny space (2^threads), so
        # the set algebra is memoized the same way the sorted view is
        key = (self.free_threads, thread)
        fs = _FREE_SUB_CACHE.get(key)
        if fs is None:
            if len(_FREE_SUB_CACHE) > 4096:
                _FREE_SUB_CACHE.clear()
            fs = _FREE_SUB_CACHE[key] = self.free_threads - {thread}
        c = Context.__new__(Context)
        d = c.__dict__
        d["time"] = self.time
        d["free_threads"] = fs
        d["workers"] = self.workers
        d["rng"] = self.rng
        return c

    def free_thread(self, thread) -> "Context":
        key = (self.free_threads, thread)
        fs = _FREE_ADD_CACHE.get(key)
        if fs is None:
            if len(_FREE_ADD_CACHE) > 4096:
                _FREE_ADD_CACHE.clear()
            fs = _FREE_ADD_CACHE[key] = self.free_threads | {thread}
        c = Context.__new__(Context)
        d = c.__dict__
        d["time"] = self.time
        d["free_threads"] = fs
        d["workers"] = self.workers
        d["rng"] = self.rng
        return c

    def with_next_process(self, thread) -> "Context":
        """Assigns a fresh process id to thread after a crash."""
        workers = dict(self.workers)
        workers[thread] = next_process(self, thread)
        return replace(self, workers=workers)

    def restrict(self, threads: frozenset) -> "Context":
        """A sub-context containing only the given threads (on-threads,
        generator.clj:844-883)."""
        return replace(
            self,
            free_threads=self.free_threads & threads,
            workers={t: p for t, p in self.workers.items() if t in threads},
        )


_FREE_SORT_CACHE: dict = {}
_FREE_SUB_CACHE: dict = {}
_FREE_ADD_CACHE: dict = {}


def _thread_sort_key(t):
    return (1, 0) if t == NEMESIS else (0, t)


def next_process(ctx: Context, thread):
    """Process id for thread after its current process crashes: old + number
    of client threads; nemesis never renumbers (generator.clj:519-527)."""
    if thread == NEMESIS:
        return NEMESIS
    client_threads = sum(1 for t in ctx.workers if t != NEMESIS)
    return ctx.workers[thread] + client_threads


def context(test: dict, rng: _random.Random | None = None) -> Context:
    """Fresh context for a test: threads 0..concurrency-1 plus nemesis, all
    free, workers[i] = i (generator.clj:453-464)."""
    n = test.get("concurrency", 1)
    threads = list(range(n)) + [NEMESIS]
    return Context(
        time=0,
        free_threads=frozenset(threads),
        workers={t: t for t in threads},
        rng=rng or _random.Random(),
    )


def fill_in_op(op: dict, ctx: Context):
    """Fills in missing :time (ctx.time) and :process (some free process) on
    an op template (generator.clj:531-543). Returns PENDING if the op needs a
    process and none is free."""
    op = dict(op)
    if op.get("process") is None:
        p = ctx.some_free_process()
        if p is None:
            return PENDING
        op["process"] = p
    if op.get("time") is None:
        op["time"] = ctx.time
    op.setdefault("type", "invoke")
    op.setdefault("f", None)
    op.setdefault("value", None)
    return op


# ---------------------------------------------------------------------------
# The Generator protocol + data coercion
# ---------------------------------------------------------------------------

class Generator:
    def op(self, test: dict, ctx: Context):
        raise NotImplementedError

    def update(self, test: dict, ctx: Context, event: dict) -> "Generator":
        return self

    # Combinator sugar so gens compose fluently.
    def __rshift__(self, other):
        return then(other, self)


def as_gen(x) -> "Generator | None":
    """Coerces plain data to a generator (generator.clj:545-620)."""
    if x is None or isinstance(x, Generator):
        return x
    if isinstance(x, dict):
        return OpTemplate(x)
    if isinstance(x, (list, tuple)):
        return Seq([e for e in x if e is not None])
    if callable(x):
        return Fn(x)
    raise TypeError(f"don't know how to treat {x!r} as a generator")


@dataclass(frozen=True)
class OpTemplate(Generator):
    """A dict is a generator that emits exactly one op, then is exhausted."""

    template: dict

    def op(self, test, ctx):
        op = fill_in_op(self.template, ctx)
        if op is PENDING:
            return (PENDING, self)
        return (op, None)


@dataclass(frozen=True)
class Fn(Generator):
    """A callable invoked as f(test, ctx) or f() each time an op is needed.
    Returns an op-map or generator; the fn itself stays in place. Exhausted
    when the call returns None (generator.clj:575-599)."""

    f: Callable

    def op(self, test, ctx):
        # the calling convention (f(test, ctx) vs f()) is discovered once
        # by trial and memoized: the old raise-and-retry probe cost ~1µs
        # of exception machinery on EVERY op for zero-arity fns — the
        # single hottest line of the simulated scheduler. The memo lives
        # outside the dataclass fields, so equality/hash are unchanged.
        f = self.f
        style = self.__dict__.get("_style")
        if style == 0:
            x = f()
        elif style == 1:
            x = f(test, ctx)
        else:
            try:
                x = f(test, ctx)
                object.__setattr__(self, "_style", 1)
            except TypeError as e:
                if "positional argument" in str(e):
                    x = f()
                    object.__setattr__(self, "_style", 0)
                else:
                    raise
        return self.op_tail(test, ctx, x)

    def op_tail(self, test, ctx, x):
        """Fn.op's tail after ``x = f()`` — split out so the native
        scheduler lane (columnar_ext.c sim_lane) can hand back an
        already-consumed x on bail without calling f twice."""
        if x is None:
            return None
        if type(x) is dict:
            # exactly what as_gen→OpTemplate.op would produce — one op,
            # inner generator exhausted, the fn stays as continuation —
            # with fill_in_op's body inlined (x may be a shared template,
            # so the copy is load-bearing; only the frames are shed)
            op = dict(x)
            if op.get("process") is None:
                p = ctx.some_free_process()
                if p is None:
                    return (PENDING, self)
                op["process"] = p
            if op.get("time") is None:
                op["time"] = ctx.time
            op.setdefault("type", "invoke")
            op.setdefault("f", None)
            op.setdefault("value", None)
            return (op, self)
        gen = as_gen(x)
        res = gen.op(test, ctx)
        if res is None:
            return None
        op, gen2 = res
        if op is PENDING:
            return (PENDING, self)
        # emitted one op from the result; the fn remains our continuation
        return (op, self if gen2 is None else Seq([gen2, self]))


@dataclass(frozen=True)
class Seq(Generator):
    """Emits each element generator in order (vectors/seqs as generators)."""

    gens: tuple

    def __init__(self, gens: Iterable):
        object.__setattr__(self, "gens", tuple(gens))

    def op(self, test, ctx):
        gens = self.gens
        while gens:
            g = as_gen(gens[0])
            if g is None:
                gens = gens[1:]
                continue
            res = g.op(test, ctx)
            if res is None:
                gens = gens[1:]
                continue
            op, g2 = res
            rest = (g2,) + gens[1:] if g2 is not None else gens[1:]
            if op is PENDING and not rest:
                return (PENDING, Seq(()))
            return (op, Seq(rest) if rest else None)
        return None

    def update(self, test, ctx, event):
        if not self.gens:
            return self
        g = as_gen(self.gens[0])
        if g is None:
            return self
        g2 = g.update(test, ctx, event)
        return Seq((g2,) + self.gens[1:])


# ---------------------------------------------------------------------------
# Combinators
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Validate(Generator):
    """Checks that emitted ops are well-formed (generator.clj:622-676)."""

    gen: Any

    def op(self, test, ctx):
        g = as_gen(self.gen)
        if g is None:
            return None
        res = g.op(test, ctx)
        if res is None:
            return None
        op, g2 = res
        if op is not PENDING:
            problems = []
            if not isinstance(op, dict):
                problems.append(f"op {op!r} is not a dict")
            else:
                if op.get("type") not in ("invoke", "info", "sleep", "log"):
                    problems.append(f"bad :type {op.get('type')!r}")
                if op.get("type") == "invoke":
                    p = op.get("process")
                    if p not in ctx.free_processes():
                        problems.append(f"process {p!r} is not free")
                if not isinstance(op.get("time"), int):
                    problems.append("no :time")
            if problems:
                raise ValueError(f"invalid op {op!r}: {problems}")
        return (op, Validate(g2) if g2 is not None else None)

    def update(self, test, ctx, event):
        g = as_gen(self.gen)
        if g is None:
            return self
        return Validate(g.update(test, ctx, event))


@dataclass(frozen=True)
class FriendlyExceptions(Generator):
    """Wraps op/update to re-raise with generator context
    (generator.clj:678-718)."""

    gen: Any

    def op(self, test, ctx):
        g = as_gen(self.gen)
        if g is None:
            return None
        try:
            res = g.op(test, ctx)
        except Exception as e:
            raise RuntimeError(
                f"generator {type(g).__name__} threw {e!r} when asked for an op"
            ) from e
        if res is None:
            return None
        op, g2 = res
        return (op, FriendlyExceptions(g2) if g2 is not None else None)

    def update(self, test, ctx, event):
        g = as_gen(self.gen)
        if g is None:
            return self
        try:
            return FriendlyExceptions(g.update(test, ctx, event))
        except Exception as e:
            raise RuntimeError(
                f"generator {type(g).__name__} threw {e!r} on update {event!r}"
            ) from e


@dataclass(frozen=True)
class Trace(Generator):
    """Logs every op/update with context (generator.clj:720-763)."""

    k: str
    gen: Any

    def op(self, test, ctx):
        g = as_gen(self.gen)
        if g is None:
            logger.info("%s op -> exhausted", self.k)
            return None
        res = g.op(test, ctx)
        logger.info("%s op(time=%d free=%s) -> %r", self.k, ctx.time,
                    sorted(ctx.free_threads, key=_thread_sort_key), res and res[0])
        if res is None:
            return None
        op, g2 = res
        return (op, Trace(self.k, g2) if g2 is not None else None)

    def update(self, test, ctx, event):
        g = as_gen(self.gen)
        logger.info("%s update %r", self.k, event)
        if g is None:
            return self
        return Trace(self.k, g.update(test, ctx, event))


@dataclass(frozen=True)
class Map(Generator):
    """Applies f to every emitted op (generator.clj:765-796)."""

    f: Callable
    gen: Any

    def op(self, test, ctx):
        g = as_gen(self.gen)
        if g is None:
            return None
        res = g.op(test, ctx)
        if res is None:
            return None
        op, g2 = res
        if op is not PENDING:
            op = self.f(op)
        return (op, Map(self.f, g2) if g2 is not None else None)

    def update(self, test, ctx, event):
        g = as_gen(self.gen)
        if g is None:
            return self
        return Map(self.f, g.update(test, ctx, event))


def f_map(f_mapping: dict, gen) -> Generator:
    """Rewrites op :f via a mapping dict (for nemesis composition)."""
    def rewrite(op):
        op = dict(op)
        if op.get("f") in f_mapping:
            op["f"] = f_mapping[op["f"]]
        return op
    return Map(rewrite, gen)


def op_timeout(timeout_s, gen) -> Generator:
    """Stamps ``timeout_s`` onto every emitted op — the per-op deadline
    override the interpreter honors ahead of ``test['op_timeout_s']`` /
    ``JEPSEN_TPU_OP_TIMEOUT_S`` (doc/robustness.md). ``None``/``0``
    exempts these ops from deadlines entirely (e.g. a legitimately
    slow schema migration riding alongside deadline-bounded traffic)."""
    def stamp(op):
        return {**op, "timeout_s": timeout_s}
    return Map(stamp, gen)


@dataclass(frozen=True)
class Filter(Generator):
    """Emits only ops satisfying pred (generator.clj:798-817)."""

    pred: Callable
    gen: Any

    def op(self, test, ctx):
        g = as_gen(self.gen)
        while g is not None:
            res = g.op(test, ctx)
            if res is None:
                return None
            op, g2 = res
            if op is PENDING or self.pred(op):
                return (op, Filter(self.pred, g2) if g2 is not None else None)
            g = g2  # skip this op
        return None

    def update(self, test, ctx, event):
        g = as_gen(self.gen)
        if g is None:
            return self
        return Filter(self.pred, g.update(test, ctx, event))


@dataclass(frozen=True)
class OnUpdate(Generator):
    """Calls (f this test ctx event) to transform the whole generator on
    every update (generator.clj:827-842)."""

    f: Callable
    gen: Any

    def op(self, test, ctx):
        g = as_gen(self.gen)
        if g is None:
            return None
        res = g.op(test, ctx)
        if res is None:
            return None
        op, g2 = res
        return (op, OnUpdate(self.f, g2) if g2 is not None else None)

    def update(self, test, ctx, event):
        return self.f(self, test, ctx, event)


@dataclass(frozen=True)
class OnThreads(Generator):
    """Restricts gen to threads satisfying pred (generator.clj:844-883)."""

    pred: Callable  # thread -> bool
    gen: Any

    def _threads(self, ctx: Context) -> frozenset:
        return frozenset(t for t in ctx.workers if self.pred(t))

    def op(self, test, ctx):
        g = as_gen(self.gen)
        if g is None:
            return None
        sub = ctx.restrict(self._threads(ctx))
        res = g.op(test, sub)
        if res is None:
            return None
        op, g2 = res
        return (op, OnThreads(self.pred, g2) if g2 is not None else None)

    def update(self, test, ctx, event):
        g = as_gen(self.gen)
        if g is None:
            return self
        p = event.get("process")
        thread = ctx.thread_of(p) if p != NEMESIS else NEMESIS
        if thread is not None and self.pred(thread):
            sub = ctx.restrict(self._threads(ctx))
            return OnThreads(self.pred, g.update(test, sub, event))
        return self


def on_threads(threads, gen) -> Generator:
    ts = frozenset(threads)
    return OnThreads(lambda t: t in ts, gen)


def clients(gen) -> Generator:
    """Restricts to client threads (generator.clj:1093-1103)."""
    return OnThreads(lambda t: t != NEMESIS, gen)


def nemesis_gen(gen) -> Generator:
    """Restricts to the nemesis thread (generator.clj:1105-1115)."""
    return OnThreads(lambda t: t == NEMESIS, gen)


def soonest_op_map(candidates: Sequence[tuple]) -> tuple | None:
    """Given (op, gen, weight-ish) candidate tuples, picks the one whose op
    has the earliest time; PENDING sorts last; ties break by weight-ish
    random choice (generator.clj:885-927). Candidates are (op, gen, key)."""
    best = None
    best_time = None
    for cand in candidates:
        op = cand[0]
        if op is PENDING:
            if best is None:
                best = cand
                best_time = None
        else:
            t = op.get("time", 0)
            if best_time is None or t < best_time:
                best = cand
                best_time = t
    return best


@dataclass(frozen=True)
class Any_(Generator):
    """Emits the soonest op from any of several generators
    (generator.clj:929-953). Updates propagate to all."""

    gens: tuple

    def op(self, test, ctx):
        candidates = []
        alive = []
        for i, g in enumerate(self.gens):
            g = as_gen(g)
            if g is None:
                continue
            res = g.op(test, ctx)
            if res is None:
                continue
            alive.append((i, g))
            candidates.append((res[0], res[1], i))
        if not candidates:
            return None
        best = soonest_op_map(candidates)
        op, g2, i = best
        new_gens = []
        for j, g in enumerate(self.gens):
            if as_gen(g) is None:
                new_gens.append(g)
            elif j == i:
                new_gens.append(g2)
            else:
                new_gens.append(g)
        if op is PENDING:
            return (PENDING, self)
        return (op, Any_(tuple(new_gens)))

    def update(self, test, ctx, event):
        return Any_(tuple(
            as_gen(g).update(test, ctx, event) if as_gen(g) is not None else g
            for g in self.gens
        ))


def any_gen(*gens) -> Generator:
    return Any_(tuple(gens))


@dataclass(frozen=True)
class EachThread(Generator):
    """Gives each thread its own private copy of gen
    (generator.clj:955-1007)."""

    gen: Any
    per_thread: tuple = ()  # ((thread, gen-or-EXHAUSTED), ...)

    _EXHAUSTED = ("__exhausted__",)

    def _table(self):
        return dict(self.per_thread)

    def op(self, test, ctx):
        table = self._table()
        candidates = []
        for t in sorted(ctx.free_threads, key=_thread_sort_key):
            g = table.get(t, self.gen)
            if g is EachThread._EXHAUSTED:
                continue
            g = as_gen(g)
            if g is None:
                continue
            sub = ctx.restrict(frozenset([t]))
            res = g.op(test, sub)
            if res is None:
                table[t] = EachThread._EXHAUSTED
                continue
            candidates.append((res[0], res[1], t))
        if not candidates:
            # exhausted only when every thread's gen is exhausted
            if all(table.get(t, self.gen) is EachThread._EXHAUSTED for t in ctx.workers):
                return None
            return (PENDING, replace(self, per_thread=tuple(table.items())))
        best = soonest_op_map(candidates)
        op, g2, t = best
        if op is PENDING:
            return (PENDING, replace(self, per_thread=tuple(table.items())))
        table[t] = g2 if g2 is not None else EachThread._EXHAUSTED
        return (op, replace(self, per_thread=tuple(table.items())))

    def update(self, test, ctx, event):
        p = event.get("process")
        t = NEMESIS if p == NEMESIS else ctx.thread_of(p)
        if t is None:
            return self
        table = self._table()
        g = table.get(t, self.gen)
        if g is EachThread._EXHAUSTED:
            return self
        g = as_gen(g)
        if g is None:
            return self
        sub = ctx.restrict(frozenset([t]))
        table[t] = g.update(test, sub, event)
        return replace(self, per_thread=tuple(table.items()))


def each_thread(gen) -> Generator:
    return EachThread(gen)


@dataclass(frozen=True)
class Reserve(Generator):
    """Reserves thread ranges for specific generators; remaining threads get
    the default (generator.clj:1009-1089). Args: [(n1, gen1), (n2, gen2), ...],
    default_gen."""

    ranges: tuple  # ((frozenset_of_threads, gen), ...)
    default: Any

    def op(self, test, ctx):
        candidates = []
        reserved = frozenset().union(*[r[0] for r in self.ranges]) if self.ranges else frozenset()
        for i, (threads, g) in enumerate(self.ranges):
            g = as_gen(g)
            if g is None:
                continue
            sub = ctx.restrict(threads)
            res = g.op(test, sub)
            if res is not None:
                candidates.append((res[0], res[1], i))
        dg = as_gen(self.default)
        if dg is not None:
            rest = frozenset(t for t in ctx.workers if t not in reserved)
            res = dg.op(test, ctx.restrict(rest))
            if res is not None:
                candidates.append((res[0], res[1], -1))
        if not candidates:
            return None
        op, g2, i = soonest_op_map(candidates)
        if op is PENDING:
            return (PENDING, self)
        if i == -1:
            return (op, replace(self, default=g2))
        ranges = list(self.ranges)
        ranges[i] = (ranges[i][0], g2)
        return (op, replace(self, ranges=tuple(ranges)))

    def update(self, test, ctx, event):
        p = event.get("process")
        t = NEMESIS if p == NEMESIS else ctx.thread_of(p)
        if t is None:
            return self
        for i, (threads, g) in enumerate(self.ranges):
            if t in threads:
                g = as_gen(g)
                if g is None:
                    return self
                ranges = list(self.ranges)
                ranges[i] = (threads, g.update(test, ctx.restrict(threads), event))
                return replace(self, ranges=tuple(ranges))
        dg = as_gen(self.default)
        if dg is None:
            return self
        reserved = frozenset().union(*[r[0] for r in self.ranges]) if self.ranges else frozenset()
        rest = frozenset(x for x in ctx.workers if x not in reserved)
        return replace(self, default=dg.update(test, ctx.restrict(rest), event))


def reserve(*args) -> Generator:
    """reserve(n1, gen1, n2, gen2, ..., default_gen): first n1 threads run
    gen1, next n2 run gen2, ..., all other threads (incl. nemesis? no —
    clients only by convention) run default."""
    *pairs, default = args
    assert len(pairs) % 2 == 0, "reserve takes n,gen pairs plus a default"
    ranges = []
    start = 0
    for i in range(0, len(pairs), 2):
        n, g = pairs[i], pairs[i + 1]
        ranges.append((frozenset(range(start, start + n)), g))
        start += n
    return Reserve(tuple(ranges), default)


@dataclass(frozen=True)
class Mix(Generator):
    """Uniform random mixture of generators; exhausted ones drop out
    (generator.clj:1124-1154)."""

    gens: tuple

    def op(self, test, ctx):
        gens = list(self.gens)
        while gens:
            i = ctx.rng.randrange(len(gens))
            g = as_gen(gens[i])
            if g is None:
                gens.pop(i)
                continue
            res = g.op(test, ctx)
            if res is None:
                gens.pop(i)
                continue
            op, g2 = res
            if op is PENDING:
                return (PENDING, Mix(tuple(gens)))
            gens[i] = g2 if g2 is not None else None
            if gens[i] is None:
                gens.pop(i)
            return (op, Mix(tuple(gens)) if gens else None)
        return None


def mix(gens) -> Generator:
    return Mix(tuple(gens))


@dataclass(frozen=True)
class Limit(Generator):
    """At most n ops (generator.clj:1156-1170)."""

    remaining: int
    gen: Any

    def op(self, test, ctx):
        remaining = self.remaining
        if remaining <= 0:
            return None
        g = as_gen(self.gen)
        if g is None:
            return None
        return self.op_tail(g.op(test, ctx))

    def op_tail(self, res):
        """Limit.op's tail after the inner generator produced ``res`` —
        the native lane's bail handoff (simulate._lane_attempt)
        re-enters here with its consumed inner result."""
        if res is None:
            return None
        remaining = self.remaining
        op, g2 = res
        if op is PENDING:
            return (PENDING, _mk_limit(remaining, g2))
        return (op, _mk_limit(remaining - 1, g2) if g2 is not None else None)

    def update(self, test, ctx, event):
        g = as_gen(self.gen)
        if g is None:
            return self
        g2 = g.update(test, ctx, event)
        if g2 is g and g is self.gen:
            # inner generator ignored the event (Fn and friends return
            # self): the copy the old code built here was ==-identical,
            # so returning self is observationally the same value
            return self
        return Limit(self.remaining, g2)


def _mk_limit(remaining, gen) -> "Limit":
    """Limit built by direct __dict__ store — ==/hash-identical to
    Limit(remaining, gen), without the frozen-dataclass __init__ that
    routes both fields through object.__setattr__ (one Limit is built
    per emitted op, so this is scheduler-hot)."""
    lim = Limit.__new__(Limit)
    d = lim.__dict__
    d["remaining"] = remaining
    d["gen"] = gen
    return lim


def limit(n: int, gen) -> Generator:
    return Limit(n, gen)


def once(gen) -> Generator:
    """Exactly one op (generator.clj:1172-1175)."""
    return Limit(1, gen)


@dataclass(frozen=True)
class Log(Generator):
    """Emits a single :log pseudo-op (generator.clj:1177-1181); handled
    in-worker, excluded from history."""

    msg: str

    def op(self, test, ctx):
        op = fill_in_op({"type": "log", "value": self.msg, "f": None}, ctx)
        if op is PENDING:
            return (PENDING, self)
        return (op, None)


def log(msg: str) -> Generator:
    return Log(msg)


@dataclass(frozen=True)
class Repeat(Generator):
    """Emits the same underlying generator's op forever (or n times),
    never advancing it (generator.clj:1183-1210)."""

    remaining: int  # -1 = infinite
    gen: Any

    def op(self, test, ctx):
        if self.remaining == 0:
            return None
        g = as_gen(self.gen)
        if g is None:
            return None
        res = g.op(test, ctx)
        if res is None:
            return None
        op, _ = res
        if op is PENDING:
            return (PENDING, self)
        nxt = self.remaining - 1 if self.remaining > 0 else -1
        return (op, Repeat(nxt, self.gen) if nxt != 0 else None)

    def update(self, test, ctx, event):
        g = as_gen(self.gen)
        if g is None:
            return self
        return Repeat(self.remaining, g.update(test, ctx, event))


def repeat(*args) -> Generator:
    """repeat(gen) or repeat(n, gen)."""
    if len(args) == 1:
        return Repeat(-1, args[0])
    return Repeat(args[0], args[1])


_FRESH = "__cycle_fresh__"


@dataclass(frozen=True)
class Cycle(Generator):
    """Restarts gen from its original state when exhausted. ``remaining``
    counts cycles left to start; -1 = infinite."""

    remaining: int
    original: Any
    gen: Any = _FRESH

    def op(self, test, ctx):
        remaining, g = self.remaining, self.gen
        for _ in range(2):  # at most one restart per call
            if g is _FRESH:
                if remaining == 0:
                    return None
                if remaining > 0:
                    remaining -= 1
                g = self.original
            gg = as_gen(g)
            res = gg.op(test, ctx) if gg is not None else None
            if res is None:
                g = _FRESH
                continue
            op, g2 = res
            nxt = Cycle(remaining, self.original, g2 if g2 is not None else _FRESH)
            if op is PENDING:
                return (PENDING, nxt)
            return (op, nxt)
        return None

    def update(self, test, ctx, event):
        if self.gen is _FRESH:
            return self
        g = as_gen(self.gen)
        if g is None:
            return self
        return replace(self, gen=g.update(test, ctx, event))


def cycle(gen, times: int = -1) -> Generator:
    return Cycle(times, gen)


@dataclass(frozen=True)
class ProcessLimit(Generator):
    """Stops after n distinct processes have participated
    (generator.clj:1212-1237)."""

    n: int
    gen: Any
    seen: frozenset = frozenset()

    def op(self, test, ctx):
        g = as_gen(self.gen)
        if g is None:
            return None
        res = g.op(test, ctx)
        if res is None:
            return None
        op, g2 = res
        if op is PENDING:
            return (PENDING, replace(self, gen=g2))
        seen = self.seen | {op.get("process")}
        if len(seen) > self.n:
            return None
        return (op, replace(self, gen=g2, seen=seen) if g2 is not None else None)

    def update(self, test, ctx, event):
        g = as_gen(self.gen)
        if g is None:
            return self
        return replace(self, gen=g.update(test, ctx, event))


def process_limit(n: int, gen) -> Generator:
    return ProcessLimit(n, gen)


@dataclass(frozen=True)
class TimeLimit(Generator):
    """Passes ops through for dt seconds from the first op
    (generator.clj:1239-1263)."""

    dt_nanos: int
    gen: Any
    deadline: int | None = None

    def op(self, test, ctx):
        g = as_gen(self.gen)
        if g is None:
            return None
        res = g.op(test, ctx)
        if res is None:
            return None
        op, g2 = res
        if op is PENDING:
            return (PENDING, replace(self, gen=g2))
        deadline = self.deadline
        if deadline is None:
            deadline = op["time"] + self.dt_nanos
        if op["time"] >= deadline:
            return None
        return (op, replace(self, gen=g2, deadline=deadline) if g2 is not None else None)

    def update(self, test, ctx, event):
        g = as_gen(self.gen)
        if g is None:
            return self
        return replace(self, gen=g.update(test, ctx, event))


def time_limit(dt_seconds: float, gen) -> Generator:
    return TimeLimit(secs_to_nanos(dt_seconds), gen)


@dataclass(frozen=True)
class Stagger(Generator):
    """Schedules ops at uniform random intervals averaging dt seconds —
    a *total* rate across all threads, not per-thread
    (generator.clj:1265-1305)."""

    dt_nanos: int
    gen: Any
    next_time: int | None = None

    def op(self, test, ctx):
        g = as_gen(self.gen)
        if g is None:
            return None
        res = g.op(test, ctx)
        if res is None:
            return None
        op, g2 = res
        if op is PENDING:
            return (PENDING, replace(self, gen=g2))
        nt = self.next_time if self.next_time is not None else ctx.time
        op = dict(op)
        op["time"] = max(op["time"], nt)
        nt2 = nt + int(ctx.rng.random() * 2 * self.dt_nanos)
        return (op, replace(self, gen=g2, next_time=nt2) if g2 is not None else None)

    def update(self, test, ctx, event):
        g = as_gen(self.gen)
        if g is None:
            return self
        return replace(self, gen=g.update(test, ctx, event))


def stagger(dt_seconds: float, gen) -> Generator:
    return Stagger(secs_to_nanos(dt_seconds), gen)


@dataclass(frozen=True)
class Delay(Generator):
    """Emits ops no faster than every dt seconds (generator.clj:1344-1370)."""

    dt_nanos: int
    gen: Any
    next_time: int | None = None

    def op(self, test, ctx):
        g = as_gen(self.gen)
        if g is None:
            return None
        res = g.op(test, ctx)
        if res is None:
            return None
        op, g2 = res
        if op is PENDING:
            return (PENDING, replace(self, gen=g2))
        nt = self.next_time if self.next_time is not None else ctx.time
        op = dict(op)
        op["time"] = max(op["time"], nt)
        return (op, replace(self, gen=g2, next_time=op["time"] + self.dt_nanos)
                if g2 is not None else None)

    def update(self, test, ctx, event):
        g = as_gen(self.gen)
        if g is None:
            return self
        return replace(self, gen=g.update(test, ctx, event))


def delay(dt_seconds: float, gen) -> Generator:
    return Delay(secs_to_nanos(dt_seconds), gen)


@dataclass(frozen=True)
class Sleep(Generator):
    """One :sleep pseudo-op; the worker sleeps dt seconds
    (generator.clj:1372-1376)."""

    dt_seconds: float

    def op(self, test, ctx):
        op = fill_in_op({"type": "sleep", "value": self.dt_seconds, "f": None}, ctx)
        if op is PENDING:
            return (PENDING, self)
        return (op, None)


def sleep(dt_seconds: float) -> Generator:
    return Sleep(dt_seconds)


@dataclass(frozen=True)
class Synchronize(Generator):
    """Waits until every thread is free before unleashing gen
    (generator.clj:1378-1397)."""

    gen: Any
    released: bool = False

    def op(self, test, ctx):
        g = as_gen(self.gen)
        if g is None:
            return None
        if not self.released:
            if frozenset(ctx.workers) != ctx.free_threads:
                return (PENDING, self)
        res = g.op(test, ctx)
        if res is None:
            return None
        op, g2 = res
        if op is PENDING:
            return (PENDING, replace(self, released=True, gen=g2))
        return (op, replace(self, released=True, gen=g2) if g2 is not None else None)

    def update(self, test, ctx, event):
        g = as_gen(self.gen)
        if g is None:
            return self
        return replace(self, gen=g.update(test, ctx, event))


def synchronize(gen) -> Generator:
    return Synchronize(gen)


def phases(*gens) -> Generator:
    """Each phase waits for all threads to go idle before starting
    (generator.clj:1399-1409)."""
    return Seq([Synchronize(g) for g in gens])


def then(b, a) -> Generator:
    """a, then (once all threads idle) b (generator.clj:1411-1416)."""
    return Seq([a, Synchronize(b)])


@dataclass(frozen=True)
class UntilOk(Generator):
    """Passes ops through until some op completes :ok
    (generator.clj:1418-1436)."""

    gen: Any
    done: bool = False

    def op(self, test, ctx):
        if self.done:
            return None
        g = as_gen(self.gen)
        if g is None:
            return None
        res = g.op(test, ctx)
        if res is None:
            return None
        op, g2 = res
        if op is PENDING:
            return (PENDING, replace(self, gen=g2))
        return (op, replace(self, gen=g2) if g2 is not None else None)

    def update(self, test, ctx, event):
        if event.get("type") == "ok":
            return replace(self, done=True)
        g = as_gen(self.gen)
        if g is None:
            return self
        return replace(self, gen=g.update(test, ctx, event))


def until_ok(gen) -> Generator:
    return UntilOk(gen)


@dataclass(frozen=True)
class FlipFlop(Generator):
    """Alternates ops between two generators (generator.clj:1438-1452)."""

    a: Any
    b: Any
    which: int = 0

    def op(self, test, ctx):
        gens = [self.a, self.b]
        g = as_gen(gens[self.which])
        if g is None:
            return None
        res = g.op(test, ctx)
        if res is None:
            return None
        op, g2 = res
        if op is PENDING:
            gens[self.which] = g2
            return (PENDING, FlipFlop(gens[0], gens[1], self.which))
        gens[self.which] = g2
        return (op, FlipFlop(gens[0], gens[1], 1 - self.which))

    def update(self, test, ctx, event):
        return self


def flip_flop(a, b) -> Generator:
    return FlipFlop(a, b)


def validate(gen) -> Generator:
    return Validate(gen)


def friendly_exceptions(gen) -> Generator:
    return FriendlyExceptions(gen)


def trace(k: str, gen) -> Generator:
    return Trace(k, gen)


def gen_map(f: Callable, gen) -> Generator:
    return Map(f, gen)


def gen_filter(pred: Callable, gen) -> Generator:
    return Filter(pred, gen)


def on_update(f: Callable, gen) -> Generator:
    return OnUpdate(f, gen)
