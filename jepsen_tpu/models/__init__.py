"""Consistency models for linearizability checking.

Equivalent capability to knossos.model (external dep of the reference,
surface used at jepsen/src/jepsen/checker.clj:19-25,185-216 and
jepsen/src/jepsen/tests/causal.clj:12-31): a Model is an immutable state
machine; ``step(model, op)`` returns the next model or an ``Inconsistent``.

Two forms exist side by side:

* Object models (this module): the CPU oracle path. Hashable, immutable.
* :class:`IntSpec` (int-encoded transition functions): the device path. A
  model whose state and op arguments intern to int32 ids, with a pure
  ``step_ids`` function traceable under jit/vmap — the form the TPU
  just-in-time-linearization kernel (jepsen_tpu.ops.jitlin) consumes.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable


@dataclass(frozen=True)
class Inconsistent:
    msg: str

    def is_inconsistent(self) -> bool:
        return True


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


def is_inconsistent(m) -> bool:
    return isinstance(m, Inconsistent)


class Model:
    """Immutable state machine. Subclasses must be hashable and implement
    step(op) -> Model | Inconsistent."""

    def step(self, op: dict) -> "Model | Inconsistent":
        raise NotImplementedError


@dataclass(frozen=True)
class NoOp(Model):
    """Accepts every op."""

    def step(self, op):
        return self


@dataclass(frozen=True)
class Register(Model):
    """A read/write register (knossos.model/register)."""

    value: Any = None

    def step(self, op):
        f, v = op.get("f"), op.get("value")
        if f == "write":
            return Register(v)
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(f"can't read {v!r} from register {self.value!r}")
        return inconsistent(f"unknown op f={f!r}")


@dataclass(frozen=True)
class CASRegister(Model):
    """A register supporting read/write/cas (knossos.model/cas-register) —
    the model of the reference tutorial's etcd test and BASELINE config 1-2.
    cas value is a pair [old, new]."""

    value: Any = None

    def step(self, op):
        f, v = op.get("f"), op.get("value")
        if f == "write":
            return CASRegister(v)
        if f == "cas":
            old, new = v
            if old == self.value:
                return CASRegister(new)
            return inconsistent(f"can't CAS {self.value!r} from {old!r} to {new!r}")
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(f"can't read {v!r} from register {self.value!r}")
        return inconsistent(f"unknown op f={f!r}")


@dataclass(frozen=True)
class Mutex(Model):
    """A single mutex (knossos.model/mutex): acquire/release."""

    locked: bool = False

    def step(self, op):
        f = op.get("f")
        if f == "acquire":
            if self.locked:
                return inconsistent("already held")
            return Mutex(True)
        if f == "release":
            if not self.locked:
                return inconsistent("not held")
            return Mutex(False)
        return inconsistent(f"unknown op f={f!r}")


_INVALID_FENCE = 0


def _op_fence(op) -> int:
    """Fence token from an acquire completion (hazelcast.clj get-fence
    :564-566): ok acquires carry the fence as the op value; anything
    else (pending/indeterminate acquires, releases) is the invalid
    fence 0."""
    v = op.get("value")
    if isinstance(v, dict):
        v = v.get("fence")
    return v if isinstance(v, int) and not isinstance(v, bool) \
        else _INVALID_FENCE


def _op_client(op):
    """Lock-owner identity. The reference maps invocation uids to client
    names through a side map (hazelcast.clj:514-516) because its JVM
    clients multiplex threads; here each logical process IS one client
    session, so the process id is the owner."""
    v = op.get("value")
    if isinstance(v, dict) and v.get("client") is not None:
        return v.get("client")
    return op.get("process")


@dataclass(frozen=True)
class OwnerMutex(Model):
    """Owner-aware non-reentrant mutex (hazelcast.clj OwnerAwareMutex
    :539-555): acquire only when free, release only by the holder."""

    owner: Any = None

    def step(self, op):
        f, c = op.get("f"), _op_client(op)
        if c is None:
            return inconsistent("no owner!")
        if f == "acquire":
            if self.owner is None:
                return OwnerMutex(c)
            return inconsistent(f"{c!r} can't acquire: {self.owner!r} holds")
        if f == "release":
            if self.owner is None or self.owner != c:
                return inconsistent(f"{c!r} can't release: not holder")
            return OwnerMutex(None)
        return inconsistent(f"unknown op f={f!r}")


@dataclass(frozen=True)
class ReentrantMutex(Model):
    """Reentrant mutex with a bounded hold count (hazelcast.clj
    ReentrantMutex :516-533, reentrant-lock-acquire-count=2): the holder
    may re-acquire up to ``max_holds`` times; releases peel one hold."""

    owner: Any = None
    holds: int = 0
    max_holds: int = 2

    def step(self, op):
        f, c = op.get("f"), _op_client(op)
        if c is None:
            return inconsistent("no owner!")
        if f == "acquire":
            if self.holds < self.max_holds and \
                    (self.owner is None or self.owner == c):
                return ReentrantMutex(c, self.holds + 1, self.max_holds)
            return inconsistent(f"{c!r} can't acquire {self!r}")
        if f == "release":
            if self.owner is None or self.owner != c:
                return inconsistent(f"{c!r} can't release {self!r}")
            return ReentrantMutex(None if self.holds == 1 else self.owner,
                                  self.holds - 1, self.max_holds)
        return inconsistent(f"unknown op f={f!r}")


@dataclass(frozen=True)
class FencedMutex(Model):
    """Non-reentrant mutex checking fencing-token monotonicity
    (hazelcast.clj FencedMutex :569-589): an acquire may carry an
    unknown fence (0, e.g. a crashed acquire linearized late) or a
    fence strictly greater than every fence seen so far."""

    owner: Any = None
    fence: int = _INVALID_FENCE

    def step(self, op):
        f, c = op.get("f"), _op_client(op)
        if c is None:
            return inconsistent("no owner!")
        if f == "acquire":
            fence = _op_fence(op)
            if self.owner is not None:
                return inconsistent(f"{c!r} can't acquire {self!r}")
            if fence == _INVALID_FENCE:
                return FencedMutex(c, self.fence)
            if fence > self.fence:
                return FencedMutex(c, fence)
            return inconsistent(f"fence {fence} not above {self.fence}")
        if f == "release":
            if self.owner is None or self.owner != c:
                return inconsistent(f"{c!r} can't release {self!r}")
            return FencedMutex(None, self.fence)
        return inconsistent(f"unknown op f={f!r}")


@dataclass(frozen=True)
class ReentrantFencedMutex(Model):
    """Reentrant fenced mutex (hazelcast.clj ReentrantFencedMutex
    :597-625): bounded re-acquire, with fences monotone across lock
    ownership and constant within one held incarnation (re-acquiring
    while holding returns the same fence or none)."""

    owner: Any = None
    holds: int = 0
    fence: int = _INVALID_FENCE       # fence of the current incarnation
    highest: int = _INVALID_FENCE     # highest fence ever observed
    max_holds: int = 2

    def _with(self, **kw):
        d = dict(owner=self.owner, holds=self.holds, fence=self.fence,
                 highest=self.highest, max_holds=self.max_holds)
        d.update(kw)
        return ReentrantFencedMutex(**d)

    def step(self, op):
        f, c = op.get("f"), _op_client(op)
        if c is None:
            return inconsistent("no owner!")
        if f == "acquire":
            fence = _op_fence(op)
            fresh = fence == _INVALID_FENCE or fence > self.highest
            if self.owner is None:
                if fresh:
                    return self._with(owner=c, holds=1, fence=fence,
                                      highest=max(fence, self.highest))
                return inconsistent(f"fence {fence} ≤ {self.highest}")
            if self.owner != c or self.holds == self.max_holds:
                return inconsistent(f"{c!r} can't acquire {self!r}")
            if self.fence == _INVALID_FENCE:
                # held without a known fence: a re-acquire may reveal it
                if fresh:
                    return self._with(holds=self.holds + 1, fence=fence,
                                      highest=max(fence, self.highest))
                return inconsistent(f"fence {fence} ≤ {self.highest}")
            if fence == _INVALID_FENCE or fence == self.fence:
                return self._with(holds=self.holds + 1)
            return inconsistent(
                f"re-acquire fence {fence} ≠ held {self.fence}")
        if f == "release":
            if self.owner is None or self.owner != c:
                return inconsistent(f"{c!r} can't release {self!r}")
            if self.holds == 1:
                return self._with(owner=None, holds=0,
                                  fence=_INVALID_FENCE)
            return self._with(holds=self.holds - 1)
        return inconsistent(f"unknown op f={f!r}")


@dataclass(frozen=True)
class AcquiredPermits(Model):
    """Counting-semaphore permit model (hazelcast.clj
    AcquiredPermitsModel :631-650, num-permits=2): at most ``permits``
    acquired across clients; a client releases only what it holds."""

    acquired: tuple = ()   # sorted ((client, count>0), ...)
    permits: int = 2

    def step(self, op):
        f, c = op.get("f"), _op_client(op)
        if c is None:
            return inconsistent("no owner!")
        held = dict(self.acquired)
        if f == "acquire":
            if sum(held.values()) < self.permits:
                held[c] = held.get(c, 0) + 1
                return AcquiredPermits(tuple(sorted(held.items())),
                                       self.permits)
            return inconsistent(f"{c!r} can't acquire: no permits free")
        if f == "release":
            if held.get(c, 0) > 0:
                held[c] -= 1
                if not held[c]:
                    del held[c]
                return AcquiredPermits(tuple(sorted(held.items())),
                                       self.permits)
            return inconsistent(f"{c!r} releases nothing held")
        return inconsistent(f"unknown op f={f!r}")


@dataclass(frozen=True)
class FIFOQueue(Model):
    """A FIFO queue: enqueue/dequeue (knossos.model/fifo-queue)."""

    items: tuple = ()

    def step(self, op):
        f, v = op.get("f"), op.get("value")
        if f == "enqueue":
            return FIFOQueue(self.items + (v,))
        if f == "dequeue":
            if not self.items:
                return inconsistent("dequeue from empty queue")
            if self.items[0] != v:
                return inconsistent(f"dequeue {v!r} but head is {self.items[0]!r}")
            return FIFOQueue(self.items[1:])
        return inconsistent(f"unknown op f={f!r}")


@dataclass(frozen=True)
class UnorderedQueue(Model):
    """A queue where dequeue may return any enqueued element
    (knossos.model/unordered-queue); used by checker.queue
    (checker.clj:218-238)."""

    items: frozenset = frozenset()

    def step(self, op):
        f, v = op.get("f"), op.get("value")
        if f == "enqueue":
            # multiset via (value, seq) tags is overkill here; jepsen's
            # unordered-queue uses a multiset — emulate with counted tuples.
            items = dict(self.items)
            items[v] = items.get(v, 0) + 1
            return UnorderedQueue(frozenset(items.items()))
        if f == "dequeue":
            items = dict(self.items)
            if items.get(v, 0) <= 0:
                return inconsistent(f"dequeue {v!r} not present")
            items[v] -= 1
            if items[v] == 0:
                del items[v]
            return UnorderedQueue(frozenset(items.items()))
        return inconsistent(f"unknown op f={f!r}")


@dataclass(frozen=True)
class SetModel(Model):
    """A grow-only set: add/read."""

    items: frozenset = frozenset()

    def step(self, op):
        f, v = op.get("f"), op.get("value")
        if f == "add":
            return SetModel(self.items | {v})
        if f == "read":
            if v is None or frozenset(v) == self.items:
                return self
            return inconsistent("set read mismatch")
        return inconsistent(f"unknown op f={f!r}")


# ---------------------------------------------------------------------------
# Int-encoded model specs: the device-side form.
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class IntSpec:
    """A model whose state is a single int32 and whose ops are (f_code, a, b)
    int triples, with a jit-traceable transition.

    step_ids(state, f_code, a, b) -> (new_state, ok_bool) where arrays are
    jnp int32/bool and the function must be shape-polymorphic under vmap.
    ``init_state`` is the interned id of the initial model state.

    For the CAS register: state = value id; write v: -> v, always ok;
    read v: ok iff v == state (v==0, i.e. None, reads anything);
    cas (a,b): ok iff state == a, -> b.
    """

    name: str
    init_state: int
    num_f: int
    step_ids: Callable  # (state, f, a, b) -> (state', ok)


CAS_F_READ, CAS_F_WRITE, CAS_F_CAS = 0, 1, 2


def cas_register_spec(init_state: int = 0) -> IntSpec:
    """Device-encodable CAS register. Ops encode as (f, a, b):
    read v -> (0, v_id, 0); write v -> (1, v_id, 0); cas [u,v] -> (2, u_id, v_id).
    A read of value-id 0 (None) matches any state — used for indeterminate
    reads."""

    def step_ids(state, f, a, b):
        import jax.numpy as jnp
        is_read = f == CAS_F_READ
        is_write = f == CAS_F_WRITE
        is_cas = f == CAS_F_CAS
        ok = (
            (is_read & ((a == 0) | (a == state)))
            | is_write
            | (is_cas & (state == a))
        )
        new_state = jnp.where(is_write, a, jnp.where(is_cas & ok, b, state))
        return new_state, ok

    return IntSpec("cas-register", init_state, 3, step_ids)


def register_spec(init_state: int = 0) -> IntSpec:
    """Read/write register (no cas) — same encoding minus cas."""
    spec = cas_register_spec(init_state)
    return IntSpec("register", init_state, 2, spec.step_ids)


@dataclass(frozen=True)
class MultiRegister(Model):
    """A register map supporting transactional reads/writes over keys
    (yugabyte/src/yugabyte/multi_key_acid.clj:17-37 MultiRegister): one
    op f="txn" whose value is [[f, k, v], ...] with f "r"/"w"; a read of
    None is always legal, a read of v must match the key's current value
    (missing keys read as None)."""

    entries: tuple = ()  # sorted ((k, v), ...)

    def get(self, k):
        for kk, v in self.entries:
            if kk == k:
                return v
        return None

    def step(self, op):
        entries = dict(self.entries)
        for f, k, v in op.get("value") or ():
            if f == "r":
                if v is not None and v != entries.get(k):
                    return inconsistent(
                        f"{entries.get(k)!r} ≠ {v!r} at key {k!r}")
            elif f == "w":
                entries[k] = v
            else:
                return inconsistent(f"unknown txn micro-op {f!r}")
        return MultiRegister(tuple(sorted(entries.items())))


def multi_register_spec(n_keys: int = 3, n_values: int = 5) -> IntSpec:
    """Device-encodable multi-register (the multi-key-acid model).

    State interns the whole key→value map as base-(V+1) digits (digit 0
    = unset/None, 1..V = values), so K keys × V values is only (V+1)^K
    states — 216 at the workload's 3×5, squarely in the dense-table
    kernel's regime. A txn op packs per-key actions as base-(2V+2)
    digits of ``a``: 0 none, 1 read-None, 2+v read-v, 2+V+v write-v.
    ``step_ids`` decodes with a static loop over keys (shape-polymorphic
    jnp arithmetic, no data-dependent control flow)."""
    V, K = n_values, n_keys
    SB = V + 1          # state digit base
    AB = 2 * V + 2      # action digit base
    if AB ** K >= (1 << 31):
        raise ValueError(f"txn encoding overflows int32: ({AB})^{K}")

    def step_ids(state, f, a, b):
        import jax.numpy as jnp
        ok = jnp.full(jnp.shape(state), True)
        new_state = state
        acts = a
        for k in range(K):
            act = acts % AB
            acts = acts // AB
            digit = (new_state // (SB ** k)) % SB
            is_rv = (act >= 2) & (act < 2 + V)
            is_w = act >= 2 + V
            ok = ok & (~is_rv | (digit == act - 1))  # read v: digit == v+1
            wdigit = jnp.where(is_w, act - (1 + V), digit)
            new_state = new_state + (wdigit - digit) * (SB ** k)
        return new_state, ok

    return IntSpec(f"multi-register-{K}x{V}", 0, 1, step_ids)


@dataclass(frozen=True)
class Memo:
    """Wrapper marking a model as memoizable by (hash) — knossos.model/memo
    analog. Object models here are frozen dataclasses, hence hashable, so
    memoization is structural; this exists for API parity."""

    model: Model
