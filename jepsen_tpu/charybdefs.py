"""Filesystem fault injection via CharybdeFS (reference:
charybdefs/src/jepsen/charybdefs.clj).

Builds scylladb/charybdefs — a FUSE passthrough filesystem that injects
per-syscall EIO/latency faults — from source on each db node (thrift from
source first, charybdefs.clj:7-38; then git clone + thrift codegen +
cmake + make, :40-60), mounts it at ``/faulty`` backed by ``/real``
(:61-65), and exposes the cookbook fault recipes (:67-85). A DB whose
data directory lives under /faulty gets filesystem faults injected by
the ``nemesis()`` below.
"""
from __future__ import annotations

import logging

from jepsen_tpu import control, nemesis as nem, os_setup
from jepsen_tpu.control import util as cu

logger = logging.getLogger("jepsen.charybdefs")

THRIFT_DIR = "/opt/thrift"
# old releases live on archive.apache.org only (the dist mirrors rotate
# them out)
THRIFT_URL = ("https://archive.apache.org/dist/thrift/0.10.0/"
              "thrift-0.10.0.tar.gz")
DIR = "/opt/charybdefs"
BIN = f"{DIR}/charybdefs"
REPO = "https://github.com/scylladb/charybdefs.git"
MOUNT = "/faulty"
BACKING = "/real"

THRIFT_DEPS = ["automake", "bison", "flex", "g++", "git",
               "libboost-all-dev", "libevent-dev", "libssl-dev", "libtool",
               "make", "pkg-config", "python3-setuptools", "libglib2.0-dev"]
BUILD_DEPS = ["build-essential", "cmake", "libfuse-dev", "fuse"]


def install_thrift() -> None:
    """Thrift compiler + C++/python libs from source (charybdefs needs
    matching versions; distros only package the compiler —
    charybdefs.clj:7-38)."""
    if cu.file_exists("/usr/bin/thrift"):
        return
    with control.su():
        os_setup.install(THRIFT_DEPS)
        logger.info("Building thrift (this takes several minutes)")
        cu.install_archive(THRIFT_URL, THRIFT_DIR)
        with control.cd(THRIFT_DIR):
            control.exec_("./configure", "--prefix=/usr")
            control.exec_("make", "-j4")
            control.exec_("make", "install")
        with control.cd(f"{THRIFT_DIR}/lib/py"):
            control.exec_("python3", "setup.py", "install")


def install() -> None:
    """Ensures charybdefs is built and the faulty fs mounted at /faulty
    (charybdefs.clj:40-65)."""
    install_thrift()
    if not cu.file_exists(BIN):
        with control.su():
            os_setup.install(BUILD_DEPS)
            # a half-finished prior build leaves DIR non-empty, which
            # would fail the clone forever — start clean for idempotence
            cu.rm_rf(DIR)
            control.exec_("mkdir", "-p", DIR)
            control.exec_("chmod", "777", DIR)
        control.exec_("git", "clone", "--depth", "1", REPO, DIR)
        with control.cd(DIR):
            control.exec_("thrift", "-r", "--gen", "cpp", "server.thrift")
            control.exec_("cmake", "CMakeLists.txt")
            control.exec_("make")
    with control.su():
        control.exec_("modprobe", "fuse")
        control.exec_(control.lit(f"umount {MOUNT} || /bin/true"))
        control.exec_("mkdir", "-p", BACKING, MOUNT)
        control.exec_(BIN, MOUNT,
                      f"-oallow_other,modules=subdir,subdir={BACKING}")
        control.exec_("chmod", "777", BACKING, MOUNT)


def _cookbook(flag: str) -> None:
    with control.cd(f"{DIR}/cookbook"):
        control.exec_("./recipes", flag)


def break_all() -> None:
    """All filesystem operations fail with EIO (charybdefs.clj:72-75)."""
    _cookbook("--io-error")


def break_one_percent() -> None:
    """1% of disk operations fail (charybdefs.clj:77-80)."""
    _cookbook("--probability")


def clear() -> None:
    """Clears a previous fault injection (charybdefs.clj:82-85)."""
    _cookbook("--clear")


class FSFaultNemesis(nem.Nemesis):
    """Injects filesystem faults on target nodes. Op fs: ``break-fs``
    (value: node list or None for all; mode 'all' or 'one-percent' via
    value dict) and ``heal-fs``."""

    def fs(self):
        return {"break-fs", "heal-fs"}

    def setup(self, test):
        control.on_nodes(test, lambda n: install())
        return self

    def invoke(self, test, op):
        f = op.get("f")
        v = op.get("value") or {}
        nodes = v.get("nodes") or list(test.get("nodes") or [])
        mode = v.get("mode", "all")
        if f == "break-fs":
            fault = break_all if mode == "all" else break_one_percent
            control.on_nodes(test, lambda n: fault(), nodes=nodes)
            return {**op, "type": "info",
                    "value": {"f": "break-fs", "mode": mode, "nodes": nodes}}
        if f == "heal-fs":
            control.on_nodes(test, lambda n: clear(), nodes=nodes)
            return {**op, "type": "info",
                    "value": {"f": "heal-fs", "nodes": nodes}}
        return {**op, "type": "info", "error": ["unknown-f", f]}

    def teardown(self, test):
        try:
            control.on_nodes(test, lambda n: clear())
        except Exception:  # noqa: BLE001
            logger.exception("charybdefs clear failed during teardown")
