"""Structured diagnostics shared by the preflight and lint engines.

Both engines emit typed, machine-readable findings (stable ``code``,
``severity``, location, fix hint) so CI can annotate and tooling can
gate on them — mirroring how the checker returns structured anomaly
maps instead of prose. Text rendering is ruff-style one-liners; JSON
rendering is one object per finding (``--format=json``).
"""
from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEVERITY_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}


@dataclass(frozen=True)
class Diagnostic:
    """One preflight finding against a test map.

    ``path`` is the test-map path the diagnostic is about (``"generator"``,
    ``"op_timeout_s"``, ...), not a file path — a test is data, so its
    diagnostics address data."""

    code: str           # stable id, e.g. "GEN001"
    severity: str       # error | warning | info
    path: str           # test-map path, e.g. "generator" or "op_timeout_s"
    message: str
    hint: str | None = None

    def render(self) -> str:
        out = f"preflight: {self.severity}: {self.code} [{self.path}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_json(self) -> dict:
        return asdict(self)


@dataclass(frozen=True)
class Finding:
    """One lint finding against a source location.

    ``key()`` is the baseline identity: file + enclosing definition +
    rule, deliberately *without* line numbers so a waiver survives
    unrelated edits to the same file."""

    rule: str           # e.g. "lock-guard"
    code: str           # e.g. "JTL001"
    path: str           # repo-relative file path
    line: int
    col: int
    qualname: str       # enclosing function/class qualname ("<module>" at top level)
    message: str
    hint: str | None = None
    severity: str = ERROR

    def key(self) -> str:
        return f"{self.path}::{self.qualname}::{self.rule}"

    def render(self) -> str:
        out = (f"{self.path}:{self.line}:{self.col}: {self.code} "
               f"[{self.rule}] {self.message}")
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_json(self) -> dict:
        d = asdict(self)
        d["key"] = self.key()
        return d


def sort_diagnostics(diags):
    return sorted(diags, key=lambda d: (_SEVERITY_ORDER.get(d.severity, 9),
                                        d.code, d.path))


def sort_findings(findings):
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code))


def render_json(items) -> str:
    return "\n".join(json.dumps(x.to_json()) for x in items) + ("\n" if items else "")
