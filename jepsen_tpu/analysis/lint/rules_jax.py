"""JAX invariant rules.

* ``no-host-effects-in-jit`` (JTJ001) — a traced function runs its
  Python body ONCE at trace time; ``time.time()``, ``random.*``, I/O,
  and ``print`` inside ``@jax.jit`` / pallas kernels silently freeze
  into the compiled program (or fire only on retrace) — the classic
  "my timestamp never changes" bug.
* ``donation-reuse`` (JTJ002) — a buffer passed at a
  ``donate_argnums`` position is dead after dispatch; reading it again
  is use-after-free that XLA may or may not catch (the jitlin pallas
  fallback retry is the in-repo incident: the non-donating wrapper
  exists precisely because the donated carry was about to be reused).
* ``recompile-hazard`` (JTJ003) — ``jax.jit(...)`` constructed inside a
  loop retraces every iteration, and a ``static_argnums`` position fed
  the loop variable recompiles per call: both turn a compile-once hot
  path into a compile-always cold one.
* ``no-host-roundtrip`` (JTJ004) — arrays obtained from the history
  IR's device placement (``device_columns`` / ``shard_leading`` /
  ``shard_chunked``) are device-resident by contract; pulling them
  back to host with ``np.asarray``/``np.array``/``jax.device_get`` or
  ``.tolist()`` inside checker-path code silently re-pays the H2D/D2H
  tunnel the IR exists to avoid. Waivable per line with
  ``# lint: ignore[no-host-roundtrip]`` when a host read is the point
  (e.g. a final verdict gather).
* ``threshold-dtype`` (JTJ005) — ``jnp.dot(...,
  preferred_element_type=jnp.float32)`` whose result feeds a ``> 0``
  threshold, in kernel scope. The threshold is the proof the operands
  live in the boolean 0/1 semiring (the product is consumed as
  reachability, not magnitude), and an f32 matmul then computes
  AND/OR at 1/4 the MXU's int8 operand density — the pattern the
  packed-boolean kernel rework removed (ops/pallas_matrix.py,
  doc/performance.md "Packed boolean kernels"). Kernel scope =
  proven-jitted functions, plus every function of a module that
  imports pallas (kernel bodies there are reached through
  ``pallas_call`` indirections the jit index can't always prove).
  Waivable per line where f32 is load-bearing (e.g. the probe-verified
  terminal fallback variant every backend can lower).

The jit rules only scan modules that import ``jax`` (or pallas), and
only the bodies of functions proven jitted: decorated with ``jit`` /
``partial(jax.jit, ...)``, wrapped via ``name = jax.jit(fn, ...)``, or
passed to ``pallas_call``. The host-roundtrip rule scans every module
(device-placement results can flow anywhere).
"""
from __future__ import annotations

import ast

from jepsen_tpu.analysis.diagnostics import Finding
from jepsen_tpu.analysis.lint.astcache import ModuleInfo
from jepsen_tpu.analysis.lint.callgraph import body_calls


def _imports_jax(mod: ModuleInfo) -> bool:
    if any(v == "jax" or v.startswith("jax.") for v in mod.imports.values()):
        return True
    return any(m == "jax" or m.startswith("jax.")
               for m, _ in mod.import_names.values())


def _is_jax_jit(node, mod: ModuleInfo) -> bool:
    """node is the callable expression ``jax.jit`` / imported ``jit``."""
    if isinstance(node, ast.Attribute) and node.attr == "jit" \
            and isinstance(node.value, ast.Name):
        target = (mod.imports.get(node.value.id)
                  or ".".join(mod.import_names.get(node.value.id, ())))
        return target == "jax" or node.value.id == "jax"
    if isinstance(node, ast.Name):
        imp = mod.import_names.get(node.id)
        return imp is not None and imp[0] == "jax" and imp[1] == "jit"
    return False


def _jit_call_kwargs(call: ast.Call) -> dict:
    out = {}
    for k in call.keywords:
        if k.arg in ("donate_argnums", "donate_argnames",
                     "static_argnums", "static_argnames"):
            out[k.arg] = k.value
    return out


def _literal_ints(node) -> tuple:
    """Positions from a literal int / tuple-of-ints node; () = unknown."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                vals.append(el.value)
        return tuple(vals)
    return ()


class _JitIndex:
    """Per-module index of jit-traced functions and jitted callables."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.traced: dict[str, dict] = {}     # func qualname -> jit kwargs
        self.wrappers: dict[str, dict] = {}   # bound name -> jit kwargs
        self._build()

    def _func_by_simple_name(self, name: str):
        hits = [q for q, fi in self.mod.functions.items()
                if fi.node.name == name]
        return hits[0] if len(hits) == 1 else None

    def _mark(self, qualname: str, kwargs: dict):
        self.traced.setdefault(qualname, {}).update(kwargs)

    def _build(self):
        mod = self.mod
        # decorators
        for q, fi in mod.functions.items():
            for dec in fi.node.decorator_list:
                if _is_jax_jit(dec, mod):
                    self._mark(q, {})
                elif isinstance(dec, ast.Call):
                    if _is_jax_jit(dec.func, mod):
                        self._mark(q, _jit_call_kwargs(dec))
                    elif self._is_partial_jit(dec):
                        self._mark(q, _jit_call_kwargs(dec))
        # jax.jit(fn, ...) calls + pallas_call(kernel, ...) anywhere
        for n in ast.walk(mod.tree):
            if not isinstance(n, ast.Call):
                continue
            if _is_jax_jit(n.func, mod) and n.args:
                kwargs = _jit_call_kwargs(n)
                inner = n.args[0]
                if isinstance(inner, ast.Name):
                    q = self._func_by_simple_name(inner.id)
                    if q is not None:
                        self._mark(q, kwargs)
            f = n.func
            if isinstance(f, ast.Attribute) and f.attr == "pallas_call" \
                    and n.args and isinstance(n.args[0], ast.Name):
                q = self._func_by_simple_name(n.args[0].id)
                if q is not None:
                    self._mark(q, {"pallas": True})
            elif isinstance(f, ast.Name) and f.id == "pallas_call" \
                    and n.args and isinstance(n.args[0], ast.Name):
                q = self._func_by_simple_name(n.args[0].id)
                if q is not None:
                    self._mark(q, {"pallas": True})
        # name = jax.jit(fn, ...): the bound name is a jitted callable
        for n in ast.walk(mod.tree):
            if not isinstance(n, ast.Assign):
                continue
            for call in ast.walk(n.value):
                if isinstance(call, ast.Call) and _is_jax_jit(call.func,
                                                              self.mod):
                    kwargs = _jit_call_kwargs(call)
                    if not kwargs:
                        continue
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            self.wrappers.setdefault(t.id, {}).update(kwargs)

    def _is_partial_jit(self, call: ast.Call) -> bool:
        f = call.func
        is_partial = (isinstance(f, ast.Name) and f.id == "partial") or (
            isinstance(f, ast.Attribute) and f.attr == "partial")
        return (is_partial and call.args
                and _is_jax_jit(call.args[0], self.mod))


# ---------------------------------------------------------------------------
# JTJ001 — host effects under jit
# ---------------------------------------------------------------------------

_BANNED_BUILTINS = {"open", "print", "input"}
_EFFECT_MODULES = {"time", "random", "os"}


def _host_effect(call: ast.Call, mod: ModuleInfo) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        if f.id in _BANNED_BUILTINS and f.id not in mod.import_names:
            return f"{f.id}()"
        imp = mod.import_names.get(f.id)
        if imp is not None and imp[0] in ("time", "random"):
            return f"{imp[0]}.{imp[1]}()"
        return None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        recv = f.value.id
        # an alias bound from jax (e.g. `from jax import random`) is fine
        imp = mod.import_names.get(recv)
        if imp is not None and imp[0].startswith("jax"):
            return None
        if recv in _EFFECT_MODULES:
            return f"{recv}.{f.attr}()"
        if recv in ("np", "numpy") and f.attr == "random":
            return f"{recv}.random()"
    # np.random.<x>() / numpy.random.<x>()
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Attribute) \
            and isinstance(f.value.value, ast.Name) \
            and f.value.value.id in ("np", "numpy") \
            and f.value.attr == "random":
        return f"{f.value.value.id}.random.{f.attr}()"
    return None


def _walk_with_nested(func_node):
    """Calls inside the function INCLUDING nested defs — a nested helper
    defined and called inside a traced body inlines into the trace."""
    out = []
    for n in ast.walk(func_node):
        if isinstance(n, ast.Call):
            out.append(n)
    return out


def no_host_effects_in_jit(mod: ModuleInfo) -> list[Finding]:
    if not _imports_jax(mod):
        return []
    idx = _JitIndex(mod)
    out: list[Finding] = []
    for q, meta in sorted(idx.traced.items()):
        fi = mod.functions.get(q)
        if fi is None or "no-host-effects-in-jit" in fi.ignores:
            continue
        kind = "pallas kernel" if meta.get("pallas") else "jitted function"
        for call in _walk_with_nested(fi.node):
            effect = _host_effect(call, mod)
            if effect is None:
                continue
            if "no-host-effects-in-jit" in mod.line_ignores(call.lineno):
                continue
            out.append(Finding(
                rule="no-host-effects-in-jit", code="JTJ001",
                path=mod.relpath, line=call.lineno,
                col=call.col_offset + 1, qualname=q,
                message=(f"{effect} inside {kind} {fi.node.name!r} runs "
                         "once at trace time and freezes into the "
                         "compiled program"),
                hint="compute host values outside the traced function "
                     "and pass them in as arguments (use jax.random "
                     "with explicit keys for randomness)"))
    return out


# ---------------------------------------------------------------------------
# JTJ002 — donated buffer read after dispatch
# ---------------------------------------------------------------------------

def donation_reuse(mod: ModuleInfo) -> list[Finding]:
    if not _imports_jax(mod):
        return []
    idx = _JitIndex(mod)
    donated = {name: _literal_ints(kw["donate_argnums"])
               for name, kw in idx.wrappers.items()
               if "donate_argnums" in kw}
    donated = {n: pos for n, pos in donated.items() if pos}
    if not donated:
        return []
    out: list[Finding] = []
    for q, fi in mod.functions.items():
        if "donation-reuse" in fi.ignores:
            continue
        calls = [c for c in body_calls(fi.node)
                 if isinstance(c.func, ast.Name) and c.func.id in donated]
        if not calls:
            continue
        names = [n for n in ast.walk(fi.node) if isinstance(n, ast.Name)]
        for call in calls:
            for pos in donated[call.func.id]:
                if pos >= len(call.args):
                    continue
                arg = call.args[pos]
                if not isinstance(arg, ast.Name):
                    continue
                stores = sorted(n.lineno for n in names
                                if n.id == arg.id
                                and isinstance(n.ctx, ast.Store))
                for n in names:
                    if n.id != arg.id or not isinstance(n.ctx, ast.Load) \
                            or n.lineno <= call.lineno:
                        continue
                    # a store on the call line itself (x = fast(x)) is
                    # the canonical rebind-from-result pattern
                    rebound = any(call.lineno <= s <= n.lineno
                                  for s in stores)
                    if rebound:
                        continue
                    if "donation-reuse" in mod.line_ignores(n.lineno):
                        continue
                    out.append(Finding(
                        rule="donation-reuse", code="JTJ002",
                        path=mod.relpath, line=n.lineno,
                        col=n.col_offset + 1, qualname=q,
                        message=(f"{arg.id!r} was donated to "
                                 f"{call.func.id}() at line "
                                 f"{call.lineno} (donate_argnums="
                                 f"{pos}) and is read again here — "
                                 "its buffer may already be reused"),
                        hint="keep a non-donating wrapper for retry "
                             "paths, or rebind the variable from the "
                             "dispatch result"))
                    break  # one finding per donated arg per call
    return out


# ---------------------------------------------------------------------------
# JTJ003 — recompile hazards
# ---------------------------------------------------------------------------

def _loop_bodies(func_node):
    """(loop_node, loop_target_names) for every for/while lexically in
    the function (nested defs excluded)."""
    out = []
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        if isinstance(n, (ast.For, ast.While)):
            targets: set = set()
            if isinstance(n, ast.For):
                for t in ast.walk(n.target):
                    if isinstance(t, ast.Name):
                        targets.add(t.id)
            out.append((n, targets))
        stack.extend(ast.iter_child_nodes(n))
    return out


def _in_loop_walk(loop_node):
    """Nodes lexically inside a loop body, skipping nested defs."""
    stack = list(loop_node.body) + list(getattr(loop_node, "orelse", []))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            # a def in a loop still re-decorates per iteration; surface
            # its decorators but not its body
            for dec in getattr(n, "decorator_list", []):
                yield dec
                for sub in ast.walk(dec):
                    yield sub
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def recompile_hazard(mod: ModuleInfo) -> list[Finding]:
    if not _imports_jax(mod):
        return []
    idx = _JitIndex(mod)
    statics = {name: kw for name, kw in idx.wrappers.items()
               if "static_argnums" in kw or "static_argnames" in kw}
    out: list[Finding] = []
    for q, fi in mod.functions.items():
        if "recompile-hazard" in fi.ignores:
            continue
        for loop, targets in _loop_bodies(fi.node):
            for n in _in_loop_walk(loop):
                if not isinstance(n, ast.Call):
                    continue
                if "recompile-hazard" in mod.line_ignores(n.lineno):
                    continue
                if _is_jax_jit(n.func, mod):
                    out.append(Finding(
                        rule="recompile-hazard", code="JTJ003",
                        path=mod.relpath, line=n.lineno,
                        col=n.col_offset + 1, qualname=q,
                        message="jax.jit(...) constructed inside a loop "
                                "— every iteration builds a fresh "
                                "wrapper and retraces",
                        hint="hoist the jitted callable out of the loop "
                             "(cache it, as ops.jitlin's kernel cache "
                             "does)"))
                    continue
                f = n.func
                if isinstance(f, ast.Name) and f.id in statics and targets:
                    kw = statics[f.id]
                    pos = _literal_ints(kw.get("static_argnums",
                                                ast.Constant(value=None)))
                    hazard = None
                    for p in pos:
                        if p < len(n.args):
                            used = {x.id for x in ast.walk(n.args[p])
                                    if isinstance(x, ast.Name)}
                            if used & targets:
                                hazard = p
                                break
                    if hazard is None and "static_argnames" in kw:
                        want = set()
                        sn = kw["static_argnames"]
                        if isinstance(sn, ast.Constant):
                            want = {sn.value}
                        elif isinstance(sn, (ast.Tuple, ast.List)):
                            want = {e.value for e in sn.elts
                                    if isinstance(e, ast.Constant)}
                        for k in n.keywords:
                            if k.arg in want:
                                used = {x.id for x in ast.walk(k.value)
                                        if isinstance(x, ast.Name)}
                                if used & targets:
                                    hazard = k.arg
                                    break
                    if hazard is not None:
                        out.append(Finding(
                            rule="recompile-hazard", code="JTJ003",
                            path=mod.relpath, line=n.lineno,
                            col=n.col_offset + 1, qualname=q,
                            message=(f"{f.id}() takes the loop variable "
                                     f"at static position {hazard!r} — "
                                     "every distinct value recompiles"),
                            hint="make the argument dynamic (traced), "
                                 "or bucket it so the static set stays "
                                 "small"))
    return out


# ---------------------------------------------------------------------------
# JTJ004 — device-resident IR arrays round-tripped to host
# ---------------------------------------------------------------------------

#: calls whose result is device-resident by contract (the history IR's
#: placement surface and the parallel staging helpers)
_DEVICE_SOURCES = {"device_columns", "shard_leading", "shard_chunked"}

#: receiver method that materializes on host
_ROUNDTRIP_METHODS = {"tolist"}

#: np./jax. level functions that materialize on host
_ROUNDTRIP_FUNCS = {("np", "asarray"), ("np", "array"),
                    ("numpy", "asarray"), ("numpy", "array"),
                    ("jax", "device_get")}


def _taint_events(func_node) -> list:
    """(lineno, name, source) for every Assign target in the function,
    line-ordered. ``source`` is True (bound from a device-source call),
    ("alias", base_name) (bound from a subscript of another name), or
    False (any other binding — CLEARS taint: a name rebound to host
    data must not stay flagged)."""
    events = []
    for n in ast.walk(func_node):
        if not isinstance(n, ast.Assign):
            continue
        val = n.value
        if isinstance(val, ast.Call) \
                and isinstance(val.func, ast.Attribute) \
                and val.func.attr in _DEVICE_SOURCES:
            src = True
        elif isinstance(val, ast.Call) and isinstance(val.func, ast.Name) \
                and val.func.id in _DEVICE_SOURCES:
            src = True
        elif isinstance(val, ast.Subscript) \
                and isinstance(val.value, ast.Name):
            src = ("alias", val.value.id)
        else:
            src = False
        for t in n.targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    events.append((n.lineno, sub.id, src))
    events.sort(key=lambda e: e[0])
    return events


def _tainted_at(events, line) -> dict[str, int]:
    """name -> taint lineno for names device-tainted at ``line``,
    replaying bindings in line order (last binding wins)."""
    cur: dict[str, int] = {}
    for ln, nm, src in events:
        if ln >= line:
            break
        if src is True:
            cur[nm] = ln
        elif src is False:
            cur.pop(nm, None)
        else:  # subscript alias: tainted iff its base currently is
            if src[1] in cur:
                cur[nm] = ln
            else:
                cur.pop(nm, None)
    return cur


def _mentions(node, names) -> str | None:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in names:
            return sub.id
    return None


def _imports_pallas(mod: ModuleInfo) -> bool:
    if any("pallas" in v for v in mod.imports.values()):
        return True
    return any("pallas" in m or n == "pallas"
               for m, n in mod.import_names.values())


def _is_jnp_dot_f32(call: ast.Call, mod: ModuleInfo) -> bool:
    """``jnp.dot(..., preferred_element_type=jnp.float32)`` (any alias
    of jax.numpy as the receiver)."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "dot"
            and isinstance(f.value, ast.Name)):
        return False
    target = mod.imports.get(f.value.id)
    if not (f.value.id == "jnp" or target == "jax.numpy"):
        return False
    for k in call.keywords:
        if k.arg == "preferred_element_type":
            v = k.value
            return isinstance(v, ast.Attribute) and v.attr == "float32"
    return False


def _threshold_dot(node, mod: ModuleInfo):
    """The ``dot > 0`` / ``0 < dot`` threshold Compare; returns the dot
    Call or None."""
    if not isinstance(node, ast.Compare) or len(node.ops) != 1:
        return None
    op, left, right = node.ops[0], node.left, node.comparators[0]
    if isinstance(op, ast.Gt) and isinstance(left, ast.Call) \
            and isinstance(right, ast.Constant) and right.value == 0 \
            and _is_jnp_dot_f32(left, mod):
        return left
    if isinstance(op, ast.Lt) and isinstance(right, ast.Call) \
            and isinstance(left, ast.Constant) and left.value == 0 \
            and _is_jnp_dot_f32(right, mod):
        return right
    return None


def threshold_dtype(mod: ModuleInfo) -> list[Finding]:
    pallas_mod = _imports_pallas(mod)
    if not pallas_mod and not _imports_jax(mod):
        return []
    # kernel scope: proven-jitted/pallas bodies; in a pallas-importing
    # module, every function (kernel defs there reach pallas_call
    # through closures and name indirections the index can't prove)
    if pallas_mod:
        spans = list(mod.functions.values())
    else:
        idx = _JitIndex(mod)
        spans = [mod.functions[q] for q in idx.traced
                 if q in mod.functions]
    if not spans:
        return []

    def innermost(lineno):
        best = None
        for fi in spans:
            if fi.lineno <= lineno <= fi.end_lineno:
                if best is None or fi.lineno > best.lineno:
                    best = fi
        return best

    out: list[Finding] = []
    seen: set = set()
    for node in ast.walk(mod.tree):
        dot = _threshold_dot(node, mod)
        if dot is None:
            continue
        key = (node.lineno, node.col_offset)
        if key in seen:
            continue
        seen.add(key)
        fi = innermost(node.lineno)
        if fi is None or "threshold-dtype" in fi.ignores:
            continue
        if "threshold-dtype" in (mod.line_ignores(node.lineno)
                                 | mod.line_ignores(dot.lineno)):
            continue
        out.append(Finding(
            rule="threshold-dtype", code="JTJ005",
            path=mod.relpath, line=dot.lineno,
            col=dot.col_offset + 1, qualname=fi.qualname,
            message="thresholded f32 dot: the > 0 test proves the "
                    "operands live in the 0/1 boolean semiring, and an "
                    "f32 matmul computes that AND/OR at 1/4 the MXU's "
                    "int8 operand density",
            hint="feed int8 0/1 operands with preferred_element_type="
                 "jnp.int32 (or the bit-packed uint32 path) and keep "
                 "the > 0 threshold; waive with # lint: "
                 "ignore[threshold-dtype] where f32 is load-bearing"))
    return out


def no_host_roundtrip(mod: ModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    for q, fi in mod.functions.items():
        if "no-host-roundtrip" in fi.ignores:
            continue
        events = _taint_events(fi.node)
        if not any(src is True for _, _, src in events):
            continue
        for call in ast.walk(fi.node):
            if not isinstance(call, ast.Call):
                continue
            tainted = _tainted_at(events, call.lineno)
            if not tainted:
                continue
            f = call.func
            hit = what = None
            if isinstance(f, ast.Attribute) \
                    and f.attr in _ROUNDTRIP_METHODS:
                hit = _mentions(f.value, tainted)
                what = f".{f.attr}()"
            elif isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and (f.value.id, f.attr) in _ROUNDTRIP_FUNCS \
                    and call.args:
                hit = _mentions(call.args[0], tainted)
                what = f"{f.value.id}.{f.attr}()"
            if hit is None:
                continue
            if "no-host-roundtrip" in mod.line_ignores(call.lineno):
                continue
            out.append(Finding(
                rule="no-host-roundtrip", code="JTJ004",
                path=mod.relpath, line=call.lineno,
                col=call.col_offset + 1, qualname=q,
                message=(f"{what} on {hit!r} (device-resident: bound "
                         f"from a device-placement call at line "
                         f"{tainted[hit]}) round-trips IR arrays back "
                         "to host inside a checker path"),
                hint="consume the device arrays in-kernel (shard_map/"
                     "jit) or keep a host-side copy from before "
                     "placement; waive with # lint: "
                     "ignore[no-host-roundtrip] when a host gather is "
                     "the point"))
    return out
