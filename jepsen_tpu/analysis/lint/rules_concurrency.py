"""Concurrency + durability invariant rules.

Each rule encodes an invariant that was violated at least once in PRs
1-4 and caught only by human review (doc/static-analysis.md maps each
rule to its incident):

* ``lock-guard`` (JTL001) — Eraser-style lock-set discipline: an
  attribute the class mutates under ``with self._lock`` anywhere must be
  mutated under it everywhere (``__init__`` and helpers provably called
  only under the lock are exempt).
* ``thread-owner`` (JTL002) — ``# owner: scheduler|worker|any``
  annotations plus call-graph reachability: worker-reachable code must
  never call a scheduler-only mutator (the PR 4 concurrent-close race
  class).
* ``no-unbounded-block`` (JTL003) — no timeout-less ``Queue.get`` /
  ``join`` / ``recv`` / ``wait`` reachable from the scheduler loop: one
  silent unbounded block wedges the whole run (the bug class PR 4's
  deadline layer exists to kill).
* ``fsync-pairing`` (JTL004) — ``os.fsync`` without a preceding
  ``flush`` on the same handle syncs stale buffers; and in a class
  annotated ``# durability: fsync`` every writing method must carry the
  full flush+fsync pair (the WAL/fault-registry durability contract
  from PR 3).
"""
from __future__ import annotations

import ast

from jepsen_tpu.analysis.diagnostics import Finding
from jepsen_tpu.analysis.lint.astcache import ModuleInfo
from jepsen_tpu.analysis.lint.callgraph import CallGraph, body_calls

MUTATOR_METHODS = frozenset({
    "append", "add", "clear", "pop", "popitem", "update", "extend",
    "remove", "discard", "setdefault", "insert", "appendleft", "popleft",
    "sort", "reverse",
})

_INIT_METHODS = ("__init__", "__new__", "__post_init__")


def _is_lock_ctor(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else "")
    return name in ("Lock", "RLock")


def _self_attr(node, class_name: str | None = None):
    """'attr' when node is ``self.attr`` / ``cls.attr`` (or
    ``ClassName.attr`` for class-level state), else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        if node.value.id in ("self", "cls") or node.value.id == class_name:
            return node.attr
    return None


class _Mutation:
    __slots__ = ("attr", "lineno", "col", "locked", "method", "desc")

    def __init__(self, attr, lineno, col, locked, method, desc):
        self.attr, self.lineno, self.col = attr, lineno, col
        self.locked, self.method, self.desc = locked, method, desc


def _with_lock_items(node, lock_attrs, class_name):
    for item in node.items:
        a = _self_attr(item.context_expr, class_name)
        if a in lock_attrs:
            return True
    return False


def _scan_method(mod, method_fi, lock_attrs, class_name):
    """(mutations, locked_selfcalls, all_selfcalls) for one method.
    Nested defs are scanned for mutations but NEVER count as
    lock-guarded: a closure runs when it is *called*, not where its
    ``with`` block happens to enclose its definition."""
    mutations: list[_Mutation] = []
    locked_calls: list[str] = []
    all_calls: list[str] = []

    def note(attr, node, desc, locked):
        mutations.append(_Mutation(attr, node.lineno, node.col_offset,
                                   locked, method_fi, desc))

    def walk(node, locked: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, False)
                continue
            child_locked = locked
            if isinstance(child, ast.With) and _with_lock_items(
                    child, lock_attrs, class_name):
                child_locked = True
            if isinstance(child, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (child.targets
                           if isinstance(child, ast.Assign)
                           else [child.target])
                for t in targets:
                    a = _self_attr(t, class_name)
                    if a is not None:
                        note(a, child, f"self.{a} rebound", locked)
                    elif isinstance(t, ast.Subscript):
                        a = _self_attr(t.value, class_name)
                        if a is not None:
                            note(a, child, f"self.{a}[...] stored", locked)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        for el in t.elts:
                            a = _self_attr(el, class_name)
                            if a is not None:
                                note(a, child, f"self.{a} rebound", locked)
            elif isinstance(child, ast.Delete):
                for t in child.targets:
                    a = _self_attr(t, class_name) or (
                        _self_attr(t.value, class_name)
                        if isinstance(t, ast.Subscript) else None)
                    if a is not None:
                        note(a, child, f"self.{a} deleted", locked)
            elif isinstance(child, ast.Call):
                f = child.func
                if isinstance(f, ast.Attribute):
                    a = _self_attr(f.value, class_name)
                    if a is not None and f.attr in MUTATOR_METHODS:
                        note(a, child, f"self.{a}.{f.attr}()", locked)
                    if isinstance(f.value, ast.Name) \
                            and f.value.id == "self":
                        all_calls.append(f.attr)
                        if locked:
                            locked_calls.append(f.attr)
            walk(child, child_locked)

    walk(method_fi.node, False)
    return mutations, locked_calls, all_calls


def lock_guard(mod: ModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    for cq, ci in mod.classes.items():
        # methods = direct function children of the class (plus their
        # closures, scanned inside _scan_method)
        methods = {q: fi for q, fi in mod.functions.items()
                   if q.startswith(cq + ".")
                   and "." not in q[len(cq) + 1:]}
        if not methods:
            continue
        lock_attrs: set = set()
        for fi in methods.values():
            for n in ast.walk(fi.node):
                if isinstance(n, ast.Assign) and _is_lock_ctor(n.value):
                    for t in n.targets:
                        a = _self_attr(t, ci.name)
                        if a is not None:
                            lock_attrs.add(a)
        for stmt in ci.node.body:  # class-level: _lock = Lock()
            if isinstance(stmt, ast.Assign) and _is_lock_ctor(stmt.value):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        lock_attrs.add(t.id)
        if not lock_attrs:
            continue

        per_method: dict = {}
        lockheld_callees: set = set()   # self.m() seen under a lock
        unlocked_callees: set = set()   # self.m() seen outside any lock
        for q, fi in methods.items():
            muts, locked_calls, all_calls = _scan_method(
                mod, fi, lock_attrs, ci.name)
            per_method[q] = (fi, muts)
            in_init = fi.node.name in _INIT_METHODS
            for c in all_calls:
                if c in locked_calls or in_init:
                    lockheld_callees.add(c)
                else:
                    unlocked_callees.add(c)
        guarded = {m.attr for fi, muts in per_method.values()
                   for m in muts
                   if m.locked and fi.node.name not in _INIT_METHODS}
        guarded -= set(lock_attrs)
        if not guarded:
            continue
        # helper methods provably called only under the lock (or from
        # __init__, before the object is shared) inherit the guard
        exempt_methods = lockheld_callees - unlocked_callees
        for q, (fi, muts) in per_method.items():
            name = fi.node.name
            if name in _INIT_METHODS or name in exempt_methods:
                continue
            if "lock-guard" in fi.ignores or "lock-guard" in ci.ignores:
                continue
            for m in muts:
                if m.locked or m.attr not in guarded:
                    continue
                if "lock-guard" in mod.line_ignores(m.lineno):
                    continue
                locks = "/".join(sorted(f"self.{a}" for a in lock_attrs))
                out.append(Finding(
                    rule="lock-guard", code="JTL001", path=mod.relpath,
                    line=m.lineno, col=m.col + 1, qualname=q,
                    message=(f"{m.desc} outside `with {locks}` but "
                             f"self.{m.attr} is lock-guarded elsewhere "
                             f"in {ci.name}"),
                    hint="mutate under the lock, or annotate the line "
                         "with `# lint: ignore[lock-guard]` and document "
                         "the single-writer argument"))
    return out


# ---------------------------------------------------------------------------

def thread_owner(graph: CallGraph) -> list[Finding]:
    out: list[Finding] = []
    workers = [n for n, fi in graph.functions.items() if fi.owner == "worker"]
    for root in workers:
        seen = graph.reachable(
            [root], through=lambda n: graph.owner(n) != "scheduler")
        for node, (parent, lineno) in seen.items():
            if graph.owner(node) != "scheduler" or parent is None:
                continue
            pmod = graph.modules.get(parent[0])
            pfi = graph.functions.get(parent)
            if pmod is not None and (
                    "thread-owner" in pmod.line_ignores(lineno)
                    or (pfi is not None and "thread-owner" in pfi.ignores)):
                continue
            chain = " -> ".join(q for _, q in graph.path_to(seen, node))
            out.append(Finding(
                rule="thread-owner", code="JTL002",
                path=parent[0], line=lineno, col=1, qualname=parent[1],
                message=(f"worker-owned {root[1]!r} reaches "
                         f"scheduler-only {node[1]!r} ({chain})"),
                hint="scheduler-only mutators may only run on the "
                     "scheduler thread; hand results over via the "
                     "completion queue instead"))
    return out


_BLOCKING = ("get", "join", "wait", "recv")

# Receiver methods that prove "this is a queue" (so its zero-arg .get()
# blocks). dict.get/ContextVar.get share the name but not these.
_QUEUE_EVIDENCE = frozenset({"put", "put_nowait", "get_nowait",
                             "task_done", "qsize"})


def _queue_receivers(mod: ModuleInfo) -> frozenset:
    out: set = set()
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _QUEUE_EVIDENCE:
            d = _recv_dump(n.func.value)
            if d is not None:
                out.add(d)
    return frozenset(out)


def _unbounded_block_call(call: ast.Call, queues: frozenset) -> str | None:
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in _BLOCKING:
        return None
    kwnames = {k.arg for k in call.keywords}
    if "timeout" in kwnames:
        return None
    if f.attr == "recv":
        return "recv() with no timeout mechanism"
    if call.args or any(k.arg is None for k in call.keywords):
        return None  # dict.get(k)/str.join(xs)-style calls take args
    if f.attr == "get" and _recv_dump(f.value) not in queues:
        return None  # no queue evidence: dict/ContextVar-style .get()
    return f"{f.attr}() without a timeout"


def no_unbounded_block(graph: CallGraph) -> list[Finding]:
    out: list[Finding] = []
    roots = [n for n, fi in graph.functions.items()
             if fi.owner == "scheduler"]
    seen = graph.reachable(
        [root for root in roots],
        through=lambda n: graph.owner(n) in (None, "any", "scheduler"))
    root_of: dict = {}
    for node in seen:
        chain = graph.path_to(seen, node)
        root_of[node] = chain[0]
    queue_evidence: dict = {}
    for node in seen:
        fi = graph.functions.get(node)
        if fi is None or fi.owner == "worker":
            continue
        mod = graph.modules.get(node[0])
        if mod is None or "no-unbounded-block" in fi.ignores:
            continue
        queues = queue_evidence.get(node[0])
        if queues is None:
            queues = queue_evidence[node[0]] = _queue_receivers(mod)
        for call in body_calls(fi.node):
            why = _unbounded_block_call(call, queues)
            if why is None:
                continue
            if "no-unbounded-block" in mod.line_ignores(call.lineno):
                continue
            src = root_of.get(node, node)
            via = ("" if src == node
                   else f" (reachable from scheduler-owned {src[1]!r})")
            out.append(Finding(
                rule="no-unbounded-block", code="JTL003",
                path=node[0], line=call.lineno, col=call.col_offset + 1,
                qualname=node[1],
                message=f"{why} on the scheduler path{via}",
                hint="pass timeout= (poll in a loop if the wait is "
                     "legitimately long) so a hung peer can never wedge "
                     "the scheduler silently"))
    return out


# ---------------------------------------------------------------------------

def _recv_dump(node) -> str | None:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001
        return None


def fsync_pairing(mod: ModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    for q, fi in mod.functions.items():
        if "fsync-pairing" in fi.ignores:
            continue
        calls = body_calls(fi.node)
        flush_of: dict[str, int] = {}   # receiver dump -> first flush line
        for c in calls:
            f = c.func
            if isinstance(f, ast.Attribute) and f.attr == "flush":
                d = _recv_dump(f.value)
                if d is not None and d not in flush_of:
                    flush_of[d] = c.lineno
        for c in calls:
            f = c.func
            if not (isinstance(f, ast.Attribute) and f.attr == "fsync"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "os" and c.args):
                continue
            arg = c.args[0]
            if not (isinstance(arg, ast.Call)
                    and isinstance(arg.func, ast.Attribute)
                    and arg.func.attr == "fileno"):
                continue  # fsync(fd) on a raw descriptor: can't pair
            recv = _recv_dump(arg.func.value)
            if recv is None:
                continue
            if "fsync-pairing" in mod.line_ignores(c.lineno):
                continue
            flushed_at = flush_of.get(recv)
            if flushed_at is None or flushed_at > c.lineno:
                out.append(Finding(
                    rule="fsync-pairing", code="JTL004", path=mod.relpath,
                    line=c.lineno, col=c.col_offset + 1, qualname=q,
                    message=(f"os.fsync({recv}.fileno()) without a "
                             f"preceding {recv}.flush() — buffered "
                             "writes are not yet in the kernel, so the "
                             "fsync persists stale data"),
                    hint=f"call {recv}.flush() before os.fsync()"))

    # durability-annotated classes: every writing method carries the pair
    for cq, ci in mod.classes.items():
        if ci.durability != "fsync":
            continue
        methods = {q: fi for q, fi in mod.functions.items()
                   if q.startswith(cq + ".")
                   and "." not in q[len(cq) + 1:]}
        for q, fi in methods.items():
            if "fsync-pairing" in fi.ignores:
                continue
            calls = body_calls(fi.node)
            writes = [c for c in calls
                      if isinstance(c.func, ast.Attribute)
                      and c.func.attr == "write"
                      and _self_attr(c.func.value, ci.name) is not None]
            if not writes:
                continue
            has_flush = any(isinstance(c.func, ast.Attribute)
                            and c.func.attr == "flush" for c in calls)
            has_fsync = any(isinstance(c.func, ast.Attribute)
                            and c.func.attr == "fsync" for c in calls)
            if has_flush and has_fsync:
                continue
            w = writes[0]
            if "fsync-pairing" in mod.line_ignores(w.lineno):
                continue
            missing = [x for x, ok in (("flush", has_flush),
                                       ("fsync", has_fsync)) if not ok]
            out.append(Finding(
                rule="fsync-pairing", code="JTL004", path=mod.relpath,
                line=w.lineno, col=w.col_offset + 1, qualname=q,
                message=(f"{ci.name} is `# durability: fsync` but "
                         f"{fi.node.name} writes without "
                         f"{' or '.join(missing)}"),
                hint="pair every durable write with flush + os.fsync "
                     "(interval batching is fine — the calls must "
                     "exist on the path)"))
    return out
