"""Concurrency + durability invariant rules.

Each rule encodes an invariant that was violated at least once in PRs
1-4 and caught only by human review (doc/static-analysis.md maps each
rule to its incident):

* ``lock-guard`` (JTL001) — Eraser-style lock-set discipline: an
  attribute the class mutates under ``with self._lock`` anywhere must be
  mutated under it everywhere (``__init__`` and helpers provably called
  only under the lock are exempt).
* ``thread-owner`` (JTL002) — ``# owner: scheduler|worker|any``
  annotations plus call-graph reachability: worker-reachable code must
  never call a scheduler-only mutator (the PR 4 concurrent-close race
  class).
* ``no-unbounded-block`` (JTL003) — no timeout-less ``Queue.get`` /
  ``join`` / ``recv`` / ``wait`` reachable from the scheduler loop: one
  silent unbounded block wedges the whole run (the bug class PR 4's
  deadline layer exists to kill).
* ``fsync-pairing`` (JTL004) — ``os.fsync`` without a preceding
  ``flush`` on the same handle syncs stale buffers; and in a class
  annotated ``# durability: fsync`` every writing method must carry the
  full flush+fsync pair (the WAL/fault-registry durability contract
  from PR 3).
* ``lock-order`` (JTL005) — lockdep-style deadlock detection over the
  interprocedural lock-acquisition-order graph: cycles between locks,
  calls that re-acquire a held non-reentrant ``Lock``, lock-held calls
  into ``# blocking:``-annotated functions, and unbounded blocking
  primitives executed while holding a lock.
* ``cond-wait`` (JTL006) — condition-variable discipline: ``wait()``
  must sit in a ``while``-predicate loop under the condition's own
  lock, ``notify`` must run under the lock, and a timeout-less
  ``wait()`` reachable from a scheduler-owned root escalates (one
  missed notify would wedge the run silently).

The reachability rules traverse the thread-spawn edges the callgraph
rework added (``Thread(target=...)``, ``submit``, ``# thread-helper:``
idioms): ``thread-owner`` follows every edge kind, ``no-unbounded-block``
follows calls + ``sync-spawn`` (a detached thread's block can't wedge
its spawner), and the lock analyses follow calls + ``sync-spawn`` but
never ``spawn`` (a fresh thread does not inherit held locks).
"""
from __future__ import annotations

import ast

from jepsen_tpu.analysis.diagnostics import Finding
from jepsen_tpu.analysis.lint.astcache import ModuleInfo
from jepsen_tpu.analysis.lint.callgraph import (
    CALL, SPAWN, SYNC_SPAWN, CallGraph, body_calls,
)

MUTATOR_METHODS = frozenset({
    "append", "add", "clear", "pop", "popitem", "update", "extend",
    "remove", "discard", "setdefault", "insert", "appendleft", "popleft",
    "sort", "reverse",
})

_INIT_METHODS = ("__init__", "__new__", "__post_init__")


def _is_lock_ctor(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else "")
    return name in ("Lock", "RLock")


def _self_attr(node, class_name: str | None = None):
    """'attr' when node is ``self.attr`` / ``cls.attr`` (or
    ``ClassName.attr`` for class-level state), else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        if node.value.id in ("self", "cls") or node.value.id == class_name:
            return node.attr
    return None


class _Mutation:
    __slots__ = ("attr", "lineno", "col", "locked", "method", "desc")

    def __init__(self, attr, lineno, col, locked, method, desc):
        self.attr, self.lineno, self.col = attr, lineno, col
        self.locked, self.method, self.desc = locked, method, desc


def _with_lock_items(node, lock_attrs, class_name):
    for item in node.items:
        a = _self_attr(item.context_expr, class_name)
        if a in lock_attrs:
            return True
    return False


def _scan_method(mod, method_fi, lock_attrs, class_name):
    """(mutations, locked_selfcalls, all_selfcalls, ref_calls) for one
    method. Nested defs are scanned for mutations but NEVER count as
    lock-guarded: a closure runs when it is *called*, not where its
    ``with`` block happens to enclose its definition. ``ref_calls`` are
    ``self.m`` references passed as call arguments (thread-spawn
    targets): always unlocked, wherever they lexically sit."""
    mutations: list[_Mutation] = []
    locked_calls: list[str] = []
    all_calls: list[str] = []
    ref_calls: list[str] = []

    def note(attr, node, desc, locked):
        mutations.append(_Mutation(attr, node.lineno, node.col_offset,
                                   locked, method_fi, desc))

    def walk(node, locked: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, False)
                continue
            child_locked = locked
            if isinstance(child, ast.With) and _with_lock_items(
                    child, lock_attrs, class_name):
                child_locked = True
            if isinstance(child, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (child.targets
                           if isinstance(child, ast.Assign)
                           else [child.target])
                for t in targets:
                    a = _self_attr(t, class_name)
                    if a is not None:
                        note(a, child, f"self.{a} rebound", locked)
                    elif isinstance(t, ast.Subscript):
                        a = _self_attr(t.value, class_name)
                        if a is not None:
                            note(a, child, f"self.{a}[...] stored", locked)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        for el in t.elts:
                            a = _self_attr(el, class_name)
                            if a is not None:
                                note(a, child, f"self.{a} rebound", locked)
            elif isinstance(child, ast.Delete):
                for t in child.targets:
                    a = _self_attr(t, class_name) or (
                        _self_attr(t.value, class_name)
                        if isinstance(t, ast.Subscript) else None)
                    if a is not None:
                        note(a, child, f"self.{a} deleted", locked)
            elif isinstance(child, ast.Call):
                f = child.func
                if isinstance(f, ast.Attribute):
                    a = _self_attr(f.value, class_name)
                    if a is not None and f.attr in MUTATOR_METHODS:
                        note(a, child, f"self.{a}.{f.attr}()", locked)
                    if isinstance(f.value, ast.Name) \
                            and f.value.id == "self":
                        all_calls.append(f.attr)
                        if locked:
                            locked_calls.append(f.attr)
                # a `self.m` REFERENCE handed to a call
                # (Thread(target=self.m), executor.submit(self.m)) runs
                # on whatever thread eventually invokes it — never
                # provably under this lock, even when the spawn site is
                # inside the `with`. Count it as an unlocked call so
                # the helper-exemption can't blow through a thread edge.
                for arg in list(child.args) + [k.value
                                               for k in child.keywords]:
                    a = (arg.attr if isinstance(arg, ast.Attribute)
                         and isinstance(arg.value, ast.Name)
                         and arg.value.id in ("self", "cls") else None)
                    if a is not None:
                        ref_calls.append(a)
            walk(child, child_locked)

    walk(method_fi.node, False)
    return mutations, locked_calls, all_calls, ref_calls


def lock_guard(mod: ModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    for cq, ci in mod.classes.items():
        # methods = direct function children of the class (plus their
        # closures, scanned inside _scan_method)
        methods = {q: fi for q, fi in mod.functions.items()
                   if q.startswith(cq + ".")
                   and "." not in q[len(cq) + 1:]}
        if not methods:
            continue
        lock_attrs: set = set()
        for fi in methods.values():
            for n in ast.walk(fi.node):
                if isinstance(n, ast.Assign) and _is_lock_ctor(n.value):
                    for t in n.targets:
                        a = _self_attr(t, ci.name)
                        if a is not None:
                            lock_attrs.add(a)
        for stmt in ci.node.body:  # class-level: _lock = Lock()
            if isinstance(stmt, ast.Assign) and _is_lock_ctor(stmt.value):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        lock_attrs.add(t.id)
        if not lock_attrs:
            continue

        per_method: dict = {}
        lockheld_callees: set = set()   # self.m() seen under a lock
        unlocked_callees: set = set()   # self.m() seen outside any lock
        for q, fi in methods.items():
            muts, locked_calls, all_calls, ref_calls = _scan_method(
                mod, fi, lock_attrs, ci.name)
            per_method[q] = (fi, muts)
            in_init = fi.node.name in _INIT_METHODS
            for c in all_calls:
                if c in locked_calls or in_init:
                    lockheld_callees.add(c)
                else:
                    unlocked_callees.add(c)
            # spawn-target references escape the lock even from __init__
            # (the thread runs after the object is shared)
            unlocked_callees.update(ref_calls)
        guarded = {m.attr for fi, muts in per_method.values()
                   for m in muts
                   if m.locked and fi.node.name not in _INIT_METHODS}
        guarded -= set(lock_attrs)
        if not guarded:
            continue
        # helper methods provably called only under the lock (or from
        # __init__, before the object is shared) inherit the guard
        exempt_methods = lockheld_callees - unlocked_callees
        for q, (fi, muts) in per_method.items():
            name = fi.node.name
            if name in _INIT_METHODS or name in exempt_methods:
                continue
            if "lock-guard" in fi.ignores or "lock-guard" in ci.ignores:
                continue
            for m in muts:
                if m.locked or m.attr not in guarded:
                    continue
                if "lock-guard" in mod.line_ignores(m.lineno):
                    continue
                locks = "/".join(sorted(f"self.{a}" for a in lock_attrs))
                out.append(Finding(
                    rule="lock-guard", code="JTL001", path=mod.relpath,
                    line=m.lineno, col=m.col + 1, qualname=q,
                    message=(f"{m.desc} outside `with {locks}` but "
                             f"self.{m.attr} is lock-guarded elsewhere "
                             f"in {ci.name}"),
                    hint="mutate under the lock, or annotate the line "
                         "with `# lint: ignore[lock-guard]` and document "
                         "the single-writer argument"))
    return out


# ---------------------------------------------------------------------------

def thread_owner(graph: CallGraph) -> list[Finding]:
    # roots: explicitly worker-annotated functions PLUS thread-spawn
    # targets without an annotation (the owner transition — a spawned
    # target runs on a fresh thread, so scheduler-only code it reaches
    # is exactly the PR-4 concurrent-close race class). Spawn edges are
    # traversed too: a thread spawned from a worker is still not the
    # scheduler.
    out: list[Finding] = []
    workers = [n for n in graph.functions
               if graph.effective_owner(n) == "worker"]
    for root in workers:
        seen = graph.reachable(
            [root], through=lambda n: graph.owner(n) != "scheduler")
        for node, (parent, lineno) in seen.items():
            if graph.owner(node) != "scheduler" or parent is None:
                continue
            pmod = graph.modules.get(parent[0])
            pfi = graph.functions.get(parent)
            if pmod is not None and (
                    "thread-owner" in pmod.line_ignores(lineno)
                    or (pfi is not None and "thread-owner" in pfi.ignores)):
                continue
            chain = " -> ".join(q for _, q in graph.path_to(seen, node))
            out.append(Finding(
                rule="thread-owner", code="JTL002",
                path=parent[0], line=lineno, col=1, qualname=parent[1],
                message=(f"worker-owned {root[1]!r} reaches "
                         f"scheduler-only {node[1]!r} ({chain})"),
                hint="scheduler-only mutators may only run on the "
                     "scheduler thread; hand results over via the "
                     "completion queue instead"))
    return out


_BLOCKING = ("get", "join", "wait", "recv")

# Receiver methods that prove "this is a queue" (so its zero-arg .get()
# blocks). dict.get/ContextVar.get share the name but not these.
_QUEUE_EVIDENCE = frozenset({"put", "put_nowait", "get_nowait",
                             "task_done", "qsize"})


def _queue_receivers(mod: ModuleInfo) -> frozenset:
    out: set = set()
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _QUEUE_EVIDENCE:
            d = _recv_dump(n.func.value)
            if d is not None:
                out.add(d)
    return frozenset(out)


def _unbounded_block_call(call: ast.Call, queues: frozenset) -> str | None:
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in _BLOCKING:
        return None
    kwnames = {k.arg for k in call.keywords}
    if "timeout" in kwnames:
        return None
    if f.attr == "recv":
        return "recv() with no timeout mechanism"
    if call.args or any(k.arg is None for k in call.keywords):
        return None  # dict.get(k)/str.join(xs)-style calls take args
    if f.attr == "get" and _recv_dump(f.value) not in queues:
        return None  # no queue evidence: dict/ContextVar-style .get()
    return f"{f.attr}() without a timeout"


def scheduler_reachable(graph: CallGraph):
    """{node: (parent, lineno, via_sync)} closure from scheduler-owned
    roots — plain calls through non-worker-annotated nodes, plus
    ``sync-spawn`` edges (the caller waits for the spawned work, so its
    block is the scheduler's block). Detached ``spawn`` edges are never
    followed: a parked worker thread can't wedge its spawner.
    ``via_sync`` records whether the path crossed a sync-spawn edge —
    nodes so reached are scanned even when worker-annotated."""
    seen: dict = {}
    frontier = [(n, None, 0, False) for n, fi in graph.functions.items()
                if fi.owner == "scheduler"]
    while frontier:
        node, parent, lineno, via_sync = frontier.pop()
        prev = seen.get(node)
        # re-visit on a via_sync UPGRADE (False -> True): the first
        # visit may have arrived on a plain-call path that stops at a
        # worker-annotated leaf, while a sync-spawn path to the same
        # node must both scan it and expand through it — first-visit-
        # wins would silently drop those findings depending on source
        # order
        if prev is not None and (prev[2] or not via_sync):
            continue
        seen[node] = (parent, lineno, via_sync)
        if parent is not None and not via_sync \
                and graph.owner(node) not in (None, "any", "scheduler"):
            continue  # worker-annotated leaf on a plain-call path
        for callee, ln, kind in graph.edges.get(node, ()):
            if kind == SPAWN:
                continue
            frontier.append((callee, node, ln,
                             via_sync or kind == SYNC_SPAWN))
    return seen


def no_unbounded_block(graph: CallGraph) -> list[Finding]:
    out: list[Finding] = []
    seen = scheduler_reachable(graph)
    path_index = {n: (p, ln) for n, (p, ln, _v) in seen.items()}
    root_of: dict = {}
    for node in seen:
        chain = graph.path_to(path_index, node)
        root_of[node] = chain[0]
    queue_evidence: dict = {}
    for node, (_parent, _ln, via_sync) in seen.items():
        fi = graph.functions.get(node)
        if fi is None or (fi.owner == "worker" and not via_sync):
            continue
        mod = graph.modules.get(node[0])
        if mod is None or "no-unbounded-block" in fi.ignores:
            continue
        queues = queue_evidence.get(node[0])
        if queues is None:
            queues = queue_evidence[node[0]] = _queue_receivers(mod)
        for call in body_calls(fi.node):
            why = _unbounded_block_call(call, queues)
            if why is None:
                continue
            if "no-unbounded-block" in mod.line_ignores(call.lineno):
                continue
            src = root_of.get(node, node)
            via = ("" if src == node
                   else f" (reachable from scheduler-owned {src[1]!r})")
            out.append(Finding(
                rule="no-unbounded-block", code="JTL003",
                path=node[0], line=call.lineno, col=call.col_offset + 1,
                qualname=node[1],
                message=f"{why} on the scheduler path{via}",
                hint="pass timeout= (poll in a loop if the wait is "
                     "legitimately long) so a hung peer can never wedge "
                     "the scheduler silently"))
    return out


# ---------------------------------------------------------------------------
# lock-order (JTL005): lockdep-style deadlock detection
# ---------------------------------------------------------------------------

_LOCKLIKE = ("Lock", "RLock", "Condition")
# non-reentrant constructors: re-acquiring on the same thread deadlocks
_NON_REENTRANT = ("Lock",)


def _lock_ctor_kind(node) -> str | None:
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else "")
    return name if name in _LOCKLIKE else None


class _LockInventory:
    """Per-module lock-like attributes: ``locks[(scope, attr)] = kind``
    where scope is the class qualname ('' for module globals), plus the
    Condition->associated-lock map (``Condition(self._lock)`` acquires
    ``self._lock``, so ordering identity must collapse to it)."""

    def __init__(self, mod: ModuleInfo):
        self.locks: dict = {}
        self.cv_assoc: dict = {}   # (scope, cv_attr) -> assoc lock attr
        for cq, ci in mod.classes.items():
            methods = [fi for q, fi in mod.functions.items()
                       if q.startswith(cq + ".")
                       and "." not in q[len(cq) + 1:]]
            for fi in methods:
                for n in ast.walk(fi.node):
                    if isinstance(n, ast.Assign):
                        kind = _lock_ctor_kind(n.value)
                        if kind is None:
                            continue
                        for t in n.targets:
                            a = _self_attr(t, ci.name)
                            if a is not None:
                                self.locks[(cq, a)] = kind
                                self._note_assoc(cq, a, n.value, ci.name)
            for stmt in ci.node.body:
                if isinstance(stmt, ast.Assign):
                    kind = _lock_ctor_kind(stmt.value)
                    if kind is None:
                        continue
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.locks[(cq, t.id)] = kind
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign):
                kind = _lock_ctor_kind(stmt.value)
                if kind is None:
                    continue
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.locks[("", t.id)] = kind

    def _note_assoc(self, scope, attr, ctor, class_name):
        if _lock_ctor_kind(ctor) == "Condition" and ctor.args:
            assoc = _self_attr(ctor.args[0], class_name)
            if assoc is not None:
                self.cv_assoc[(scope, attr)] = assoc

    def lock_id(self, mod, fi, expr):
        """(relpath, scope, attr) for a with-item context expression
        that names a known lock, else None. Conditions constructed over
        an explicit lock collapse to that lock's identity."""
        scope = _enclosing_class(mod, fi)
        a = _self_attr(expr, scope.rsplit(".", 1)[-1] if scope else None)
        if a is not None and scope is not None:
            assoc = self.cv_assoc.get((scope, a))
            if assoc is not None and (scope, assoc) in self.locks:
                a = assoc
            if (scope, a) in self.locks:
                return (mod.relpath, scope, a)
        if isinstance(expr, ast.Name) and ("", expr.id) in self.locks:
            return (mod.relpath, "", expr.id)
        return None

    def kind(self, lock_id) -> str | None:
        return self.locks.get((lock_id[1], lock_id[2]))


def _enclosing_class(mod: ModuleInfo, fi) -> str | None:
    parts = fi.qualname.split(".")
    for i in range(len(parts) - 1, 0, -1):
        cq = ".".join(parts[:i])
        if cq in mod.classes:
            return cq
    return None


def _lock_name(lock_id) -> str:
    _rel, scope, attr = lock_id
    return f"{scope.rsplit('.', 1)[-1]}.{attr}" if scope else attr


class _FuncLockScan:
    """Lexical lock-region scan of ONE function: direct acquisitions,
    direct nested-order edges, direct same-``Lock`` re-acquisition, and
    every call made while holding at least one lock. Nested defs are
    skipped — a closure acquires when *called*, and it is its own graph
    node."""

    def __init__(self, mod, fi, inv: _LockInventory, queues: frozenset):
        self.acquires: set = set()
        self.order_edges: list = []      # (L1, L2, lineno)
        self.self_deadlocks: list = []   # (L, lineno, col)
        self.held_calls: list = []       # (lineno, col, tuple(held))
        self.held_blockers: list = []    # (lineno, col, why, held)
        self._inv = inv
        self._mod = mod
        self._fi = fi
        self._queues = queues
        self._walk(fi.node, [])

    def _walk(self, node, held: list):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            child_held = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in child.items:
                    lid = self._inv.lock_id(self._mod, self._fi,
                                            item.context_expr)
                    if lid is None:
                        continue
                    self.acquires.add(lid)
                    # order against the outer held set AND the items
                    # already acquired by THIS statement: `with a, b:`
                    # is sugar for nested withs, so it contributes the
                    # same a -> b edge
                    for h in held + acquired:
                        if h != lid:
                            self.order_edges.append((h, lid, child.lineno))
                    if (lid in held or lid in acquired) \
                            and self._inv.kind(lid) in _NON_REENTRANT:
                        self.self_deadlocks.append(
                            (lid, child.lineno, child.col_offset))
                    acquired.append(lid)
                if acquired:
                    child_held = held + acquired
            elif isinstance(child, ast.Call) and held:
                self.held_calls.append(
                    (child.lineno, child.col_offset, tuple(held)))
                why = _unbounded_block_call(child, self._queues)
                if why is not None and not self._wait_on_held_cv(
                        child, held):
                    self.held_blockers.append(
                        (child.lineno, child.col_offset, why, tuple(held)))
            self._walk(child, child_held)

    def _wait_on_held_cv(self, call: ast.Call, held: list) -> bool:
        """``cv.wait()`` while holding ``cv`` RELEASES the lock for the
        duration of the wait — the textbook pattern, not a lock-held
        block. (Its while-loop/timeout discipline is cond-wait's job.)"""
        f = call.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in ("wait", "wait_for")):
            return False
        rid = self._inv.lock_id(self._mod, self._fi, f.value)
        return rid is not None and rid in held


def lock_order(graph: CallGraph) -> list[Finding]:
    """Interprocedural lock-order analysis. Traverses plain call and
    ``sync-spawn`` edges only: a detached thread does not inherit the
    spawner's held locks (its acquisitions are its own thread's
    ordering problem, analyzed from its own root)."""
    out: list[Finding] = []
    inventories: dict = {}
    scans: dict = {}
    queue_evidence: dict = {}
    for rel, mod in graph.modules.items():
        inv = inventories[rel] = _LockInventory(mod)
        queues = queue_evidence[rel] = _queue_receivers(mod)
        for q, fi in mod.functions.items():
            scans[(rel, q)] = _FuncLockScan(mod, fi, inv, queues)

    # transitive acquisition sets (fixpoint over call/sync-spawn edges)
    eff = {n: set(s.acquires) for n, s in scans.items()}
    # nodes that (transitively) reach a `# blocking:`-annotated function
    blocking_rep = {n: f"{fi.qualname} (# blocking: {fi.blocking})"
                    for n, fi in graph.functions.items()
                    if fi.blocking is not None}
    changed = True
    while changed:
        changed = False
        for node, edges in graph.edges.items():
            if node not in eff:
                continue
            for callee, _ln, kind in edges:
                if kind == SPAWN:
                    continue
                ce = eff.get(callee)
                if ce and not ce <= eff[node]:
                    eff[node] |= ce
                    changed = True
                rep = blocking_rep.get(callee)
                if rep is not None and node not in blocking_rep:
                    blocking_rep[node] = rep
                    changed = True

    def waived(mod, fi, lineno) -> bool:
        return ("lock-order" in fi.ignores
                or "lock-order" in mod.line_ignores(lineno))

    order_graph: dict = {}   # L1 -> {L2: (path, lineno, qualname)}
    for node, scan in scans.items():
        mod = graph.modules[node[0]]
        fi = graph.functions[node]
        for lid, lineno, col in scan.self_deadlocks:
            if waived(mod, fi, lineno):
                continue
            out.append(Finding(
                rule="lock-order", code="JTL005", path=node[0],
                line=lineno, col=col + 1, qualname=node[1],
                message=(f"nested `with {_lock_name(lid)}` re-acquires a "
                         "non-reentrant Lock already held — guaranteed "
                         "self-deadlock"),
                hint="use an RLock, or restructure so the inner region "
                     "runs outside the lock"))
        for lineno, col, why, held in scan.held_blockers:
            if waived(mod, fi, lineno):
                continue
            locks = ", ".join(sorted(_lock_name(h) for h in held))
            out.append(Finding(
                rule="lock-order", code="JTL005", path=node[0],
                line=lineno, col=col + 1, qualname=node[1],
                message=(f"{why} while holding {locks} — every other "
                         "user of the lock blocks behind a wait that "
                         "may never end"),
                hint="release the lock before blocking, or bound the "
                     "wait with timeout="))
        # direct nested-with order edges. A waived site contributes no
        # edge — `# lint: ignore[lock-order]` on the acquisition line
        # (or the def) must suppress the cycles it participates in, the
        # same escape hatch every other diagnostic of this rule honors.
        for L1, L2, lineno in scan.order_edges:
            if waived(mod, fi, lineno):
                continue
            order_graph.setdefault(L1, {}).setdefault(
                L2, (node[0], lineno, node[1]))
        # calls made under a lock: what does the callee acquire?
        edges_by_line: dict = {}
        for callee, ln, kind in graph.edges.get(node, ()):
            if kind != SPAWN:
                edges_by_line.setdefault(ln, []).append(callee)
        for lineno, col, held in scan.held_calls:
            for callee in edges_by_line.get(lineno, ()):
                for lid in eff.get(callee, ()):
                    for h in held:
                        if h == lid:
                            if inventories[lid[0]].kind(lid) \
                                    in _NON_REENTRANT \
                                    and not waived(mod, fi, lineno):
                                out.append(Finding(
                                    rule="lock-order", code="JTL005",
                                    path=node[0], line=lineno,
                                    col=col + 1, qualname=node[1],
                                    message=(
                                        f"call into {callee[1]!r} may "
                                        f"re-acquire non-reentrant "
                                        f"{_lock_name(lid)} already "
                                        "held here — self-deadlock"),
                                    hint="split a _locked() helper that "
                                         "assumes the lock, or use an "
                                         "RLock"))
                        elif not waived(mod, fi, lineno):
                            order_graph.setdefault(h, {}).setdefault(
                                lid, (node[0], lineno, node[1]))
                rep = blocking_rep.get(callee)
                if rep is not None and not waived(mod, fi, lineno):
                    locks = ", ".join(sorted(_lock_name(h) for h in held))
                    out.append(Finding(
                        rule="lock-order", code="JTL005", path=node[0],
                        line=lineno, col=col + 1, qualname=node[1],
                        message=(f"call into blocking {rep} while "
                                 f"holding {locks}"),
                        hint="blocking/RPC work must not run under a "
                             "lock; snapshot state, release, then call"))

    out.extend(_order_cycles(order_graph))
    return out


def _order_cycles(order_graph: dict) -> list[Finding]:
    """One finding per lock-order cycle (Tarjan SCCs of the
    acquired-before digraph; any SCC with a cycle is an AB-BA deadlock
    waiting for the right interleaving)."""
    out: list[Finding] = []
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    def strongconnect(v):
        # iterative Tarjan (the lock graph is tiny, but recursion limits
        # are not worth betting on)
        work = [(v, iter(order_graph.get(v, ())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(order_graph.get(w, ()))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in list(order_graph):
        if v not in index:
            strongconnect(v)
    for comp in sccs:
        cyclic = len(comp) > 1 or (
            comp and comp[0] in order_graph.get(comp[0], ()))
        if not cyclic:
            continue
        comp = sorted(comp)
        ring = " -> ".join(_lock_name(x) for x in comp + [comp[0]])
        # anchor at the lexically first edge site inside the component
        sites = [order_graph[a][b] for a in comp
                 for b in order_graph.get(a, ()) if b in comp]
        path, lineno, qualname = min(sites)
        out.append(Finding(
            rule="lock-order", code="JTL005", path=path, line=lineno,
            col=1, qualname=qualname,
            message=(f"lock-order cycle: {ring} — two threads taking "
                     "these locks in opposite orders deadlock"),
            hint="impose one global acquisition order (document it next "
                 "to the lock constructors) or merge the locks"))
    return out


# ---------------------------------------------------------------------------
# cond-wait (JTL006): condition-variable discipline
# ---------------------------------------------------------------------------

def cond_wait(graph: CallGraph) -> list[Finding]:
    out: list[Finding] = []
    sched = scheduler_reachable(graph)
    for rel, mod in graph.modules.items():
        inv = _LockInventory(mod)
        cvs = {key for key, kind in inv.locks.items()
               if kind == "Condition"}
        if not cvs:
            continue
        for q, fi in mod.functions.items():
            scope = _enclosing_class(mod, fi) or ""
            cls_name = scope.rsplit(".", 1)[-1] if scope else None
            node = (rel, q)
            on_sched = node in sched and not (
                graph.owner(node) == "worker" and not sched[node][2])

            def guard_names(cv_attr):
                names = {cv_attr}
                assoc = inv.cv_assoc.get((scope, cv_attr))
                if assoc is not None:
                    names.add(assoc)
                return names

            def visit(n, held: frozenset, in_while: bool):
                for child in ast.iter_child_nodes(n):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        continue
                    child_held, child_while = held, in_while
                    if isinstance(child, (ast.With, ast.AsyncWith)):
                        got = set()
                        for item in child.items:
                            a = _self_attr(item.context_expr, cls_name)
                            if a is not None:
                                got.add(a)
                        if got:
                            child_held = held | got
                    elif isinstance(child, ast.While):
                        child_while = True
                    if isinstance(child, ast.Call):
                        _check_cv_call(child, held, in_while)
                    visit(child, child_held, child_while)

            def _check_cv_call(call, held, in_while):
                f = call.func
                if not (isinstance(f, ast.Attribute)
                        and f.attr in ("wait", "wait_for", "notify",
                                       "notify_all")):
                    return
                a = _self_attr(f.value, cls_name)
                if a is None or (scope, a) not in cvs:
                    return
                if "cond-wait" in fi.ignores \
                        or "cond-wait" in mod.line_ignores(call.lineno):
                    return
                loc = dict(rule="cond-wait", code="JTL006", path=rel,
                           line=call.lineno, col=call.col_offset + 1,
                           qualname=q)
                under_lock = bool(guard_names(a) & held)
                if not under_lock:
                    out.append(Finding(
                        **loc,
                        message=(f"self.{a}.{f.attr}() outside `with "
                                 f"self.{a}` — {'waiting' if 'wait' in f.attr else 'notifying'} "
                                 "without the condition's lock races "
                                 "the predicate"),
                        hint=f"wrap in `with self.{a}:`"))
                if f.attr == "wait" and not in_while:
                    out.append(Finding(
                        **loc,
                        message=(f"self.{a}.wait() not inside a "
                                 "while-predicate loop — spurious "
                                 "wakeups and stolen notifies break a "
                                 "naked wait"),
                        hint="loop: `while not <predicate>: "
                             f"self.{a}.wait(...)` (or use wait_for)"))
                kwnames = {k.arg for k in call.keywords}
                timeout_less = ("timeout" not in kwnames
                                and ((f.attr == "wait" and not call.args)
                                     or (f.attr == "wait_for"
                                         and len(call.args) < 2)))
                if timeout_less and f.attr in ("wait", "wait_for") \
                        and on_sched:
                    out.append(Finding(
                        **loc,
                        message=(f"timeout-less self.{a}.{f.attr}() "
                                 "reachable from a scheduler-owned root "
                                 "— one missed notify wedges the run "
                                 "silently"),
                        hint="pass timeout= and re-check the predicate "
                             "in the loop"))

            visit(fi.node, frozenset(), False)
    return out


# ---------------------------------------------------------------------------

def _recv_dump(node) -> str | None:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001
        return None


def fsync_pairing(mod: ModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    for q, fi in mod.functions.items():
        if "fsync-pairing" in fi.ignores:
            continue
        calls = body_calls(fi.node)
        flush_of: dict[str, int] = {}   # receiver dump -> first flush line
        for c in calls:
            f = c.func
            if isinstance(f, ast.Attribute) and f.attr == "flush":
                d = _recv_dump(f.value)
                if d is not None and d not in flush_of:
                    flush_of[d] = c.lineno
        for c in calls:
            f = c.func
            if not (isinstance(f, ast.Attribute) and f.attr == "fsync"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "os" and c.args):
                continue
            arg = c.args[0]
            if not (isinstance(arg, ast.Call)
                    and isinstance(arg.func, ast.Attribute)
                    and arg.func.attr == "fileno"):
                continue  # fsync(fd) on a raw descriptor: can't pair
            recv = _recv_dump(arg.func.value)
            if recv is None:
                continue
            if "fsync-pairing" in mod.line_ignores(c.lineno):
                continue
            flushed_at = flush_of.get(recv)
            if flushed_at is None or flushed_at > c.lineno:
                out.append(Finding(
                    rule="fsync-pairing", code="JTL004", path=mod.relpath,
                    line=c.lineno, col=c.col_offset + 1, qualname=q,
                    message=(f"os.fsync({recv}.fileno()) without a "
                             f"preceding {recv}.flush() — buffered "
                             "writes are not yet in the kernel, so the "
                             "fsync persists stale data"),
                    hint=f"call {recv}.flush() before os.fsync()"))

    # durability-annotated classes: every writing method carries the pair
    for cq, ci in mod.classes.items():
        if ci.durability != "fsync":
            continue
        methods = {q: fi for q, fi in mod.functions.items()
                   if q.startswith(cq + ".")
                   and "." not in q[len(cq) + 1:]}
        for q, fi in methods.items():
            if "fsync-pairing" in fi.ignores:
                continue
            calls = body_calls(fi.node)
            writes = [c for c in calls
                      if isinstance(c.func, ast.Attribute)
                      and c.func.attr == "write"
                      and _self_attr(c.func.value, ci.name) is not None]
            if not writes:
                continue
            has_flush = any(isinstance(c.func, ast.Attribute)
                            and c.func.attr == "flush" for c in calls)
            has_fsync = any(isinstance(c.func, ast.Attribute)
                            and c.func.attr == "fsync" for c in calls)
            if has_flush and has_fsync:
                continue
            w = writes[0]
            if "fsync-pairing" in mod.line_ignores(w.lineno):
                continue
            missing = [x for x, ok in (("flush", has_flush),
                                       ("fsync", has_fsync)) if not ok]
            out.append(Finding(
                rule="fsync-pairing", code="JTL004", path=mod.relpath,
                line=w.lineno, col=w.col_offset + 1, qualname=q,
                message=(f"{ci.name} is `# durability: fsync` but "
                         f"{fi.node.name} writes without "
                         f"{' or '.join(missing)}"),
                hint="pair every durable write with flush + os.fsync "
                     "(interval batching is fine — the calls must "
                     "exist on the path)"))
    return out
