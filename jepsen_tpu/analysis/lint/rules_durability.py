"""Durability-protocol rules (JTD001, rule ``durability-protocol``).

The repo's crash-safety story rests on two hand-rolled disciplines the
PR-13 checkpoint work made load-bearing everywhere:

* **atomic replace** — durable documents are published by
  temp-write -> flush -> fsync -> rename (``utils.atomic_write_json``).
  Skipping the fsync means ``os.replace`` can publish a name whose
  *data* is still in the page cache: a power cut leaves a torn or
  empty file under the durable name — exactly the torn-document class
  the rename exists to prevent.
* **record-before-act** — the durable record of an action (fault
  registry inject rows, membership pre-op member sets) must hit disk
  BEFORE the action fires, or a crash between the two strands state no
  recovery pass knows about.

Three diagnostics, all rule ``durability-protocol``:

1. *fsync-before-rename*: a function that writes a file and then
   ``os.replace``/``os.rename``s it must call ``os.fsync`` before the
   rename (line order; waivable where process-crash atomicity is all
   that's wanted and power loss is accepted).
2. *durable overwrite*: inside a class annotated ``# durability: ...``,
   a direct ``open(<self path>, "w"/"wb")`` outside ``__init__`` with
   no subsequent rename bypasses the atomic-replace helper — a crash
   mid-write leaves the durable artifact truncated. (``__init__`` is
   exempt: creating a fresh append-only file is the WAL protocol.)
3. *record-after-act*: in a function annotated ``# durability:
   record-before-act`` (or any method of a class so annotated) that
   performs act calls (``.invoke/.apply/.inject/.fire/.execute/
   .exec_``), a durable ``.record*``/``._record*`` call must appear on
   an earlier line than the first act. Late *re*-records after the act
   are fine — there must simply exist a record that precedes it.
"""
from __future__ import annotations

import ast

from jepsen_tpu.analysis.diagnostics import Finding
from jepsen_tpu.analysis.lint.astcache import ModuleInfo
from jepsen_tpu.analysis.lint.callgraph import body_calls

RULE = "durability-protocol"
CODE = "JTD001"

_INIT_METHODS = ("__init__", "__new__", "__post_init__")

# attribute-call names that fire the action a durable record protects
ACT_ATTRS = frozenset({"invoke", "apply", "inject", "fire", "execute",
                       "exec_"})

_WRITE_ATTRS = frozenset({"write", "writelines", "dump", "copyfileobj"})


def _attr_call(call: ast.Call) -> str | None:
    f = call.func
    return f.attr if isinstance(f, ast.Attribute) else None


def _is_os_call(call: ast.Call, mod: ModuleInfo, name: str) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == name \
            and isinstance(f.value, ast.Name):
        return mod.imports.get(f.value.id) == "os" or f.value.id == "os"
    return False


def _open_mode(call: ast.Call) -> str | None:
    """The literal mode of an ``open``/``io.open`` call, or None."""
    f = call.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else "")
    if name not in ("open", "fdopen"):
        return None
    mode = None
    if len(call.args) > 1:
        mode = call.args[1]
    for k in call.keywords:
        if k.arg == "mode":
            mode = k.value
    if mode is None:
        return "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _mentions_self_attr(expr, class_name) -> str | None:
    """First ``self.<attr>`` mentioned anywhere inside ``expr``."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name) \
                and n.value.id in ("self", "cls"):
            return n.attr
        if isinstance(n, ast.Name) and class_name is not None \
                and n.id == class_name:
            return n.id
    return None


def _fsync_before_rename(mod: ModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    for q, fi in mod.functions.items():
        if RULE in fi.ignores:
            continue
        calls = body_calls(fi.node)
        renames = [c for c in calls
                   if _is_os_call(c, mod, "replace")
                   or _is_os_call(c, mod, "rename")]
        if not renames:
            continue
        write_lines = [c.lineno for c in calls
                       if _attr_call(c) in _WRITE_ATTRS]
        wrote = bool(write_lines) \
            or any((_open_mode(c) or "r").strip("b").rstrip("+")
                   in ("w", "a", "x") for c in calls)
        if not wrote:
            continue  # a pure rename (store rotation) is not a publish
        fsyncs = [c.lineno for c in calls if _is_os_call(c, mod, "fsync")]
        for rn in renames:
            if RULE in mod.line_ignores(rn.lineno):
                continue
            # the fsync must land BETWEEN the last write preceding this
            # rename and the rename itself: an fsync that published an
            # EARLIER file must not vouch for a later unfsynced one
            # (a function can publish two documents; each needs its own
            # flush-to-disk before its rename)
            last_write = max((w for w in write_lines if w < rn.lineno),
                             default=0)
            if any(last_write <= ln <= rn.lineno for ln in fsyncs):
                continue
            out.append(Finding(
                rule=RULE, code=CODE, path=mod.relpath, line=rn.lineno,
                col=rn.col_offset + 1, qualname=q,
                message=("os.replace/rename publishes a freshly-written "
                         "file without fsync — a power cut can leave a "
                         "torn or empty document under the durable "
                         "name"),
                hint="flush + os.fsync(f.fileno()) before the rename "
                     "(utils.atomic_write_json is the house pattern), "
                     "or waive with # lint: ignore[durability-protocol] "
                     "where process-crash atomicity is all that's "
                     "needed"))
    return out


def _durable_overwrite(mod: ModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    for cq, ci in mod.classes.items():
        if not ci.durabilities:
            continue
        methods = {q: fi for q, fi in mod.functions.items()
                   if q.startswith(cq + ".")
                   and "." not in q[len(cq) + 1:]}
        for q, fi in methods.items():
            if fi.node.name in _INIT_METHODS or RULE in fi.ignores:
                continue
            calls = body_calls(fi.node)
            rename_lines = [c.lineno for c in calls
                            if _is_os_call(c, mod, "replace")
                            or _is_os_call(c, mod, "rename")]
            for c in calls:
                mode = _open_mode(c)
                if mode is None or mode.strip("b").rstrip("+") not in \
                        ("w", "x"):
                    continue
                if not c.args or _mentions_self_attr(
                        c.args[0], ci.name) is None:
                    continue  # a scratch path, not the durable artifact
                if any(rl >= c.lineno for rl in rename_lines):
                    # this open feeds a later rename: the atomic-replace
                    # path, which diagnostic 1 audits. A rename BEFORE
                    # the open vouches for nothing — a method that
                    # atomically publishes one artifact may still
                    # overwrite a second one in place.
                    continue
                if RULE in mod.line_ignores(c.lineno):
                    continue
                out.append(Finding(
                    rule=RULE, code=CODE, path=mod.relpath,
                    line=c.lineno, col=c.col_offset + 1, qualname=q,
                    message=(f"direct open(..., {mode!r}) overwrites a "
                             f"durable artifact of {ci.name} "
                             f"(# durability: "
                             f"{', '.join(sorted(ci.durabilities))}) "
                             "in place — a crash mid-write truncates "
                             "it"),
                    hint="write via utils.atomic_write_json / "
                         "tmp+fsync+os.replace, or append-only"))
    return out


def _record_before_act(mod: ModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    for q, fi in mod.functions.items():
        annotated = "record-before-act" in fi.durabilities
        if not annotated and fi.class_name is not None:
            for cq, ci in mod.classes.items():
                if ci.name == fi.class_name \
                        and q.startswith(cq + ".") \
                        and "record-before-act" in ci.durabilities:
                    annotated = True
                    break
        if not annotated or RULE in fi.ignores:
            continue
        calls = body_calls(fi.node)
        records = [c.lineno for c in calls
                   if (_attr_call(c) or "").lstrip("_")
                   .startswith("record")]
        acts = [c for c in calls if _attr_call(c) in ACT_ATTRS]
        if not acts:
            continue
        first_act = min(acts, key=lambda c: (c.lineno, c.col_offset))
        if any(ln < first_act.lineno for ln in records):
            continue
        if RULE in mod.line_ignores(first_act.lineno):
            continue
        what = "no durable record call at all" if not records else \
            "the record lands only after the action fired"
        out.append(Finding(
            rule=RULE, code=CODE, path=mod.relpath,
            line=first_act.lineno, col=first_act.col_offset + 1,
            qualname=q,
            message=(f"acts before durably recording ({what}) — a crash "
                     "between the action and its record strands state "
                     "no recovery pass knows about"),
            hint="record the injection/reconfiguration to the durable "
                 "registry BEFORE firing it (record-before-act)"))
    return out


def durability_protocol(mod: ModuleInfo) -> list[Finding]:
    return (_fsync_before_rename(mod) + _durable_overwrite(mod)
            + _record_before_act(mod))
