"""JTN rules: native-code (C/C++) invariants over the token layer.

The host ingest spine (``native/columnar_ext.c``, ``native/wgl.cpp``)
parses **network-delivered adversarial bytes** (the PR-16/19 fleet
receiver feeds it), so the invariants these rules encode are exactly
the remotely-triggerable memory-safety classes:

* ``jtn-alloc-check`` (JTN001) — an allocation result
  (``malloc``/``realloc``/``PyList_New``/…) dereferenced before any
  NULL check, and statement-position ``PyArg_Parse*`` calls whose
  failure return is discarded.
* ``jtn-cleanup-return`` (JTN002) — in a function using goto-cleanup
  discipline, a direct ``return NULL``/``return -1`` between the
  first ``goto`` and its cleanup label bypasses the release path
  (the classic error-path leak/refcount-imbalance shape).
* ``jtn-errcheck`` (JTN003) — ambiguous-failure conversions
  (``PyLong_AsLongLong`` returns -1 both for the value -1 and for an
  error) must be followed by ``PyErr_Occurred()`` — the checked
  ``fast_int``/``as_i64`` idiom in columnar_ext.c.
* ``jtn-gil-call`` (JTN004) — no CPython API call between
  ``Py_BEGIN_ALLOW_THREADS`` and ``Py_END_ALLOW_THREADS`` (the GIL is
  released there; touching an object is a race, not a bug report).
* ``jtn-bounds-guard`` (JTN005) — an array *write* indexed by a
  variable that is never compared against anything in the whole
  function: an index derived from ``consumed``/chunk length with no
  bound anywhere is an OOB write waiting for the right input.

These are token-level heuristics, not a verifier — flow-insensitive
by design, with the same waiver discipline as the Python rules
(``/* lint: ignore[rule] */`` + why-comment for provably-safe idioms).
doc/static-analysis.md "Native code" records the honest limits.
"""
from __future__ import annotations

from jepsen_tpu.analysis.diagnostics import Finding
from jepsen_tpu.analysis.lint.csrc import CFuncInfo, CModuleInfo, Tok

RULE_ALLOC = "jtn-alloc-check"
RULE_CLEANUP = "jtn-cleanup-return"
RULE_ERRCHECK = "jtn-errcheck"
RULE_GIL = "jtn-gil-call"
RULE_BOUNDS = "jtn-bounds-guard"

CODES = {RULE_ALLOC: "JTN001", RULE_CLEANUP: "JTN002",
         RULE_ERRCHECK: "JTN003", RULE_GIL: "JTN004",
         RULE_BOUNDS: "JTN005"}

# allocators whose NULL return the very next deref would crash on
ALLOC_FNS = frozenset({
    "malloc", "calloc", "realloc",
    "PyMem_Malloc", "PyMem_Calloc", "PyMem_Realloc", "PyMem_RawMalloc",
    "PyList_New", "PyDict_New", "PyTuple_New", "PyUnicode_New",
    "PyByteArray_FromStringAndSize", "PyBytes_FromStringAndSize",
})
# must-check-result calls: a discarded failure return silently
# proceeds with unconverted arguments
MUST_CHECK_CALLS = ("PyArg_ParseTuple", "PyArg_ParseTupleAndKeywords",
                    "PyArg_Parse", "PyArg_UnpackTuple")
# conversions where the error return collides with a legal value
FALLIBLE_CONVERSIONS = frozenset({
    "PyLong_AsLongLong", "PyLong_AsLong", "PyLong_AsSsize_t",
    "PyLong_AsUnsignedLongLong", "PyLong_AsSize_t",
    "PyFloat_AsDouble", "PyNumber_AsSsize_t",
    "PyDict_GetItemWithError",
})
# identifiers legal while the GIL is released
_GIL_SAFE = frozenset({
    "Py_BEGIN_ALLOW_THREADS", "Py_END_ALLOW_THREADS",
    "Py_BLOCK_THREADS", "Py_UNBLOCK_THREADS",
})


def _waived(mod: CModuleInfo, fi: CFuncInfo, rule: str, line: int) -> bool:
    # trailing waiver, or one on the line directly above: C statements
    # routinely fill the line, so the why-comment + waiver sit above
    return (rule in fi.ignores or rule in mod.line_ignores(line)
            or rule in mod.line_ignores(line - 1))


def _finding(rule: str, mod: CModuleInfo, fi: CFuncInfo, tok: Tok,
             message: str, hint: str | None = None) -> Finding:
    return Finding(rule=rule, code=CODES[rule], path=mod.relpath,
                   line=tok.line, col=tok.col, qualname=fi.qualname,
                   message=message, hint=hint)


def _match_paren(toks: list[Tok], open_idx: int) -> int:
    depth = 0
    for i in range(open_idx, len(toks)):
        t = toks[i].text
        if t == "(":
            depth += 1
        elif t == ")":
            depth -= 1
            if depth == 0:
                return i
    return len(toks) - 1


def _body(mod: CModuleInfo, fi: CFuncInfo) -> tuple[list[Tok], int, int]:
    return mod.tokens, fi.body_start + 1, fi.body_end


# -- JTN001: unchecked allocation --------------------------------------

def _is_null_token(t: Tok) -> bool:
    return t.text in ("NULL", "nullptr") or (t.kind == "num"
                                             and t.text == "0")


def _occurrence_is_check(toks: list[Tok], i: int) -> bool:
    """True when ``toks[i]`` (the alloc'd var) participates in a NULL
    check: ``!v``, ``v == NULL``, ``v != NULL``, or a bare truth test
    between boolean/paren delimiters."""
    prev = toks[i - 1].text if i > 0 else ""
    nxt = toks[i + 1].text if i + 1 < len(toks) else ""
    if prev == "!":
        return True
    if nxt in ("==", "!=") and i + 2 < len(toks) \
            and _is_null_token(toks[i + 2]):
        return True
    if prev in ("(", "&&", "||") and nxt in (")", "&&", "||", "?"):
        return True
    return False


def _occurrence_is_deref(toks: list[Tok], i: int) -> bool:
    prev = toks[i - 1].text if i > 0 else ""
    nxt = toks[i + 1].text if i + 1 < len(toks) else ""
    if nxt in ("[", "->", "."):
        return True
    if prev == "*":
        # `*v` deref vs `a * v` multiply: deref when the token before
        # the star is an operator/open-paren/assign/statement edge
        pp = toks[i - 2].text if i >= 2 else ";"
        if pp in (";", "{", "}", "(", ",", "=", "return", "+", "-",
                  "==", "!=", "&&", "||"):
            return True
    return False


def alloc_check(mod: CModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    toks = mod.tokens
    for fi in mod.functions.values():
        _, lo, hi = _body(mod, fi)
        i = lo
        while i < hi:
            t = toks[i]
            if t.kind != "id":
                i += 1
                continue
            # statement-position PyArg_* call: result discarded
            if t.text.startswith(MUST_CHECK_CALLS) \
                    and i + 1 < hi and toks[i + 1].text == "(":
                prev = toks[i - 1].text
                if prev in (";", "{", "}") \
                        and not _waived(mod, fi, RULE_ALLOC, t.line):
                    out.append(_finding(
                        RULE_ALLOC, mod, fi, t,
                        f"{t.text} return value discarded — a failed "
                        "parse leaves the output arguments garbage",
                        hint="wrap it: if (!PyArg_…(...)) return NULL;"))
                i = _match_paren(toks, i + 1) + 1
                continue
            if t.text not in ALLOC_FNS or i + 1 >= hi \
                    or toks[i + 1].text != "(":
                i += 1
                continue
            close = _match_paren(toks, i + 1)
            # assignment target: `v = alloc(...)` (possibly `type *v =`)
            if i < 2 or toks[i - 1].text != "=" \
                    or toks[i - 2].kind != "id":
                i = close + 1
                continue
            var = toks[i - 2].text
            # inside a condition (`if (!(v = malloc(...)))`) — the
            # check is the enclosing expression
            depth = 0
            guarded = False
            for k in range(lo, i - 2):
                if toks[k].text == "(":
                    depth += 1
                elif toks[k].text == ")":
                    depth -= 1
            if depth > 0:
                guarded = True
            if not guarded:
                # first later occurrence of var decides: check -> ok;
                # deref -> finding; anything else (passed on, returned,
                # reassigned) -> out of scope for this rule
                k = close + 1
                while k < hi:
                    if toks[k].kind == "id" and toks[k].text == var:
                        if _occurrence_is_check(toks, k):
                            guarded = True
                        elif _occurrence_is_deref(toks, k):
                            if not _waived(mod, fi, RULE_ALLOC, t.line):
                                out.append(_finding(
                                    RULE_ALLOC, mod, fi, t,
                                    f"{t.text}() result {var!r} is "
                                    "dereferenced (line "
                                    f"{toks[k].line}) before any NULL "
                                    "check",
                                    hint="check the allocation before "
                                         "touching it; on failure take "
                                         "the function's error path"))
                        break
                    k += 1
            i = close + 1
    return out


# -- JTN002: error return bypassing goto-cleanup -----------------------

# `return 0` is deliberately absent: it is the SUCCESS value for
# int-returning CPython protocols, so flagging it would bury the
# signal in noise
_ERROR_RETURNS = (("NULL",), ("nullptr",), ("-", "1"))


def _labels_and_gotos(toks: list[Tok], lo: int, hi: int):
    labels: dict[str, int] = {}
    gotos: list[tuple[str, int]] = []
    i = lo
    while i < hi:
        t = toks[i]
        if t.kind == "id":
            if t.text == "goto" and i + 1 < hi \
                    and toks[i + 1].kind == "id":
                gotos.append((toks[i + 1].text, i))
                i += 2
                continue
            if i + 1 < hi and toks[i + 1].text == ":" \
                    and t.text not in ("default", "case", "public",
                                       "private", "protected") \
                    and (i + 2 >= hi or toks[i + 2].text != ":"):
                prev = toks[i - 1].text if i > lo else "{"
                if prev in (";", "{", "}", ":"):
                    labels.setdefault(t.text, i)
        elif t.text == "case":
            # skip `case X:` so the colon isn't taken for a label
            while i < hi and toks[i].text != ":":
                i += 1
        elif t.text == "?":
            # skip ternary up to its ':' at the same paren depth
            depth = 0
            i += 1
            while i < hi:
                x = toks[i].text
                if x in ("(", "["):
                    depth += 1
                elif x in (")", "]"):
                    depth -= 1
                elif x == ":" and depth <= 0:
                    break
                elif x in (";", "{", "}"):
                    break
                i += 1
        i += 1
    return labels, gotos


def cleanup_return(mod: CModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    toks = mod.tokens
    for fi in mod.functions.values():
        _, lo, hi = _body(mod, fi)
        labels, gotos = _labels_and_gotos(toks, lo, hi)
        # cleanup labels: goto targets defined AFTER their first goto
        cleanup = [labels[n] for n, gi in
                   {n: gi for n, gi in reversed(gotos)}.items()
                   if n in labels and labels[n] > gi]
        if not cleanup:
            continue
        first_goto = min(gi for n, gi in gotos
                         if n in labels and labels[n] > gi)
        first_label = min(cleanup)
        i = first_goto
        while i < first_label:
            t = toks[i]
            if t.kind == "id" and t.text == "return":
                tail = tuple(x.text for x in toks[i + 1:i + 3])
                is_err = any(tail[:len(sig)] == sig
                             and toks[i + 1 + len(sig)].text == ";"
                             for sig in _ERROR_RETURNS
                             if i + 1 + len(sig) < hi)
                if is_err and not _waived(mod, fi, RULE_CLEANUP, t.line):
                    out.append(_finding(
                        RULE_CLEANUP, mod, fi, t,
                        "direct error return inside a goto-cleanup "
                        "region — it bypasses the cleanup label's "
                        "releases",
                        hint="route the error through the cleanup "
                             "label (goto …), or waive with a "
                             "why-comment if provably nothing is "
                             "owned here"))
            i += 1
    return out


# -- JTN003: PyErr_Occurred discipline ---------------------------------

_ERRCHECK_WINDOW = 64  # tokens of slack after the call


def errcheck(mod: CModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    toks = mod.tokens
    for fi in mod.functions.values():
        _, lo, hi = _body(mod, fi)
        i = lo
        while i < hi:
            t = toks[i]
            if t.kind != "id" or t.text not in FALLIBLE_CONVERSIONS \
                    or i + 1 >= hi or toks[i + 1].text != "(":
                i += 1
                continue
            close = _match_paren(toks, i + 1)
            window = toks[close:min(close + _ERRCHECK_WINDOW, hi)]
            # PyErr_Clear (tolerant-path discard) and PyErr_Fetch are
            # error-AWARE handling too, not just PyErr_Occurred
            checked = any(w.kind == "id" and w.text in
                          ("PyErr_Occurred", "PyErr_Clear",
                           "PyErr_Fetch", "fast_int", "as_i64")
                          for w in window)
            if not checked and not _waived(mod, fi, RULE_ERRCHECK,
                                           t.line):
                out.append(_finding(
                    RULE_ERRCHECK, mod, fi, t,
                    f"{t.text}() error return is ambiguous (-1/NULL "
                    "is also a legal value) and no PyErr_Occurred() "
                    "follows",
                    hint="check `== -1 && PyErr_Occurred()` (the "
                         "as_i64 idiom), or waive with a why-comment "
                         "when the input is provably in range"))
            i = close + 1
    return out


# -- JTN004: CPython API while the GIL is released ---------------------

def gil_call(mod: CModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    toks = mod.tokens
    for fi in mod.functions.values():
        _, lo, hi = _body(mod, fi)
        released = False
        for i in range(lo, hi):
            t = toks[i]
            if t.kind != "id":
                continue
            if t.text == "Py_BEGIN_ALLOW_THREADS":
                released = True
                continue
            if t.text in ("Py_END_ALLOW_THREADS", "Py_BLOCK_THREADS"):
                released = False
                continue
            if t.text == "Py_UNBLOCK_THREADS":
                released = True
                continue
            if not released:
                continue
            if (t.text.startswith(("Py", "_Py"))
                    and t.text not in _GIL_SAFE
                    and i + 1 < hi and toks[i + 1].text == "("
                    and not _waived(mod, fi, RULE_GIL, t.line)):
                out.append(_finding(
                    RULE_GIL, mod, fi, t,
                    f"{t.text}() called between "
                    "Py_BEGIN/END_ALLOW_THREADS — the GIL is released "
                    "here; touching CPython state is a data race",
                    hint="move the call outside the allow-threads "
                         "block, or re-acquire with Py_BLOCK_THREADS"))
    return out


# -- JTN005: unguarded variable-index array write ----------------------

def bounds_guard(mod: CModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    toks = mod.tokens
    for fi in mod.functions.values():
        _, lo, hi = _body(mod, fi)
        # an identifier counts as bounded when it participates in a
        # comparison anywhere in the function, OR is assigned through a
        # mask/modulo (`idx = hash & (cap - 1)` — the open-addressing
        # probe idiom IS the bounds guard)
        compared: set[str] = set()
        for i in range(lo, hi):
            if toks[i].text in ("<", ">", "<=", ">=", "==", "!="):
                for j in (i - 1, i + 1):
                    if lo <= j < hi and toks[j].kind == "id":
                        compared.add(toks[j].text)
            elif toks[i].text == "=" and i > lo \
                    and toks[i - 1].kind == "id":
                k = i + 1
                while k < hi and toks[k].text != ";":
                    if toks[k].text in ("&", "%", "&="):
                        compared.add(toks[i - 1].text)
                        break
                    k += 1
        i = lo
        while i < hi - 4:
            t = toks[i]
            # pattern: name [ idx ] =   /  name [ idx ++ ] =
            if t.kind == "id" and toks[i + 1].text == "[":
                j = i + 2
                idx = None
                if toks[j].kind == "id":
                    idx = toks[j]
                    j += 1
                    if j < hi and toks[j].text in ("++", "--"):
                        j += 1
                elif toks[j].text in ("++", "--") \
                        and toks[j + 1].kind == "id":
                    idx = toks[j + 1]
                    j += 2
                if idx is not None and j < hi \
                        and toks[j].text == "]" and j + 1 < hi \
                        and toks[j + 1].text == "=" \
                        and idx.text not in compared \
                        and not _waived(mod, fi, RULE_BOUNDS, t.line):
                    out.append(_finding(
                        RULE_BOUNDS, mod, fi, t,
                        f"write to {t.text}[{idx.text}…] but "
                        f"{idx.text!r} is never compared against any "
                        "bound in this function",
                        hint="guard the index against the buffer's "
                             "capacity before the write (or waive "
                             "with the invariant that bounds it)"))
            i += 1
    return out
