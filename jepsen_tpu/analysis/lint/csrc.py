"""Parsed-C-module cache for the native lint rules.

The token-level twin of ``astcache.py``: every JTN rule shares ONE
tokenization per ``.c``/``.cpp`` file — the token stream, the raw
source lines, the comment map, and a pre-built index of function
definitions found by brace matching. Cached by the same
``(mtime_ns, size, crc32)`` stamp, so the tier-1 self-lint gate and
repeated CLI runs never re-tokenize an unchanged file.

This is deliberately NOT a C parser. It is a lexer plus a
brace-matched function index, which is exactly enough for the JTN
rule families (unchecked allocs, cleanup-bypassing returns,
``PyErr_Occurred`` discipline, GIL-released CPython calls, unguarded
index writes) and nothing more — doc/static-analysis.md "Native code"
spells out the honest limits. Waivers mirror the Python side:

* ``/* lint: ignore[rule-a,rule-b] */`` (or the ``//`` form) trailing
  a line waives those rules on that line; on a function's signature
  or opening-brace line, for the whole function.
* ``/* lint: skip-file */`` anywhere skips the file.

Preprocessor directives (``#include``/``#define`` bodies, with
backslash continuations) are consumed wholesale and never tokenized
into the stream — a function-like macro body is invisible to the
rules, which is a documented limit, not a bug.
"""
from __future__ import annotations

import re
import zlib
from dataclasses import dataclass, field
from pathlib import Path

_IGNORE_RE = re.compile(r"lint:\s*ignore\[([^\]]+)\]")
_SKIP_FILE_RE = re.compile(r"lint:\s*skip-file\b")

C_SUFFIXES = (".c", ".cc", ".cpp", ".cxx", ".h", ".hpp")

_TOKEN_RE = re.compile(
    r"""
      (?P<comment>//[^\n]*|/\*.*?\*/)
    | (?P<str>"(?:\\.|[^"\\\n])*")
    | (?P<char>'(?:\\.|[^'\\\n])*')
    | (?P<num>0[xX][0-9a-fA-F]+[uUlL]*
        |\d+(?:\.\d*)?(?:[eE][+-]?\d+)?[uUlLfF]*
        |\.\d+(?:[eE][+-]?\d+)?[fF]?)
    | (?P<id>[A-Za-z_]\w*)
    | (?P<punct>->|\+\+|--|<<=|>>=|<<|>>|<=|>=|==|!=|&&|\|\||::
        |[-+*/%&|^!~<>=?:;,.(){}\[\]#\\])
    """,
    re.X | re.S)

# C/C++ keywords the function indexer must not mistake for a function
# name in front of a brace-delimited body
_BODY_KEYWORDS = frozenset({
    "if", "else", "for", "while", "do", "switch", "struct", "union",
    "enum", "class", "namespace", "try", "catch", "sizeof", "return",
})
_SCOPE_KEYWORDS = frozenset({"namespace", "class", "struct", "union",
                             "extern"})


@dataclass
class Tok:
    __slots__ = ("kind", "text", "line", "col")
    kind: str       # comment tokens are stripped before the stream
    text: str
    line: int
    col: int

    def __repr__(self):  # pragma: no cover - debug aid
        return f"Tok({self.kind},{self.text!r},{self.line})"


@dataclass
class CFuncInfo:
    name: str
    qualname: str
    lineno: int           # line of the opening brace's signature
    end_lineno: int
    body_start: int       # token index of '{'
    body_end: int         # token index of matching '}'
    ignores: frozenset = frozenset()


@dataclass
class CModuleInfo:
    path: Path
    relpath: str
    lines: list[str]
    tokens: list[Tok]
    comments: dict[int, str]      # lineno -> comment text on that line
    functions: dict[str, CFuncInfo] = field(default_factory=dict)
    skip: bool = False

    def line_ignores(self, lineno: int) -> frozenset:
        return _parse_ignores(self.comments.get(lineno, ""))


def _parse_ignores(comment: str) -> frozenset:
    m = _IGNORE_RE.search(comment or "")
    if not m:
        return frozenset()
    return frozenset(r.strip() for r in m.group(1).split(",") if r.strip())


def _tokenize(source: str) -> tuple[list[Tok], dict[int, str]]:
    toks: list[Tok] = []
    comments: dict[int, str] = {}
    line = 1
    line_start = 0
    pos = 0
    n = len(source)
    while pos < n:
        ch = source[pos]
        if ch == "\n":
            line += 1
            pos += 1
            line_start = pos
            continue
        if ch in " \t\r\f\v":
            pos += 1
            continue
        m = _TOKEN_RE.match(source, pos)
        if m is None:  # stray byte: skip it, stay tolerant
            pos += 1
            continue
        kind = m.lastgroup or "punct"
        text = m.group(0)
        col = pos - line_start + 1
        if kind == "comment":
            # map every line the comment touches (a trailing single-line
            # waiver and a boxed multi-line header both resolve)
            parts = text.split("\n")
            for i, part in enumerate(parts):
                comments[line + i] = (comments.get(line + i, "")
                                      + " " + part)
            if len(parts) > 1:
                # a boxed multi-line comment's marker must resolve from
                # the line the comment ENDS on (the one adjacent to the
                # waived statement/signature): carry the full text there
                end = line + len(parts) - 1
                comments[end] = comments[end] + " " + " ".join(parts[:-1])
        else:
            toks.append(Tok(kind, text, line, col))
        line += text.count("\n")
        if "\n" in text:
            line_start = m.end() - (len(text) - text.rfind("\n") - 1)
        pos = m.end()
    return toks, comments


def _strip_directives(toks: list[Tok]) -> list[Tok]:
    """Drops preprocessor logical lines (``#`` first-on-line through
    end of line, following backslash continuations)."""
    out: list[Tok] = []
    i = 0
    n = len(toks)
    prev_line = -1
    while i < n:
        t = toks[i]
        if t.text == "#" and t.line != prev_line:
            # consume the directive's logical line
            cur = t.line
            i += 1
            while i < n:
                nxt = toks[i]
                if nxt.line == cur:
                    if nxt.text == "\\":
                        cur += 1  # continuation: extend one line
                    i += 1
                    continue
                if nxt.line == cur + 1 and toks[i - 1].text == "\\":
                    cur = nxt.line
                    continue
                break
            prev_line = cur
            continue
        prev_line = t.line
        out.append(t)
        i += 1
    return out


def _match_brace(toks: list[Tok], open_idx: int) -> int:
    """Token index of the ``}`` matching ``toks[open_idx] == '{'``;
    len(toks)-1 when unbalanced (tolerant)."""
    depth = 0
    for i in range(open_idx, len(toks)):
        t = toks[i].text
        if t == "{":
            depth += 1
        elif t == "}":
            depth -= 1
            if depth == 0:
                return i
    return len(toks) - 1


def _func_ignores(mod: CModuleInfo, sig_line: int, brace_line: int,
                  name_line: int) -> frozenset:
    out: set = set()
    for ln in {sig_line, sig_line - 1, name_line, brace_line}:
        out |= mod.line_ignores(ln)
    return frozenset(out)


def _index_functions(mod: CModuleInfo) -> None:
    """Brace-matched function discovery: a top-level (or class/
    namespace-nested) ``name ( ... ) {`` is a function definition.
    Initializer braces (``= {...}``), control-flow bodies, and
    aggregate definitions are skipped or recursed as appropriate."""
    toks = mod.tokens

    def scan(lo: int, hi: int) -> None:
        i = lo
        while i < hi:
            if toks[i].text != "{":
                i += 1
                continue
            close = _match_brace(toks, i)
            # look back for `ident ( ... )` directly before the brace
            j = i - 1
            func_name = None
            if j >= lo and toks[j].text == ")":
                depth = 0
                k = j
                while k >= lo:
                    if toks[k].text == ")":
                        depth += 1
                    elif toks[k].text == "(":
                        depth -= 1
                        if depth == 0:
                            break
                    k -= 1
                if k > lo:
                    prev = toks[k - 1]
                    if (prev.kind == "id"
                            and prev.text not in _BODY_KEYWORDS):
                        func_name = prev
            if func_name is not None:
                name = func_name.text
                qual = name
                seq = 2
                while qual in mod.functions:  # overloads / statics
                    qual = f"{name}#{seq}"
                    seq += 1
                fi = CFuncInfo(
                    name=name, qualname=qual, lineno=func_name.line,
                    end_lineno=toks[close].line, body_start=i,
                    body_end=close,
                    ignores=_func_ignores(mod, func_name.line,
                                          toks[i].line, func_name.line))
                mod.functions[qual] = fi
                i = close + 1
                continue
            # aggregate/namespace scope: recurse so methods inside a
            # class/namespace body (wgl.cpp's FlatSet) are indexed
            scope = False
            k = j
            while k >= lo and k >= j - 4:
                if toks[k].kind == "id" and toks[k].text in _SCOPE_KEYWORDS:
                    scope = True
                    break
                if toks[k].kind == "str" and k >= 1 \
                        and toks[k - 1].text == "extern":
                    scope = True  # extern "C" { ... }
                    break
                if toks[k].text in ("=", ";", "}", "{", ")"):
                    break
                k -= 1
            if scope:
                scan(i + 1, close)
            i = close + 1

    scan(0, len(toks))


_CACHE: dict[str, tuple[tuple, CModuleInfo]] = {}


def parse_c_module(path, root=None) -> CModuleInfo | None:
    """Cached tokenize+index; None when the file can't be read. Same
    stamp discipline as ``astcache.parse_module``."""
    p = Path(path)
    try:
        st = p.stat()
        raw = p.read_bytes()
    except OSError:
        return None
    stamp = (st.st_mtime_ns, st.st_size, zlib.crc32(raw))
    key = str(p.resolve())
    hit = _CACHE.get(key)
    if hit is not None and hit[0] == stamp:
        return hit[1]
    source = raw.decode("utf-8", "replace")
    rel = str(p)
    if root is not None:
        try:
            rel = str(p.resolve().relative_to(Path(root).resolve()))
        except ValueError:
            rel = str(p)
    toks, comments = _tokenize(source)
    mod = CModuleInfo(path=p, relpath=rel, lines=source.splitlines(),
                      tokens=_strip_directives(toks), comments=comments)
    mod.skip = any(_SKIP_FILE_RE.search(c) for c in comments.values())
    _index_functions(mod)
    _CACHE[key] = (stamp, mod)
    return mod


def cache_info() -> dict:
    return {"modules": len(_CACHE)}
