"""telemetry-name rule (JTM001): metric/trace-name hygiene + doc drift.

Every ``Counter``/``Gauge``/``Histogram``/timer registration with a
literal name is collected package-wide and checked:

* **snake_case** — ``^[a-z][a-z0-9_]*$`` (Prometheus-safe, grep-safe).
* **suffix conventions** — counters end in ``_total``; histograms and
  timers end in a unit suffix (``_seconds``/``_ops``/``_bytes``/
  ``_steps``). Gauges are free-form (they carry ``_frac``/``_active``/
  unit suffixes by convention but legitimately vary).
* **kind-unique** — one name must map to one instrument kind across
  the whole package: re-registering ``x_total`` as a gauge elsewhere
  would raise at runtime only if both call sites execute in one
  process, i.e. exactly the silent-until-production class.
* **label consistency** — two literal ``labels=(...)`` tuples for the
  same name must agree (the registry raises on mismatch at runtime).
* **doc cross-check** — metric names cited in
  ``doc/observability.md`` (the ``name{labels}`` form, or bare
  ``*_total`` names) must exist in code: a silent rename strands the
  operators' dashboards. (Skipped when the doc isn't under the lint
  root — fixture trees.)

Causal-trace emissions (``jepsen_tpu.trace``,
doc/observability.md "Causal trace") are held to the same hygiene:
every ``span``/``begin``/``instant``/``complete``/``window_begin``/
``window_end`` call with literal track/name arguments must use
**kebab-case** for both — track and span names are the trace's metric
names (Perfetto queries, the web summary, and the offline
differential all key on them), so "Worker_0" vs "worker-0" drift is
exactly the dashboard-stranding class the metric checks exist for.
Dynamic names (f-strings — the per-worker tracks) are not literals
and are skipped.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from jepsen_tpu.analysis.diagnostics import Finding
from jepsen_tpu.analysis.lint.callgraph import CallGraph

RULE = "telemetry-name"
CODE = "JTM001"

_KINDS = {"counter": "counter", "gauge": "gauge",
          "histogram": "histogram", "timer": "histogram"}

_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")
_COUNTER_SUFFIX = ("_total",)
_HIST_SUFFIXES = ("_seconds", "_ops", "_bytes", "_steps")

# trace-emission methods whose first two args are (track, name) —
# both must be kebab-case when literal. `.end(track)` is excluded:
# it carries no span name, and every literal track a .end names is
# opened by one of these.
_TRACE_METHODS = ("span", "begin", "instant", "complete",
                  "window_begin", "window_end")
_KEBAB = re.compile(r"^[a-z][a-z0-9]*(-[a-z0-9]+)*$")

DOC_NAME = Path("doc") / "observability.md"
# `name{labels}` citations are unambiguous; bare names are only
# trusted as metric citations when they carry the _total suffix no
# knob/file name uses
_DOC_CITED = re.compile(r"`([a-z][a-z0-9_]*)\{[^}`\n]*\}`"
                        r"|`([a-z][a-z0-9_]*_total)`")


class _Reg:
    __slots__ = ("name", "kind", "labels", "path", "line", "col",
                 "qualname")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


def _literal_labels(call: ast.Call):
    """The ``labels=(...)`` tuple when it is a literal, else None."""
    for k in call.keywords:
        if k.arg != "labels":
            continue
        v = k.value
        if isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in v.elts):
            return tuple(e.value for e in v.elts)
    return None


def _enclosing_qualname(mod, lineno: int) -> str:
    best = "<module>"
    best_span = None
    for q, fi in mod.functions.items():
        if fi.lineno <= lineno <= fi.end_lineno:
            span = fi.end_lineno - fi.lineno
            if best_span is None or span < best_span:
                best, best_span = q, span
    return best


def _registrations(mod) -> list[_Reg]:
    out: list[_Reg] = []
    for n in ast.walk(mod.tree):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in _KINDS and n.args):
            continue
        arg = n.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            continue
        out.append(_Reg(name=arg.value, kind=_KINDS[n.func.attr],
                        labels=_literal_labels(n), path=mod.relpath,
                        line=n.lineno, col=n.col_offset + 1,
                        qualname=_enclosing_qualname(mod, n.lineno)))
    return out


def telemetry_name(graph: CallGraph) -> list[Finding]:
    out: list[Finding] = []
    regs: list[_Reg] = []
    for rel, mod in graph.modules.items():
        for r in _registrations(mod):
            fi = mod.functions.get(r.qualname)
            if fi is not None and RULE in fi.ignores:
                continue
            if RULE in mod.line_ignores(r.line):
                continue
            regs.append(r)

    def finding(r: _Reg, message: str, hint: str | None = None):
        out.append(Finding(rule=RULE, code=CODE, path=r.path,
                           line=r.line, col=r.col, qualname=r.qualname,
                           message=message, hint=hint))

    by_name: dict[str, list[_Reg]] = {}
    for r in regs:
        by_name.setdefault(r.name, []).append(r)
        if not _SNAKE.match(r.name):
            finding(r, f"metric name {r.name!r} is not snake_case",
                    "lowercase letters, digits, underscores only")
            continue
        if r.kind == "counter" and not r.name.endswith(_COUNTER_SUFFIX):
            finding(r, f"counter {r.name!r} must end in _total "
                       "(Prometheus counter convention)",
                    "rename to <thing>_total; update "
                    "doc/observability.md citations")
        if r.kind == "histogram" \
                and not r.name.endswith(_HIST_SUFFIXES):
            finding(r, f"histogram {r.name!r} lacks a unit suffix",
                    "append _seconds/_ops/_bytes/_steps so the unit is "
                    "in the name")

    for name, rs in sorted(by_name.items()):
        kinds = sorted({r.kind for r in rs})
        if len(kinds) > 1:
            r = rs[-1]
            finding(r, f"metric {name!r} registered as "
                       f"{' and '.join(kinds)} across the package — "
                       "the registry raises at runtime when both call "
                       "sites meet",
                    "one name, one instrument kind")
        label_sets = {r.labels for r in rs if r.labels is not None}
        if len(label_sets) > 1:
            r = rs[-1]
            pretty = " vs ".join(str(s) for s in sorted(label_sets))
            finding(r, f"metric {name!r} registered with conflicting "
                       f"label sets ({pretty})",
                    "label names are part of the series identity; "
                    "unify them")

    out.extend(_trace_names(graph))
    out.extend(_doc_drift(graph, set(by_name), by_name))
    return out


def _trace_names(graph: CallGraph) -> list[Finding]:
    """Kebab-case check over literal trace track/span names: a
    ``tracer.span("Bad_Track", "Do Stuff")`` drifts the track
    vocabulary every trace consumer keys on."""
    out: list[Finding] = []
    for rel, mod in graph.modules.items():
        for n in ast.walk(mod.tree):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _TRACE_METHODS
                    and len(n.args) >= 2):
                continue
            lits = []
            for role, arg in (("track", n.args[0]), ("span", n.args[1])):
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str):
                    lits.append((role, arg.value))
            if not lits:
                continue  # dynamic (f-string worker tracks): skipped
            if RULE in mod.line_ignores(n.lineno):
                continue
            qual = _enclosing_qualname(mod, n.lineno)
            fi = mod.functions.get(qual)
            if fi is not None and RULE in fi.ignores:
                continue
            for role, value in lits:
                if _KEBAB.match(value):
                    continue
                out.append(Finding(
                    rule=RULE, code=CODE, path=mod.relpath,
                    line=n.lineno, col=n.col_offset + 1, qualname=qual,
                    message=(f"trace {role} name {value!r} is not "
                             "kebab-case — track/span names are the "
                             "trace's query keys"),
                    hint="lowercase letters, digits, dashes only "
                         "(doc/observability.md \"Causal trace\")"))
    return out


def _doc_drift(graph: CallGraph, registered: set,
               by_name: dict | None = None) -> list[Finding]:
    if graph.root is None:
        return []
    doc = Path(graph.root) / DOC_NAME
    try:
        text = doc.read_text(encoding="utf-8")
    except OSError:
        return []
    out: list[Finding] = []
    seen: set = set()
    for i, line in enumerate(text.splitlines(), 1):
        for m in _DOC_CITED.finditer(line):
            name = m.group(1) or m.group(2)
            if name in registered or name in seen:
                continue
            seen.add(name)
            out.append(Finding(
                rule=RULE, code=CODE, path=str(DOC_NAME), line=i, col=1,
                qualname="<doc>",
                message=(f"doc/observability.md cites metric {name!r} "
                         "but nothing in the linted tree registers it "
                         "— a silent rename strands dashboards"),
                hint="update the doc (or restore the metric name)"))
    # reverse direction for the fleet vocabulary: every registered
    # fleet_* metric must be cited in the doc's "Fleet plane" section —
    # the fleet dashboard is operator-facing from day one, so an
    # undocumented series IS the drift (the forward check can't see it:
    # nothing cites it). Scoped to fleet_* to keep the rule additive
    # for the pre-fleet vocabulary; the HA series ride the same prefix
    # (fleet_lease_* for leased checking/fencing, fleet_ship_* for
    # shipper re-syncs, fleet_ingest_shed_total / fleet_degraded_total
    # for backpressure + degraded mode — doc/robustness.md "Fleet HA").
    for name, rs in sorted((by_name or {}).items()):
        if not name.startswith("fleet_"):
            continue
        if re.search(r"`" + re.escape(name) + r"[`{]", text):
            continue
        r = rs[0]
        out.append(Finding(
            rule=RULE, code=CODE, path=r.path, line=r.line, col=r.col,
            qualname=r.qualname,
            message=(f"fleet metric {name!r} is not cited in "
                     "doc/observability.md — the fleet dashboard "
                     "vocabulary must stay documented"),
            hint="cite it (backticked, with its labels) in the "
                 "\"Fleet plane\" section"))
    return out
