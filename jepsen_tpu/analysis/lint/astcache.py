"""Parsed-module cache for the linter.

Every rule shares ONE parse per file: the AST, the raw source lines,
the comment map (``ast`` drops comments, so they come from ``tokenize``),
and a pre-built index of function/class definitions with their
annotations. Cached by (path, mtime, size) so the self-lint tier-1 test
and repeated CLI runs in one process never re-parse an unchanged file —
the whole package lints in a few seconds, comfortably inside the tier-1
budget.

Annotations are structured comments the rules consume:

* ``# owner: scheduler|worker|any`` on (or directly above) a ``def`` —
  thread-ownership for the ``thread-owner`` / ``no-unbounded-block``
  rules.
* ``# durability: fsync`` on a ``class`` — every writing method must
  pair flush+fsync (``fsync-pairing``). ``# durability:
  record-before-act`` (on a ``class`` or ``def``) — durable record
  calls must precede the action they protect (``durability-protocol``).
  Comma-separated lists compose: ``# durability: fsync,
  record-before-act``.
* ``# blocking: rpc|io|...`` on a ``def`` — the function can block on
  a remote peer / slow I/O; the ``lock-order`` rule flags calls into it
  made while holding a lock.
* ``# thread-helper: spawn(arg=N)`` / ``# thread-helper:
  sync-spawn(arg=N)`` on a ``def`` — the function runs its Nth
  positional argument on (an)other thread(s); ``sync-spawn`` means the
  caller waits for them (``utils.real_pmap``), ``spawn`` means it does
  not have to (``utils.timeout``). The call graph turns call sites of
  such helpers into thread-spawn edges.
* ``# lint: ignore[rule-a,rule-b]`` trailing a line — waives those
  rules' findings on that line (on a ``def``/``class`` line: for the
  whole definition).
* ``# lint: skip-file`` anywhere — the file is not linted.
"""
from __future__ import annotations

import ast
import io
import re
import tokenize
import zlib
from dataclasses import dataclass, field
from pathlib import Path

_OWNER_RE = re.compile(r"#\s*owner:\s*(scheduler|worker|any)\b")
_DURABILITY_RE = re.compile(r"#\s*durability:\s*([\w-]+(?:\s*,\s*[\w-]+)*)")
_BLOCKING_RE = re.compile(r"#\s*blocking:\s*([\w-]+)")
_THREAD_HELPER_RE = re.compile(
    r"#\s*thread-helper:\s*(spawn|sync-spawn)\s*\(\s*arg\s*=\s*(\d+)\s*\)")
_IGNORE_RE = re.compile(r"#\s*lint:\s*ignore\[([^\]]+)\]")
_SKIP_FILE_RE = re.compile(r"#\s*lint:\s*skip-file\b")

OWNERS = ("scheduler", "worker", "any")


def _split_durabilities(raw: str | None) -> frozenset:
    if not raw:
        return frozenset()
    return frozenset(p.strip() for p in raw.split(",") if p.strip())


@dataclass
class FuncInfo:
    qualname: str
    node: ast.AST                  # FunctionDef | AsyncFunctionDef
    class_name: str | None         # innermost enclosing class, if any
    owner: str | None              # from "# owner:" annotation
    ignores: frozenset             # rules waived for the whole definition
    lineno: int
    end_lineno: int
    durabilities: frozenset = frozenset()  # "# durability:" on the def
    blocking: str | None = None    # from "# blocking:" annotation
    thread_helper: tuple | None = None  # ("spawn"|"sync-spawn", arg index)


@dataclass
class ClassInfo:
    name: str
    qualname: str
    node: ast.ClassDef
    durability: str | None
    ignores: frozenset
    bases: tuple                   # base-class name strings
    durabilities: frozenset = frozenset()


@dataclass
class ModuleInfo:
    path: Path
    relpath: str
    tree: ast.Module
    lines: list[str]               # raw source lines, 1-indexed via [i-1]
    comments: dict[int, str]       # lineno -> full comment text
    functions: dict[str, FuncInfo] = field(default_factory=dict)
    classes: dict[str, ClassInfo] = field(default_factory=dict)
    imports: dict[str, str] = field(default_factory=dict)  # alias -> module
    import_names: dict[str, tuple] = field(default_factory=dict)
    # import_names: local name -> (module, original_name) for from-imports
    skip: bool = False

    def line_ignores(self, lineno: int) -> frozenset:
        """Rules waived by a trailing ``# lint: ignore[...]`` comment."""
        return _parse_ignores(self.comments.get(lineno, ""))

    def def_annotation_match(self, node, regex):
        """First regex match in the comment trailing the def/class line,
        any decorator line, or the line directly above."""
        candidates = [node.lineno]
        for dec in getattr(node, "decorator_list", []):
            candidates.append(dec.lineno)
        first = min(candidates)
        candidates.append(first - 1)
        for ln in candidates:
            m = regex.search(self.comments.get(ln, ""))
            if m:
                return m
        return None

    def def_annotation(self, node, regex):
        m = self.def_annotation_match(node, regex)
        return m.group(1) if m else None

    def def_ignores(self, node) -> frozenset:
        out: set = set()
        for ln in [node.lineno, node.lineno - 1]:
            out |= _parse_ignores(self.comments.get(ln, ""))
        return frozenset(out)


def _parse_ignores(comment: str) -> frozenset:
    m = _IGNORE_RE.search(comment or "")
    if not m:
        return frozenset()
    return frozenset(r.strip() for r in m.group(1).split(",") if r.strip())


def _comment_map(source: str) -> dict[int, str]:
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass  # a file that parses but won't tokenize cleanly: no comments
    return out


def _index(mod: ModuleInfo) -> None:
    """Fills functions/classes/imports by one walk with qualname scopes."""

    def visit(node, scope: str, class_name: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{scope}.{child.name}" if scope else child.name
                owner = mod.def_annotation(child, _OWNER_RE)
                helper = None
                hm = mod.def_annotation_match(child, _THREAD_HELPER_RE)
                if hm is not None:
                    helper = (hm.group(1), int(hm.group(2)))
                mod.functions[q] = FuncInfo(
                    qualname=q, node=child, class_name=class_name,
                    owner=owner, ignores=mod.def_ignores(child),
                    lineno=child.lineno,
                    end_lineno=getattr(child, "end_lineno", child.lineno),
                    durabilities=_split_durabilities(
                        mod.def_annotation(child, _DURABILITY_RE)),
                    blocking=mod.def_annotation(child, _BLOCKING_RE),
                    thread_helper=helper)
                visit(child, q, class_name)
            elif isinstance(child, ast.ClassDef):
                q = f"{scope}.{child.name}" if scope else child.name
                bases = tuple(_base_name(b) for b in child.bases)
                durability = mod.def_annotation(child, _DURABILITY_RE)
                mod.classes[q] = ClassInfo(
                    name=child.name, qualname=q, node=child,
                    durability=durability,
                    ignores=mod.def_ignores(child), bases=bases,
                    durabilities=_split_durabilities(durability))
                visit(child, q, child.name)
            elif isinstance(child, ast.Import):
                for alias in child.names:
                    mod.imports[alias.asname or alias.name.split(".")[0]] \
                        = alias.name
            elif isinstance(child, ast.ImportFrom):
                if child.module and child.level == 0:
                    for alias in child.names:
                        mod.import_names[alias.asname or alias.name] = (
                            child.module, alias.name)
            else:
                visit(child, scope, class_name)

    visit(mod.tree, "", None)


def _base_name(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


_CACHE: dict[str, tuple[tuple, ModuleInfo]] = {}


def parse_module(path, root=None) -> ModuleInfo | None:
    """Cached parse; None when the file doesn't parse (a syntax error is
    a job for the test suite, not the linter).

    The cache key is ``(mtime_ns, size, crc32(content))``: an editor or
    test harness that rewrites a file with same-size content inside one
    filesystem timestamp tick (coarse mtime granularity) must still
    invalidate — the crc costs one cheap read per call, while the
    expensive parse + tokenize + index is what the cache skips."""
    p = Path(path)
    try:
        st = p.stat()
        raw = p.read_bytes()
    except OSError:
        return None
    stamp = (st.st_mtime_ns, st.st_size, zlib.crc32(raw))
    key = str(p.resolve())
    hit = _CACHE.get(key)
    if hit is not None and hit[0] == stamp:
        return hit[1]
    try:
        source = raw.decode("utf-8")
        tree = ast.parse(source)
    except (SyntaxError, ValueError):
        return None
    rel = str(p)
    if root is not None:
        try:
            rel = str(p.resolve().relative_to(Path(root).resolve()))
        except ValueError:
            rel = str(p)
    mod = ModuleInfo(path=p, relpath=rel, tree=tree,
                     lines=source.splitlines(),
                     comments=_comment_map(source))
    mod.skip = any(_SKIP_FILE_RE.search(c) for c in mod.comments.values())
    _index(mod)
    _CACHE[key] = (stamp, mod)
    return mod


def cache_info() -> dict:
    return {"modules": len(_CACHE)}
