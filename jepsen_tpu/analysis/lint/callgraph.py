"""Intra-package call graph with thread-spawn edges.

Name-based static resolution — deliberately conservative and cheap:

* ``foo(...)`` resolves through the lexical scope chain (sibling nested
  defs, module-level functions), then ``from x import foo``.
* ``self.m(...)`` resolves to the method in the caller's class, then its
  named base classes (same module or from-imported).
* ``mod.f(...)`` resolves through ``import``/``from pkg import mod``
  aliases to the target module's top-level ``f``.

Anything else (calls on locals, protocol dispatch) is *unresolved* and
simply absent from the graph: an edge we cannot prove is an edge we do
not traverse, so reachability sets stay small and findings stay precise.

Thread-spawn edges (closing PR-5's documented "thread targets are not
edges" limit): a function *reference* handed to a thread-creation idiom
becomes an edge tagged with how the target will run —

* ``kind="spawn"`` — the target runs on another thread and the caller
  does not (have to) wait for it: ``threading.Thread(target=f)``,
  ``threading.Timer(t, f)``, ``executor.submit(f, ...)``, and helpers
  annotated ``# thread-helper: spawn(arg=N)`` (``utils.timeout``).
* ``kind="sync-spawn"`` — the target runs on other thread(s) but the
  caller blocks until they finish, so a wedge in the target IS a wedge
  in the caller: helpers annotated ``# thread-helper: sync-spawn(arg=N)``
  (``utils.real_pmap``, ``utils.bounded_pmap``).

Both kinds carry an **owner transition**: a spawn target without an
explicit ``# owner:`` annotation is implicitly worker-owned — it runs
on a fresh thread, never the scheduler's (``effective_owner``). Rules
choose which kinds to traverse: ``thread-owner`` follows everything
(any spawned thread is still not the scheduler), ``no-unbounded-block``
follows plain calls and ``sync-spawn`` (a detached thread's block can't
wedge the spawner), and the lock-order analysis follows calls and
``sync-spawn`` but never ``spawn`` (a new thread does not inherit the
spawner's held locks).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

from jepsen_tpu.analysis.lint.astcache import FuncInfo, ModuleInfo

Node = tuple  # (relpath, qualname)

CALL = "call"
SPAWN = "spawn"
SYNC_SPAWN = "sync-spawn"

# Thread-class constructors: class name -> how the target argument is
# passed ((keyword name, positional index) — Timer's is its second
# positional, Thread's is keyword-only in practice).
_THREAD_CTORS = {
    "Thread": ("target", None),
    "Timer": ("function", 1),
}


@dataclass
class CallGraph:
    edges: dict            # Node -> list[(Node, lineno, kind)]
    functions: dict        # Node -> FuncInfo
    modules: dict          # relpath -> ModuleInfo
    spawn_targets: dict = field(default_factory=dict)  # Node -> kind
    root: object = None    # lint root (Path) — doc cross-checks live here

    def owner(self, node: Node) -> str | None:
        fi = self.functions.get(node)
        return fi.owner if fi is not None else None

    def effective_owner(self, node: Node) -> str | None:
        """The explicit ``# owner:`` annotation, else the spawn-implied
        owner: a thread-spawn target runs on a fresh thread, so absent
        an annotation it is worker-owned — the owner transition that
        lets reachability rules see through thread creation."""
        owner = self.owner(node)
        if owner is not None:
            return owner
        if node in self.spawn_targets:
            return "worker"
        return None

    def reachable(self, roots, through=None, kinds=None):
        """BFS closure from ``roots``; ``through(node) -> bool`` gates
        which nodes are expanded (the node itself is still visited);
        ``kinds`` restricts which edge kinds are traversed (default:
        all). Returns {node: (parent, lineno)} for path
        reconstruction."""
        seen: dict = {}
        frontier = [(r, None, 0) for r in roots]
        while frontier:
            node, parent, lineno = frontier.pop()
            if node in seen:
                continue
            seen[node] = (parent, lineno)
            if through is not None and not through(node) and parent is not None:
                continue
            for callee, ln, kind in self.edges.get(node, ()):
                if kinds is not None and kind not in kinds:
                    continue
                if callee not in seen:
                    frontier.append((callee, node, ln))
        return seen

    def path_to(self, seen: dict, node: Node) -> list[Node]:
        out = [node]
        while True:
            parent = seen.get(node, (None, 0))[0]
            if parent is None:
                break
            out.append(parent)
            node = parent
        return list(reversed(out))


def body_calls(func_node: ast.AST):
    """Call nodes lexically inside ``func_node``, excluding nested
    def/class bodies (those are their own graph nodes)."""
    out: list[ast.Call] = []
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        if isinstance(n, ast.Call):
            out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def module_dotted(relpath: str) -> str:
    name = relpath[:-3] if relpath.endswith(".py") else relpath
    name = name.replace("\\", "/").replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


def _spawn_arg(call: ast.Call, kw: str | None, pos: int | None):
    """The target-function expression of a spawn call, or None."""
    if kw is not None:
        for k in call.keywords:
            if k.arg == kw:
                return k.value
    if pos is not None and len(call.args) > pos:
        return call.args[pos]
    return None


def build(modules: list[ModuleInfo], root=None) -> CallGraph:
    by_rel = {m.relpath: m for m in modules}
    by_dotted = {module_dotted(m.relpath): m for m in modules}
    functions: dict = {}
    for m in modules:
        for q, fi in m.functions.items():
            functions[(m.relpath, q)] = fi

    def mod_func(dotted: str, name: str):
        target = by_dotted.get(dotted)
        if target is None:
            return None
        if name in target.functions:
            return (target.relpath, name)
        return None

    def resolve_class(mod: ModuleInfo, cname: str):
        """ClassInfo for a simple class name, same module first, then
        a from-import."""
        for q, ci in mod.classes.items():
            if ci.name == cname:
                return mod, ci
        imp = mod.import_names.get(cname)
        if imp is not None:
            target = by_dotted.get(imp[0])
            if target is not None:
                for q, ci in target.classes.items():
                    if ci.name == imp[1]:
                        return target, ci
        return None, None

    def resolve_method(mod: ModuleInfo, fi: FuncInfo, attr: str):
        """self.<attr>() — caller's class, then named bases (one hop)."""
        cls_q = fi.qualname.rsplit(".", 1)[0] if "." in fi.qualname else ""
        # walk out to the innermost enclosing class qualname
        parts = fi.qualname.split(".")
        for i in range(len(parts) - 1, 0, -1):
            cq = ".".join(parts[:i])
            if cq in mod.classes:
                cls_q = cq
                break
        else:
            return None
        cand = f"{cls_q}.{attr}"
        if cand in mod.functions:
            return (mod.relpath, cand)
        for base in mod.classes[cls_q].bases:
            if not base:
                continue
            bmod, bci = resolve_class(mod, base)
            if bci is None:
                continue
            bq = f"{bci.qualname}.{attr}"
            if bq in bmod.functions:
                return (bmod.relpath, bq)
        return None

    def resolve_name(mod: ModuleInfo, fi: FuncInfo, name: str):
        parts = fi.qualname.split(".")
        # lexical scope chain: own nested defs, then each enclosing level
        for i in range(len(parts), -1, -1):
            cand = ".".join(parts[:i] + [name]) if i else name
            if cand in mod.functions:
                return (mod.relpath, cand)
        imp = mod.import_names.get(name)
        if imp is not None:
            return mod_func(imp[0], imp[1]) or mod_func(
                f"{imp[0]}.{imp[1]}", name)
        return None

    def resolve_callable(m: ModuleInfo, fi: FuncInfo, f):
        """A call's func expression -> Node, shared by plain calls and
        spawn-target references."""
        if isinstance(f, ast.Name):
            return resolve_name(m, fi, f.id)
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            recv = f.value.id
            if recv in ("self", "cls"):
                return resolve_method(m, fi, f.attr)
            imp = m.imports.get(recv)
            if imp is not None:
                return mod_func(imp, f.attr)
            nm = m.import_names.get(recv)
            if nm is not None:
                return mod_func(f"{nm[0]}.{nm[1]}", f.attr)
        return None

    def resolve_ref(m: ModuleInfo, fi: FuncInfo, expr):
        """A function REFERENCE (spawn target) -> Node. Unwraps
        ``functools.partial(f, ...)``."""
        if isinstance(expr, ast.Call):
            f = expr.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if name == "partial" and expr.args:
                return resolve_ref(m, fi, expr.args[0])
            return None
        if isinstance(expr, (ast.Name, ast.Attribute)):
            return resolve_callable(m, fi, expr)
        return None

    def thread_ctor(m: ModuleInfo, f) -> str | None:
        """'Thread'/'Timer' when the call constructs a threading class."""
        if isinstance(f, ast.Attribute) and f.attr in _THREAD_CTORS \
                and isinstance(f.value, ast.Name):
            if m.imports.get(f.value.id) == "threading":
                return f.attr
        if isinstance(f, ast.Name):
            imp = m.import_names.get(f.id)
            if imp is not None and imp[0] == "threading" \
                    and imp[1] in _THREAD_CTORS:
                return imp[1]
        return None

    edges: dict = {}
    spawn_targets: dict = {}

    def note_spawn(out, m, fi, expr, lineno, kind):
        target = resolve_ref(m, fi, expr)
        if target is None:
            return
        out.append((target, lineno, kind))
        # "spawn" (detached) dominates for the owner transition; either
        # way the target runs off the spawner's thread
        if spawn_targets.get(target) != SPAWN:
            spawn_targets[target] = kind

    for m in modules:
        for q, fi in m.functions.items():
            node = (m.relpath, q)
            out: list = []
            for call in body_calls(fi.node):
                f = call.func
                target = resolve_callable(m, fi, f)
                if target is not None and target != node:
                    out.append((target, call.lineno, CALL))
                # thread-spawn idioms ------------------------------------
                ctor = thread_ctor(m, f)
                if ctor is not None:
                    kw, pos = _THREAD_CTORS[ctor]
                    expr = _spawn_arg(call, kw, pos)
                    if expr is not None:
                        note_spawn(out, m, fi, expr, call.lineno, SPAWN)
                elif isinstance(f, ast.Attribute) and f.attr == "submit" \
                        and call.args:
                    # executor.submit(fn, ...) — and the repo's
                    # DispatchPipeline.submit(prep_fn, dispatch_fn):
                    # every positional callable runs on another thread
                    for a in call.args:
                        note_spawn(out, m, fi, a, call.lineno, SPAWN)
                elif target is not None:
                    helper = functions.get(target)
                    spec = helper.thread_helper if helper is not None \
                        else None
                    if spec is not None:
                        kind, idx = spec
                        if len(call.args) > idx:
                            note_spawn(out, m, fi, call.args[idx],
                                       call.lineno,
                                       SYNC_SPAWN if kind == SYNC_SPAWN
                                       else SPAWN)
            if out:
                edges[node] = out
    return CallGraph(edges=edges, functions=functions, modules=by_rel,
                     spawn_targets=spawn_targets, root=root)
