"""Lightweight intra-package call graph for reachability rules.

Name-based static resolution — deliberately conservative and cheap:

* ``foo(...)`` resolves through the lexical scope chain (sibling nested
  defs, module-level functions), then ``from x import foo``.
* ``self.m(...)`` resolves to the method in the caller's class, then its
  named base classes (same module or from-imported).
* ``mod.f(...)`` resolves through ``import``/``from pkg import mod``
  aliases to the target module's top-level ``f``.

Anything else (calls on locals, protocol dispatch, higher-order
``target=fn`` references) is *unresolved* and simply absent from the
graph. That is the right default for the thread-owner and
no-unbounded-block rules: an edge we cannot prove is an edge we do not
traverse, so reachability sets stay small and findings stay precise.
A function *reference* (``Thread(target=run)``) is intentionally not an
edge — spawning a thread is exactly where ownership changes hands.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass

from jepsen_tpu.analysis.lint.astcache import FuncInfo, ModuleInfo

Node = tuple  # (relpath, qualname)


@dataclass
class CallGraph:
    edges: dict            # Node -> list[(Node, lineno)]
    functions: dict        # Node -> FuncInfo
    modules: dict          # relpath -> ModuleInfo

    def owner(self, node: Node) -> str | None:
        fi = self.functions.get(node)
        return fi.owner if fi is not None else None

    def reachable(self, roots, through=None):
        """BFS closure from ``roots``; ``through(node) -> bool`` gates
        which nodes are expanded (the node itself is still visited).
        Returns {node: (parent, lineno)} for path reconstruction."""
        seen: dict = {}
        frontier = [(r, None, 0) for r in roots]
        while frontier:
            node, parent, lineno = frontier.pop()
            if node in seen:
                continue
            seen[node] = (parent, lineno)
            if through is not None and not through(node) and parent is not None:
                continue
            for callee, ln in self.edges.get(node, ()):
                if callee not in seen:
                    frontier.append((callee, node, ln))
        return seen

    def path_to(self, seen: dict, node: Node) -> list[Node]:
        out = [node]
        while True:
            parent = seen.get(node, (None, 0))[0]
            if parent is None:
                break
            out.append(parent)
            node = parent
        return list(reversed(out))


def body_calls(func_node: ast.AST):
    """Call nodes lexically inside ``func_node``, excluding nested
    def/class bodies (those are their own graph nodes)."""
    out: list[ast.Call] = []
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        if isinstance(n, ast.Call):
            out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def module_dotted(relpath: str) -> str:
    name = relpath[:-3] if relpath.endswith(".py") else relpath
    name = name.replace("\\", "/").replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


def build(modules: list[ModuleInfo]) -> CallGraph:
    by_rel = {m.relpath: m for m in modules}
    by_dotted = {module_dotted(m.relpath): m for m in modules}
    functions: dict = {}
    for m in modules:
        for q, fi in m.functions.items():
            functions[(m.relpath, q)] = fi

    def mod_func(dotted: str, name: str):
        target = by_dotted.get(dotted)
        if target is None:
            return None
        if name in target.functions:
            return (target.relpath, name)
        return None

    def resolve_class(mod: ModuleInfo, cname: str):
        """ClassInfo for a simple class name, same module first, then
        a from-import."""
        for q, ci in mod.classes.items():
            if ci.name == cname:
                return mod, ci
        imp = mod.import_names.get(cname)
        if imp is not None:
            target = by_dotted.get(imp[0])
            if target is not None:
                for q, ci in target.classes.items():
                    if ci.name == imp[1]:
                        return target, ci
        return None, None

    def resolve_method(mod: ModuleInfo, fi: FuncInfo, attr: str):
        """self.<attr>() — caller's class, then named bases (one hop)."""
        cls_q = fi.qualname.rsplit(".", 1)[0] if "." in fi.qualname else ""
        # walk out to the innermost enclosing class qualname
        parts = fi.qualname.split(".")
        for i in range(len(parts) - 1, 0, -1):
            cq = ".".join(parts[:i])
            if cq in mod.classes:
                cls_q = cq
                break
        else:
            return None
        cand = f"{cls_q}.{attr}"
        if cand in mod.functions:
            return (mod.relpath, cand)
        for base in mod.classes[cls_q].bases:
            if not base:
                continue
            bmod, bci = resolve_class(mod, base)
            if bci is None:
                continue
            bq = f"{bci.qualname}.{attr}"
            if bq in bmod.functions:
                return (bmod.relpath, bq)
        return None

    def resolve_name(mod: ModuleInfo, fi: FuncInfo, name: str):
        parts = fi.qualname.split(".")
        # lexical scope chain: own nested defs, then each enclosing level
        for i in range(len(parts), -1, -1):
            cand = ".".join(parts[:i] + [name]) if i else name
            if cand in mod.functions:
                return (mod.relpath, cand)
        imp = mod.import_names.get(name)
        if imp is not None:
            return mod_func(imp[0], imp[1]) or mod_func(
                f"{imp[0]}.{imp[1]}", name)
        return None

    edges: dict = {}
    for m in modules:
        for q, fi in m.functions.items():
            node = (m.relpath, q)
            out: list = []
            for call in body_calls(fi.node):
                f = call.func
                target = None
                if isinstance(f, ast.Name):
                    target = resolve_name(m, fi, f.id)
                elif isinstance(f, ast.Attribute) and isinstance(
                        f.value, ast.Name):
                    recv = f.value.id
                    if recv in ("self", "cls"):
                        target = resolve_method(m, fi, f.attr)
                    else:
                        imp = m.imports.get(recv)
                        if imp is not None:
                            target = mod_func(imp, f.attr)
                        else:
                            nm = m.import_names.get(recv)
                            if nm is not None:
                                target = mod_func(
                                    f"{nm[0]}.{nm[1]}", f.attr)
                if target is not None and target != node:
                    out.append((target, call.lineno))
            if out:
                edges[node] = out
    return CallGraph(edges=edges, functions=functions, modules=by_rel)
