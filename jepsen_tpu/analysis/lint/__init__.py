"""The invariant linter: driver, baseline, and rendering.

``lint_paths([...])`` walks the given files/directories, parses each
module once (shared AST cache — the whole package lints in seconds),
runs every rule, and splits the results against the checked-in baseline
file. The baseline (``lint-baseline.txt`` next to the linted package)
holds deliberate waivers keyed by ``path::qualname::rule`` — no line
numbers, so entries survive unrelated edits. The tier-1 self-lint test
fails on any non-baselined finding, which turns every future regression
of these invariant classes into a red build instead of a review catch.

CLI: ``jepsen-tpu lint [paths...] [--format=json] [--baseline FILE]
[--update-baseline]``.
"""
from __future__ import annotations

import fnmatch
import logging
from dataclasses import dataclass, field
from pathlib import Path

from jepsen_tpu.analysis.diagnostics import (
    Finding, render_json, sort_findings,
)
from jepsen_tpu.analysis.lint import (
    astcache, callgraph, csrc, rules_concurrency, rules_durability,
    rules_jax, rules_native, rules_telemetry,
)

logger = logging.getLogger("jepsen.analysis.lint")

BASELINE_NAME = "lint-baseline.txt"

# (rule name, per-module fn | None, global fn | None)
RULES = (
    ("lock-guard", rules_concurrency.lock_guard, None),
    ("fsync-pairing", rules_concurrency.fsync_pairing, None),
    ("durability-protocol", rules_durability.durability_protocol, None),
    ("no-host-effects-in-jit", rules_jax.no_host_effects_in_jit, None),
    ("donation-reuse", rules_jax.donation_reuse, None),
    ("recompile-hazard", rules_jax.recompile_hazard, None),
    ("no-host-roundtrip", rules_jax.no_host_roundtrip, None),
    ("threshold-dtype", rules_jax.threshold_dtype, None),
    ("thread-owner", None, rules_concurrency.thread_owner),
    ("no-unbounded-block", None, rules_concurrency.no_unbounded_block),
    ("lock-order", None, rules_concurrency.lock_order),
    ("cond-wait", None, rules_concurrency.cond_wait),
    ("telemetry-name", None, rules_telemetry.telemetry_name),
)

# per-C-module rules (name, fn over a csrc.CModuleInfo) — the JTN
# family; they ride the same baseline/waiver/--rule machinery, just
# over the token layer instead of the AST
C_RULES = (
    ("jtn-alloc-check", rules_native.alloc_check),
    ("jtn-cleanup-return", rules_native.cleanup_return),
    ("jtn-errcheck", rules_native.errcheck),
    ("jtn-gil-call", rules_native.gil_call),
    ("jtn-bounds-guard", rules_native.bounds_guard),
)

RULE_NAMES = tuple(r[0] for r in RULES) + tuple(r[0] for r in C_RULES)


@dataclass
class Report:
    findings: list = field(default_factory=list)   # actionable (not baselined)
    baselined: list = field(default_factory=list)  # matched a waiver
    stale_waivers: list = field(default_factory=list)  # baseline keys unmatched
    files: int = 0

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0


_C_SUFFIXES = (".c", ".cc", ".cpp", ".cxx")


def _collect_files(paths) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files = [f for f in p.rglob("*")
                     if f.suffix in (".py",) + _C_SUFFIXES
                     and "__pycache__" not in f.parts]
            out.extend(sorted(files))
        elif p.suffix in (".py",) + _C_SUFFIXES:
            out.append(p)
    return out


def resolve_rules(rules) -> set | None:
    """Expands ``--rule`` names (globs allowed: ``jtn-*``) against
    RULE_NAMES; raises on anything that matches nothing — a typo'd
    --rule must not produce a green "0 findings" run."""
    if not rules:
        return None
    out: set = set()
    for r in rules:
        hits = fnmatch.filter(RULE_NAMES, r)
        if not hits:
            raise ValueError(f"unknown lint rule(s) [{r!r}]; "
                             f"known: {', '.join(RULE_NAMES)}")
        out.update(hits)
    return out


def _guess_root(paths) -> Path:
    """The directory findings are reported relative to (and where the
    default baseline lives): the parent of the first linted package."""
    first = Path(paths[0]).resolve() if paths else Path(".").resolve()
    return first.parent if first.is_dir() else first.parent.parent


def load_baseline(path) -> dict[str, str]:
    """key -> raw line; tolerant of comments/blanks."""
    out: dict[str, str] = {}
    try:
        for line in Path(path).read_text(encoding="utf-8").splitlines():
            stripped = line.split("#", 1)[0].strip()
            if stripped:
                out[stripped] = line
    except OSError:
        pass
    return out


def write_baseline(path, findings) -> None:
    """Regenerates the baseline from ``findings``. An entry that already
    exists keeps its original line verbatim — the per-key WHY comment
    the header mandates must survive a regeneration, not be flattened
    to a bare key."""
    existing = load_baseline(path)
    keys = sorted({f.key() for f in findings})
    header = ("# jepsen-tpu lint baseline — deliberate waivers, one\n"
              "# `path::qualname::rule` key per line (no line numbers:\n"
              "# entries survive unrelated edits). Keep this near-empty;\n"
              "# every entry needs a comment saying WHY the invariant\n"
              "# doesn't apply. Regenerate: jepsen-tpu lint --update-baseline\n")
    body = "".join((existing.get(k, k)).rstrip("\n") + "\n" for k in keys)
    Path(path).write_text(header + body, encoding="utf-8")


def lint_paths(paths, baseline=None, root=None, rules=None) -> Report:
    """Lints files/directories. ``baseline`` defaults to
    ``<root>/lint-baseline.txt``; pass ``baseline=False`` to skip.
    ``rules`` optionally restricts to a subset of rule names."""
    paths = list(paths) or ["jepsen_tpu"]
    resolved = resolve_rules(rules)
    root = Path(root) if root is not None else _guess_root(paths)
    files = _collect_files(paths)
    if not files:
        raise ValueError(f"no lintable files found under {paths} — a "
                         "mistyped path would otherwise lint nothing "
                         "and exit green")
    report = Report(files=len(files))
    modules = []
    cmodules = []
    for f in files:
        if f.suffix in _C_SUFFIXES:
            cmod = csrc.parse_c_module(f, root=root)
            if cmod is not None and not cmod.skip:
                cmodules.append(cmod)
            continue
        mod = astcache.parse_module(f, root=root)
        if mod is not None and not mod.skip:
            modules.append(mod)
    selected = resolved if resolved is not None else set(RULE_NAMES)
    findings: list[Finding] = []
    for name, per_module, _global in RULES:
        if name not in selected or per_module is None:
            continue
        for mod in modules:
            try:
                findings.extend(per_module(mod))
            except Exception:  # noqa: BLE001 — one bad file never kills lint
                logger.exception("rule %s crashed on %s", name, mod.relpath)
    for name, per_cmodule in C_RULES:
        if name not in selected:
            continue
        for cmod in cmodules:
            try:
                findings.extend(per_cmodule(cmod))
            except Exception:  # noqa: BLE001 — one bad file never kills lint
                logger.exception("rule %s crashed on %s", name,
                                 cmod.relpath)
    global_rules = [g for name, _p, g in RULES
                    if g is not None and name in selected]
    if global_rules:
        graph = callgraph.build(modules, root=root)
        for g in global_rules:
            try:
                findings.extend(g(graph))
            except Exception:  # noqa: BLE001
                logger.exception("global rule %s crashed", g.__name__)

    # dedup (two worker roots can blame the same call site)
    seen: set = set()
    unique: list[Finding] = []
    for f in sort_findings(findings):
        k = (f.path, f.line, f.col, f.rule, f.message)
        if k not in seen:
            seen.add(k)
            unique.append(f)

    waivers: dict[str, str] = {}
    if baseline is not False:
        bpath = Path(baseline) if baseline else root / BASELINE_NAME
        waivers = load_baseline(bpath)
    matched: set = set()
    for f in unique:
        if f.key() in waivers:
            matched.add(f.key())
            report.baselined.append(f)
        else:
            report.findings.append(f)
    report.stale_waivers = sorted(set(waivers) - matched)
    _record_metrics(report)
    return report


def _record_metrics(report: Report) -> None:
    """``lint_findings_total{rule}`` into the installed registry, so
    waiver growth / finding counts surface in the run's metrics exports
    (a NULL registry makes this free)."""
    from jepsen_tpu import telemetry
    reg = telemetry.get_registry()
    if not reg.enabled:
        return
    c = reg.counter("lint_findings_total",
                    "invariant-linter findings by rule (non-baselined)",
                    labels=("rule",))
    for f in report.findings:
        c.inc(rule=f.rule)
    b = reg.counter("lint_baselined_findings_total",
                    "lint findings suppressed by the baseline file "
                    "(waiver growth is a smell worth a dashboard)",
                    labels=("rule",))
    for f in report.baselined:
        b.inc(rule=f.rule)


def render_text(report: Report) -> str:
    lines = [f.render() for f in report.findings]
    if report.baselined:
        lines.append(f"{len(report.baselined)} finding(s) suppressed by "
                     "baseline")
    if report.stale_waivers:
        lines.append("stale baseline entries (nothing matches them — "
                     "remove):")
        lines.extend(f"  {k}" for k in report.stale_waivers)
    n = len(report.findings)
    lines.append(f"{n} finding(s) in {report.files} file(s)"
                 if n else f"all clear: 0 findings in {report.files} "
                           "file(s)")
    return "\n".join(lines)


def render_report_json(report: Report) -> str:
    import json
    rows = [f.to_json() for f in report.findings]
    for f in report.baselined:
        rows.append({**f.to_json(), "baselined": True})
    summary = {"summary": True, "files": report.files,
               "findings": len(report.findings),
               "baselined": len(report.baselined),
               "stale_waivers": report.stale_waivers}
    return "\n".join(json.dumps(r) for r in rows + [summary]) + "\n"


__all__ = [
    "BASELINE_NAME", "C_RULES", "RULE_NAMES", "Report", "lint_paths",
    "load_baseline", "resolve_rules",
    "render_json", "render_report_json", "render_text", "write_baseline",
]
