"""Static analysis: preflight test-spec validation + invariant linting.

Jepsen's whole value proposition is catching bugs *before* production —
this package turns that lens on the framework itself, in the spirit of
Elle (infer anomalies from structure instead of hoping a test trips
them) and Eraser-style lock-set race detection:

* :mod:`jepsen_tpu.analysis.preflight` — validates a test map before
  ``core.run`` touches any node: bounded symbolic enumeration of the
  generator (via :mod:`jepsen_tpu.generator.simulate`) checks every
  emitted ``:f`` against the client's declared op surface and every
  nemesis ``:f`` against :func:`jepsen_tpu.nemesis.faults.classify`
  healability, plus type/range checks on the runtime knobs and
  checker/model compatibility. A mis-specified test fails in seconds on
  the control node instead of minutes into cluster/TPU time.

* :mod:`jepsen_tpu.analysis.lint` — an AST + call-graph linter over the
  package itself, encoding the concurrency/durability/JAX invariants
  that PR 1-4 reviews had to enforce by hand (lock-guarded attribute
  mutation, scheduler/worker thread ownership, no unbounded blocking in
  the scheduler, flush+fsync pairing, host effects under ``jit``,
  donated-buffer reuse, recompile hazards). ``jepsen-tpu lint`` runs
  it; a tier-1 test keeps ``jepsen_tpu/`` itself at zero non-baselined
  findings.

See doc/static-analysis.md for the rule catalog and diagnostic codes.
"""
from __future__ import annotations

from jepsen_tpu.analysis.diagnostics import Diagnostic, Finding  # noqa: F401
from jepsen_tpu.analysis.preflight import PreflightFailed  # noqa: F401
