"""Preflight: static validation of a test map before the run starts.

A mis-specified test — a generator emitting an ``:f`` the client doesn't
implement, a nemesis kind nothing can heal, a garbage timeout knob —
historically surfaced minutes into a run, after node setup, DB cycling
and TPU compile time were already spent, as a history full of
``unknown-f`` fails (or worse, a cluster left broken by an unhealable
fault). Preflight catches those in milliseconds on the control node:

* **Generator enumeration** — a bounded, deterministic symbolic run of
  the generator via :mod:`jepsen_tpu.generator.simulate` (seeded model
  workers, hard op-count and wall-clock caps, so it terminates on any
  generator). Every emitted client ``:f`` is checked against the
  client's declared op surface (:meth:`jepsen_tpu.client.Client.
  supported_fs`), every nemesis ``:f`` against the nemesis'
  :meth:`~jepsen_tpu.nemesis.Nemesis.fs` surface and
  :func:`jepsen_tpu.nemesis.faults.classify` healability. Generators
  built from *stateful* callables (closure counters, iterators, global
  ``random``) are detected and skipped — enumerating them would consume
  the very state the real run needs (diagnostic GEN005 notes the skip).

* **Knob checks** — type/range validation of the runtime knobs
  (``op_timeout_s``, ``drain_timeout_s``, ``stall_s``,
  ``wal_fsync_interval``, ``metrics_interval``, ``time_limit``,
  ``concurrency`` vs node count, time-limit vs op-timeout sanity).

* **Checker/model compatibility** — a linearizable checker whose model
  doesn't recognize the generator's op surface yields garbage verdicts;
  preflight cross-checks the enumerated ``:f`` set against the model.

``core.run`` runs preflight by default; ``preflight: False`` in the
test map (or ``--no-preflight``) restores the old behavior
bit-identically. ``jepsen-tpu preflight`` runs it standalone. Error
diagnostics raise :class:`PreflightFailed`; warnings are logged and the
run proceeds. ``preflight_allow: ["NEM002", ...]`` in the test map
downgrades named codes to warnings (the documented waiver for tests
that *deliberately* use unhealable file faults).

Diagnostic codes (doc/static-analysis.md):

====== ======== ======================================================
code   severity meaning
====== ======== ======================================================
GEN001 error    generator emits an ``:f`` outside the client's surface
GEN002 warning  generator emitted no ops at all
GEN003 info     enumeration truncated at the op/wall cap
GEN004 warning  generator raised during enumeration
GEN005 info     generator is stateful; enumeration skipped
GEN006 error    generator emits a malformed op
CLI001 error    client ops emitted but the test has no client
NEM001 warning  nemesis ops emitted but the test has no nemesis
NEM002 error    nemesis ``:f`` maps to an unhealable fault kind
NEM003 error    nemesis ``:f`` outside the nemesis' declared surface
NEM004 error    nemesis package misconfigured (State surface/knobs)
NEM005 error    membership package is unhealable (no/bad heal spec)
NEM006 error    clock-rate faults requested but libfaketime is absent
KNB001 error    knob has a non-numeric type
KNB002 error    knob out of range
KNB003 error    concurrency invalid
KNB004 warning  concurrency leaves nodes without a client worker
KNB005 warning  per-op deadline exceeds the run's time limit
KNB006 warning  stringly-typed numeric knob
KNB007 error    enum knob outside its value set (matrix_variant, env
                routing knobs)
CHK001 warning  checker model doesn't recognize enumerated ops
====== ======== ======================================================
"""
from __future__ import annotations

import dataclasses
import datetime
import decimal
import dis
import fractions
import logging
import os
import pathlib
import re
import types
import uuid
from enum import Enum
from typing import Any

from jepsen_tpu.analysis.diagnostics import (
    ERROR, INFO, WARNING, Diagnostic, sort_diagnostics,
)

logger = logging.getLogger("jepsen.analysis.preflight")

# Enumeration caps: generous enough to exercise phase structure, small
# enough to stay invisible next to node setup. Tunable per test map.
DEFAULT_OP_CAP = 256
DEFAULT_WALL_CAP_S = 2.0


class PreflightFailed(Exception):
    """Raised by :func:`check` when any error-severity diagnostic fired.
    ``diagnostics`` holds every finding; ``errors`` just the fatal ones."""

    def __init__(self, diagnostics: list[Diagnostic]):
        self.diagnostics = list(diagnostics)
        self.errors = [d for d in diagnostics if d.severity == ERROR]
        lines = [d.render() for d in self.errors]
        super().__init__(
            f"preflight failed with {len(self.errors)} error(s):\n"
            + "\n".join(lines))


# ---------------------------------------------------------------------------
# Statefulness detection — is this generator safe to enumerate?
# ---------------------------------------------------------------------------

_MUTABLE_CELL_TYPES = (list, dict, set, bytearray)
# Closure cell types that cannot carry run state a symbolic enumeration
# could consume. Anything else — notably an arbitrary object instance,
# like the live MembershipNemesis a membership generator closes over —
# is treated as stateful: calling through it during enumeration could
# mutate the very model the real run needs. Common immutable value
# types (Path, datetime, Decimal, patterns, UUIDs, enums) stay inert so
# ordinary data-closure generators keep full enumeration coverage.
_INERT_CELL_TYPES = (type(None), str, int, float, bool, bytes, complex,
                     range, type, types.ModuleType,
                     pathlib.PurePath, datetime.date, datetime.time,
                     datetime.timedelta, datetime.tzinfo,
                     decimal.Decimal, fractions.Fraction,
                     re.Pattern, uuid.UUID, Enum)
_STATE_OPS = frozenset(
    {"STORE_DEREF", "DELETE_DEREF", "STORE_GLOBAL", "DELETE_GLOBAL"})
_MISSING = object()


def _stateful_callable(fn, _depth: int = 0) -> str | None:
    """A reason string when calling ``fn`` during enumeration could
    consume state the real run needs (closure counters, iterators,
    the global ``random`` stream), else None. Conservative: anything
    we can't prove stateless is treated as stateful — a skipped
    enumeration is safe, a corrupted run is not."""
    if _depth > 4:
        return "callable nesting too deep to prove stateless"
    if isinstance(fn, types.MethodType):
        return f"bound method {getattr(fn, '__qualname__', fn)!r}"
    if not isinstance(fn, types.FunctionType):
        return f"non-function callable {type(fn).__name__!r}"
    for cell in fn.__closure__ or ():
        try:
            v = cell.cell_contents
        except ValueError:
            return "unresolved closure cell"
        reason = _stateful_cell(v, fn, _depth)
        if reason:
            return reason
    try:
        for ins in dis.get_instructions(fn):
            if ins.opname in _STATE_OPS:
                return (f"{fn.__qualname__!r} rebinds nonlocal/global "
                        "state")
            if ins.opname == "LOAD_GLOBAL":
                reason = _stateful_global(fn, ins.argval, _depth)
                if reason:
                    return reason
    except Exception:  # noqa: BLE001 — bytecode we can't read, assume worst
        return "unreadable bytecode"
    return None


def _stateful_cell(v, fn, depth: int) -> str | None:
    """Why a closure-cell VALUE makes enumeration unsafe, or None.
    Recurses into nested immutable containers, partials, and plain
    functions; allows module-level builtins (``math.sqrt`` — bound to a
    module) while rejecting instance-bound ones (``random.random`` is a
    bound method of the hidden global ``Random``); treats any other
    object instance (nemesis, connection, RNG) as live run state."""
    import functools
    if depth > 4:
        return "cell nesting too deep to prove stateless"
    if hasattr(v, "__next__"):
        return f"closure over an iterator in {fn.__qualname__!r}"
    if isinstance(v, _MUTABLE_CELL_TYPES):
        return (f"closure over a mutable {type(v).__name__} in "
                f"{fn.__qualname__!r}")
    if isinstance(v, types.FunctionType):
        return _stateful_callable(v, depth + 1)
    if isinstance(v, types.BuiltinFunctionType):
        owner = getattr(v, "__self__", None)
        if owner is None or isinstance(owner, types.ModuleType):
            return None
        return (f"{fn.__qualname__!r} closes over builtin method "
                f"{v.__name__!r} bound to a {type(owner).__name__}")
    if isinstance(v, functools.partial):
        for part in (v.func, *v.args, *v.keywords.values()):
            reason = _stateful_cell(part, fn, depth + 1)
            if reason:
                return reason
        return None
    if isinstance(v, (tuple, frozenset)):
        for el in v:
            reason = _stateful_cell(el, fn, depth + 1)
            if reason:
                return reason
        return None
    if isinstance(v, _INERT_CELL_TYPES):
        return None
    # an object instance: calling the closure can read/advance its
    # live state
    return (f"closure over a {type(v).__name__} instance in "
            f"{fn.__qualname__!r}")


def _stateful_global(fn, name, depth: int) -> str | None:
    """Why the global ``name`` referenced by ``fn`` makes enumeration
    unsafe, or None. Resolves through ``fn.__globals__`` so
    ``from random import randint``-style imports and stateful global
    helpers are caught, not just the bare module name."""
    v = fn.__globals__.get(name, _MISSING)
    if v is _MISSING:
        return None  # a builtin (len, range, ...) — stateless
    if isinstance(v, types.ModuleType):
        if v.__name__ == "random":
            return (f"{fn.__qualname__!r} draws from the global random "
                    "stream")
        return None
    mod = getattr(v, "__module__", None)
    if mod == "random":
        return (f"{fn.__qualname__!r} draws from the global random "
                f"stream (via {name!r})")
    if hasattr(v, "__next__"):
        return f"{fn.__qualname__!r} reads global iterator {name!r}"
    if isinstance(v, _MUTABLE_CELL_TYPES):
        return (f"{fn.__qualname__!r} references global mutable "
                f"{type(v).__name__} {name!r}")
    if isinstance(v, types.MethodType):
        return f"{fn.__qualname__!r} calls global bound method {name!r}"
    if isinstance(v, types.FunctionType):
        # a global helper is only safe if IT is provably stateless
        return _stateful_callable(v, depth + 1)
    return None  # modules/classes/constants: calls on them don't touch
    #              generator state the run needs (conservatively allowed)


def _stateful_reason(value, _seen: set | None = None) -> str | None:
    """Walks a generator value tree; returns why it is NOT statically
    enumerable, or None when every component is pure data / provably
    stateless callables."""
    from jepsen_tpu import generator as gen_mod

    seen = _seen if _seen is not None else set()
    if id(value) in seen:
        return None
    seen.add(id(value))
    if value is None or isinstance(value, (str, int, float, bool, bytes)):
        return None
    if isinstance(value, dict):
        for v in value.values():
            r = _stateful_reason(v, seen)
            if r:
                return r
        return None
    if isinstance(value, (list, tuple, set, frozenset)):
        for v in value:
            r = _stateful_reason(v, seen)
            if r:
                return r
        return None
    if callable(value) and not isinstance(value, gen_mod.Generator):
        return _stateful_callable(value)
    if isinstance(value, gen_mod.Generator):
        if not dataclasses.is_dataclass(value):
            return f"opaque generator {type(value).__name__!r}"
        for f in dataclasses.fields(value):
            r = _stateful_reason(getattr(value, f.name), seen)
            if r:
                return r
        return None
    # an unrecognized embedded object (connection, RNG, ...): refuse
    return f"embedded {type(value).__name__!r} object"


# ---------------------------------------------------------------------------
# Surfaces
# ---------------------------------------------------------------------------

def _unwrap_client(client):
    """Peels wrapper clients: ``client.Validate`` holds the wrapped
    client in ``.client``, ``tracing.TracedClient`` in ``.inner`` —
    a ``--trace`` run must get the same surface check as a bare one."""
    from jepsen_tpu.client import Client
    for _ in range(8):
        for attr in ("client", "inner"):
            inner = getattr(client, attr, None)
            if isinstance(inner, Client):
                client = inner
                break
        else:
            return client
    return client


def _client_surface(test: dict):
    """The client's declared op surface, or None when unknown (no client
    wired yet, or the client doesn't declare one — the check is then
    skipped, never guessed)."""
    client = test.get("client")
    if client is None:
        return None
    client = _unwrap_client(client)
    fn = getattr(client, "supported_fs", None)
    if not callable(fn):
        return None
    try:
        surface = fn(test)
    except Exception:  # noqa: BLE001 — a broken surface is no surface
        logger.exception("client supported_fs() raised; skipping check")
        return None
    return None if surface is None else set(surface)


def _nemesis_surface(test: dict):
    nemesis = test.get("nemesis")
    if nemesis is None:
        return None
    fn = getattr(nemesis, "fs", None)
    if not callable(fn):
        return None
    try:
        surface = set(fn() or ())
    except Exception:  # noqa: BLE001
        logger.exception("nemesis fs() raised; skipping check")
        return None
    # the base protocol returns an empty set for "not declared"
    return surface or None


def _model_surface(model) -> set | None:
    """Op fs a linearizability model recognizes; None = unknown."""
    try:
        from jepsen_tpu.models import CASRegister, MultiRegister
    except Exception:  # noqa: BLE001
        return None
    if isinstance(model, CASRegister):
        return {"read", "write", "cas"}
    if isinstance(model, MultiRegister):
        return {"txn"}
    return None


def _walk_checkers(checker, out: list, _depth: int = 0) -> None:
    if checker is None or _depth > 6:
        return
    out.append(checker)
    sub = getattr(checker, "checkers", None)
    if isinstance(sub, dict):
        for c in sub.values():
            _walk_checkers(c, out, _depth + 1)
    inner = getattr(checker, "checker", None)
    if inner is not None and inner is not checker:
        _walk_checkers(inner, out, _depth + 1)


# ---------------------------------------------------------------------------
# Knob checks
# ---------------------------------------------------------------------------

# (key, allow_none, min_inclusive) — min None = any finite value
_NUMERIC_KNOBS = (
    ("op_timeout_s", True, 0.0),
    ("drain_timeout_s", True, 0.0),
    ("stall_s", True, 0.0),
    ("wal_fsync_interval", True, None),
    ("metrics_interval", True, None),
    ("time_limit", True, 0.0),
    # live checker daemon knobs (doc/observability.md "Live checking");
    # the daemon itself coerces tolerantly (live.daemon.coerce_knob) —
    # preflight is where a garbage value becomes an error instead of a
    # silently-defaulted warning
    ("live_poll_s", True, 0.0),
    ("live_lag_budget_ops", True, 0.0),
    ("live_max_runs", True, 1.0),
    ("live_check_budget_s", True, 0.0),
    # multi-device sharding (doc/performance.md "Multi-device
    # sharding"): mesh width cap for the sharded checker rung —
    # parallel.coerce_devices coerces tolerantly at runtime, preflight
    # is where garbage becomes an error
    ("mesh_devices", True, 0.0),
    # anomaly forensics (doc/observability.md "Anomaly forensics"):
    # witness-shrink bounds — checker/explain coerces tolerantly at
    # runtime, preflight is where garbage becomes an error
    ("explain_shrink_budget", True, 0.0),
    ("explain_max_witness_ops", True, 1.0),
    # resumable checks + the elastic mesh (doc/robustness.md
    # "Resumable checks and the elastic mesh"): seconds between durable
    # check.ckpt persists (<= 0 disables — so any finite value passes
    # range), and the mesh shrink ladder's floor width
    ("check_ckpt_interval", True, None),
    ("mesh_min_devices", True, 0.0),
    # causal trace (doc/observability.md "Causal trace"): the flight
    # recorder's ring capacity — trace.flight_recorder_events coerces
    # tolerantly at runtime (garbage warns + default), preflight is
    # where it becomes an error. 0 disables the recorder.
    ("flight_recorder_events", True, 0.0),
    # fleet plane knobs (doc/observability.md "Fleet plane"): the
    # fleet daemon coerces tolerantly (fleet.fleet_knob) — preflight
    # is where garbage becomes an error
    ("fleet_port", True, 0.0),
    ("fleet_ingest_budget_s", True, 0.0),
    ("fleet_max_runs", True, 1.0),
    # fleet HA knobs (doc/robustness.md "Fleet HA"): leased-checking
    # TTL (0 disables leasing) and the receiver's free-disk shed floor
    ("fleet_lease_ttl_s", True, 0.0),
    ("fleet_disk_headroom_mb", True, 0.0),
    # host ingest spine (doc/performance.md "Host ingest spine"): the
    # chunked-scheduler drain size — interpreter._knob coerces
    # tolerantly at runtime (garbage warns + default, 0/None = per-op
    # fallback), preflight is where garbage becomes an error
    ("sched_batch_ops", True, 0.0),
    # schedule fuzzer knobs (doc/robustness.md "Schedule fuzzing"):
    # the hunt coerces tolerantly (fuzz.hunt.fuzz_knob) — preflight is
    # where garbage becomes an error. fuzz_seed accepts any finite
    # value (a seed is just entropy).
    ("fuzz_trials", True, 1.0),
    ("fuzz_pool_workers", True, 0.0),
    ("fuzz_trial_ops", True, 8.0),
    ("fuzz_seed", True, None),
)

# bool knobs, tolerantly coerced at runtime (parallel.coerce_flag —
# bools and 0/1 pass, yes/no strings warn, garbage errors here instead
# of silently reading as unset): the sharded-rung switch, the
# anomaly-forensics switch, the history-IR switches
# (doc/performance.md "History IR"), and the fused-combine toggle
# (doc/performance.md "Packed boolean kernels")
_BOOL_KNOBS = ("checker_sharded", "explain", "ir_enabled",
               "ir_stream_from_wal", "combine_fused", "resume_check",
               "trace", "ingest_native", "native_san")
_BOOL_STRINGS = ("1", "0", "true", "false", "yes", "no", "on", "off")

# enum knobs, tolerantly coerced at runtime (pallas_matrix
# coerce_variant / _env_choice — garbage warns and reads as unset/auto;
# preflight is where it becomes an error). Each entry: (knob, value
# set, hint). The variant set is DERIVED from pallas_matrix.VARIANTS
# (module-level imports there are stdlib+numpy, cheap here) so a new
# kernel representation can never be rejected by a stale preflight
# copy. The env rows mirror the test-map rows: a malformed env routing
# knob silently degrades a whole sweep to the default, so the gate
# names it before any device contact.
from jepsen_tpu.ops.pallas_matrix import VARIANTS as _MATRIX_VARIANTS

_VARIANT_VALUES = ("auto",) + _MATRIX_VARIANTS
_ENUM_KNOBS = (
    ("matrix_variant", _VARIANT_VALUES,
     "pins the matrix-kernel representation (probe-gated; a pinned "
     "variant that can't run demotes down the auto order)"),
)
_ENV_ENUM_KNOBS = (
    ("JEPSEN_TPU_MATRIX_VARIANT", _VARIANT_VALUES,
     "pins the matrix-kernel representation for this process"),
    ("JEPSEN_TPU_PALLAS_PROBE", ("auto", "force", "skip"),
     "probe sidecar policy: auto = cached verdicts, force = re-probe, "
     "skip = trust the shape gates"),
    ("JEPSEN_TPU_FUSE_COMBINE", _BOOL_STRINGS,
     "forces the fused/tree chunk combine (unset = probe decides)"),
    ("JEPSEN_TPU_RESUME_CHECK", _BOOL_STRINGS,
     "process-wide twin of resume_check (durable check.ckpt "
     "auto-resume, doc/robustness.md)"),
    ("JEPSEN_TPU_TRACE", _BOOL_STRINGS,
     "process-wide twin of the trace knob (run-wide causal trace to "
     "trace.json, doc/observability.md)"),
    ("JEPSEN_TPU_NATIVE_SAN", _BOOL_STRINGS,
     "process-wide twin of native_san (route the native ingest spine "
     "through the ASan+UBSan build; unavailable => Python twins with "
     "the san-unavailable fallback reason, doc/static-analysis.md "
     "\"Native code\")"),
)

# numeric env twins: a malformed value silently degrades the whole
# sweep to the default at runtime, so the gate names it here
# (key, hint)
_ENV_NUMERIC_KNOBS = (
    ("JEPSEN_TPU_CHECK_CKPT_INTERVAL",
     "seconds between durable check.ckpt persists (<= 0 disables)"),
    ("JEPSEN_TPU_MESH_MIN_DEVICES",
     "the elastic mesh shrink ladder's floor width (below it the "
     "checker demotes to single-device)"),
    ("JEPSEN_TPU_FLIGHT_RECORDER_EVENTS",
     "process-wide twin of flight_recorder_events (the crash/stall "
     "flight recorder's ring capacity; 0 disables)"),
    ("JEPSEN_TPU_FLEET_PORT",
     "process-wide twin of fleet_port (the fleet daemon's ingest/"
     "status port, doc/observability.md \"Fleet plane\")"),
    ("JEPSEN_TPU_FLEET_INGEST_BUDGET_S",
     "process-wide twin of fleet_ingest_budget_s (the pool's per-poll "
     "verdict budget in predicted CPU seconds)"),
    ("JEPSEN_TPU_FLEET_MAX_RUNS",
     "process-wide twin of fleet_max_runs (the pool's admission cap "
     "on concurrently tracked runs)"),
    ("JEPSEN_TPU_FLEET_LEASE_TTL_S",
     "process-wide twin of fleet_lease_ttl_s (leased-checking TTL; "
     "0 disables leasing, doc/robustness.md \"Fleet HA\")"),
    ("JEPSEN_TPU_FLEET_DISK_HEADROOM_MB",
     "process-wide twin of fleet_disk_headroom_mb (the receiver's "
     "free-disk floor below which chunks shed with 429)"),
    ("JEPSEN_TPU_FUZZ_TRIALS",
     "process-wide twin of fuzz_trials (the hunt's trial budget, "
     "doc/robustness.md \"Schedule fuzzing\")"),
    ("JEPSEN_TPU_FUZZ_POOL_WORKERS",
     "process-wide twin of fuzz_pool_workers (trial pool processes; "
     "0/1 runs trials inline)"),
    ("JEPSEN_TPU_FUZZ_TRIAL_OPS",
     "process-wide twin of fuzz_trial_ops (client ops per fuzz "
     "trial)"),
    ("JEPSEN_TPU_FUZZ_SEED",
     "process-wide twin of fuzz_seed (the hunt seed; the whole "
     "search replays bit-identically from it)"),
)

_UNSET = object()


def _check_knobs(test: dict) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    for key, allow_none, lo in _NUMERIC_KNOBS:
        v = test.get(key, _UNSET)
        if v is _UNSET or (v is None and allow_none):
            continue
        if isinstance(v, bool):
            out.append(Diagnostic(
                "KNB001", ERROR, key,
                f"{key} must be a number, got bool {v!r}",
                hint=f"use a numeric value (0 disables {key})"))
            continue
        if isinstance(v, str):
            try:
                v = float(v)
            except ValueError:
                out.append(Diagnostic(
                    "KNB001", ERROR, key,
                    f"{key} must be a number, got {v!r}",
                    hint="the runtime would fall back to the default "
                         "with a warning; fix the test map instead"))
                continue
            out.append(Diagnostic(
                "KNB006", WARNING, key,
                f"{key} is a string ({v!r}); prefer a plain number"))
        if not isinstance(v, (int, float)):
            out.append(Diagnostic(
                "KNB001", ERROR, key,
                f"{key} must be a number, got {type(v).__name__}"))
            continue
        if lo is not None and v < lo:
            out.append(Diagnostic(
                "KNB002", ERROR, key,
                f"{key}={v!r} is below the minimum {lo!r}",
                hint="0 disables a timeout knob; negatives are "
                     "meaningless here"))

    for key in _BOOL_KNOBS:
        v = test.get(key, _UNSET)
        if v is _UNSET or v is None:
            continue
        if isinstance(v, bool) or v in (0, 1):
            continue
        if isinstance(v, str) and v.strip().lower() in _BOOL_STRINGS:
            out.append(Diagnostic(
                "KNB006", WARNING, key,
                f"{key} is a string ({v!r}); prefer a plain bool"))
            continue
        hints = {
            "checker_sharded": "true enables the sharded checker rung, "
                               "false forces single-device; unset = env "
                               "default + cost model",
            "explain": "true (the default) derives anomaly forensics on "
                       "invalid verdicts; false skips localization and "
                       "artifacts",
            "ir_enabled": "true (the default) shares one columnar "
                          "history IR across all checkers; false "
                          "restores per-checker encodes (bit-identical)",
            "ir_stream_from_wal": "true streams the IR build from the "
                                  "run's WAL as ops complete; false "
                                  "(the default) encodes at analyze "
                                  "time",
        }
        hints["combine_fused"] = (
            "true pins the fused streaming chunk combine, false the "
            "tree combine; unset = env default + probe")
        hints["resume_check"] = (
            "true (the default) resumes an interrupted check from its "
            "durable check.ckpt; false (analyze --no-resume-check) "
            "re-checks from zero")
        hints["trace"] = (
            "true streams the run-wide causal trace to trace.json "
            "(Perfetto) plus the per-client span log; the flight "
            "recorder stays on either way (flight_recorder_events)")
        hints["ingest_native"] = (
            "true (the default) lets the probed C ingest spine run the "
            "WAL hot loop; false forces the Python twins")
        hints["native_san"] = (
            "true routes the native ingest spine through the ASan+UBSan "
            "build (requires the runtime LD_PRELOADed; otherwise the "
            "Python twins run, counted san-unavailable); false/unset = "
            "the plain -O3 build")
        out.append(Diagnostic(
            "KNB001", ERROR, key,
            f"{key} must be a bool, got {v!r}", hint=hints.get(key)))

    for key, values, hint in _ENUM_KNOBS:
        v = test.get(key, _UNSET)
        if v is _UNSET or v is None:
            continue
        if isinstance(v, str) and v.strip().lower() in values:
            continue
        out.append(Diagnostic(
            "KNB007", ERROR, key,
            f"{key}={v!r} is not one of {'|'.join(values)}",
            hint=hint + "; the runtime would warn and fall back to "
                 "'auto' — fix the test map instead"))

    for key, values, hint in _ENV_ENUM_KNOBS:
        raw = os.environ.get(key)
        if raw is None or raw == "":
            continue
        if raw.strip().lower() in values:
            continue
        out.append(Diagnostic(
            "KNB007", ERROR, key,
            f"env {key}={raw!r} is not one of {'|'.join(values)}",
            hint=hint + "; the runtime would warn and use the default"))

    for key, hint in _ENV_NUMERIC_KNOBS:
        raw = os.environ.get(key)
        if raw is None or raw == "":
            continue
        try:
            float(raw)
        except ValueError:
            out.append(Diagnostic(
                "KNB001", ERROR, key,
                f"env {key}={raw!r} is not a number",
                hint=hint + "; the runtime would warn and use the "
                     "default"))

    # fleet_receivers (doc/robustness.md "Fleet HA"): the shipper's
    # failover endpoint list — a comma-separated string or a list of
    # base URLs. The runtime (fleet.fleet_receivers) tolerantly reads
    # garbage as unset; here a malformed entry is an error, because a
    # silently-empty list means no failover when the receiver dies.
    _RECV_HINT = ("a list of receiver base URLs (or one comma-"
                  "separated string), e.g. ['http://pool-a:8091', "
                  "'http://pool-b:8091']")
    for origin, value in (
            ("fleet_receivers", test.get("fleet_receivers", _UNSET)),
            ("JEPSEN_TPU_FLEET_RECEIVERS",
             os.environ.get("JEPSEN_TPU_FLEET_RECEIVERS", _UNSET))):
        if value is _UNSET or value is None or value == "":
            continue
        if isinstance(value, str):
            entries = [p.strip() for p in value.split(",")]
        elif isinstance(value, (list, tuple)):
            entries = [p.strip() if isinstance(p, str) else p
                       for p in value]
        else:
            out.append(Diagnostic(
                "KNB001", ERROR, origin,
                f"{origin} must be a URL list or comma-separated "
                f"string, got {type(value).__name__}",
                hint=_RECV_HINT))
            continue
        for p in entries:
            if not isinstance(p, str):
                out.append(Diagnostic(
                    "KNB001", ERROR, origin,
                    f"{origin} entry {p!r} is not a string",
                    hint=_RECV_HINT))
            elif p and not (p.startswith("http://")
                            or p.startswith("https://")):
                out.append(Diagnostic(
                    "KNB007", ERROR, origin,
                    f"{origin} entry {p!r} is not an http(s) base URL",
                    hint=_RECV_HINT))

    nodes = list(test.get("nodes") or [])
    conc_raw = test.get("concurrency", 1)
    try:
        from jepsen_tpu.utils import parse_concurrency
        conc = parse_concurrency(conc_raw, len(nodes))
    except Exception as e:  # noqa: BLE001
        out.append(Diagnostic(
            "KNB003", ERROR, "concurrency",
            f"unparsable concurrency {conc_raw!r}: {e}",
            hint="use an int or the '3n' per-node form"))
        conc = None
    if conc is not None and conc < 1:
        out.append(Diagnostic(
            "KNB003", ERROR, "concurrency",
            f"concurrency={conc} — a run needs at least one worker"))
    elif conc is not None and nodes and conc < len(nodes) \
            and test.get("client") is not None:
        out.append(Diagnostic(
            "KNB004", WARNING, "concurrency",
            f"concurrency={conc} < {len(nodes)} nodes: "
            f"{len(nodes) - conc} node(s) never see a client",
            hint="use '1n' (one worker per node) or more"))

    ot, tl = test.get("op_timeout_s"), test.get("time_limit")
    if isinstance(ot, (int, float)) and not isinstance(ot, bool) and ot > 0 \
            and isinstance(tl, (int, float)) and not isinstance(tl, bool) \
            and 0 < tl < ot:
        out.append(Diagnostic(
            "KNB005", WARNING, "op_timeout_s",
            f"op_timeout_s={ot} exceeds time_limit={tl}: a hung op "
            "extends the run past its time limit before the deadline "
            "can fire",
            hint="set op_timeout_s below time_limit, or accept the "
                 "longer worst-case run"))
    return out


# ---------------------------------------------------------------------------
# Generator enumeration
# ---------------------------------------------------------------------------

def _cap_knob(test: dict, key: str, default, cast, diags: list) -> Any:
    """Preflight's own cap knobs, coerced with the same tolerance the
    subsystem preaches: garbage becomes a KNB001 diagnostic + the
    default, never a raw ValueError out of the gate itself."""
    v = test.get(key, default)
    try:
        if isinstance(v, bool):
            raise ValueError("bool is not a count")
        v = cast(v)
        if key != "preflight_seed" and v <= 0:
            raise ValueError("must be positive")
        return v
    except (TypeError, ValueError) as e:
        diags.append(Diagnostic(
            "KNB001", ERROR, key,
            f"{key}={test.get(key)!r} is not a usable "
            f"{cast.__name__} ({e}); enumeration used the default "
            f"{default!r}"))
        return default


def _enumerate(test: dict) -> tuple[list[dict], list[Diagnostic]]:
    """Bounded symbolic run of the generator; returns (invocations,
    diagnostics-from-enumeration). Never touches real clients, nodes,
    or wall-clock sleeps."""
    from jepsen_tpu import generator as gen_mod
    from jepsen_tpu.generator import simulate as sim

    gen_value = test.get("generator")
    if gen_value is None:
        return [], []
    reason = _stateful_reason(gen_value)
    if reason:
        return [], [Diagnostic(
            "GEN005", INFO, "generator",
            f"generator is not statically enumerable ({reason}); "
            "op-surface checks skipped",
            hint="build generators from data/pure callables to get "
                 "preflight coverage")]

    diags: list[Diagnostic] = []
    op_cap = _cap_knob(test, "preflight_ops", DEFAULT_OP_CAP, int, diags)
    wall_cap = _cap_knob(test, "preflight_wall_s", DEFAULT_WALL_CAP_S,
                         float, diags)
    seed = _cap_knob(test, "preflight_seed", 0, int, diags)
    stats: dict = {}
    try:
        # simulate's limit counts scheduler STEPS (dispatch and
        # completion each cost one), so 4x the op budget bounds the
        # invocation count at roughly 2x preflight_ops. ``stats``
        # reports which cap (if any) ended the run, so truncation is
        # NEVER silent — a pseudo-op-heavy generator can exhaust steps
        # with few invocations.
        history = sim.quick(test, gen_mod.validate(gen_value),
                            seed=seed, limit=op_cap * 4,
                            max_wall_s=wall_cap, stats=stats)
    except ValueError as e:
        if "invalid op" in str(e):
            return [], [Diagnostic(
                "GEN006", ERROR, "generator",
                f"generator emits a malformed op: {e}",
                hint="ops need type invoke/info/sleep/log and a free "
                     "process; see jepsen_tpu.generator.Validate")]
        return [], [Diagnostic(
            "GEN004", WARNING, "generator",
            f"generator raised during bounded enumeration: {e!r}")]
    except Exception as e:  # noqa: BLE001 — enumeration must never crash
        return [], [Diagnostic(
            "GEN004", WARNING, "generator",
            f"generator raised during bounded enumeration: {e!r}",
            hint="the simulated scheduler completes every op :ok with "
                 "zero latency; generators that depend on richer "
                 "completions may not be enumerable")]
    from jepsen_tpu.generator import NEMESIS
    # dispatched client ops are :invoke; nemesis packages emit their
    # dispatches as :info op templates (db_package, partition_package,
    # ...), which the simulated scheduler appends as-is — both are
    # "what the generator asks for" and both feed the surface checks
    invocations = [op for op in history
                   if op.get("type") == "invoke"
                   or (op.get("process") == NEMESIS
                       and op.get("type") == "info")]
    if stats.get("step_limited") or stats.get("wall_limited"):
        # ONLY the stats flags mean truncation — a generator that
        # exhausted naturally under the caps got full coverage, however
        # many ops it emitted, and must not be branded a prefix
        cause = ("wall-clock cap" if stats.get("wall_limited")
                 else "step cap")
        diags.append(Diagnostic(
            "GEN003", INFO, "generator",
            f"enumeration truncated by the {cause} at "
            f"{len(invocations)} op(s) / {stats.get('steps', 0)} "
            "step(s); coverage is a prefix",
            hint="raise preflight_ops / preflight_wall_s in the test "
                 "map for deeper coverage"))
    if not history:
        diags.append(Diagnostic(
            "GEN002", WARNING, "generator",
            "generator emitted no ops in the bounded enumeration",
            hint="an empty run produces an empty history; is a "
                 "time_limit/limit wrapper zeroed out?"))
    return invocations, diags


def _check_ops(test: dict, invocations: list[dict]) -> list[Diagnostic]:
    from jepsen_tpu.generator import NEMESIS
    from jepsen_tpu.nemesis.faults import UNHEALABLE_KINDS, classify

    out: list[Diagnostic] = []
    client_fs: set = set()
    nemesis_fs: set = set()
    for op in invocations:
        if op.get("process") == NEMESIS:
            nemesis_fs.add(op.get("f"))
        else:
            client_fs.add(op.get("f"))

    if client_fs and test.get("client") is None:
        out.append(Diagnostic(
            "CLI001", ERROR, "client",
            f"generator emits client ops ({_fmt_fs(client_fs)}) but the "
            "test has no client",
            hint="wire a client into the test map, or restrict the "
                 "generator to the nemesis thread"))
    surface = _client_surface(test)
    if surface is not None:
        for f in sorted(client_fs - surface, key=str):
            out.append(Diagnostic(
                "GEN001", ERROR, "generator",
                f"generator emits :f {f!r} outside the client's "
                f"supported surface {_fmt_fs(surface)}",
                hint="fix the generator's :f, or extend the client's "
                     "supported_fs()"))

    if nemesis_fs and test.get("nemesis") is None:
        out.append(Diagnostic(
            "NEM001", WARNING, "nemesis",
            f"generator emits nemesis ops ({_fmt_fs(nemesis_fs)}) but "
            "the test has no nemesis; they will all no-op to :info"))
    nem_surface = _nemesis_surface(test)
    if nem_surface is not None:
        for f in sorted(nemesis_fs - nem_surface, key=str):
            out.append(Diagnostic(
                "NEM003", ERROR, "nemesis",
                f"nemesis op :f {f!r} is outside the nemesis' declared "
                f"surface {_fmt_fs(nem_surface)}",
                hint="f_map the generator and nemesis consistently"))
    for f in sorted(nemesis_fs, key=str):
        phase, kind = classify(f)
        if phase == "begin" and kind in UNHEALABLE_KINDS:
            out.append(Diagnostic(
                "NEM002", ERROR, "nemesis",
                f"nemesis op :f {f!r} injects an unhealable fault kind "
                f"{kind!r} — no teardown, crash-path replay, or `cli "
                "heal` can undo it",
                hint="add 'NEM002' to the test map's preflight_allow "
                     "list if the damage is deliberate (the db cycle "
                     "must rebuild the node)"))

    # checker/model compatibility over the enumerated client surface
    checkers: list = []
    _walk_checkers(test.get("checker"), checkers)
    for c in checkers:
        model = getattr(c, "model", None)
        if model is None:
            continue
        msurface = _model_surface(model)
        if msurface is None:
            continue
        unknown = {f for f in client_fs if f is not None} - msurface
        if unknown:
            out.append(Diagnostic(
                "CHK001", WARNING, "checker",
                f"{type(c).__name__}'s model {type(model).__name__} "
                f"recognizes {_fmt_fs(msurface)} but the generator "
                f"emits {_fmt_fs(unknown)}; those ops will read as "
                "inconsistent",
                hint="match the workload's model to its op surface"))
    return out


def _fmt_fs(fs) -> str:
    return "{" + ", ".join(repr(f) for f in sorted(fs, key=str)) + "}"


# ---------------------------------------------------------------------------
# Nemesis package self-checks (NEM004/NEM005/NEM006)
# ---------------------------------------------------------------------------

def _walk_nemeses(nemesis, out: list, _depth: int = 0) -> None:
    """Flattens a composed nemesis tree: wrappers hold the inner in
    ``.nemesis``/``.inner``, Compose in ``.nemeses``."""
    if nemesis is None or _depth > 6 \
            or any(nemesis is seen for seen in out):
        return
    out.append(nemesis)
    for attr in ("nemesis", "inner"):
        sub = getattr(nemesis, attr, None)
        if sub is not None and sub is not nemesis:
            _walk_nemeses(sub, out, _depth + 1)
    subs = getattr(nemesis, "nemeses", None)
    if isinstance(subs, (list, tuple)):
        for sub in subs:
            _walk_nemeses(sub, out, _depth + 1)


def _nemesis_package_diags(test: dict) -> list[Diagnostic]:
    """Package-declared static checks: any nemesis in the composed tree
    may implement ``preflight_diags(test) -> [Diagnostic]`` (no node
    contact allowed). This is how the membership package validates its
    State surface/knobs/healability (NEM004/NEM005) and the clock-rate
    package surfaces a missing libfaketime (NEM006) BEFORE the run —
    generator enumeration cannot reach them: their generators are
    stateful by design (GEN005)."""
    out: list[Diagnostic] = []
    nems: list = []
    _walk_nemeses(test.get("nemesis"), nems)
    for n in nems:
        fn = getattr(n, "preflight_diags", None)
        if not callable(fn):
            continue
        try:
            out.extend(fn(test) or ())
        except Exception:  # noqa: BLE001 — a broken check is no check
            logger.exception("%s.preflight_diags raised; skipping",
                             type(n).__name__)
    return out


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def preflight(test: dict) -> list[Diagnostic]:
    """Every preflight diagnostic for ``test``, sorted errors-first.
    Pure: no node contact, no sleeps, no mutation of the test map."""
    diags = _check_knobs(test)
    invocations, gen_diags = _enumerate(test)
    diags.extend(gen_diags)
    diags.extend(_check_ops(test, invocations))
    diags.extend(_nemesis_package_diags(test))
    allowed = {str(c) for c in (test.get("preflight_allow") or ())}
    if allowed:
        diags = [
            Diagnostic(d.code, WARNING, d.path,
                       d.message + " (downgraded by preflight_allow)",
                       hint=d.hint)
            if d.severity == ERROR and d.code in allowed else d
            for d in diags
        ]
    return sort_diagnostics(diags)


def check(test: dict) -> list[Diagnostic]:
    """Runs :func:`preflight`; logs warnings/infos, raises
    :class:`PreflightFailed` when any error fired, and counts failures
    into the installed telemetry registry
    (``preflight_failures_total{code}``). Returns the diagnostics when
    the test passes."""
    from jepsen_tpu import telemetry

    diags = preflight(test)
    errors = [d for d in diags if d.severity == ERROR]
    for d in diags:
        if d.severity == ERROR:
            logger.error("%s", d.render())
        elif d.severity == WARNING:
            logger.warning("%s", d.render())
        else:
            logger.info("%s", d.render())
    reg = telemetry.get_registry()
    if reg.enabled and errors:
        c = reg.counter("preflight_failures_total",
                        "test maps rejected by preflight, by diagnostic "
                        "code", labels=("code",))
        for d in errors:
            c.inc(code=d.code)
    if errors:
        raise PreflightFailed(diags)
    return diags
