"""DB client protocol (reference: jepsen/src/jepsen/client.clj).

A Client runs operations against the system under test. Lifecycle:
``open`` (fresh connection for a process) -> ``setup`` (once) ->
``invoke`` per op -> ``teardown`` -> ``close`` (client.clj:9-34).
Clients marked ``reusable`` survive process crashes without reopening
(client.clj:29-44, used by the interpreter at interpreter.clj:33-67).
"""
from __future__ import annotations

import contextlib
from typing import Any


class Client:
    reusable = False

    def open(self, test: dict, node: str) -> "Client":
        """Returns a client bound to a connection against node. Called once
        per process; must be re-entrant on fresh instances."""
        return self

    def setup(self, test: dict) -> None:
        """One-time database setup through this client."""

    def invoke(self, test: dict, op: dict) -> dict:
        """Applies op, returning its completion (type ok/fail/info).

        Deadline contract (doc/robustness.md): the interpreter bounds
        every invoke with a per-op deadline (``op['timeout_s']`` →
        ``test['op_timeout_s']`` → ``JEPSEN_TPU_OP_TIMEOUT_S``). An
        invoke that outlives its deadline has an indeterminate ``info``
        completion synthesized for it and its worker replaced; whatever
        this method eventually returns is quarantined to the run's
        ``late.jsonl`` — never appended to history — and ``close`` is
        then called from this client's own (zombie) worker thread, never
        concurrently with a still-running invoke. The replacement worker
        calls ``open`` for a FRESH client while the hung invoke may
        still be blocked: ``open`` must hand out independently usable
        connections (its documented contract above); a client whose
        ``open`` returns a shared object must tolerate a concurrent
        invoke on it."""
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        """One-time cleanup."""

    def close(self, test: dict) -> None:
        """Releases this client's connection."""

    def supported_fs(self, test: dict) -> set | None:
        """The op ``:f`` surface this client implements, or None when
        unknown/unbounded. Preflight (jepsen_tpu.analysis.preflight)
        checks every generator-emitted ``:f`` against this set BEFORE
        the run touches a node — a declared surface turns the classic
        history-full-of-``unknown-f`` misconfiguration into an instant
        structured diagnostic. Returning None skips the check (never
        guesses)."""
        return None


class NoopClient(Client):
    """Accepts every op (jepsen.client/noop)."""

    reusable = True

    def invoke(self, test, op):
        return {**op, "type": "ok"}


class Validate(Client):
    """Wraps a client, checking completions are well-formed
    (client.clj:64-114)."""

    def __init__(self, client: Client):
        self.client = client
        self.reusable = client.reusable

    def open(self, test, node):
        opened = self.client.open(test, node)
        if opened is None:
            raise ValueError(f"{self.client!r}.open returned None")
        v = Validate(opened)
        return v

    def setup(self, test):
        self.client.setup(test)

    def invoke(self, test, op):
        completion = self.client.invoke(test, op)
        problems = []
        if not isinstance(completion, dict):
            raise ValueError(f"client completion {completion!r} is not an op")
        if completion.get("type") not in ("ok", "fail", "info"):
            problems.append(f"bad type {completion.get('type')!r}")
        if completion.get("process") != op.get("process"):
            problems.append("completion process differs from invocation")
        if completion.get("f") != op.get("f"):
            problems.append("completion f differs from invocation")
        if problems:
            raise ValueError(f"invalid completion {completion!r} for {op!r}: {problems}")
        return completion

    def teardown(self, test):
        self.client.teardown(test)

    def close(self, test):
        self.client.close(test)


def validate(client: Client) -> Client:
    return Validate(client)


@contextlib.contextmanager
def with_client(client: Client, test: dict, node: str):
    """open -> yield -> close (client.clj:116-126)."""
    c = client.open(test, node)
    try:
        yield c
    finally:
        c.close(test)


def is_client(x: Any) -> bool:
    return isinstance(x, Client)
