"""Transaction micro-op utilities (reference: txn/src/jepsen/txn.clj +
txn/micro_op.clj).

A transactional op's value is a list of micro-ops ``[f, k, v]``, e.g.
``[["r", "x", [1, 2]], ["append", "x", 3]]``.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable

READ_FS = ("r", "read")
WRITE_FS = ("w", "write", "append")


def mop(f, k, v=None) -> list:
    return [f, k, v]


def is_read(m) -> bool:
    return m[0] in READ_FS


def is_write(m) -> bool:
    return m[0] in WRITE_FS


def op_mops(op: dict) -> list:
    """[(op, mop)] pairs for an op (txn.clj:19-22)."""
    return [(op, m) for m in (op.get("value") or [])]


def reduce_mops(f: Callable, init, history: Iterable[dict]):
    """Reduces (acc, op, mop) over every micro-op in a history
    (txn.clj:5-17)."""
    acc = init
    for op in history:
        for m in op.get("value") or []:
            acc = f(acc, op, m)
    return acc


def ext_reads(txn: list) -> dict:
    """External reads: keys read before any write in this txn
    (txn.clj:24-39). {k: value-read}"""
    out: dict = {}
    written: set = set()
    for f, k, v in txn:
        kk = _hk(k)
        if f in READ_FS:
            if kk not in written and kk not in out:
                out[kk] = v
        else:
            written.add(kk)
    return out


def ext_writes(txn: list) -> dict:
    """External writes: the final write to each key (txn.clj:41-53).
    {k: value-written} (for append, the appended element)."""
    out: dict = {}
    for f, k, v in txn:
        if f in WRITE_FS:
            out[_hk(k)] = v
    return out


def int_write_mops(txn: list) -> list:
    """Writes overwritten within their own txn (txn.clj:55-73). For
    append-only workloads this is empty (appends accumulate)."""
    out = []
    last_write: dict = {}
    for i, (f, k, v) in enumerate(txn):
        if f in ("w", "write"):
            kk = _hk(k)
            if kk in last_write:
                out.append(txn[last_write[kk]])
            last_write[kk] = i
    return out


def _hk(k):
    return tuple(k) if isinstance(k, list) else k
