"""Op and history model.

An *op* is a plain dict (the reference's "test is data" stance,
core.clj:326-352): ``{"type": ..., "process": ..., "f": ..., "value": ...,
"time": ..., "index": ...}`` plus arbitrary extra keys. ``type`` is one of
``invoke | ok | fail | info``; ``process`` is an int worker process id or the
string ``"nemesis"``.

A *history* is a list of such ops in real-time order. For TPU checkers,
``ColumnarHistory`` re-encodes a history as a struct-of-arrays (int columns +
value interning) so it is checker-ready without a per-op serialization hop —
the design stance of SURVEY.md §7. Semantics of indexing/pairing follow
knossos.history (``index`` at core.clj:228; pairing per util.clj:700-735).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, Iterator, Sequence

import numpy as np

INVOKE, OK, FAIL, INFO = "invoke", "ok", "fail", "info"
TYPES = (INVOKE, OK, FAIL, INFO)
TYPE_CODE = {t: i for i, t in enumerate(TYPES)}
NEMESIS_PROCESS = -1

__all__ = [
    "INVOKE", "OK", "FAIL", "INFO", "TYPES", "TYPE_CODE",
    "op", "invoke_op", "is_invoke", "is_ok", "is_fail", "is_info",
    "index", "pairs", "completions", "invocations", "pair_index",
    "Intern", "ColumnarHistory",
]


def op(type: str, process, f, value=None, time: int = 0, **extra) -> dict:
    o = {"type": type, "process": process, "f": f, "value": value, "time": time}
    o.update(extra)
    return o


def invoke_op(process, f, value=None, **extra) -> dict:
    return op(INVOKE, process, f, value, **extra)


def is_invoke(o: dict) -> bool:
    return o.get("type") == INVOKE


def is_ok(o: dict) -> bool:
    return o.get("type") == OK


def is_fail(o: dict) -> bool:
    return o.get("type") == FAIL


def is_info(o: dict) -> bool:
    return o.get("type") == INFO


def is_client_op(o: dict) -> bool:
    return isinstance(o.get("process"), int) and o["process"] >= 0


def index(history: Iterable[dict]) -> list[dict]:
    """Assigns sequential :index to every op (knossos.history/index,
    invoked at core.clj:228). Returns new op dicts; originals untouched."""
    out = []
    for i, o in enumerate(history):
        o = dict(o)
        o["index"] = i
        out.append(o)
    return out


def pair_index(history: Sequence[dict]) -> tuple[np.ndarray, np.ndarray]:
    """For an indexed history, returns (completion_of, invocation_of) int32
    arrays: completion_of[i] is the index of the completion of invocation i
    (or -1); invocation_of[j] the inverse. Nemesis/info ops pair like client
    ops (an invoke by process p completes at p's next non-invoke op)."""
    n = len(history)
    completion_of = np.full(n, -1, dtype=np.int32)
    invocation_of = np.full(n, -1, dtype=np.int32)
    open_invoke: dict[Any, int] = {}
    for i, o in enumerate(history):
        p = o.get("process")
        if o.get("type") == INVOKE:
            open_invoke[p] = i
        else:
            j = open_invoke.pop(p, None)
            if j is not None:
                completion_of[j] = i
                invocation_of[i] = j
    return completion_of, invocation_of


def pairs(history: Sequence[dict]) -> Iterator[tuple[dict, dict | None]]:
    """Yields (invocation, completion-or-None) pairs in invocation order."""
    completion_of, _ = pair_index(history)
    for i, o in enumerate(history):
        if o.get("type") == INVOKE:
            j = completion_of[i]
            yield o, (history[j] if j >= 0 else None)


def completions(history: Sequence[dict]) -> list[dict]:
    return [o for o in history if o.get("type") in (OK, FAIL, INFO)]


def invocations(history: Sequence[dict]) -> list[dict]:
    return [o for o in history if o.get("type") == INVOKE]


class Intern:
    """Interns arbitrary hashable values to dense int32 ids. id 0 is reserved
    for None (the 'no value' sentinel), so checkers can treat 0 as nil."""

    def __init__(self):
        self.table: list[Any] = [None]
        self._ids: dict[Any, int] = {None: 0}

    def id(self, v) -> int:
        try:
            i = self._ids.get(v)
        except TypeError:  # unhashable: fall back to repr key
            v = ("__unhashable__", repr(v))
            i = self._ids.get(v)
        if i is None:
            i = len(self.table)
            self._ids[v] = i
            self.table.append(v)
        return i

    def value(self, i: int):
        return self.table[i]

    def __len__(self):
        return len(self.table)


@dataclass
class ColumnarHistory:
    """Struct-of-arrays history: the device-ready form.

    Columns are plain numpy; checkers move the slices they need to device.
    ``values`` keeps the original Python objects; workload-specific encoders
    (e.g. register read/write/cas int triples) build their own dense columns
    from them via :class:`Intern`.
    """

    types: np.ndarray        # int8, TYPE_CODE
    processes: np.ndarray    # int32, nemesis = -1
    fs: np.ndarray           # int32 into f_table
    times: np.ndarray        # int64 relative nanos
    indices: np.ndarray      # int32
    completion_of: np.ndarray  # int32, -1 if none
    invocation_of: np.ndarray  # int32, -1 if none
    f_table: list = field(default_factory=list)
    values: list = field(default_factory=list)
    ops: list = field(default_factory=list)  # original dicts (host-side)

    @classmethod
    def from_ops(cls, history: Sequence[dict]) -> "ColumnarHistory":
        history = list(history)
        n = len(history)
        f_intern = Intern()
        types = np.zeros(n, dtype=np.int8)
        processes = np.zeros(n, dtype=np.int32)
        fs = np.zeros(n, dtype=np.int32)
        times = np.zeros(n, dtype=np.int64)
        indices = np.arange(n, dtype=np.int32)
        values = []
        for i, o in enumerate(history):
            types[i] = TYPE_CODE.get(o.get("type"), 3)
            p = o.get("process")
            processes[i] = p if isinstance(p, int) else NEMESIS_PROCESS
            fs[i] = f_intern.id(o.get("f"))
            times[i] = o.get("time", 0) or 0
            idx = o.get("index")
            if idx is not None:
                indices[i] = idx
            values.append(o.get("value"))
        completion_of, invocation_of = pair_index(history)
        return cls(
            types=types, processes=processes, fs=fs, times=times,
            indices=indices, completion_of=completion_of,
            invocation_of=invocation_of, f_table=list(f_intern.table),
            values=values, ops=history,
        )

    def __len__(self) -> int:
        return len(self.types)

    def f_id(self, f) -> int:
        try:
            return self.f_table.index(f)
        except ValueError:
            return -1

    def mask_f(self, f) -> np.ndarray:
        return self.fs == self.f_id(f)

    @property
    def is_invoke(self) -> np.ndarray:
        return self.types == TYPE_CODE[INVOKE]

    @property
    def is_ok(self) -> np.ndarray:
        return self.types == TYPE_CODE[OK]

    @property
    def is_fail(self) -> np.ndarray:
        return self.types == TYPE_CODE[FAIL]

    @property
    def is_info(self) -> np.ndarray:
        return self.types == TYPE_CODE[INFO]
