"""Persistence: store/<name>/<timestamp>/ with logs, history, results.

Reference: jepsen/src/jepsen/store.clj. Layout mirrors :118-147 (path/path!),
save-1!/save-2! split (:388-413 — history persists *before* analysis so
checking is re-entrant), current/latest symlinks (:316-342), and logging
init (:431-451). Formats are JSON-lines for history and JSON for results
(the reference's fressian/edn become jsonl + an .npz columnar sidecar —
the EDN->numpy hop of BASELINE.json's north star is thereby free).
"""
from __future__ import annotations

import datetime
import json
import logging
import os
import shutil
from pathlib import Path
from typing import Any

logger = logging.getLogger("jepsen")

BASE_DIR = "store"

# Dropped before serialization (store.clj:160-168)
NONSERIALIZABLE_KEYS = {
    "db", "os", "net", "client", "checker", "nemesis", "generator", "model",
    "remote", "barrier", "tracer",
}

# Telemetry artifacts a run may leave next to history/results
# (see doc/observability.md): exported metrics, the span logs (the
# per-client trace.jsonl and the run-wide Perfetto trace.json), the
# live checker daemon's streaming verdict, and the jax.profiler trace
# dir.
TELEMETRY_FILES = ("metrics.prom", "metrics.json", "trace.jsonl",
                   "trace.json", "trace-derived.json",
                   "live-status.json")
PROFILE_DIR = "profile"

# Robustness forensics (doc/robustness.md): completions quarantined
# from reaped zombie workers, the stall watchdog's thread-stack dumps,
# the flight recorder's crash/stall dump (doc/observability.md "Causal
# trace"), and an interrupted check's durable checkpoint / the live
# daemon's restart snapshot (both cleared on completion — their
# PRESENCE marks an interrupted check/daemon). Present only when the
# run actually produced them.
FORENSIC_FILES = ("late.jsonl", "stall-threads.txt",
                  "flight-recorder.jsonl", "check.ckpt",
                  "live-session.ckpt")

# Anomaly forensics (doc/observability.md "Anomaly forensics"): the
# first-anomaly + minimal-witness artifact and its rendered timeline,
# written on INVALID verdicts (and by `jepsen-tpu explain`).
EXPLAIN_FILES = ("anomaly.json", "witness-timeline.html")


def _artifact_files(run_dir: Path, names) -> dict:
    """{artifact-name: Path} for whichever of ``names`` exist as files
    in a stored run directory (the shared probe behind each artifact
    family's helper)."""
    out: dict[str, Path] = {}
    for name in names:
        p = Path(run_dir) / name
        if p.is_file():
            out[name] = p
    return out


def telemetry_artifacts(run_dir: Path) -> dict:
    """{artifact-name: Path} for the telemetry files present in a stored
    run directory (the web UI links these alongside the classics)."""
    out = _artifact_files(run_dir, TELEMETRY_FILES)
    p = Path(run_dir) / PROFILE_DIR
    if p.is_dir():
        out[PROFILE_DIR] = p
    return out


def forensic_artifacts(run_dir: Path) -> dict:
    """{artifact-name: Path} for the robustness forensics present in a
    stored run directory (late.jsonl / stall-threads.txt)."""
    return _artifact_files(run_dir, FORENSIC_FILES)


def explain_artifacts(run_dir: Path) -> dict:
    """{artifact-name: Path} for the anomaly-forensics artifacts present
    in a stored run directory (anomaly.json / witness-timeline.html)."""
    return _artifact_files(run_dir, EXPLAIN_FILES)


def base_dir(test: dict) -> Path:
    return Path(test.get("store_dir", BASE_DIR))


def test_dir(test: dict) -> Path:
    return base_dir(test) / str(test.get("name", "noop")) / str(test["start_time"])


def path(test: dict, *components) -> Path:
    return test_dir(test).joinpath(*[str(c) for c in components])


def path_mk(test: dict, *components) -> Path:
    """path + mkdir -p of the parent (store.clj path!)."""
    p = path(test, *components)
    p.parent.mkdir(parents=True, exist_ok=True)
    return p


def _serializable(x: Any):
    if isinstance(x, dict):
        return {str(k): _serializable(v) for k, v in x.items()
                if not (isinstance(k, str) and k.startswith("_"))}
    if isinstance(x, (list, tuple)):
        return [_serializable(v) for v in x]
    if isinstance(x, (set, frozenset)):
        return sorted((_serializable(v) for v in x), key=repr)
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    if isinstance(x, Path):
        return str(x)
    import numpy as np
    if isinstance(x, np.generic):
        return x.item()
    return repr(x)


def serializable_test(test: dict) -> dict:
    return _serializable({
        k: v for k, v in test.items()
        if k not in NONSERIALIZABLE_KEYS and not str(k).startswith("_")
        and k not in ("history", "results")
    })


def write_history(test: dict) -> None:
    """history.jsonl: one op per line (store.clj:354-371). Also writes
    history.txt in the reference's human format."""
    from jepsen_tpu.utils import op2str
    history = test.get("history") or []
    with open(path_mk(test, "history.jsonl"), "w") as f:
        for op in history:
            f.write(json.dumps(_serializable(op)) + "\n")
    with open(path_mk(test, "history.txt"), "w") as f:
        for op in history:
            f.write(op2str(op) + "\n")


def first_client_f(history) -> str | None:
    """The first CLIENT op's ``:f`` — the cheap workload-shape probe
    shared by the columnar sidecar and offline forensics. Looks only at
    int-process ops: a nemesis op firing before the first client invoke
    must not mask the workload (the encoders themselves drop
    non-int-process ops)."""
    return next(
        (op.get("f") for op in history
         if isinstance(op.get("process"), int) and op.get("process") >= 0
         and op.get("f") is not None), None)


def write_columnar(test: dict) -> None:
    """history.npz: the serialized history IR, checker-ready (the
    EDN->numpy serialization of BASELINE's north star, built at save
    time). The sidecar is the IR's persistence format
    (jepsen_tpu.history_ir.sidecar): canonical packed columns + the
    value intern table, plus the derived view products — ``elle_*``
    Elle builder columns and ``lin_*`` register EventStream — so later
    re-checks run straight off arrays with no PyObject parse. Views are
    derived through the run's shared IR (``history_ir.of``), so a run
    whose checkers already encoded pays nothing extra here."""
    from jepsen_tpu import history_ir
    from jepsen_tpu.history_ir import sidecar
    history = test.get("history") or []
    if not history:
        return
    dh = history_ir.of(test, history)
    if dh is None:  # ir_enabled: False still persists a sidecar
        dh = history_ir.DeviceHistory.from_ops(history)
    sidecar.save(path_mk(test, "history.npz"), dh)


def load_columnar(test_name: str, timestamp: str, store_dir: str = BASE_DIR):
    """Reloads the .npz sidecar as a DeviceHistory (the history IR,
    sans Python op dicts — those live in history.jsonl). This is the
    restart format for checker jobs (SURVEY.md §5.4: analysis is
    re-entrant; the sidecar skips the jsonl parse + re-encoding on
    re-check). DeviceHistory subclasses the old ColumnarHistory return
    type, so existing callers are unaffected."""
    from jepsen_tpu.history_ir import sidecar
    p = path({"name": test_name, "start_time": timestamp,
              "store_dir": store_dir}, "history.npz")
    return sidecar.load(p)


def note_sidecar_load_failure(what: str, exc: BaseException | None = None) -> None:
    """A corrupt/unreadable history.npz sidecar fell back to the jsonl
    history: log it and bump ``store_sidecar_load_failures_total`` so
    the fallback is visible in telemetry instead of silent (the
    pre-IR code swallowed these bare)."""
    logger.warning("history.npz sidecar unreadable for %s (%r); "
                   "falling back to history.jsonl", what, exc)
    try:
        from jepsen_tpu import telemetry
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.counter(
                "store_sidecar_load_failures_total",
                "corrupt/unreadable history.npz sidecars that fell "
                "back to the jsonl history").inc()
    except Exception:  # noqa: BLE001 — telemetry never blocks a fallback
        logger.exception("sidecar-failure telemetry recording failed")


def _load_prefixed(test_name: str, timestamp: str, store_dir: str,
                   prefix: str, probe_key: str) -> dict | None:
    import numpy as np
    p = path({"name": test_name, "start_time": timestamp,
              "store_dir": store_dir}, "history.npz")
    with np.load(p, allow_pickle=True) as z:
        if probe_key not in z:
            return None
        return {k[len(prefix):]: z[k] for k in z.files
                if k.startswith(prefix)}


def load_elle_columns(test_name: str, timestamp: str,
                      store_dir: str = BASE_DIR) -> dict | None:
    """The stored Elle builder columns (``elle_*`` in history.npz), or
    None when the run predates them / the history wasn't storable."""
    return _load_prefixed(test_name, timestamp, store_dir, "elle_",
                          "elle_n_ok")


def load_linear_columns(test_name: str, timestamp: str,
                        store_dir: str = BASE_DIR) -> dict | None:
    """The stored register EventStream columns (``lin_*``), or None."""
    return _load_prefixed(test_name, timestamp, store_dir, "lin_",
                          "lin_n_slots")


def write_results(test: dict) -> None:
    with open(path_mk(test, "results.json"), "w") as f:
        json.dump(_serializable(test.get("results")), f, indent=2)


def write_test(test: dict) -> None:
    with open(path_mk(test, "test.json"), "w") as f:
        json.dump(serializable_test(test), f, indent=2, default=repr)


def save_1(test: dict) -> dict:
    """Post-run save: history + test map, before analysis
    (store.clj:388-399, core.clj:395)."""
    write_history(test)
    write_columnar(test)
    write_test(test)
    update_symlinks(test)
    return test


def save_2(test: dict) -> dict:
    """Post-analysis save: results + rewrite test (store.clj:401-413)."""
    write_results(test)
    write_test(test)
    update_symlinks(test)
    return test


def update_symlinks(test: dict) -> None:
    """store/<name>/latest and store/current (store.clj:316-342)."""
    d = test_dir(test)
    for link in [base_dir(test) / str(test.get("name", "noop")) / "latest",
                 base_dir(test) / "current"]:
        try:
            link.parent.mkdir(parents=True, exist_ok=True)
            if link.is_symlink() or link.exists():
                link.unlink()
            link.symlink_to(d.resolve())
        except OSError:
            logger.debug("couldn't update symlink %s", link)


def load_results(test_name: str, timestamp: str, store_dir: str = BASE_DIR) -> dict:
    with open(Path(store_dir) / test_name / timestamp / "results.json") as f:
        return json.load(f)


def load_history(test_name: str, timestamp: str, store_dir: str = BASE_DIR) -> list[dict]:
    """Reads history.jsonl, tolerating the torn final line a crash (or a
    disk-full save) can leave — a truncated tail is dropped with a
    warning instead of raising json.JSONDecodeError, so re-analysis of
    a damaged run still sees every complete op."""
    from jepsen_tpu.journal import read_jsonl_tolerant
    p = Path(store_dir) / test_name / timestamp / "history.jsonl"
    ops, truncated = read_jsonl_tolerant(p)
    if truncated:
        logger.warning("history.jsonl at %s has a torn final line; "
                       "dropped it", p)
    return ops


# the name the recovery tooling uses (doc/robustness.md); same reader
read_history = load_history


def load_test(test_name: str, timestamp: str, store_dir: str = BASE_DIR) -> dict:
    d = Path(store_dir) / test_name / timestamp
    with open(d / "test.json") as f:
        test = json.load(f)
    try:
        test["history"] = load_history(test_name, timestamp, store_dir)
    except FileNotFoundError:
        pass
    try:
        test["results"] = load_results(test_name, timestamp, store_dir)
    except FileNotFoundError:
        pass
    return test


def tests(test_name: str | None = None, store_dir: str = BASE_DIR) -> dict:
    """{name: {timestamp: path}} (store.clj:284-303)."""
    base = Path(store_dir)
    out: dict = {}
    if not base.exists():
        return out
    names = [test_name] if test_name else [p.name for p in base.iterdir()
                                           if p.is_dir() and p.name != "current"]
    for name in names:
        d = base / name
        if not d.is_dir():
            continue
        out[name] = {p.name: p for p in sorted(d.iterdir())
                     if p.is_dir() and p.name != "latest" and not p.is_symlink()}
    return out


def latest(store_dir: str = BASE_DIR):
    """Most recent test dir across all names (store.clj:305-314)."""
    best = None
    for name, runs in tests(store_dir=store_dir).items():
        for ts, p in runs.items():
            if best is None or ts > best[1]:
                best = (name, ts, p)
    return best


def delete(test_name: str | None = None, store_dir: str = BASE_DIR) -> None:
    """Deletes stored runs (store.clj:461-478)."""
    base = Path(store_dir)
    target = base / test_name if test_name else base
    if target.exists():
        shutil.rmtree(target)


def start_time() -> str:
    return datetime.datetime.now().strftime("%Y%m%dT%H%M%S.%f")[:-3]


_log_handler: dict = {}


def start_logging(test: dict) -> None:
    """Per-test jepsen.log file appender + console (store.clj:431-451)."""
    stop_logging()
    root = logging.getLogger("jepsen")
    root.setLevel(logging.INFO)
    if not any(isinstance(h, logging.StreamHandler) for h in root.handlers):
        sh = logging.StreamHandler()
        sh.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s [%(threadName)s] %(name)s: %(message)s"))
        root.addHandler(sh)
    fh = logging.FileHandler(path_mk(test, "jepsen.log"))
    fh.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s [%(threadName)s] %(name)s: %(message)s"))
    root.addHandler(fh)
    _log_handler["fh"] = fh


def stop_logging() -> None:
    fh = _log_handler.pop("fh", None)
    if fh is not None:
        logging.getLogger("jepsen").removeHandler(fh)
        fh.close()
