"""Device kernels (JAX/XLA) for the compute-bound checker cores.

These replace the reference's JVM-hosted hot loops (knossos linear/wgl
search, elle graph algorithms — SURVEY.md §2.5 "JVM-hosted hot kernels")
with batched fixed-shape tensor programs:

* jitlin — just-in-time linearization as a lax.scan over history events,
  frontier-of-configurations as (bitmask, state) arrays, sort-based dedup.
* scc — strongly-connected components / cycle detection via iterative label
  propagation over edge lists (the Elle dependency-graph core).
"""
