"""Batched set-full analysis kernel (BASELINE config 4).

The reference's set-full checker (jepsen/src/jepsen/checker.clj:294-592)
walks a per-element state machine over every read. Here the whole
history becomes one dense boolean *membership matrix* ``member[R, E]``
(reads x interned elements) plus three time vectors, and every
element's verdict — stable / lost / never-read, plus stale-read
detection and stable-visibility latency — is a handful of masked
row-reductions over the matrix, computed for all elements at once on
device. Rows are the TPU-friendly axis: R and E are padded to bucketed
shapes so XLA caches one program per bucket, and the element axis can
be sharded over a mesh (each shard reduces its own columns; no
cross-device traffic).

Verdict codes: 0 = stable, 1 = lost, 2 = never-read.
"""
from __future__ import annotations

import threading
import time

import numpy as np

STABLE, LOST, NEVER_READ = 0, 1, 2

_NEG = np.float32(-3.4e38)
_POS = np.float32(3.4e38)

# Kernel-only wall time of the calling thread's most recent
# classify_elements call (dispatch + readback, excluding the host
# history parse) — bench.py reads this so the hbm_frac roofline
# fraction divides bytes moved by the DEVICE time, not the whole
# checker stage. Thread-local: concurrent checkers must not read each
# other's timing.
_LAST = threading.local()


def last_kernel_seconds() -> float:
    return getattr(_LAST, "value", 0.0)


def modeled_bytes(n_reads: int, n_elements: int) -> int:
    """Bytes-moved model for one classify_elements dispatch — the
    denominator side of the membership kernel's ``hbm_frac`` roofline
    accounting (VERDICT r5 weak #3: the 3.49x ratio carried no evidence
    of whether it was near the memory-bound ceiling).

    The kernel is elementwise/reduction-only (no matmuls), so its
    ceiling is HBM bandwidth over the [R, E] matrix passes. Counted per
    padded cell (Rb x Eb, the shapes actually dispatched):

    * packed H2D transfer (1/8 B) + the bit-unpack write (1 B)
    * four bool-matrix reads: the masked member uses in m, later,
      lp, la (4 B)
    * seen_t f32 write + read for the min-reduce (8 B)
    * the ``later`` mask write + its three reads (4 B)
    * lp and la: each a where-select write + max-reduce read (16 B)

    ~33 B/cell total. A LOWER bound — XLA may materialize more
    intermediates, never fewer passes than the dataflow needs — so the
    reported fraction is conservative: a fraction near 1 proves
    memory-bound; a small fraction proves headroom."""
    Rb, Eb = _bucketed(max(n_reads, 1)), _bucketed(max(n_elements, 1))
    cells = Rb * Eb
    per_cell = 0.125 + 1 + 4 + 8 + 4 + 16
    return int(cells * per_cell)


def _build_classify(R: int, E: int):
    import jax.numpy as jnp

    def classify(member, t_read, read_valid, invoke_t, ok_t, has_ok, el_valid):
        """member: bool[R, E]; t_read: f32[R]; read_valid: bool[R];
        invoke_t/ok_t: f32[E]; has_ok/el_valid: bool[E].

        Returns (code i32[E], stale bool[E], latency f32[E]) — latency is
        meaningful only where code == STABLE.
        """
        m = member & read_valid[:, None]                      # [R, E]
        seen_t = jnp.where(m, t_read[:, None], _POS)
        first_seen = seen_t.min(axis=0)                       # +inf if never
        # known time: add-ok time, else first sighting
        known = jnp.where(has_ok, ok_t, first_seen)           # [E]
        never_known = known >= _POS

        later = read_valid[:, None] & (t_read[:, None] >= known[None, :])
        any_later = later.any(axis=0)

        lp = jnp.where(later & member, t_read[:, None], _NEG).max(axis=0)
        la = jnp.where(later & ~member, t_read[:, None], _NEG).max(axis=0)
        has_present = lp > _NEG
        has_absent = la > _NEG

        lost = has_absent & (~has_present | (la > lp))
        never_read = never_known | ~any_later
        code = jnp.where(never_read, NEVER_READ,
                         jnp.where(lost, LOST, STABLE)).astype(jnp.int32)
        # stale: absent after known, but present again later (only
        # meaningful for stable elements)
        stale = (code == STABLE) & has_absent
        stable_from = jnp.where(has_absent, la, known)
        latency = jnp.maximum(0.0, stable_from - invoke_t)
        code = jnp.where(el_valid, code, NEVER_READ)
        return code, stale & el_valid, latency

    return classify


_JIT_CACHE: dict = {}


def _bucketed(n: int, floor: int = 64) -> int:
    from jepsen_tpu.ops.jitlin import _bucket
    return _bucket(n, floor=floor)


def classify_elements(member: np.ndarray, t_read: np.ndarray,
                      invoke_t: np.ndarray, ok_t: np.ndarray,
                      has_ok: np.ndarray):
    """Pads to bucketed [R, E] shapes and runs the device kernel.
    Returns (code[E], stale[E], latency[E]) numpy arrays."""
    import jax
    import jax.numpy as jnp

    R, E = member.shape
    Rb, Eb = _bucketed(max(R, 1)), _bucketed(max(E, 1))
    key = (Rb, Eb)
    fn = _JIT_CACHE.get(key)
    if fn is None:
        base = _build_classify(Rb, Eb)

        def unpack_and_classify(packed, *rest):
            # bit-unpack on device: the [R, E] membership matrix ships
            # as uint8 bits (8x less host->device traffic — the matrix
            # is the whole transfer cost on tunnel-attached devices)
            bits = (packed[:, :, None]
                    >> jnp.arange(8, dtype=jnp.uint8)) & 1
            m = bits.reshape(Rb, -1)[:, :Eb].astype(bool)
            return base(m, *rest)

        fn = jax.jit(unpack_and_classify)
        _JIT_CACHE[key] = fn

    mem = np.zeros((Rb, Eb), dtype=bool)
    mem[:R, :E] = member
    mem = np.packbits(mem, axis=1, bitorder="little")
    tr = np.full((Rb,), _POS, dtype=np.float32)
    tr[:R] = t_read
    rv = np.zeros((Rb,), dtype=bool)
    rv[:R] = True
    iv = np.zeros((Eb,), dtype=np.float32)
    iv[:E] = invoke_t
    okt = np.full((Eb,), _POS, dtype=np.float32)
    okt[:E] = ok_t
    hok = np.zeros((Eb,), dtype=bool)
    hok[:E] = has_ok
    ev = np.zeros((Eb,), dtype=bool)
    ev[:E] = True

    t0 = time.perf_counter()
    code, stale, latency = fn(jnp.asarray(mem), jnp.asarray(tr),
                              jnp.asarray(rv), jnp.asarray(iv),
                              jnp.asarray(okt), jnp.asarray(hok),
                              jnp.asarray(ev))
    # one batched host transfer (three sequential syncs would pay a
    # tunnel round-trip each)
    code, stale, latency = jax.device_get((code, stale, latency))
    _LAST.value = time.perf_counter() - t0
    return code[:E], stale[:E], latency[:E]
