"""Pallas TPU kernel for the transfer-matrix chunk product.

The block-composed matrix kernel (ops/jitlin.py _build_matrix_kernel,
the TPU analog of knossos's wgl search — checker.clj:185-216) advances
every chunk's composed operator by one return per ``lax.scan`` step.
Under XLA each step materializes ~6 [G, MV, MV] intermediates in HBM
(L build, I+L, the closure squarings, the kill product, the compose),
and on long histories (the scale path's ~2k-step segments) that HBM
round-trip traffic — not the matmul FLOPs — bounds the step.

This kernel fuses the ENTIRE T-step product per chunk: one pallas
program per chunk g keeps its running product P in a VMEM scratch
buffer across all T returns and only writes the final [MV, MV] chunk
product to HBM. Per-step HBM traffic drops from ~6 full [G, MV, MV]
arrays to zero.

The L build is re-formulated to be layout-friendly (no [M, V, M, V]
reshapes, which relayout badly on TPU tiles):

    L = sum_s pend_s * (R_s (kron) Mt_s^T)
      = sum_s pend_s * Rexp_s * (U1 @ Mt_s^T @ U2)

where ``Rexp_s[(a,w),(b,v)] = R_s[a,b]`` is a STATIC [MV, MV]
block-expansion of the slot-s receiver map, and ``U1 @ X @ U2`` tiles a
[V, V] matrix over every (a, b) block — two tiny matmuls plus one VPU
elementwise multiply, instead of a Kronecker construction. The kill
gather becomes a matmul with a static per-slot kill matrix
``Kexp_s[r, kill_idx_s[r]] = kill_mask_s[r]``. Products accumulate in
f32 (counts <= MV <= 2^12 are exact) and threshold back to 0/1, so the
boolean-semiring result is bit-identical to the XLA path — the
differential tests in tests/test_pallas_matrix.py pin that. Two
data-dependent skips ride ``lax.cond``: closure squarings a step's
pending-op count can't use, and whole padding steps (valid=0), which
compose the identity.

``chunk_product`` returns a jitted callable or None when the regime
doesn't fit (VMEM budget, dtype caps) or pallas lowering fails on this
backend — callers fall back to the XLA scan path.
"""
from __future__ import annotations

import functools
import logging
import os

import numpy as np

logger = logging.getLogger("jepsen.pallas")

# VMEM budget gate: the two static [S, MV, MV] tables plus ~4 [MV, MV]
# scratch/working buffers must fit comfortably; MV <= 512 and S <= 8
# keeps the residents under ~8 MB
PALLAS_MAX_MV = 512
PALLAS_MAX_SLOTS = 8

# L-build pre-tiling budget: when the whole [U, MV, MV] pre-tiled uop
# table fits this many bytes of VMEM alongside the static tables, the
# per-step U1 @ Mt^T @ U2 tiling dots move OFF the critical path — they
# run once in XLA before the pallas program instead of 2*S heavily
# padded [MV, V] x [V, V] MXU dots per step (V is ~8-16 in the matrix
# regime: those dots under-tile the 128-lane MXU badly, so their cost
# is far above their FLOP share).
PALLAS_PRETILE_BYTES = 4 << 20


def available() -> bool:
    """Pallas path enabled? (env kill-switch for triage)."""
    return not os.environ.get("JEPSEN_TPU_NO_PALLAS")


def _static_tables(S: int, V: int):
    """Host-side static operator tables for (S, V), expanded from the
    SAME receiver/kill constructor the XLA scan path uses
    (jitlin.receiver_kill_tables — one source of truth, so the two
    kernels' bit-identical-verdict guarantee can't drift):

    - Rexp [S, MV, MV]: receiver map R_s block-expanded (R_s[a,b]
      broadcast over the V*V cells of each (a,b) block)
    - Kexp [S, MV, MV]: the closure-then-kill row gather+mask as a
      matrix (A = Kexp_s @ B  ==  B rows gathered at kill_idx_s, masked)
    - U1 [MV, V], U2 [V, MV]: the tiling maps (U1 @ X @ U2 repeats a
      [V, V] X over every block)
    """
    from jepsen_tpu.ops.jitlin import receiver_kill_tables

    M = 1 << S
    MV = M * V
    rows = np.arange(MV)
    ww = rows % V
    receiver, kill_idx, kill_mask = receiver_kill_tables(S, V)

    Rexp = np.stack([receiver[t][rows // V][:, rows // V]
                     for t in range(S)]).astype(np.float32)
    Kexp = np.zeros((S, MV, MV), np.float32)
    for s in range(S):
        Kexp[s, rows, kill_idx[s]] = kill_mask[s]

    U1 = np.zeros((MV, V), np.float32)
    U1[rows, ww] = 1.0
    U2 = np.zeros((V, MV), np.float32)
    U2[ww, rows] = 1.0
    return Rexp, Kexp, U1, U2


@functools.lru_cache(maxsize=16)
def _build(S: int, V: int, T: int, U: int, interpret: bool = False,
           pretile: bool = False):
    """Compile-cached pallas chunk-product for static shapes.

    Returns fn(pend [T,G,S] f32, ids [T,G,S] i32, mtT [U,V,V] f32,
    slots [T,G] i32, valid [T,G] f32) -> P [G, MV, MV] bf16 — the
    per-chunk composed operator product over its T returns.

    With ``pretile`` the [U, MV, MV] tiled uop table U1 @ Mt_u^T @ U2 is
    precomputed ONCE in XLA before the pallas program (exact: tiling
    repeats Mt's cells, no accumulation), and the kernel's L build
    becomes a gather + VPU multiply — the per-step under-tiled [MV, V]
    dots leave the critical path entirely.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M = 1 << S
    MV = M * V
    n_sq = 0
    while (1 << n_sq) < S:
        n_sq += 1
    # f32 throughout: measured FASTER than bf16 on this kernel (both
    # all-bf16 and mixed variants lost ~25% — the bf16 (16, 128) tile
    # shape slows the per-step thresholds/selects more than the MXU
    # rate buys at MV=256).
    # The tables stay NUMPY here: _build is lru_cached and its first
    # call may run inside an active jit trace (chunk_product is invoked
    # while scan_total_pallas traces), where jnp.asarray would yield
    # that trace's tracers — cached into the closure, they leak into
    # every later trace sharing the (S, V, T, U) key and kill the
    # pallas path with UnexpectedTracerError (surfaced by the real-TPU
    # parity tier once the chunk retune multiplied the shape keys).
    # grid_fn stages them per trace instead.
    Rexp, Kexp, U1, U2 = _static_tables(S, V)

    def kernel(pend_ref, ids_ref, mtT_ref, slot_ref, val_ref,
               rexp_ref, kexp_ref, u1_ref, u2_ref, out_ref):
        eye = (jax.lax.broadcasted_iota(jnp.int32, (MV, MV), 0)
               == jax.lax.broadcasted_iota(jnp.int32, (MV, MV), 1)
               ).astype(jnp.float32)

        def bool_mm(x, y):
            # f32 0/1 inputs and accumulation: exact (a positive count
            # can't round to zero), and the measured-fastest dtype here
            return (jnp.dot(x, y, preferred_element_type=jnp.float32)
                    > 0).astype(jnp.float32)

        def step(t, P):
            # padding rows (valid=0) compose the identity: skip outright
            return lax.cond(val_ref[0, t, 0] > 0, _live_step,
                            lambda tt, PP: PP, t, P)

        def _live_step(t, P):
            # L = sum_s pend[t,s] * Rexp_s * tile(Mt_s^T)
            L = jnp.zeros((MV, MV), jnp.float32)
            for s in range(S):
                idx = ids_ref[0, t, s]
                if pretile:
                    # mtT_ref holds the pre-tiled [U, MV, MV] table:
                    # pure gather + VPU multiply, no per-step dots
                    tile = mtT_ref[pl.dslice(idx, 1), :, :][0]
                else:
                    mtT = mtT_ref[pl.dslice(idx, 1), :, :][0]   # [V, V]
                    tile = jnp.dot(
                        jnp.dot(u1_ref[...], mtT,
                                preferred_element_type=jnp.float32),
                        u2_ref[...], preferred_element_type=jnp.float32)
                L = L + pend_ref[0, t, s] * rexp_ref[s] * tile
            Bm = ((L + eye) > 0).astype(jnp.float32)
            # closure saturates once the exponent reaches the number of
            # pending ops (each linearization consumes one), so skip
            # squarings a sparse step can't use
            npend = jnp.sum(pend_ref[0, t, :])
            for _i in range(n_sq):
                Bm = lax.cond(npend > (1 << _i),
                              lambda B: bool_mm(B, B),
                              lambda B: B, Bm)   # (I+L)^(2^k) -> closure
            ks = kexp_ref[pl.dslice(slot_ref[0, t, 0], 1), :, :][0]
            A = bool_mm(ks, Bm)                  # closure-then-kill
            return bool_mm(A, P)

        P = lax.fori_loop(0, T, step, eye)
        out_ref[0] = P.astype(jnp.bfloat16)

    def grid_fn(pend, ids, mtT, slots, valid):
        # grids arrive [G, T, S] / [G, T, 1]: blocking only on the
        # leading grid axis keeps every block's trailing dims equal to
        # the array's — the Mosaic block-shape rule (trailing two dims
        # divisible by (8, 128) or equal to the array's)
        G = pend.shape[0]
        full = lambda shape: pl.BlockSpec(
            shape, lambda g: (0,) * len(shape), memory_space=pltpu.VMEM)
        if pretile:
            # off-critical-path L-build: tile every uop's Mt^T over the
            # (a, b) blocks once, in XLA (each output cell copies ONE
            # Mt cell — exact, no accumulation)
            mt_in = jnp.einsum("iv,uvw,wj->uij", jnp.asarray(U1), mtT,
                               jnp.asarray(U2))
            mt_spec = full((U, MV, MV))
        else:
            mt_in = mtT
            mt_spec = full((U, V, V))
        return pl.pallas_call(
            kernel,
            grid=(G,),
            in_specs=[
                pl.BlockSpec((1, T, S), lambda g: (g, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, T, S), lambda g: (g, 0, 0),
                             memory_space=pltpu.VMEM),
                mt_spec,
                pl.BlockSpec((1, T, 1), lambda g: (g, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, T, 1), lambda g: (g, 0, 0),
                             memory_space=pltpu.VMEM),
                full((S, MV, MV)),
                full((S, MV, MV)),
                full((MV, V)),
                full((V, MV)),
            ],
            out_specs=pl.BlockSpec((1, MV, MV), lambda g: (g, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((G, MV, MV), jnp.bfloat16),
            interpret=interpret,
        )(pend, ids, mt_in, slots, valid,
          jnp.asarray(Rexp), jnp.asarray(Kexp),
          jnp.asarray(U1), jnp.asarray(U2))

    @jax.jit
    def run(pend, ids, mtT, slots, valid):
        """Accepts the scan-path layout (pend/ids [T, G, S], slots/valid
        [T, G]) and relayouts on device to the kernel's [G, T, ...]."""
        return grid_fn(
            jnp.transpose(pend.astype(jnp.float32), (1, 0, 2)),
            jnp.transpose(ids.astype(jnp.int32), (1, 0, 2)),
            mtT.astype(jnp.float32),
            jnp.transpose(slots.astype(jnp.int32), (1, 0))[..., None],
            jnp.transpose(valid.astype(jnp.float32), (1, 0))[..., None])

    return run


# tests set True to exercise the kernel on CPU through the production
# dispatch (pallas interpret mode); never set in production
FORCE_INTERPRET = False


def _pretile_ok(S: int, V: int, U: int) -> bool:
    MV = (1 << S) * V
    return U * MV * MV * 4 <= PALLAS_PRETILE_BYTES


def chunk_product(S: int, V: int, T: int, U: int,
                  interpret: bool | None = None):
    """The compiled kernel for these static shapes, or None when out of
    the pallas regime. Lowering/compile failures are reported by the
    first actual call — use ``enabled`` for an upfront check."""
    MV = (1 << S) * V
    if not available() or S > PALLAS_MAX_SLOTS or MV > PALLAS_MAX_MV:
        return None
    return _build(S, V, T, U,
                  FORCE_INTERPRET if interpret is None else interpret,
                  _pretile_ok(S, V, U))


_PROBED: dict = {}
_DISABLED: set = set()


def _oracle_product(S, V, pend, ids, mtT, slots, valid):
    """Numpy replay of the factored chunk product — the probe's and the
    tests' independent reference."""
    MV = (1 << S) * V
    T, G = slots.shape
    Rexp, Kexp, U1, U2 = _static_tables(S, V)
    eye = np.eye(MV, dtype=np.float32)
    n_sq = 0
    while (1 << n_sq) < S:
        n_sq += 1
    P = np.broadcast_to(eye, (G, MV, MV)).copy()
    for t in range(T):
        for g in range(G):
            L = np.zeros((MV, MV), np.float32)
            for s in range(S):
                L += (pend[t, g, s]
                      * Rexp[s] * (U1 @ mtT[ids[t, g, s]] @ U2))
            Bm = ((L + eye) > 0).astype(np.float32)
            for _ in range(n_sq):
                Bm = ((Bm @ Bm) > 0).astype(np.float32)
            A = ((Kexp[slots[t, g]] @ Bm) > 0).astype(np.float32)
            if not valid[t, g]:
                A = eye
            P[g] = ((A @ P[g]) > 0).astype(np.float32)
    return P


def enabled(S: int, V: int) -> bool:
    """Should the matrix kernel take the pallas path for (S, V)?
    Gates on the env switch and VMEM caps, then memoizes a small RANDOM
    end-to-end run checked bit-for-bit against the numpy oracle — so a
    backend that fails to lower (CPU) OR miscompiles the kernel
    disables itself and the XLA scan path takes over."""
    MV = (1 << S) * V
    if not available() or S > PALLAS_MAX_SLOTS or MV > PALLAS_MAX_MV:
        return False
    key = (S, V)
    # a disable() (runtime failure) sticks even under FORCE_INTERPRET —
    # otherwise a failing interpret-mode kernel would retrace and fail
    # on every dispatch. It is tracked apart from probe results: a
    # CPU probe failure (no pallas backend) must NOT poison forced
    # interpret-mode runs, which don't need one.
    if key in _DISABLED:
        return False
    if FORCE_INTERPRET:
        return True
    if key in _PROBED:
        return _PROBED[key]
    ok = False
    try:
        # T=256 puts the probe in the production tiling regime: T is a
        # trailing block dimension, so a tiny T (the old 3) compiled a
        # differently-padded Mosaic program than the ~1-2k-row chunks
        # production dispatches — a shape-dependent miscompile there
        # would have slipped past the probe. 256 crosses the sublane
        # tile boundary like production T does while keeping the
        # bit-for-bit numpy oracle (T*G matrix products) sub-second;
        # residual caveat: the probe's U=16 uop table is still smaller
        # than production's.
        T, U, G = 256, 16, 2
        rng = np.random.default_rng(0)
        pend = (rng.random((T, G, S)) < 0.5).astype(np.float32)
        ids = rng.integers(0, U, (T, G, S)).astype(np.int32)
        mtT = (rng.random((U, V, V)) < 0.3).astype(np.float32)
        slots = rng.integers(0, S, (T, G)).astype(np.int32)
        valid = (rng.random((T, G)) < 0.8).astype(np.float32)
        # probe the same pretile variant production dispatches at this
        # U — the two kernels differ in their L-build data path
        fn = _build(S, V, T, U, False, _pretile_ok(S, V, U))
        got = np.asarray(fn(pend, ids, mtT, slots, valid),
                         dtype=np.float32)
        ref = _oracle_product(S, V, pend, ids, mtT, slots, valid)
        ok = np.array_equal(got, ref)
        if not ok:
            logger.warning("pallas matrix kernel MISCOMPILES on this "
                           "backend (probe mismatch at S=%d V=%d); "
                           "using the XLA scan path", S, V)
    except Exception as e:  # noqa: BLE001 — any lowering failure
        logger.warning("pallas matrix kernel unavailable: %s", e)
    _PROBED[key] = ok
    return ok


def disable(S: int, V: int) -> None:
    """Permanently (for this process) route (S, V) to the XLA scan path
    — called by the dispatcher after a runtime failure. Unlike a probe
    miss, this also sticks under FORCE_INTERPRET."""
    _DISABLED.add((S, V))
