"""Pallas TPU kernels for the transfer-matrix chunk product.

The block-composed matrix kernel (ops/jitlin.py _build_matrix_kernel,
the TPU analog of knossos's wgl search — checker.clj:185-216) advances
every chunk's composed operator by one return per ``lax.scan`` step.
Under XLA each step materializes ~6 [G, MV, MV] intermediates in HBM
(L build, I+L, the closure squarings, the kill product, the compose),
and on long histories (the scale path's ~2k-step segments) that HBM
round-trip traffic — not the matmul FLOPs — bounds the step.

This kernel fuses the ENTIRE T-step product per chunk: one pallas
program per chunk g keeps its running product P in a VMEM scratch
buffer across all T returns and only writes the final [MV, MV] chunk
product to HBM. Per-step HBM traffic drops from ~6 full [G, MV, MV]
arrays to zero.

Matrix representation VARIANTS
------------------------------
Every matrix in this algebra is a boolean reachability operator — all
entries are exactly 0 or 1 and every product is thresholded back to
0/1. Doing that work as f32 matmuls wastes the hardware: the MXU
multiplies 32-bit mantissas to compute what is semantically AND/OR.
Three probe-selected representations close that gap (BENCH_r05:
``roofline_frac 0.176`` — ~80 % of the chip idle on the hottest path):

* ``f32``    — the compatibility baseline: f32 0/1 operands, f32
  accumulation, ``> 0`` threshold. Bit-exact and universally lowerable;
  the terminal fallback when the integer paths miscompile. (Naive bf16
  was measured ~25 % SLOWER here — the (16, 128) bf16 tile shape slows
  the per-step thresholds more than the MXU rate buys at MV = 256 — so
  the win has to come from operand density, not a float dtype swap.)
* ``int8``   — int8 0/1 operands through the MXU with
  ``preferred_element_type=jnp.int32`` (counts ≤ MV ≤ 2^12 are exact in
  int32), saturating ``> 0`` threshold back to int8. 4× the effective
  operand density of f32 on MXU generations with int8 feeds.
* ``packed`` — bit-packed boolean algebra: rows pack 32 entries per
  uint32 word and the product C[i,j] = OR_k A[i,k] AND B[k,j] becomes
  word-wise AND + any-nonzero over MV/32 words (the popcount>0 test of
  an AND/popcount semiring). 32× the operand density; runs on the VPU,
  so it wins where the MXU under-tiles (small MV) and is capped at
  MV ≤ PALLAS_PACKED_MAX_MV by its [MV, MV, MV/32] AND intermediate.

All variants compute the same thresholded 0/1 matrices, so results are
bit-identical to the numpy oracle and the XLA scan path — each
(S, V, variant) admits itself through the same end-to-end probe, and a
variant that fails to lower or miscompiles demotes to the next one
(PR-3 ladder semantics), never to a wrong verdict.

The L build is re-formulated to be layout-friendly (no [M, V, M, V]
reshapes, which relayout badly on TPU tiles):

    L = sum_s pend_s * (R_s (kron) Mt_s^T)
      = sum_s pend_s * Rexp_s * (U1 @ Mt_s^T @ U2)

where ``Rexp_s[(a,w),(b,v)] = R_s[a,b]`` is a STATIC [MV, MV]
block-expansion of the slot-s receiver map, and ``U1 @ X @ U2`` tiles a
[V, V] matrix over every (a, b) block — two tiny matmuls plus one VPU
elementwise multiply, instead of a Kronecker construction. The kill
gather becomes a matmul with a static per-slot kill matrix
``Kexp_s[r, kill_idx_s[r]] = kill_mask_s[r]``.

Pre-tiled L-build modes (``_pretile_mode``): with ``vmem`` the
[U, MV, MV] tiled uop table U1 @ Mt_u^T @ U2 is precomputed ONCE in XLA
and resides in VMEM (gather + VPU multiply per step, no in-kernel
dots); with ``hbm`` the same table is too big for VMEM but lives in
HBM and the per-step tiles stream in through a double-buffered DMA
pipeline (step t's closure compute overlaps step t+1's tile fetches) —
large value domains no longer fall back to the slow in-kernel L
construction. The integer variants store the table at 1 byte/entry,
which by itself extends the VMEM budget 4× over f32.

``chunk_product`` returns a jitted callable or None when the regime
doesn't fit (VMEM budget, dtype caps) or pallas lowering fails on this
backend — callers fall back to the XLA scan path.

Probe caching: the per-(S, V, variant) self-test verdicts persist in a
store-side sidecar (fs_cache) keyed by backend + jax version, so fresh
processes stop re-paying probe compiles; ``JEPSEN_TPU_PALLAS_PROBE=
force`` re-probes (and re-writes the sidecar), ``skip`` trusts the
shape gates without probing. ``probe_seconds()`` exposes this process's
cumulative probe wall (also the ``pallas_probe_seconds_total``
counter), so probe time stops hiding inside first-check compile time.
"""
from __future__ import annotations

import functools
import logging
import os
import time

import numpy as np

logger = logging.getLogger("jepsen.pallas")

# VMEM budget gate: the two static [S, MV, MV] tables plus ~4 [MV, MV]
# scratch/working buffers must fit comfortably; MV <= 512 and S <= 8
# keeps the residents under ~8 MB
PALLAS_MAX_MV = 512
PALLAS_MAX_SLOTS = 8

# packed variant cap: its AND step materializes a [MV, MV, MV/32]
# uint32 intermediate in VMEM (2 MB at MV=256, 16 MB at MV=512)
PALLAS_PACKED_MAX_MV = 256

# L-build pre-tiling budget: when the whole [U, MV, MV] pre-tiled uop
# table fits this many bytes of VMEM alongside the static tables, the
# per-step U1 @ Mt^T @ U2 tiling dots move OFF the critical path — they
# run once in XLA before the pallas program instead of 2*S heavily
# padded [MV, V] x [V, V] MXU dots per step (V is ~8-16 in the matrix
# regime: those dots under-tile the 128-lane MXU badly, so their cost
# is far above their FLOP share). Integer variants count 1 byte/entry.
PALLAS_PRETILE_BYTES = 4 << 20
# ... and past the VMEM budget the table stays in HBM and the per-step
# tiles stream in via double-buffered DMA (mode "hbm") up to this cap
PALLAS_PRETILE_HBM_BYTES = 128 << 20

#: auto-probe preference order: densest representation first; each
#: candidate must pass its (S, V, variant) differential probe before
#: taking a production dispatch, and a runtime failure demotes to the
#: next (jitlin._dispatch_total's variant loop)
VARIANTS = ("packed", "int8", "f32")


def available() -> bool:
    """Pallas path enabled? (env kill-switch for triage)."""
    return not os.environ.get("JEPSEN_TPU_NO_PALLAS")


_ENV_WARNED: set = set()


def _env_choice(name: str, choices: tuple, default: str) -> str:
    """Tolerant env enum knob: unset/empty -> default, a valid choice
    passes, garbage warns ONCE per distinct value and degrades to the
    default (these knobs are re-read on every matrix dispatch — a bad
    sweep variable must neither make the module unusable nor flood the
    log of a segmented run)."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    v = raw.strip().lower()
    if v in choices:
        return v
    if (name, raw) not in _ENV_WARNED:
        _ENV_WARNED.add((name, raw))
        logger.warning("ignoring malformed %s=%r (want one of %s)",
                       name, raw, "|".join(choices))
    return default


def matrix_variant() -> str:
    """The operator's variant preference: ``auto`` (probe order) or a
    forced member of VARIANTS (still probe-gated — a forced variant
    that fails its probe demotes down the auto order, never errors)."""
    return _env_choice("JEPSEN_TPU_MATRIX_VARIANT",
                       ("auto",) + VARIANTS, "auto")


def probe_mode() -> str:
    """``auto`` — sidecar-cached probes; ``force`` — re-probe (and
    refresh the sidecar); ``skip`` — trust the shape gates, no probe."""
    return _env_choice("JEPSEN_TPU_PALLAS_PROBE",
                       ("auto", "force", "skip"), "auto")


def fuse_combine_mode() -> bool | None:
    """JEPSEN_TPU_FUSE_COMBINE: True/False force the fused/tree chunk
    combine; None (default) = probe decides (jepsen_tpu.parallel
    coerce_flag semantics for the string forms; a malformed value warns
    once, not per dispatch)."""
    raw = os.environ.get("JEPSEN_TPU_FUSE_COMBINE")
    if raw is None or raw == "":
        return None
    from jepsen_tpu.parallel import coerce_flag
    key = ("JEPSEN_TPU_FUSE_COMBINE", raw)
    if key in _ENV_WARNED:
        return None
    out = coerce_flag(raw, knob="JEPSEN_TPU_FUSE_COMBINE")
    if out is None:
        _ENV_WARNED.add(key)
    return out


def coerce_variant(value, knob: str = "matrix_variant") -> str | None:
    """Tolerant test-map/opts variant knob: None/'' unset; a VARIANTS
    member (or 'auto') passes; garbage warns and reads as unset."""
    if value is None or value == "":
        return None
    if isinstance(value, str):
        v = value.strip().lower()
        if v == "auto":
            return None
        if v in VARIANTS:
            return v
    logger.warning("ignoring malformed %s=%r (want one of auto|%s)",
                   knob, value, "|".join(VARIANTS))
    return None


def _static_tables(S: int, V: int):
    """Host-side static operator tables for (S, V), expanded from the
    SAME receiver/kill constructor the XLA scan path uses
    (jitlin.receiver_kill_tables — one source of truth, so the two
    kernels' bit-identical-verdict guarantee can't drift):

    - Rexp [S, MV, MV]: receiver map R_s block-expanded (R_s[a,b]
      broadcast over the V*V cells of each (a,b) block)
    - Kexp [S, MV, MV]: the closure-then-kill row gather+mask as a
      matrix (A = Kexp_s @ B  ==  B rows gathered at kill_idx_s, masked)
    - U1 [MV, V], U2 [V, MV]: the tiling maps (U1 @ X @ U2 repeats a
      [V, V] X over every block)
    """
    from jepsen_tpu.ops.jitlin import receiver_kill_tables

    M = 1 << S
    MV = M * V
    rows = np.arange(MV)
    ww = rows % V
    receiver, kill_idx, kill_mask = receiver_kill_tables(S, V)

    Rexp = np.stack([receiver[t][rows // V][:, rows // V]
                     for t in range(S)]).astype(np.float32)
    Kexp = np.zeros((S, MV, MV), np.float32)
    for s in range(S):
        Kexp[s, rows, kill_idx[s]] = kill_mask[s]

    U1 = np.zeros((MV, V), np.float32)
    U1[rows, ww] = 1.0
    U2 = np.zeros((V, MV), np.float32)
    U2[ww, rows] = 1.0
    return Rexp, Kexp, U1, U2


def _pretile_mode(S: int, V: int, U: int, variant: str = "f32") -> str:
    """Where the pre-tiled [U, MV, MV] uop table lives: ``vmem``
    (gather + VPU multiply, zero per-step fetch), ``hbm`` (DMA-streamed
    tiles, double-buffered), or ``none`` (in-kernel tiling dots).
    Integer variants store 1 byte/entry — a 4× VMEM budget extension
    over f32 before HBM streaming even starts."""
    itemsize = 4 if variant == "f32" else 1
    nbytes = U * ((1 << S) * V) ** 2 * itemsize
    if nbytes <= PALLAS_PRETILE_BYTES:
        return "vmem"
    if nbytes <= PALLAS_PRETILE_HBM_BYTES:
        return "hbm"
    return "none"


@functools.lru_cache(maxsize=32)
def _build(S: int, V: int, T: int, U: int, interpret: bool = False,
           pretile: str = "none", variant: str = "f32"):
    """Compile-cached pallas chunk-product for static shapes.

    Returns fn(pend [T,G,S] f32, ids [T,G,S] i32, mtT [U,V,V] f32,
    slots [T,G] i32, valid [T,G] f32) -> P [G, MV, MV] bf16 — the
    per-chunk composed operator product over its T returns.

    ``pretile``: "vmem" precomputes the [U, MV, MV] tiled uop table
    U1 @ Mt_u^T @ U2 ONCE in XLA (exact: tiling repeats Mt's cells, no
    accumulation) and the kernel's L build becomes a gather + VPU
    multiply; "hbm" keeps that table in HBM and streams the per-step
    tiles through a 2-deep DMA pipeline; "none" keeps the under-tiled
    per-step dots. ``variant`` picks the boolean-product representation
    (module docstring): f32 / int8-MXU / bit-packed uint32.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if pretile in (False, True):    # legacy bool callers (tests)
        pretile = "vmem" if pretile else "none"
    M = 1 << S
    MV = M * V
    n_sq = 0
    while (1 << n_sq) < S:
        n_sq += 1
    # matrix dtype of the boolean operands per variant; the L build
    # stays f32 (≤ S non-negative addends — exact) and thresholds into
    # the variant dtype, products threshold back into it, and the final
    # P leaves as bf16 for the combine stage in every variant.
    vdtype = jnp.float32 if variant == "f32" else jnp.int8
    tdtype = jnp.float32 if variant == "f32" else jnp.int8
    # The tables stay NUMPY here: _build is lru_cached and its first
    # call may run inside an active jit trace (chunk_product is invoked
    # while the products wrapper traces), where jnp.asarray would yield
    # that trace's tracers — cached into the closure, they leak into
    # every later trace sharing the (S, V, T, U) key and kill the
    # pallas path with UnexpectedTracerError (surfaced by the real-TPU
    # parity tier once the chunk retune multiplied the shape keys).
    # grid_fn stages them per trace instead.
    Rexp, Kexp, U1, U2 = _static_tables(S, V)

    if variant == "int8":
        def bool_mm(x, y):
            # int8 0/1 feeds through the MXU at 4x f32 operand density;
            # int32 accumulation is exact (counts <= MV <= 2^12) and the
            # > 0 threshold saturates back to the 0/1 semiring
            return (jnp.dot(x, y, preferred_element_type=jnp.int32)
                    > 0).astype(jnp.int8)
    elif variant == "packed":
        KW = MV // 32
        # minor-most-axis iota: >= 2D keeps Mosaic's layout rules happy
        def _bitpos():
            return lax.broadcasted_iota(jnp.uint32, (1, 1, 32), 2)

        def pack_rows(m):
            # [MV, MV] 0/1 -> [MV, KW] uint32, 32 entries per word
            b = m.astype(jnp.uint32).reshape(MV, KW, 32)
            return jnp.sum(b << _bitpos(), axis=-1, dtype=jnp.uint32)

        def bool_mm(x, y):
            # C[i,j] = OR_k x[i,k] AND y[k,j]: pack x's rows and y^T's
            # rows along k, word-AND, any-nonzero (the popcount>0 test)
            # — MV^2 * MV/32 word ops instead of MV^3 MACs
            xp = pack_rows(x)
            ytp = pack_rows(y.T)
            hit = xp[:, None, :] & ytp[None, :, :]
            return jnp.any(hit != 0, axis=-1).astype(jnp.int8)
    else:
        def bool_mm(x, y):
            # f32 0/1 inputs and accumulation: exact (a positive count
            # can't round to zero). Load-bearing f32: this is the
            # probe-verified terminal variant every backend can lower —
            # the integer representations demote HERE, so it must stay.
            return (
                jnp.dot(x, y,  # lint: ignore[threshold-dtype]
                        preferred_element_type=jnp.float32) > 0
            ).astype(jnp.float32)

    def tile_dots(u1, mtT, u2):
        """U1 @ Mt^T @ U2 in-kernel (pretile 'none'): each output cell
        copies ONE Mt cell — exact in either dot dtype."""
        if variant == "f32":
            return jnp.dot(
                jnp.dot(u1, mtT, preferred_element_type=jnp.float32),
                u2, preferred_element_type=jnp.float32)
        inner = jnp.dot(u1, mtT,
                        preferred_element_type=jnp.int32).astype(jnp.int8)
        return jnp.dot(inner, u2, preferred_element_type=jnp.int32)

    def make_step(pend_ref, ids_ref, slot_ref, val_ref, rexp_ref,
                  kexp_ref, fetch_tile):
        """The shared per-return composition, parameterized over how a
        step's per-slot [MV, MV] uop tile is obtained (the three
        L-build modes). Returns (step(t, buf, P), P0)."""
        eye = (lax.broadcasted_iota(jnp.int32, (MV, MV), 0)
               == lax.broadcasted_iota(jnp.int32, (MV, MV), 1)
               ).astype(jnp.float32)

        def _live_step(t, buf, P):
            # L = sum_s pend[t,s] * Rexp_s * tile(Mt_s^T), f32 (<= S
            # non-negative 0/1 addends — exact), thresholded into the
            # variant dtype
            L = jnp.zeros((MV, MV), jnp.float32)
            for s in range(S):
                tile = fetch_tile(t, s, buf)
                L = L + (pend_ref[0, t, s] * rexp_ref[s]
                         * tile.astype(jnp.float32))
            Bm = ((L + eye) > 0).astype(vdtype)
            # closure saturates once the exponent reaches the number of
            # pending ops (each linearization consumes one), so skip
            # squarings a sparse step can't use
            npend = jnp.sum(pend_ref[0, t, :])
            for _i in range(n_sq):
                Bm = lax.cond(npend > (1 << _i),
                              lambda B: bool_mm(B, B),
                              lambda B: B, Bm)   # (I+L)^(2^k) -> closure
            ks = kexp_ref[pl.dslice(slot_ref[0, t, 0], 1), :, :][0]
            A = bool_mm(ks, Bm)                  # closure-then-kill
            return bool_mm(A, P)

        def step(t, buf, P):
            # padding rows (valid=0) compose the identity: skip outright
            return lax.cond(val_ref[0, t, 0] > 0, _live_step,
                            lambda tt, bb, PP: PP, t, buf, P)

        return step, eye.astype(vdtype)

    def kernel_resident(pend_ref, ids_ref, mtT_ref, slot_ref, val_ref,
                        rexp_ref, kexp_ref, u1_ref, u2_ref, out_ref):
        """pretile 'vmem' / 'none': every operand VMEM-resident."""
        def fetch_tile(t, s, _buf):
            idx = ids_ref[0, t, s]
            if pretile == "vmem":
                # mtT_ref holds the pre-tiled [U, MV, MV] table:
                # pure gather + VPU multiply, no per-step dots
                return mtT_ref[pl.dslice(idx, 1), :, :][0]
            mtT = mtT_ref[pl.dslice(idx, 1), :, :][0]       # [V, V]
            return tile_dots(u1_ref[...], mtT, u2_ref[...])

        step, P0 = make_step(pend_ref, ids_ref, slot_ref, val_ref,
                             rexp_ref, kexp_ref, fetch_tile)
        P = lax.fori_loop(0, T, lambda t, P: step(t, jnp.int32(0), P), P0)
        out_ref[0] = P.astype(jnp.bfloat16)

    def kernel_hbm(pend_ref, ids_ref, mtT_ref, slot_ref, val_ref,
                   rexp_ref, kexp_ref, u1_ref, u2_ref, out_ref):
        """pretile 'hbm': the [U, MV, MV] table stays in HBM; step t's
        S tiles were DMA'd into double-buffer slot t%2 while step t-1
        computed, and step t+1's fetches start before t's closure —
        the per-step L build costs a VMEM read instead of either an
        in-kernel dot chain or a VMEM-impossible resident table."""
        def scoped(scratch, sems):
            def dma(t, slot, s):
                return pltpu.make_async_copy(
                    mtT_ref.at[ids_ref[0, t, s]], scratch.at[slot, s],
                    sems.at[slot, s])

            def start(t, slot):
                for s in range(S):
                    dma(t, slot, s).start()

            def fetch_tile(t, s, slot):
                return scratch[slot, s]

            step, P0 = make_step(pend_ref, ids_ref, slot_ref, val_ref,
                                 rexp_ref, kexp_ref, fetch_tile)

            def pipelined(t, P):
                slot = t % 2

                @pl.when(t + 1 < T)
                def _():
                    # prefetch t+1's tiles while t's closure computes
                    start(t + 1, (t + 1) % 2)
                for s in range(S):
                    # near-free once the copy landed during step t-1
                    dma(t, slot, s).wait()
                return step(t, slot, P)

            start(jnp.int32(0), jnp.int32(0))
            P = lax.fori_loop(0, T, pipelined, P0)
            out_ref[0] = P.astype(jnp.bfloat16)

        pl.run_scoped(scoped,
                      scratch=pltpu.VMEM((2, S, MV, MV), tdtype),
                      sems=pltpu.SemaphoreType.DMA((2, S)))

    def grid_fn(pend, ids, mtT, slots, valid):
        # grids arrive [G, T, S] / [G, T, 1]: blocking only on the
        # leading grid axis keeps every block's trailing dims equal to
        # the array's — the Mosaic block-shape rule (trailing two dims
        # divisible by (8, 128) or equal to the array's)
        G = pend.shape[0]
        full = lambda shape: pl.BlockSpec(
            shape, lambda g: (0,) * len(shape), memory_space=pltpu.VMEM)
        if pretile in ("vmem", "hbm"):
            # off-critical-path L-build: tile every uop's Mt^T over the
            # (a, b) blocks once, in XLA (each output cell copies ONE
            # Mt cell — exact, no accumulation); integer variants store
            # the table at 1 byte/entry
            mt_in = jnp.einsum("iv,uvw,wj->uij", jnp.asarray(U1), mtT,
                               jnp.asarray(U2)).astype(tdtype)
            mt_spec = (full((U, MV, MV)) if pretile == "vmem" else
                       pl.BlockSpec(memory_space=pltpu.ANY))
        else:
            mt_in = mtT.astype(tdtype)
            mt_spec = full((U, V, V))
        kexp_in = jnp.asarray(Kexp).astype(vdtype)
        u_dtype = jnp.float32 if variant == "f32" else jnp.int8
        kern = kernel_hbm if pretile == "hbm" else kernel_resident
        return pl.pallas_call(
            kern,
            grid=(G,),
            in_specs=[
                pl.BlockSpec((1, T, S), lambda g: (g, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, T, S), lambda g: (g, 0, 0),
                             memory_space=pltpu.VMEM),
                mt_spec,
                pl.BlockSpec((1, T, 1), lambda g: (g, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, T, 1), lambda g: (g, 0, 0),
                             memory_space=pltpu.VMEM),
                full((S, MV, MV)),
                full((S, MV, MV)),
                full((MV, V)),
                full((V, MV)),
            ],
            out_specs=pl.BlockSpec((1, MV, MV), lambda g: (g, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((G, MV, MV), jnp.bfloat16),
            interpret=interpret,
        )(pend, ids, mt_in, slots, valid,
          jnp.asarray(Rexp), kexp_in,
          jnp.asarray(U1).astype(u_dtype), jnp.asarray(U2).astype(u_dtype))

    @jax.jit
    def run(pend, ids, mtT, slots, valid):
        """Accepts the scan-path layout (pend/ids [T, G, S], slots/valid
        [T, G]) and relayouts on device to the kernel's [G, T, ...]."""
        return grid_fn(
            jnp.transpose(pend.astype(jnp.float32), (1, 0, 2)),
            jnp.transpose(ids.astype(jnp.int32), (1, 0, 2)),
            mtT.astype(jnp.float32),
            jnp.transpose(slots.astype(jnp.int32), (1, 0))[..., None],
            jnp.transpose(valid.astype(jnp.float32), (1, 0))[..., None])

    return run


# tests set True to exercise the kernels on CPU through the production
# dispatch (pallas interpret mode); never set in production
FORCE_INTERPRET = False


def _pretile_ok(S: int, V: int, U: int) -> bool:
    """Legacy predicate (kept for the parity tier): does the f32 table
    fit VMEM?"""
    return _pretile_mode(S, V, U, "f32") == "vmem"


def variant_ok(variant: str, S: int, V: int) -> bool:
    """Shape gates per representation, cheaper than (and checked
    before) the differential probe."""
    MV = (1 << S) * V
    if variant not in VARIANTS:
        return False
    if S > PALLAS_MAX_SLOTS or MV > PALLAS_MAX_MV:
        return False
    if variant == "packed":
        # word packing needs a whole number of uint32 words per row,
        # and the AND intermediate caps MV (module constant)
        return MV % 32 == 0 and MV <= PALLAS_PACKED_MAX_MV
    return True


def chunk_product(S: int, V: int, T: int, U: int,
                  interpret: bool | None = None, variant: str = "f32"):
    """The compiled kernel for these static shapes, or None when out of
    the pallas regime. Lowering/compile failures are reported by the
    first actual call — use ``enabled``/``best_variant`` for an upfront
    check."""
    if not available() or not variant_ok(variant, S, V):
        return None
    mode = _pretile_mode(S, V, U, variant)
    if mode == "hbm" and not hbm_pretile_enabled(S, V, variant):
        mode = "none"           # DMA streaming unproven here: demote
    return _build(S, V, T, U,
                  FORCE_INTERPRET if interpret is None else interpret,
                  mode, variant)


# ---------------------------------------------------------------------------
# Probes: per-(S, V, variant) differential self-tests, sidecar-cached
# ---------------------------------------------------------------------------

_PROBED: dict = {}
_DISABLED: set = set()
_PROBE_SECONDS: list = [0.0]


def probe_seconds() -> float:
    """Cumulative probe wall this process (compile + oracle replay) —
    the cost ``JEPSEN_TPU_PALLAS_PROBE``'s sidecar cache avoids on
    later processes. bench.py surfaces it as ``pallas_probe_seconds``
    so it can't hide inside first-check compile time."""
    return _PROBE_SECONDS[0]


def _note_probe_seconds(dt: float) -> None:
    _PROBE_SECONDS[0] += dt
    from jepsen_tpu import telemetry
    reg = telemetry.get_registry()
    if reg.enabled:
        reg.counter("pallas_probe_seconds_total",
                    "wall seconds spent in pallas self-test probes "
                    "(kernel variants + fused combine)").inc(dt)


def _probe_sidecar_key(kind: str, *parts):
    import jax
    return ("pallas-probe", jax.default_backend(), jax.__version__,
            kind) + tuple(str(p) for p in parts)


def _sidecar_load(key):
    if probe_mode() == "force":
        return None
    try:
        from jepsen_tpu import fs_cache
        data = fs_cache.load_data(key)
    except Exception:  # noqa: BLE001 — an unreadable cache is a miss
        return None
    if isinstance(data, dict) and isinstance(data.get("ok"), bool):
        return data
    return None


def _sidecar_save(key, ok: bool, seconds: float) -> None:
    try:
        from jepsen_tpu import fs_cache
        with fs_cache.lock(key):
            fs_cache.save_data(key, {"ok": ok,
                                     "seconds": round(seconds, 4)})
    except Exception:  # noqa: BLE001 — cache write failure is cosmetic
        logger.debug("pallas probe sidecar write failed", exc_info=True)


def _transient_probe_error(e: BaseException) -> bool:
    """A probe failure that may not reproduce (device busy, co-tenant
    OOM, wedged tunnel): its verdict must NOT persist in the
    cross-process sidecar — one bad moment would otherwise silently
    pin every future process on this machine to the slow path until an
    operator thinks of JEPSEN_TPU_PALLAS_PROBE=force. Lowering/compile
    failures and oracle mismatches are deterministic per (backend, jax
    version) and do persist."""
    from jepsen_tpu.checker.ladder import is_resource_exhausted
    return is_resource_exhausted(e)


def _probe_verdict(mem_key, side_key, run_probe, describe: str) -> bool:
    """The shared probe protocol for every self-test gate (kernel
    variants, hbm pretile, fused combine): runtime-failure disables
    stick hardest (even under FORCE_INTERPRET), FORCE_INTERPRET skips
    probing (tests drive interpret kernels directly), then the
    in-process memo, the ``skip`` override, the fs_cache sidecar, and
    finally one timed differential probe whose verdict is memoized and
    — unless the failure was transient — persisted."""
    if mem_key in _DISABLED:
        return False
    if FORCE_INTERPRET:
        return True
    if mem_key in _PROBED:
        return _PROBED[mem_key]
    if probe_mode() == "skip":
        # the operator vouches for this backend: shape gates only
        _PROBED[mem_key] = True
        return True
    cached = _sidecar_load(side_key)
    if cached is not None:
        _PROBED[mem_key] = cached["ok"]
        return cached["ok"]
    ok = False
    persist = True
    t0 = time.perf_counter()
    try:
        ok = run_probe()
        if not ok:
            logger.warning("%s MISCOMPILES on this backend (probe "
                           "mismatch); demoting", describe)
    except Exception as e:  # noqa: BLE001 — any lowering failure
        persist = not _transient_probe_error(e)
        logger.warning("%s unavailable%s: %s", describe,
                       "" if persist else " (transient — not cached)", e)
    dt = time.perf_counter() - t0
    _note_probe_seconds(dt)
    if persist:
        _sidecar_save(side_key, ok, dt)
    _PROBED[mem_key] = ok
    return ok


def _oracle_product(S, V, pend, ids, mtT, slots, valid):
    """Numpy replay of the factored chunk product — the probes' and the
    tests' independent reference (variant-independent: every variant
    must reproduce it bit-for-bit)."""
    MV = (1 << S) * V
    T, G = slots.shape
    Rexp, Kexp, U1, U2 = _static_tables(S, V)
    eye = np.eye(MV, dtype=np.float32)
    n_sq = 0
    while (1 << n_sq) < S:
        n_sq += 1
    P = np.broadcast_to(eye, (G, MV, MV)).copy()
    for t in range(T):
        for g in range(G):
            L = np.zeros((MV, MV), np.float32)
            for s in range(S):
                L += (pend[t, g, s]
                      * Rexp[s] * (U1 @ mtT[ids[t, g, s]] @ U2))
            Bm = ((L + eye) > 0).astype(np.float32)
            for _ in range(n_sq):
                Bm = ((Bm @ Bm) > 0).astype(np.float32)
            A = ((Kexp[slots[t, g]] @ Bm) > 0).astype(np.float32)
            if not valid[t, g]:
                A = eye
            P[g] = ((A @ P[g]) > 0).astype(np.float32)
    return P


def _probe_inputs(S, V, T=256, U=16, G=2):
    rng = np.random.default_rng(0)
    pend = (rng.random((T, G, S)) < 0.5).astype(np.float32)
    ids = rng.integers(0, U, (T, G, S)).astype(np.int32)
    mtT = (rng.random((U, V, V)) < 0.3).astype(np.float32)
    slots = rng.integers(0, S, (T, G)).astype(np.int32)
    valid = (rng.random((T, G)) < 0.8).astype(np.float32)
    return pend, ids, mtT, slots, valid


def _run_probe(S: int, V: int, variant: str, pretile: str) -> bool:
    """One end-to-end differential probe: a random run through the REAL
    compiled kernel, checked bit-for-bit against the numpy oracle.

    T=256 puts the probe in the production tiling regime: T is a
    trailing block dimension, so a tiny T (the old 3) compiled a
    differently-padded Mosaic program than the ~1-2k-row chunks
    production dispatches — a shape-dependent miscompile there would
    have slipped past the probe. 256 crosses the sublane tile boundary
    like production T does while keeping the bit-for-bit numpy oracle
    (T*G matrix products) sub-second; residual caveat: the probe's U=16
    uop table is still smaller than production's."""
    T, U = 256, 16
    pend, ids, mtT, slots, valid = _probe_inputs(S, V, T, U)
    fn = _build(S, V, T, U, False, pretile, variant)
    got = np.asarray(fn(pend, ids, mtT, slots, valid), dtype=np.float32)
    ref = _oracle_product(S, V, pend, ids, mtT, slots, valid)
    return np.array_equal(got, ref)


def enabled(S: int, V: int, variant: str = "f32") -> bool:
    """Should the matrix kernel take the pallas path for (S, V) with
    this representation? Gates on the env switch and shape caps, then
    memoizes a small RANDOM end-to-end run checked bit-for-bit against
    the numpy oracle — so a backend that fails to lower (CPU) OR
    miscompiles the kernel disables itself and the next variant (or the
    XLA scan path) takes over. Verdicts persist per
    (backend, jax version, S, V, variant) in the fs_cache sidecar;
    ``JEPSEN_TPU_PALLAS_PROBE`` overrides (module docstring). A
    disable() (runtime failure) sticks even under FORCE_INTERPRET —
    otherwise a failing interpret-mode kernel would retrace and fail
    on every dispatch; it is tracked apart from probe results, so a
    CPU probe failure (no pallas backend) can't poison forced
    interpret-mode runs, which don't need one."""
    if not available() or not variant_ok(variant, S, V):
        return False
    # probe the same pretile variant production dispatches at this U —
    # the kernels differ in their L-build data path
    return _probe_verdict(
        (S, V, variant), _probe_sidecar_key("kernel", S, V, variant),
        lambda: _run_probe(S, V, variant, _pretile_mode(S, V, 16, variant)),
        f"pallas matrix kernel (S={S} V={V} variant={variant})")


def hbm_pretile_enabled(S: int, V: int, variant: str = "f32") -> bool:
    """Is the DMA-streamed (HBM-resident) pre-tiled L-build proven on
    this backend for (S, V, variant)? Same probe/sidecar protocol as
    ``enabled`` but exercising the ``hbm`` kernel explicitly (the
    regular probe's U=16 table always fits VMEM, so it never walks the
    DMA path). A miss demotes to the in-kernel tiling dots, never
    fails."""
    if not available() or not variant_ok(variant, S, V):
        return False
    return _probe_verdict(
        (S, V, variant, "hbm"),
        _probe_sidecar_key("kernel-hbm", S, V, variant),
        lambda: _run_probe(S, V, variant, "hbm"),
        f"pallas hbm-streamed L-build (S={S} V={V} variant={variant})")


def best_variant(S: int, V: int, force: str | None = None) -> str | None:
    """The densest representation that passes its probe for (S, V), or
    None when no pallas path is viable (XLA scan takes over). ``force``
    (or JEPSEN_TPU_MATRIX_VARIANT) pins the first candidate; a pinned
    variant that fails its gates or probe DEMOTES down the auto order
    — PR-3 semantics, never an error."""
    pref = force if force in VARIANTS else None
    if pref is None:
        env = matrix_variant()
        pref = env if env in VARIANTS else None
    order = ((pref,) + tuple(v for v in VARIANTS if v != pref)
             if pref else VARIANTS)
    for v in order:
        if enabled(S, V, v):
            return v
    return None


def disable(S: int, V: int, variant: str = "f32") -> None:
    """Permanently (for this process) route (S, V, variant) away from
    the pallas path — called by the dispatcher after a runtime failure.
    Unlike a probe miss, this also sticks under FORCE_INTERPRET."""
    _DISABLED.add((S, V, variant))


# ---------------------------------------------------------------------------
# Fused streaming combine: the chunk-product reduction as ONE kernel
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _build_combine(B: int, C: int, MV: int, interpret: bool = False):
    """One pallas program per key streams its C time-ordered chunk
    products [MV, MV] through a VMEM-resident running product:

        total_b = P[b, C-1] @ ... @ P[b, 0] @ tot0[b]

    The tree combine (jitlin._kernel_math.make_combine) round-trips
    ceil(log2 C) levels of [B, C_l, MV, MV] intermediates through HBM;
    here each product is read from HBM exactly once (the pallas grid
    pipeline double-buffers the next chunk's HBM->VMEM copy under the
    current dot) and only the [B, MV, MV] total is written back.
    Products run int8 through the MXU with int32 accumulation and a
    saturating > 0 threshold — the combine-boundary piece of the packed
    boolean algebra; boolean matrix products are exact under any
    association and any exact dtype, so the result is bit-identical to
    the tree."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(p_ref, t0_ref, out_ref, acc_ref):
        c = pl.program_id(1)

        @pl.when(c == 0)
        def _():
            acc_ref[...] = t0_ref[0].astype(jnp.int8)
        prod = jnp.dot(p_ref[0, 0].astype(jnp.int8), acc_ref[...],
                       preferred_element_type=jnp.int32)
        out = (prod > 0).astype(jnp.int8)
        acc_ref[...] = out

        @pl.when(c == C - 1)
        def _():
            out_ref[0] = out.astype(jnp.bfloat16)

    @jax.jit
    def run(P, tot0):
        """P [B, C, MV, MV] 0/1 (any float dtype), tot0 [B, MV, MV] ->
        total [B, MV, MV] bf16."""
        return pl.pallas_call(
            kernel,
            grid=(B, C),
            in_specs=[
                pl.BlockSpec((1, 1, MV, MV), lambda b, c: (b, c, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, MV, MV), lambda b, c: (b, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, MV, MV), lambda b, c: (b, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((B, MV, MV), jnp.bfloat16),
            scratch_shapes=[pltpu.VMEM((MV, MV), jnp.int8)],
            interpret=interpret,
        )(P, tot0)

    return run


def combine_product(B: int, C: int, MV: int,
                    interpret: bool | None = None):
    """The fused streaming combine for these static shapes (see
    ``_build_combine``), or None when out of regime."""
    if not available() or MV > PALLAS_MAX_MV:
        return None
    return _build_combine(
        B, C, MV, FORCE_INTERPRET if interpret is None else interpret)


def _combine_oracle(P, tot0):
    B, C, MV, _ = P.shape
    out = np.zeros((B, MV, MV), np.float32)
    for b in range(B):
        acc = np.asarray(tot0[b], np.float32)
        for c in range(C):
            acc = ((np.asarray(P[b, c], np.float32) @ acc)
                   > 0).astype(np.float32)
        out[b] = acc
    return out


def _run_combine_probe(MV: int) -> bool:
    import jax.numpy as jnp
    B, C = 2, 5
    rng = np.random.default_rng(1)
    P = (rng.random((B, C, MV, MV)) < 0.2).astype(np.float32)
    tot0 = np.broadcast_to(np.eye(MV, dtype=np.float32),
                           (B, MV, MV)).copy()
    fn = _build_combine(B, C, MV, False)
    got = np.asarray(fn(jnp.asarray(P, jnp.bfloat16),
                        jnp.asarray(tot0, jnp.bfloat16)),
                     dtype=np.float32)
    return np.array_equal(got, _combine_oracle(P, tot0))


def combine_enabled(MV: int) -> bool:
    """Should chunk combines run through the fused streaming kernel at
    this operator size? Same probe/sidecar/override protocol as
    ``enabled``; JEPSEN_TPU_FUSE_COMBINE=0 vetoes, =1 only skips the
    probe when it already passed elsewhere (a forced-on fused combine
    still never replaces a probe miss — bit-identity outranks the
    toggle)."""
    forced = fuse_combine_mode()
    if forced is False or not available() or MV > PALLAS_MAX_MV:
        return False
    return _probe_verdict(
        ("combine", MV), _probe_sidecar_key("combine", MV),
        lambda: _run_combine_probe(MV),
        f"fused combine (MV={MV})")


def disable_combine(MV: int) -> None:
    """Route combines at this MV back to the tree after a runtime
    failure (sticks under FORCE_INTERPRET, like ``disable``)."""
    _DISABLED.add(("combine", MV))
