"""TPU just-in-time-linearization kernel.

Replaces the reference's CPU-bound knossos linear/wgl searches (invoked at
jepsen/src/jepsen/checker.clj:199-203) with a fixed-shape XLA program:

* A *configuration* is (mask, state): ``mask`` = bitset over pending-op
  slots that have already been linearized; ``state`` = interned model state.
* The frontier of live configurations is a capacity-K array pair.
* Events stream through a ``lax.scan``: invokes update the per-slot op
  table; before consuming each return, the closure of the frontier under
  "linearize any pending, unlinearized op" is computed by masked batched
  expansion ([K, S] candidate grid through the model's int transition) and
  sort-based dedup (two lexicographic ``lax.sort`` passes), then configs
  that failed to linearize the returning op are killed.

The frontier is monotone within a closure, so convergence is detected by
count; overflow beyond K makes a False verdict "unknown" (a surviving
subset is still a sound witness for True). The whole kernel vmaps over a
batch of per-key histories — the jepsen.independent -> vmap mapping
(SURVEY.md §2.6, BASELINE config 3).

Shapes are static in (E, S, K): pad E via linear_encode.pad_streams and
bucket history lengths so XLA caches compilations.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from functools import partial

import numpy as np

logger = logging.getLogger("jepsen.jitlin")

# Host/device phase split of the calling thread's most recent
# matrix_check_batch call (prepass / grids / dispatch / fetch seconds) —
# bench.py folds these into the matrix-kernel attribution fields the way
# elle's bench reads columnar.LAST_PHASE_SECONDS. Thread-local:
# concurrent checkers under bounded_pmap must not read each other's
# split (or trip over a mid-update clear()).
_PHASE = threading.local()


def last_phase_seconds() -> dict:
    """The calling thread's most recent matrix dispatch phase split."""
    return dict(getattr(_PHASE, "value", {}))


def publish_phase_seconds(phases: dict) -> None:
    """Re-publishes a phase split into THIS thread's slot. The checker's
    degradation ladder runs device dispatches on a watchdog worker
    thread; it captures the split there and re-publishes on the
    dispatching thread so ``last_phase_seconds()`` keeps answering for
    the thread that owns the check."""
    _PHASE.value = dict(phases)


# Most recent dispatch routing of the calling thread: which kernel
# representation ran the chunk products ("f32"/"int8"/"packed", or
# "scan" for the XLA path) and which combine ("fused"/"tree") — the
# per-variant labels bench.py attaches to its phase/roofline fields.
_DISPATCH_INFO = threading.local()


def last_dispatch_info() -> dict:
    """{'variant': ..., 'combine': ...} of the calling thread's most
    recent matrix dispatch (empty before the first one)."""
    return dict(getattr(_DISPATCH_INFO, "value", {}))


# Per-thread routing overrides (the test-map/opts knobs `matrix_variant`
# and `combine_fused`, plumbed by checker/linearizable.py): a pinned
# variant demotes down the probe order when it can't run — PR-3
# semantics — and `combine_fused=False` pins the tree combine.
_OVERRIDE = threading.local()


def _dispatch_overrides() -> tuple:
    return (getattr(_OVERRIDE, "variant", None),
            getattr(_OVERRIDE, "fused", None))


class _routing_overrides:
    """Context manager scoping (variant, fused) overrides to one
    matrix_check_batch call on this thread."""

    def __init__(self, variant, fused):
        self._new = (variant, fused)

    def __enter__(self):
        self._old = _dispatch_overrides()
        _OVERRIDE.variant, _OVERRIDE.fused = self._new

    def __exit__(self, *exc):
        _OVERRIDE.variant, _OVERRIDE.fused = self._old


def _env_int(name: str, default: int) -> int:
    """Env-int knob that degrades to its default on malformed values
    (a bad sweep variable must not make the module unimportable)."""
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        logger.warning("ignoring malformed %s=%r", name,
                       os.environ.get(name))
        return default

SENTINEL_MASK = np.uint32(0xFFFFFFFF)
SENTINEL_STATE = np.int32(0x7FFFFFFF)

EV_INVOKE, EV_RETURN, EV_NOOP = 0, 1, 2


def _build_step(num_slots: int, capacity: int, step_ids, init_state: int,
                max_closure_iters: int | None = None):
    import jax
    import jax.numpy as jnp
    from jax import lax

    S, K = num_slots, capacity
    closure_iters = max_closure_iters or S
    slot_bits = (jnp.uint32(1) << jnp.arange(S, dtype=jnp.uint32))

    def count_valid(mask):
        return jnp.sum((mask != SENTINEL_MASK).astype(jnp.int32))

    def dedup_compact(all_mask, all_state):
        """Sort, drop duplicates, move valid entries to the front, keep K."""
        m, st = lax.sort((all_mask, all_state), num_keys=2, is_stable=False)
        dup = jnp.concatenate([
            jnp.zeros((1,), dtype=bool),
            (m[1:] == m[:-1]) & (st[1:] == st[:-1]),
        ])
        m = jnp.where(dup, SENTINEL_MASK, m)
        st = jnp.where(dup, SENTINEL_STATE, st)
        m, st = lax.sort((m, st), num_keys=2, is_stable=False)
        overflow = m[K] != SENTINEL_MASK if m.shape[0] > K else jnp.bool_(False)
        return m[:K], st[:K], overflow

    def closure(mask, state, pend_mask, cur_f, cur_a, cur_b):
        """Expands the frontier to its closure under linearizing any pending,
        unlinearized op. Early-exits when the config count stops growing."""

        def body(carry):
            mask, state, _, count, overflow, it = carry
            valid = mask != SENTINEL_MASK
            can = (
                valid[:, None]
                & ((pend_mask & slot_bits) != 0)[None, :]
                & ((mask[:, None] & slot_bits[None, :]) == 0)
            )
            st2, ok = step_ids(state[:, None], cur_f[None, :], cur_a[None, :], cur_b[None, :])
            good = can & ok
            new_mask = jnp.where(good, mask[:, None] | slot_bits[None, :], SENTINEL_MASK)
            new_state = jnp.where(good, st2, SENTINEL_STATE)
            all_mask = jnp.concatenate([mask, new_mask.reshape(-1)])
            all_state = jnp.concatenate([state, new_state.reshape(-1)])
            m, st, ovf = dedup_compact(all_mask, all_state)
            c2 = count_valid(m)
            return m, st, c2 > count, c2, overflow | ovf, it + 1

        def cond(carry):
            _, _, changed, _, _, it = carry
            return changed & (it < closure_iters)

        init = (mask, state, jnp.bool_(True), count_valid(mask), jnp.bool_(False),
                jnp.int32(0))
        mask, state, _, count, overflow, _ = lax.while_loop(cond, body, init)
        return mask, state, count, overflow

    def step_event(carry, ev):
        (mask, state, cur_f, cur_a, cur_b, pend_mask, alive, died_at,
         overflow, peak, eidx) = carry
        kind, slot, f, a, b = ev
        slot_bit = jnp.uint32(1) << slot.astype(jnp.uint32)

        def on_invoke(_):
            return (mask, state, cur_f.at[slot].set(f), cur_a.at[slot].set(a),
                    cur_b.at[slot].set(b), pend_mask | slot_bit, alive,
                    died_at, overflow, peak, eidx + 1)

        def on_return(_):
            m, st, count, ovf = closure(mask, state, pend_mask, cur_f, cur_a, cur_b)
            # keep configs that linearized the returning op; clear its bit
            # (sentinel entries have all bits set — exclude them explicitly)
            has = (m != SENTINEL_MASK) & ((m & slot_bit) != 0)
            m2 = jnp.where(has, m & ~slot_bit, SENTINEL_MASK)
            st2 = jnp.where(has, st, SENTINEL_STATE)
            m2, st2, _ = dedup_compact(
                jnp.concatenate([m2, jnp.full((S,), SENTINEL_MASK, jnp.uint32)]),
                jnp.concatenate([st2, jnp.full((S,), SENTINEL_STATE, jnp.int32)]),
            )
            now_alive = count_valid(m2) > 0
            new_died = jnp.where(alive & ~now_alive, eidx, died_at)
            return (m2, st2, cur_f, cur_a, cur_b, pend_mask & ~slot_bit,
                    alive & now_alive, new_died, overflow | ovf,
                    jnp.maximum(peak, count), eidx + 1)

        def on_noop(_):
            return (mask, state, cur_f, cur_a, cur_b, pend_mask, alive,
                    died_at, overflow, peak, eidx + 1)

        new_carry = lax.switch(kind, [on_invoke, on_return, on_noop], None)
        return new_carry, None

    def scan_from(mask0, state0, events):
        carry = (
            mask0, state0,
            jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
            jnp.zeros((S,), jnp.int32),
            jnp.uint32(0), jnp.bool_(True), jnp.int32(-1), jnp.bool_(False),
            jnp.int32(1), jnp.int32(0),
        )
        carry, _ = lax.scan(step_event, carry, events)
        (mask, state, _, _, _, _, alive, died_at, overflow, peak, _) = carry
        return mask, state, alive, died_at, overflow, peak

    def run(kind, slot, f, a, b):
        mask0 = jnp.full((K,), SENTINEL_MASK, dtype=jnp.uint32)
        mask0 = mask0.at[0].set(jnp.uint32(0))
        state0 = jnp.full((K,), SENTINEL_STATE, dtype=jnp.int32)
        state0 = state0.at[0].set(jnp.int32(init_state))
        events = (kind.astype(jnp.int32), slot.astype(jnp.int32),
                  f.astype(jnp.int32), a.astype(jnp.int32), b.astype(jnp.int32))
        _, _, alive, died_at, overflow, peak = scan_from(mask0, state0, events)
        return alive, died_at, overflow, peak

    def run_resume(kind, slot, f, a, b, mask0, state0):
        """Segmented-verification variant: starts from a prior segment's
        frontier (masks are all-zero at a quiescent cut, so only states
        carry meaning) and returns the final frontier with the verdict."""
        events = (kind.astype(jnp.int32), slot.astype(jnp.int32),
                  f.astype(jnp.int32), a.astype(jnp.int32), b.astype(jnp.int32))
        mask, state, alive, died_at, overflow, peak = scan_from(
            mask0, state0, events)
        return alive, died_at, overflow, peak, mask, state

    run.resume = run_resume
    run.init_frontier = lambda: (
        np.concatenate([np.zeros(1, np.uint32),
                        np.full(K - 1, SENTINEL_MASK, np.uint32)]),
        np.concatenate([np.asarray([init_state], np.int32),
                        np.full(K - 1, SENTINEL_STATE, np.int32)]))
    return run


def _build_dense_step(num_slots: int, num_states: int, step_ids,
                      init_state: int):
    """Exact dense-table variant of the scan.

    When per-key concurrency S and the interned state count V are small —
    the jepsen.independent regime, where per-key histories are kept short
    and values few — the *entire* configuration space is only
    ``2^S masks x V states``. The frontier then lives in a dense boolean
    table T[2^S, V] instead of a capacity-K list: closure under
    "linearize any pending op" becomes S batched boolean matmuls
    ``T[r ^ bit_t] @ M_t`` (per-slot [V, V] transition matrices, bf16 on
    the MXU with f32 accumulation) OR-reduced into T, iterated to a
    fixpoint. No sorts, no dedup, and — because the table covers the
    whole space — no capacity overflow: the verdict is always exact.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    S, V = num_slots, num_states
    M = 1 << S
    # row index tables: r ^ bit_t (the donor/receiver row permutation per
    # slot) and whether bit_t is set in r
    xor_idx = jnp.asarray(np.arange(M)[None, :] ^ (1 << np.arange(S))[:, None])
    has_bit = jnp.asarray(
        ((np.arange(M)[None, :] >> np.arange(S)[:, None]) & 1).astype(bool))
    v_range = jnp.arange(V, dtype=jnp.int32)

    def slot_matrix(f, a, b):
        """One slot's [V, V] transition matrix, plus an out-of-range flag:
        a step_ids whose states aren't dense intern ids would otherwise be
        silently misencoded — flag it so the verdict degrades to unknown
        instead of a confidently wrong exact answer."""
        st2, ok = step_ids(v_range, f, a, b)
        oob = (ok & ((st2 < 0) | (st2 >= V))).any()
        mt = ok[:, None] & (st2[:, None] == v_range[None, :])
        return mt.astype(jnp.bfloat16), oob  # [V, V]

    def closure(table, pend_mask, mt):
        pend = ((pend_mask >> jnp.arange(S, dtype=jnp.uint32)) & 1).astype(bool)
        gate = pend[:, None] & has_bit  # [S, M]: rows that may receive via t

        def body(carry):
            t, _, it = carry
            donors = t[xor_idx]  # [S, M, V]
            contrib = jnp.einsum(
                "smv,svw->smw", donors.astype(jnp.bfloat16), mt,
                preferred_element_type=jnp.float32) > 0
            t2 = t | (contrib & gate[:, :, None]).any(axis=0)
            return t2, (t2 != t).any(), it + 1

        def cond(carry):
            _, changed, it = carry
            return changed & (it < S)

        table, _, _ = lax.while_loop(
            cond, body, (table, jnp.bool_(True), jnp.int32(0)))
        return table

    def step_event(carry, ev):
        table, mt, pend_mask, alive, died_at, peak, inexact, eidx = carry
        kind, slot, f, a, b = ev
        slot_bit = jnp.uint32(1) << slot.astype(jnp.uint32)

        def on_invoke(_):
            # only this slot's [V, V] transition block changes — the rest
            # of mt rides the carry untouched
            m_slot, oob = slot_matrix(f, a, b)
            return (table, mt.at[slot].set(m_slot), pend_mask | slot_bit,
                    alive, died_at, peak, inexact | oob, eidx + 1)

        def on_return(_):
            tc = closure(table, pend_mask, mt)
            # keep configs that linearized the returning op, clearing its
            # bit: T'[r] = (s not in r) & Tc[r | bit_s]
            hasb = has_bit[slot]          # [M]
            t2 = jnp.where(~hasb[:, None], tc[xor_idx[slot]], False)
            now_alive = t2.any()
            new_died = jnp.where(alive & ~now_alive, eidx, died_at)
            count = jnp.sum(tc.astype(jnp.int32))
            return (t2, mt, pend_mask & ~slot_bit, alive & now_alive,
                    new_died, jnp.maximum(peak, count), inexact, eidx + 1)

        def on_noop(_):
            return (table, mt, pend_mask, alive, died_at, peak, inexact,
                    eidx + 1)

        return lax.switch(kind, [on_invoke, on_return, on_noop], None), None

    def scan_from(table0, events):
        carry = (
            table0,
            jnp.zeros((S, V, V), jnp.bfloat16),
            jnp.uint32(0), jnp.bool_(True), jnp.int32(-1), jnp.int32(1),
            jnp.bool_(False), jnp.int32(0),
        )
        carry, _ = lax.scan(step_event, carry, events)
        (table, _, _, alive, died_at, peak, inexact, _) = carry
        return table, alive, died_at, peak, inexact

    def run(kind, slot, f, a, b):
        table0 = jnp.zeros((M, V), dtype=bool).at[0, init_state].set(True)
        events = (kind.astype(jnp.int32), slot.astype(jnp.int32),
                  f.astype(jnp.int32), a.astype(jnp.int32), b.astype(jnp.int32))
        _, alive, died_at, peak, inexact = scan_from(table0, events)
        # the table covers the whole config space, so the only inexactness
        # is a state id escaping the intern range — surfaced on the
        # overflow channel so verdict() degrades to unknown, not wrong
        return alive, died_at, inexact, peak

    def run_resume(kind, slot, f, a, b, table0):
        """Segmented-verification variant: starts from a caller-supplied
        frontier table (a previous segment's output — the stream must be
        cut at quiescent points, i.e. no ops pending across the cut) and
        returns the final table alongside the verdict, staying on device
        between segments."""
        events = (kind.astype(jnp.int32), slot.astype(jnp.int32),
                  f.astype(jnp.int32), a.astype(jnp.int32), b.astype(jnp.int32))
        table, alive, died_at, peak, inexact = scan_from(table0, events)
        return alive, died_at, inexact, peak, table

    def init_table():
        t = np.zeros((M, V), bool)
        t[0, init_state] = True
        return t

    run.resume = run_resume
    run.init_table = init_table
    return run


def _returns_prepass(kind, slot, f, a, b):
    """Host pre-pass for the matrix kernel: the per-slot op table and
    pending mask evolve deterministically from the event stream alone
    (invokes/returns), independent of the frontier — so each return's
    (pending set, op table, returning slot) is computable up front.

    Fully vectorized (O(S) passes of O(E) numpy work, no per-event Python)
    so the prepass doesn't dominate the kernel it feeds: per slot t, the
    pending bit at event i is ``#invokes(t) <= i  >  #returns(t) <= i``
    (cumulative counts), and the current op is the last invoke of t at or
    before i, found by searchsorted into t's invoke positions.

    Returns numpy arrays over the R return events."""
    kind = np.asarray(kind)
    slot = np.asarray(slot)
    fabs = np.stack([np.asarray(f, np.int64), np.asarray(a, np.int64),
                     np.asarray(b, np.int64)], axis=1)
    S = int(slot.max(initial=0)) + 1
    ret_idx = np.nonzero(kind == EV_RETURN)[0]
    R = ret_idx.shape[0]
    if R == 0:
        return (np.zeros((0,), np.int32), np.zeros((0, S), bool),
                np.zeros((0, S, 3), np.int64), S)
    r_slot = slot[ret_idx].astype(np.int32)
    r_pend = np.zeros((R, S), bool)
    r_ops = np.zeros((R, S, 3), np.int64)
    is_inv = kind == EV_INVOKE
    is_ret = kind == EV_RETURN
    for t in range(S):
        on_t = slot == t
        inv_pos = np.nonzero(is_inv & on_t)[0]
        # pending at return event i: invokes-so-far > returns-so-far,
        # where "so-far" includes event i itself (a return of slot t at i
        # still sees t pending — it is the op being linearized-and-killed)
        n_inv = np.cumsum(is_inv & on_t)
        n_ret_before = np.cumsum(is_ret & on_t) - (is_ret & on_t)
        r_pend[:, t] = (n_inv > n_ret_before)[ret_idx]
        if inv_pos.size == 0:
            continue  # slot never invoked: never pending, op stays 0
        # current op of slot t at event i: last invoke of t at or before i
        j = np.searchsorted(inv_pos, ret_idx, side="right") - 1
        has = j >= 0
        src = inv_pos[np.where(has, j, 0)]
        r_ops[:, t, :] = np.where(has[:, None], fabs[src], 0)
    return r_slot, r_pend, r_ops, S


def receiver_kill_tables(S: int, V: int):
    """The transfer-matrix operators' static bit tables — ONE source of
    truth shared by the XLA scan kernel and the pallas kernel
    (ops/pallas_matrix.py expands these into matrix form):

    - receiver [S, M, M] f32: R_t[r | bit_t, r] = 1 for slots t not in
      mask r (the mask-receiver map of linearizing pending op t)
    - kill_idx [S, MV] i32 / kill_mask [S, MV] f32: the
      closure-then-kill row gather+mask for a return on slot s
    """
    M = 1 << S
    MV = M * V
    r = np.arange(M)
    receiver = np.zeros((S, M, M), np.float32)
    for t in range(S):
        src = r[((r >> t) & 1) == 0]
        receiver[t, src | (1 << t), src] = 1.0
    rows = np.arange(MV)
    rr, ww = rows // V, rows % V
    kill_idx = np.zeros((S, MV), np.int32)
    kill_mask = np.zeros((S, MV), np.float32)
    for s in range(S):
        ok = ((rr >> s) & 1) == 0
        kill_idx[s] = np.where(ok, (rr | (1 << s)) * V + ww, 0)
        kill_mask[s] = ok.astype(np.float32)
    return receiver, kill_idx, kill_mask


def _kernel_math(S: int, V: int, step_ids, G: int):
    """Trace-time math shared by the single-device transfer-matrix
    kernel and its shard_map mesh twin: the static receiver/kill
    tables, the boolean-matmul helpers, the per-scan-step operator
    build, and the chunk-product combiners. ``G`` is the chunk count
    one scan step advances — the global count on a single device, a
    per-device block under shard_map. Everything downstream of the
    chunk layout is built HERE exactly once, which is what keeps mesh
    and single-device verdicts bit-identical: both paths compose the
    same 0/1 operators with the same thresholded bf16 products (every
    intermediate is exactly 0/1, so any association of the boolean
    matrix product yields the same matrix)."""
    import types

    import jax
    import jax.numpy as jnp

    M = 1 << S
    MV = M * V

    receiver, kill_idx, kill_mask = receiver_kill_tables(S, V)
    n_sq = 0
    while (1 << n_sq) < S:
        n_sq += 1
    receiver_j = jnp.asarray(receiver, jnp.bfloat16)
    kill_idx_j = jnp.asarray(kill_idx)
    kill_mask_j = jnp.asarray(kill_mask, jnp.bfloat16)
    eye = jnp.eye(MV, dtype=jnp.bfloat16)
    v_range = jnp.arange(V, dtype=jnp.int32)

    def bmm(x, y):
        # bf16 accumulation is sound for the >0 test: every addend is
        # non-negative, so rounding can never produce a spurious zero (a
        # positive sum stays positive) nor a spurious positive — and the
        # bf16 output halves the HBM traffic of these [G, MV, MV]
        # intermediates, which is what bounds the step
        out = jnp.einsum("gij,gjk->gik", x, y,
                         preferred_element_type=jnp.bfloat16)
        return (out > 0).astype(jnp.bfloat16)

    def uop_tables(uops):
        """[U, 3] distinct-op table -> [U, V, V] transition matrices
        (computed once per run, gathered per step) + [U] oob flags."""
        def one(fab):
            st2, ok = step_ids(v_range, fab[0], fab[1], fab[2])
            # INVARIANT: transitions leaving [0, V) are DROPPED (the
            # equality below can't match), under-approximating
            # reachability — so alive=True with oob set proves nothing
            # and callers must treat it as unknown, never as valid. The
            # oob flag is how that escape is surfaced.
            oob = (ok & ((st2 < 0) | (st2 >= V))).any()
            return (ok[:, None] & (st2[:, None] == v_range[None, :])), oob
        mt, oob = jax.vmap(one)(uops)
        return mt.astype(jnp.bfloat16), oob

    def make_step(mt_tab, oob_tab):
        def step(carry, inp):
            P, inexact = carry
            pend_g, ids_g, s_g, val_g = inp
            mt = mt_tab[ids_g]                   # [G, S, V, V] gather
            oob = oob_tab[ids_g]                 # [G, S]
            gated = pend_g.astype(jnp.bfloat16)
            # row = (receiver mask a, NEW state w); col = (source mask b,
            # OLD state v): L[(a,w),(b,v)] = Σ_t pend_t R_t[a,b] M_t[v,w]
            # (bf16 accumulation: ≤ S non-negative addends, see bmm)
            L = jnp.einsum("gt,tab,gtvw->gawbv", gated, receiver_j, mt,
                           preferred_element_type=jnp.bfloat16)
            Bm = ((L.reshape(G, MV, MV) + eye[None]) > 0).astype(jnp.bfloat16)
            for _ in range(n_sq):
                Bm = bmm(Bm, Bm)                 # (I+L)^(2^k) → closure
            A = jax.vmap(lambda m, idx, msk: m[idx] * msk[:, None])(
                Bm, kill_idx_j[s_g], kill_mask_j[s_g])
            A = jnp.where(val_g[:, None, None], A, eye[None])
            return (bmm(A, P),
                    inexact | (oob & pend_g & val_g[:, None]).any(axis=1)), None
        return step

    def chain_time(seq):
        """[n, MV, MV] time-ordered chunk products -> their composed
        product (later chunk on the LEFT), via the same pairing tree as
        make_combine so every intermediate is a thresholded 0/1
        matrix."""
        while seq.shape[0] > 1:        # static n: unrolls at trace time
            odd = seq[-1:] if seq.shape[0] % 2 else None
            pairs = seq[:-1] if odd is not None else seq
            out = jnp.einsum("nij,njk->nik", pairs[1::2], pairs[0::2],
                             preferred_element_type=jnp.bfloat16)
            seq = (out > 0).astype(jnp.bfloat16)
            if odd is not None:
                seq = jnp.concatenate([seq, odd], axis=0)
        return seq[0]

    def make_combine(B: int, C: int, init_state: int):
        def _combine(P, inexact, tot0):
            # chain each key's C chunk products in time order: chunks are
            # chunk-major per key, so total_b = P[b,C-1] @ ... @ P[b,0] @ tot0.
            # Tree-reduced: boolean matrix product is associative, so pairing
            # neighbors per level ((P1@P0), (P3@P2), ...) computes the same
            # 0/1 product in ceil(log2 C) levels of BATCHED matmuls instead
            # of C sequential [B, MV, MV] products — the old fori_loop chain
            # was C dependent tiny matmuls of pure launch latency (256 of
            # them on the single-dispatch bench config).
            def bmm_pairs(hi, lo):
                out = jnp.einsum("bnij,bnjk->bnik", hi, lo,
                                 preferred_element_type=jnp.bfloat16)
                return (out > 0).astype(jnp.bfloat16)

            seq = P.reshape(B, C, MV, MV)
            while seq.shape[1] > 1:        # static C: unrolls at trace time
                odd = seq[:, -1:] if seq.shape[1] % 2 else None
                pairs = seq[:, :-1] if odd is not None else seq
                # later chunk on the LEFT: product order is preserved
                seq = bmm_pairs(pairs[:, 1::2], pairs[:, 0::2])
                if odd is not None:
                    seq = jnp.concatenate([seq, odd], axis=1)
            total = (jnp.einsum("bij,bjk->bik", seq[:, 0],
                                tot0.astype(jnp.bfloat16),
                                preferred_element_type=jnp.bfloat16)
                     > 0).astype(jnp.bfloat16)
            alive = (total[:, :, init_state] > 0).any(axis=1)
            return alive, inexact.reshape(B, C).any(axis=1), total
        return _combine

    return types.SimpleNamespace(
        M=M, MV=MV, n_sq=n_sq, eye=eye, v_range=v_range,
        receiver_j=receiver_j, kill_idx_j=kill_idx_j,
        kill_mask_j=kill_mask_j, bmm=bmm, uop_tables=uop_tables,
        make_step=make_step, chain_time=chain_time,
        make_combine=make_combine)


def _build_matrix_kernel(S: int, V: int, step_ids, init_state: int,
                         g_steps: int, n_chunks: int, n_keys: int = 1):
    """Block-composed transfer-matrix variant of the dense scan.

    For each return event, closure-then-kill is a *linear* boolean
    operator on the flattened [2^S * V] table: closure is (I+L)^S where
    L = sum_t pend_t * (R_t ⊗ M_t) (R_t the static mask-receiver map for
    slot t, M_t the op's [V, V] transition), computable with
    ceil(log2 S) boolean matrix squarings; kill is a row gather+mask.
    Composing the per-return matrices A_i is associative, so chunks of
    the history multiply *in parallel* (one lax.scan whose every step
    advances all chunks by one return — [G, MV, MV] batched matmuls on
    the MXU) and the G chunk products combine at the end. Sequential
    depth falls from one step per event to one per chunk-row, which is
    what makes a single long history fast on TPU; the event-by-event
    dense scan remains the exact-diagnostics path (died-at event, peak).

    With ``n_keys`` = B > 1, the same chunk axis also carries a batch of
    independent per-key histories (the jepsen.independent regime): chunk
    g = b * n_chunks + c holds key b's c-th slice of returns, every scan
    step advances all B x C chunks with one [G, MV, MV] MXU matmul, and
    the final combine chains each key's C chunk products separately.
    This replaces the latency-bound vmapped event scan with dense batched
    matmul work — sequential depth per key falls from E events to
    T = g_steps.

    Host→device traffic is kept minimal for tunneled/remote accelerators:
    the host interns the batch's distinct (f, a, b) ops into a table of
    ``n_uops`` entries, each op's [V, V] transition matrix is built ONCE
    on device, and the per-return op tables arrive as small int32 id
    grids gathered against that table each step.

    Boolean products ride bf16 inputs with f32 accumulation (counts
    <= MV = 2^S * V <= 2^12 are exact in f32) and a >0 threshold.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    B, C, T = n_keys, n_chunks, g_steps
    G = B * C

    # static tables + step/combine math (shared with the mesh twin —
    # see _kernel_math; the pallas kernel shares the bit tables via
    # receiver_kill_tables)
    math = _kernel_math(S, V, step_ids, G)
    MV, eye = math.MV, math.eye
    uop_tables = math.uop_tables
    make_step = math.make_step
    _combine = math.make_combine(B, C, init_state)

    # --- stage 1: per-chunk products ([G, MV, MV] bf16 + inexact) -----
    # The products and the combine are SEPARATE dispatches: the chunk
    # products materialize in HBM between the scan and the combine
    # either way, and the split lets the fused streaming combine (and
    # its tree fallback) pair with ANY products source — XLA scan or
    # any pallas kernel variant — without a cross-product of jits.

    def _scan_products(pend, op_ids, uops, slots, valid):
        mt_tab, oob_tab = uop_tables(uops)
        P0 = jnp.broadcast_to(eye, (G, MV, MV))
        (P, inexact), _ = lax.scan(make_step(mt_tab, oob_tab),
                                   (P0, jnp.zeros((G,), bool)),
                                   (pend, op_ids, slots, valid))
        return P, inexact

    scan_products = jax.jit(_scan_products)

    def _scan_total(pend, op_ids, uops, slots, valid, tot0):
        """The pre-split single-jit scan + tree combine: the fallback
        dispatch when neither a pallas products variant nor the fused
        combine is active (e.g. the CPU backend). One compile and one
        dispatch, exactly the old profile — the split stages below only
        engage when a pallas stage actually replaces one of them."""
        P, inexact = _scan_products(pend, op_ids, uops, slots, valid)
        return _combine(P, inexact, tot0)

    scan_total = jax.jit(_scan_total)

    _pallas_jits: dict = {}

    def pallas_products(variant: str):
        """The jitted products stage through one pallas kernel variant
        (the T-step chunk product fused into ONE program per chunk, P
        VMEM-resident across all its returns — ops/pallas_matrix.py).
        The oob → inexact reduction runs on the small id grids outside
        the kernel; boolean results are bit-identical to the scan path
        (exact accumulation of 0/1 addends, thresholded per product,
        whatever the operand representation)."""
        fn = _pallas_jits.get(variant)
        if fn is None:
            @jax.jit
            def fn(pend, op_ids, uops, slots, valid):
                from jepsen_tpu.ops import pallas_matrix
                mt_tab, oob_tab = uop_tables(uops)
                kfn = pallas_matrix.chunk_product(
                    S, V, T, uops.shape[0], variant=variant)
                mtT = jnp.transpose(mt_tab, (0, 2, 1)).astype(jnp.float32)
                P = kfn(pend, op_ids, mtT, slots, valid)
                inexact = (oob_tab[op_ids] & pend
                           & valid[..., None]).any(axis=(0, 2))
                return P, inexact
            _pallas_jits[variant] = fn
        return fn

    # --- stage 2: the chunk-product combine ---------------------------
    # donating the tot0 carry lets XLA compose chained resume segments'
    # [B, MV, MV] operator products in place. Kept as a SEPARATE
    # wrapper: a failed fused-combine dispatch already received tot0, so
    # its tree retry must never donate (use-after-donate), and the CPU
    # backend can't honor donation at all (it would warn per call).
    from jepsen_tpu.parallel.pipeline import donate_ok

    def _tree_combine(P, inexact, tot0):
        return _combine(P, inexact, tot0)

    combine_tree = jax.jit(_tree_combine)
    combine_tree_donate = (jax.jit(_tree_combine, donate_argnums=(2,))
                           if donate_ok() else combine_tree)
    scan_total_donate = (jax.jit(_scan_total, donate_argnums=(5,))
                         if donate_ok() else scan_total)

    @jax.jit
    def combine_fused(P, inexact, tot0):
        """The fused streaming combine: each key's C chunk products
        stream through HBM exactly once into a VMEM-resident running
        product (pallas_matrix._build_combine), instead of the tree's
        ceil(log2 C) levels of [B, C_l, MV, MV] HBM round-trips.
        Bit-identical: boolean matrix products are exact under any
        association."""
        from jepsen_tpu.ops import pallas_matrix
        cfn = pallas_matrix.combine_product(B, C, MV)
        total = cfn(P.reshape(B, C, MV, MV), tot0.astype(jnp.bfloat16))
        alive = (total[:, :, init_state] > 0).any(axis=1)
        return alive, inexact.reshape(B, C).any(axis=1), total

    synced_shapes: set = set()

    def _sync_first(key, out):
        # jitted dispatch is async: a Mosaic RUNTIME fault (vs the
        # lowering faults the probes catch) would otherwise surface at
        # the caller's readback, outside the dispatch try. Deterministic
        # per compiled shape, so force one sync on each shape's first
        # execution and keep later dispatches pipelined.
        if key not in synced_shapes:
            import jax
            jax.block_until_ready(out)
            synced_shapes.add(key)

    def _dispatch_total(pend, op_ids, uops, slots, valid, tot0):
        from jepsen_tpu.ops import pallas_matrix

        force_variant, force_fused = _dispatch_overrides()
        info = {"variant": "scan", "combine": "tree"}
        U = int(uops.shape[0])
        fused_want = (force_fused if force_fused is not None
                      else pallas_matrix.fuse_combine_mode())
        use_fused = (fused_want is not False
                     and pallas_matrix.combine_enabled(MV))
        prod = None
        while True:
            variant = pallas_matrix.best_variant(S, V, force=force_variant)
            if variant is None:
                break
            try:
                # warm the kernel cache (and the hbm-pretile probe)
                # OUTSIDE the jit trace below
                pallas_matrix.chunk_product(S, V, T, U, variant=variant)
                out_p = pallas_products(variant)(pend, op_ids, uops,
                                                 slots, valid)
                _sync_first((pend.shape, uops.shape, variant), out_p)
                prod = out_p
                info["variant"] = variant
                break
            except Exception:  # noqa: BLE001 — lowering/runtime failure
                logger.warning("pallas matrix variant %r failed at %s; "
                               "demoting", variant, (S, V, T),
                               exc_info=True)
                pallas_matrix.disable(S, V, variant)
                # loop: best_variant now yields the next representation
        if prod is not None or use_fused:
            if prod is None:
                prod = scan_products(pend, op_ids, uops, slots, valid)
            P, inexact = prod
            if use_fused:
                try:
                    out = combine_fused(P, inexact, tot0)
                    _sync_first((pend.shape, "combine"), out)
                    info["combine"] = "fused"
                    _DISPATCH_INFO.value = info
                    return out
                except Exception:  # noqa: BLE001
                    logger.warning("fused combine failed at MV=%d; "
                                   "using the tree combine", MV,
                                   exc_info=True)
                    pallas_matrix.disable_combine(MV)
                    _DISPATCH_INFO.value = info
                    # tot0 was handed to the failed fused dispatch —
                    # the non-donating wrapper is mandatory
                    return combine_tree(P, inexact, tot0)
            _DISPATCH_INFO.value = info
            return combine_tree_donate(P, inexact, tot0)
        # neither pallas stage is active (e.g. the CPU fallback): the
        # pre-split single-jit path — one compile, one dispatch,
        # donation as before. The combine_tree_donate return above is
        # mutually exclusive with this line (both RETURN), so tot0 is
        # never read after its donation on any one control path — the
        # line-based rule can't see the early returns, hence the
        # waiver.
        _DISPATCH_INFO.value = info
        return scan_total_donate(pend, op_ids, uops, slots, valid,
                                 tot0)  # lint: ignore[donation-reuse]

    def run(pend, op_ids, uops, slots, valid):
        """pend [T,G,S]; op_ids [T,G,S] (indices into uops [U,3]);
        slots [T,G]; valid [T,G], with chunk g = key * C + chunk.
        Returns (alive[B], inexact[B])."""
        alive, inexact, _ = _dispatch_total(pend, op_ids, uops, slots, valid,
                                       jnp.broadcast_to(eye, (B, MV, MV)))
        return alive, inexact

    def run_resume(pend, op_ids, uops, slots, valid, tot0):
        """Segmented-verification variant: ``tot0`` [B, MV, MV] is the
        composed operator product of the previous segments (block
        composition is associative, so chaining segment products equals
        one monolithic run provided segments cut at quiescent points —
        the per-segment prepass assumes no pending ops at entry).
        Returns (alive, inexact, total) with total staying on device."""
        return _dispatch_total(pend, op_ids, uops, slots, valid, tot0)

    run.resume = run_resume
    # bf16 identity: the carry dtype must match scan_total's output or
    # the second chained segment retraces (and recompiles) mid-run
    run.init_total = lambda: jnp.broadcast_to(
        jnp.eye(MV, dtype=jnp.bfloat16), (B, MV, MV))
    return run


def _build_matrix_kernel_mesh(S: int, V: int, step_ids, init_state: int,
                              g_steps: int, n_chunks: int, n_keys: int,
                              mesh):
    """shard_map twin of _build_matrix_kernel over a device mesh.

    Two sharding modes, both built from the SAME step/combine math
    (_kernel_math) so mesh and single-device verdicts are bit-identical:

    * ``n_keys == 1`` — the segmented scale path / one long history:
      the chunk axis (C time-ordered chunks of T returns) shards over
      the mesh. Each device scans its CONTIGUOUS time span of chunks
      ([C/nd, MV, MV] local products), chains them locally, and the nd
      span products tree-combine device-side after one small
      ``all_gather`` ([nd, MV, MV] — the only collective). The composed
      total applies ``tot0`` and replicates, ready to carry into the
      next round. Exposes ``resume`` + ``init_total`` like the
      single-device kernel.
    * ``n_keys > 1`` — the jepsen.independent key batch: the key axis
      shards (the dispatch pads B to a device multiple upstream), each
      device runs the full scan + per-key combine for its own keys with
      ZERO cross-device traffic, and the per-key verdicts all_gather at
      the end — B bools over ICI instead of a host-side shard walk.

    Collectives unavailable (backend without mesh support) surface as
    dispatch exceptions; the checker ladder's ``sharded`` rung demotes
    to the single-device kernels rather than failing (checker/ladder.py,
    doc/robustness.md)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:                      # newer jax moved it
        from jax import shard_map  # type: ignore[attr-defined]

    nd = int(mesh.devices.size)
    ax = mesh.axis_names[0]
    B, C, T = n_keys, n_chunks, g_steps
    if B == 1:
        if C % nd:
            raise ValueError(
                f"chunk count {C} not divisible by {nd} devices: "
                f"_matrix_plan must pad the chunk axis first")
        G_local = C // nd
    else:
        if B % nd:
            raise ValueError(
                f"key count {B} not divisible by {nd} devices: "
                f"_matrix_dispatch must pad the key axis first")
        B_local = B // nd
        G_local = B_local * C
    math = _kernel_math(S, V, step_ids, G_local)
    MV, eye = math.MV, math.eye

    def local_products(pend, op_ids, uops, slots, valid):
        """This device's chunk block through the scan: [G_local, MV, MV]
        chunk products + per-chunk inexact flags."""
        mt_tab, oob_tab = math.uop_tables(uops)
        P0 = jnp.broadcast_to(eye, (G_local, MV, MV))
        (prod, inexact), _ = lax.scan(math.make_step(mt_tab, oob_tab),
                                      (P0, jnp.zeros((G_local,), bool)),
                                      (pend, op_ids, slots, valid))
        return prod, inexact

    if B == 1:
        def seg_total(pend, op_ids, uops, slots, valid, tot0):
            prod, inexact = local_products(pend, op_ids, uops, slots, valid)
            span = math.chain_time(prod)         # this device's time span
            # device order IS time order (contiguous chunk blocks), so
            # the gathered spans chain with the same later-on-the-LEFT
            # tree as the single-device combine
            spans = lax.all_gather(span, ax)     # [nd, MV, MV]
            total = math.chain_time(spans.astype(jnp.bfloat16))
            total = (jnp.einsum("ij,jk->ik", total,
                                tot0[0].astype(jnp.bfloat16),
                                preferred_element_type=jnp.bfloat16)
                     > 0).astype(jnp.bfloat16)
            alive = (total[:, init_state] > 0).any()
            ix = lax.psum(inexact.any().astype(jnp.int32), ax) > 0
            return alive[None], ix[None], total[None]

        fn = jax.jit(shard_map(
            seg_total, mesh=mesh,
            in_specs=(P(None, ax, None), P(None, ax, None), P(),
                      P(None, ax), P(None, ax), P()),
            out_specs=(P(), P(), P()), check_rep=False))

        def run(pend, op_ids, uops, slots, valid):
            alive, inexact, _ = fn(pend, op_ids, uops, slots, valid,
                                   run.init_total())
            return alive, inexact

        run.resume = fn
        run.init_total = lambda: jnp.broadcast_to(
            jnp.eye(MV, dtype=jnp.bfloat16), (1, MV, MV))
        return run

    combine = math.make_combine(B_local, C, init_state)

    def key_verdicts(pend, op_ids, uops, slots, valid):
        prod, inexact = local_products(pend, op_ids, uops, slots, valid)
        alive, ix, _ = combine(prod, inexact,
                               jnp.broadcast_to(eye, (B_local, MV, MV)))
        # gather so every device holds the full per-key verdict vector:
        # the caller's readback touches one shard instead of walking nd
        # (device order = key-block order, so the reshape restores the
        # original key order)
        return (lax.all_gather(alive, ax).reshape(-1),
                lax.all_gather(ix, ax).reshape(-1))

    run = jax.jit(shard_map(
        key_verdicts, mesh=mesh,
        in_specs=(P(None, ax, None), P(None, ax, None), P(),
                  P(None, ax), P(None, ax)),
        out_specs=(P(), P()), check_rep=False))
    return run


# matrix-path applicability: cost is quadratic in MV = 2^S * V (each
# return becomes an [MV, MV] operator), so the value domain must be small
# — the realistic register regime (a handful of distinct values), not
# arbitrary histories. Below MIN_RETURNS the event scan's sequential
# depth is short enough that composing matrices can't pay for itself.
MATRIX_MAX_SLOTS = 8
MATRIX_MAX_STATES = 16
MATRIX_MIN_RETURNS = 2000
# per-step [G, MV, MV] f32 intermediates: cap G * MV^2 (~1 GB at f32)
MATRIX_MAX_ELEMS = 1 << 28
# keys per dispatch: G = B*C beyond ~256 goes HBM-bound superlinearly,
# so bigger key batches pipeline as bounded sub-dispatches. 128 measured
# ~10% faster than 256 at both 256 and 1024 keys on the tunneled chip —
# smaller dispatches overlap their transfers with compute better while
# C=2 keeps G at the ~256 sweet spot
MATRIX_SUB_KEYS = 128
# sub-batch size for mid-size key batches (33..128 keys): small enough
# that 2-4 dispatches pipeline host prep against device compute, large
# enough that each still fills the chunk-count target. Env-tunable for
# on-chip sweeps without an edit-recompile loop.
MATRIX_PIPELINE_KEYS = _env_int("JEPSEN_TPU_PIPELINE_KEYS", 32)
# dispatches in flight before the pipeline's delayed blocking kicks in
# (bounds the [G, MV, MV] working sets resident on device at once)
PIPELINE_DEPTH = _env_int("JEPSEN_TPU_PIPELINE_DEPTH", 2)
# events per segment of a resumable matrix chain (matrix_check_segmented
# / the checker's segmented matrix rung): also the routing threshold —
# streams longer than one segment take the resumable chain so a crash
# or demotion mid-check keeps its completed segments
MATRIX_SEGMENT_EVENTS = _env_int("JEPSEN_TPU_MATRIX_SEGMENT_EVENTS",
                                 1 << 20)


def matrix_ok(S: int, num_states: int | None, n_returns: int) -> bool:
    return (num_states is not None and S <= MATRIX_MAX_SLOTS
            and num_states <= MATRIX_MAX_STATES
            and n_returns >= MATRIX_MIN_RETURNS)


def matrix_check(stream, step_ids=None, init_state: int = 0,
                 num_states: int | None = None, force: bool = False,
                 mesh=None, variant: str | None = None,
                 combine_fused: bool | None = None):
    """Fast exact-aliveness check of ONE history via block-composed
    transfer matrices. Returns (alive, died, overflow, peak) with
    died=-1/peak=0 placeholders — callers that need the failing event or
    frontier stats re-run the event scan (only relevant when not alive).
    Returns None when the matrix regime doesn't apply (``force=True``
    skips the size gate, for differential tests). With a ``mesh`` the
    chunk axis shards over the devices (the checker ladder's ``sharded``
    rung passes parallel.auto_mesh()). ``variant`` pins the kernel
    representation and ``combine_fused`` the combine path for this call
    (both probe-gated, demote-not-fail — doc/performance.md "Packed
    boolean kernels")."""
    if step_ids is None:
        step_ids = _default_step_ids()
    num_states = num_states if num_states is not None else len(stream.intern)
    kind, slot = np.asarray(stream.kind), np.asarray(stream.slot)
    # gate BEFORE the O(E) prepass: everything the gate needs is
    # computable from cheap array reductions
    S = int(slot.max(initial=0)) + 1
    R = int((kind == EV_RETURN).sum())
    if not force and not matrix_ok(S, num_states, R):
        return None
    return matrix_check_batch([stream], step_ids=step_ids,
                              init_state=init_state,
                              num_states=num_states, mesh=mesh,
                              variant=variant,
                              combine_fused=combine_fused)[0]


def matrix_check_resume(stream, tot0=None, step_ids=None,
                        init_state: int = 0, num_states: int | None = None,
                        n_slots: int | None = None, mesh=None,
                        variant: str | None = None,
                        combine_fused: bool | None = None):
    """Segmented transfer-matrix verification of one long history: checks
    a segment starting from the composed operator product ``tot0`` of the
    prior segments (None = identity) and returns
    ``(alive, inexact, total)`` with ``total`` staying on device for the
    next segment. Block composition is associative, so chaining segment
    products equals one monolithic run — provided segments cut at
    quiescent points (the per-segment prepass assumes no pending ops at
    entry; see quiescent_cuts) and share the slot dimension (pass
    ``n_slots`` to pin S across segments whose own concurrency differs).

    This is the scale path for long SMALL-DOMAIN histories: each return
    costs one [MV, MV] composition on the MXU instead of a sequential
    frontier step, and the carry is a single [MV, MV] product.

    Segments must also share the STATE basis: pass ``num_states`` (and
    build segment streams against one interning scheme) so every
    segment's value ids mean the same thing — tot0 is checked against
    the resulting operator dimension and a mismatch raises rather than
    composing over a permuted basis.

    With a ``mesh`` the segment's chunk axis shards over the devices
    (each device scans a contiguous time span, the span products
    tree-combine device-side after one [nd, MV, MV] all_gather — see
    _build_matrix_kernel_mesh). The carry is the same replicated
    [1, MV, MV] product either way, so a chain may freely mix sharded
    and single-device segments (the ladder's sharded→device demotion
    mid-chain is sound)."""
    if step_ids is None:
        step_ids = _default_step_ids()
    if num_states is None:
        num_states = len(stream.intern)
    V = _bucket(num_states, floor=8)
    prep = _returns_prepass(np.asarray(stream.kind), np.asarray(stream.slot),
                            np.asarray(stream.f), np.asarray(stream.a),
                            np.asarray(stream.b))
    S = max(n_slots or 1, prep[3])
    if tot0 is not None and tot0.shape[-1] != (1 << S) * V:
        raise ValueError(
            f"carry dimension {tot0.shape[-1]} != (1<<{S})*{V}: segments "
            f"must share n_slots and num_states")
    R_max = prep[0].shape[0]
    if R_max == 0:
        # no returns in this segment: the chain's aliveness is whatever
        # the carried product says (a dead chain must not revive)
        if tot0 is None:
            return True, False, tot0
        alive = (np.asarray(tot0)[:, :, init_state] > 0).any(axis=1)
        return alive, False, tot0
    with _routing_overrides(variant, combine_fused):
        out = _matrix_dispatch([prep], S, R_max, V, step_ids, init_state,
                               mesh, resume=True, tot0=tot0)
    return out[0], out[1], out[2]


def matrix_segmented_config(S, V, init_state, num_states, max_segment,
                            variant, combine_fused, step_ids=None) -> dict:
    """The knob/shape fingerprint a segmented-matrix checkpoint is
    valid under — ONE constructor shared by the writer
    (matrix_check_segmented) and out-of-band checkpoint authors
    (bench.py's resume_savings stage, tests), so a fingerprint drift
    between them is impossible by construction. ``step_ids`` stamps
    the model identity: the prefix hash covers only the encoded
    columns, which are model-independent, so a model swap between
    interrupt and resume must discard on the config instead."""
    from jepsen_tpu.checker.checkpoint import step_identity
    if step_ids is None:
        step_ids = _default_step_ids()
    return {"path": "matrix", "S": S, "V": V, "init_state": init_state,
            "num_states": num_states, "max_segment": max_segment,
            "variant": variant, "combine_fused": combine_fused,
            "step": step_identity(step_ids)}


def matrix_check_segmented(stream, step_ids=None, init_state: int = 0,
                           num_states: int | None = None,
                           n_slots: int | None = None, mesh=None,
                           variant: str | None = None,
                           combine_fused: bool | None = None,
                           max_segment: int | None = None,
                           ckpt=None, carry: dict | None = None,
                           carry_sink=None):
    """One long small-domain history through a crash-resumable chain of
    :func:`matrix_check_resume` segments cut at quiescent points.
    Returns the :func:`matrix_check` quad ``(alive, -1, inexact, 0)``.

    Resumable two ways (doc/robustness.md "Resumable checks and the
    elastic mesh"):

    * ``ckpt`` — a :class:`~jepsen_tpu.checker.checkpoint.CheckpointStore`:
      the composed ``tot0`` product persists after each segment when
      the write interval elapses; a valid ``matrix`` checkpoint (same
      S/V/knobs, matching consumed-prefix hash) resumes the chain at
      its cut. Bit-identical: boolean operator products are exact
      under any association, so a resumed chain composes the same
      total as an uninterrupted one.
    * ``carry``/``carry_sink`` — the in-process twin for the checker
      ladder: after each exact segment ``carry_sink`` receives
      ``{"rep": "matrix", "tot0", "events_done", "S", "V",
      "init_state"}``, and a matching ``carry`` passed back in resumes
      mid-chain — how a watchdog-demoted or mesh-shrunk rung keeps its
      completed segments instead of restarting.

    Soundness: an INEXACT segment (oob transition) aborts the chain
    immediately WITHOUT sinking or persisting its carry — an
    under-approximate product must never seed an exact resume. Dead
    carries are likewise never persisted (the verdict settles now).
    With a ``mesh`` each segment's chunk axis shards over the devices;
    the carry is the same replicated product either way, so a chain
    may shrink or demote its mesh between segments freely."""
    if step_ids is None:
        step_ids = _default_step_ids()
    if num_states is None:
        num_states = len(stream.intern)
    V = _bucket(num_states, floor=8)
    kind = np.asarray(stream.kind)
    slot = np.asarray(stream.slot)
    S = max(n_slots or 1, int(slot.max(initial=0)) + 1)
    if max_segment is None:
        max_segment = MATRIX_SEGMENT_EVENTS
    cuts = quiescent_cuts(kind, max_segment)
    cut_set = set(cuts)
    n = len(kind)
    base, seg_i = 0, 0
    tot = None
    inexact_any = False
    config = ckpt_mod = None
    if ckpt is not None:
        from jepsen_tpu.checker import checkpoint as ckpt_mod
        config = matrix_segmented_config(S, V, init_state, num_states,
                                         max_segment, variant,
                                         combine_fused,
                                         step_ids=step_ids)
    # in-process carry first (it is at least as fresh as the durable
    # checkpoint: the sink runs every segment, the store on an interval)
    if carry is not None:
        if (carry.get("rep") == "matrix" and carry.get("S") == S
                and carry.get("V") == V
                and carry.get("init_state") == init_state
                and carry.get("events_done") in cut_set):
            tot = carry["tot0"]
            base = int(carry["events_done"])
            seg_i = cuts.index(base) + 1
            from jepsen_tpu.checker.checkpoint import count_resume
            count_resume("carry")
            logger.info("segmented matrix check resuming from in-process "
                        "carry at event %d/%d", base, n)
        else:
            logger.warning("matrix carry (S=%r V=%r events=%r) doesn't "
                           "fit this stream (S=%d V=%d); restarting",
                           carry.get("S"), carry.get("V"),
                           carry.get("events_done"), S, V)
    if tot is None and ckpt is not None:
        state = ckpt_mod.load_resume(ckpt, "matrix", config, stream)
        if state is not None and state["events_done"] in cut_set:
            tot = ckpt_mod.decode_array(state["carry"]["tot0"])
            base = int(state["events_done"])
            seg_i = cuts.index(base) + 1
            ckpt_mod.count_resume("ckpt")
            logger.info("resuming segmented matrix check from %s at "
                        "event %d/%d", ckpt.path, base, n)
        elif state is not None:
            logger.warning("matrix checkpoint's cut %d is not a "
                           "quiescent cut of this stream; restarting",
                           state["events_done"])
    from jepsen_tpu import trace as trace_mod
    tracer = trace_mod.get_tracer()
    for end in cuts:
        if end <= base:
            continue
        seg = _slice_stream(stream, base, end)
        seg_t0 = trace_mod.now_us() if tracer.enabled else 0
        alive, ix, tot = matrix_check_resume(
            seg, tot, step_ids=step_ids, init_state=init_state,
            num_states=num_states, n_slots=S, mesh=mesh, variant=variant,
            combine_fused=combine_fused)
        alive_b = bool(np.asarray(alive).all())
        ix_b = bool(np.asarray(ix).any())
        if tracer.enabled:
            tracer.complete(trace_mod.TRACK_CHECKPOINT, "segment",
                            seg_t0, trace_mod.now_us() - seg_t0,
                            args={"base": base, "end": end,
                                  "alive": alive_b, "inexact": ix_b})
        if ix_b:
            # an oob escape proves nothing — and its under-approximate
            # carry must never seed an exact resume: abort unsunk
            return alive_b, -1, True, 0
        if not alive_b:
            return False, -1, inexact_any, 0
        base = end
        seg_i += 1
        if carry_sink is not None:
            carry_sink({"rep": "matrix", "tot0": tot, "events_done": base,
                        "S": S, "V": V, "init_state": init_state})
        if ckpt is not None and base < n:
            def make_state(tot=tot, base=base, seg_i=seg_i):
                return {
                    "kind": "matrix", "config": config,
                    "events_done": base, "segment": seg_i,
                    "prefix_hash": ckpt_mod.stream_prefix_hash(stream,
                                                               base),
                    "carry": {"tot0": ckpt_mod.encode_array(
                        np.asarray(tot))},
                }
            ckpt.maybe_save(make_state, base)
    return True, -1, inexact_any, 0


def matrix_check_batch(streams, step_ids=None, init_state: int = 0,
                       num_states: int | None = None, mesh=None,
                       variant: str | None = None,
                       combine_fused: bool | None = None):
    """Batched transfer-matrix check over independent per-key histories
    (the jepsen.independent regime, BASELINE config 3). All keys' chunk
    products advance together in one [B*C, MV, MV] MXU matmul per scan
    step, then each key's chunks chain separately — so B keys cost the
    same sequential depth as one. With a mesh, the chunk axis G = B*C is
    sharded over the mesh's first axis (each device multiplies its own
    chunk block; the per-key combine re-shards on keys), so the batch
    scales over ICI like the rest of the checker data plane. Returns
    [(alive, -1, inexact, 0)] per stream; callers needing failure
    diagnostics re-run the event scan on the not-alive keys. Callers gate
    the regime (matrix_ok on max S / max V / total returns) before paying
    the prepass."""
    import jax

    if step_ids is None:
        step_ids = _default_step_ids()
    if num_states is None:
        num_states = max(len(s.intern) for s in streams)
    V = _bucket(num_states, floor=8)
    B = len(streams)
    # global (S, R_max) from cheap metadata passes, so the EXPENSIVE
    # prepass can run per sub-batch inside the dispatch pipeline below
    # (every sub-batch still compiles at the one shared shape)
    kinds = [np.asarray(s.kind) for s in streams]
    slots_np = [np.asarray(s.slot) for s in streams]
    S = max(int(sl.max(initial=0)) + 1 for sl in slots_np)
    R_max = max(int((k == EV_RETURN).sum()) for k in kinds)
    if R_max == 0:
        return [(True, -1, False, 0)] * B
    # every matrix dispatch — key batches, the ladder's sharded rung,
    # the live daemon's screens, segmented rounds via matrix_check —
    # feeds the per-device-count rate model here, so mesh_route's
    # measured-rate comparison activates no matter which caller runs
    # (doc/performance.md "The cost gate")
    total_events = sum(len(k) for k in kinds)
    t_start = time.perf_counter()

    def observe(n_devices: int) -> None:
        from jepsen_tpu.parallel import pipeline
        pipeline.observe_device_rate(n_devices, total_events,
                                     time.perf_counter() - t_start)

    def prep(i):
        s = streams[i]
        return _returns_prepass(kinds[i], slots_np[i], np.asarray(s.f),
                                np.asarray(s.a), np.asarray(s.b))

    # Key batches split into pipelined sub-dispatches: per-step cost
    # grows superlinearly with G = B*C past the measured sweet spot
    # (the [G, MV, MV] intermediates go HBM-bound), so a pipeline of
    # bounded dispatches beats one huge dispatch. Sub-batch k+1's host
    # prepass + grid build + H2D staging all run while batch k computes
    # on device (DispatchPipeline: async dispatches, delayed blocking at
    # the depth limit, one batched readback at the end) — on a tunneled
    # accelerator that hides most of the host wall-clock.
    # MATRIX_PIPELINE_KEYS extends the overlap to mid-size batches
    # (r4 weak #4 / r5 weak #2: 64-key configs were tunnel/host-bound).
    # (A mesh shards G across devices, shifting the sweet spot; the
    # mesh path keeps the single dispatch.)
    sub = MATRIX_SUB_KEYS if B > MATRIX_SUB_KEYS else MATRIX_PIPELINE_KEYS
    if mesh is None and B > sub:
        from jepsen_tpu.parallel.pipeline import DispatchPipeline

        # a short remainder sub-batch would compile at its own shape
        # (and a B'=1 tail would even flip the chunk target): pad it
        # with empty keys (R=0 -> identity product, trivially alive)
        # so EVERY dispatch shares the one compiled shape
        C, T = _matrix_plan(sub, S, R_max, V, None)
        run = _matrix_cache(S, V, step_ids, init_state, T, C, sub)
        pipe = DispatchPipeline(depth=PIPELINE_DEPTH, name="matrix")
        phases = {"prepass": 0.0, "grids": 0.0, "dispatch": 0.0}
        counts = []
        with _routing_overrides(variant, combine_fused):
            for lo in range(0, B, sub):
                def stage(lo=lo):
                    t0 = time.perf_counter()
                    sl = [prep(i) for i in range(lo, min(lo + sub, B))]
                    counts.append(len(sl))
                    sl += [_EMPTY_PREP] * (sub - len(sl))
                    t1 = time.perf_counter()
                    # build + STAGE the grids now (device_put issues the
                    # H2D copies immediately, overlapping in-flight
                    # compute)
                    grids, uops = _matrix_grids(sl, S, V, sub, C, T, None)
                    args = pipe.stage(*grids, uops)
                    phases["prepass"] += t1 - t0
                    phases["grids"] += time.perf_counter() - t1
                    return tuple(args)

                def dispatch(pend, ids, slots, valid, uops):
                    t0 = time.perf_counter()
                    out = run(pend, ids, uops, slots, valid)
                    phases["dispatch"] += time.perf_counter() - t0
                    return out

                pipe.submit(stage, dispatch)
            t0 = time.perf_counter()
            fetched = pipe.results()
        phases["fetch"] = time.perf_counter() - t0
        _publish_phases(phases)
        out = []
        for nb, (a, ix) in zip(counts, fetched):
            out += [(bool(a[b]), -1, bool(ix[b]), 0) for b in range(nb)]
        observe(1)
        return out

    phases = {}
    t0 = time.perf_counter()
    preps = [prep(i) for i in range(B)]
    phases["prepass"] = time.perf_counter() - t0
    with _routing_overrides(variant, combine_fused):
        handle = _matrix_dispatch(preps, S, R_max, V, step_ids, init_state,
                                  mesh, phases=phases)
        t0 = time.perf_counter()
        alive, inexact = jax.device_get(handle)
    phases["fetch"] = time.perf_counter() - t0
    _publish_phases(phases)
    observe(1 if mesh is None else int(mesh.devices.size))
    return [(bool(alive[b]), -1, bool(inexact[b]), 0) for b in range(B)]


def _publish_phases(phases: dict) -> None:
    """Rounds the measured host/device split and annotates it with the
    dispatch routing labels (variant + combine path) for this thread's
    ``last_phase_seconds`` readers — the per-variant attribution
    bench.py folds into the matrix metrics."""
    out = {k: round(v, 4) for k, v in phases.items()}
    out.update(last_dispatch_info())
    _PHASE.value = out


def _matrix_plan(B, S, R_max, V, mesh):
    """(C, T) for one sub-batch's chunk layout: per key, C chunks of T
    returns (padded with identity); chunk g = b*C + c. R is bucketed so
    (T, C, B) — and therefore the compiled program — is shared across
    nearby history lengths. The total chunk count targets G = B*C ≈ 256:
    measured on-device, the per-step cost grows superlinearly with G
    (the [G, MV, MV] intermediates become HBM-bound) while G ≥ ~128
    already saturates the matmul units, so more parallel chunks past
    that point only slows each of the fewer steps down. C is
    additionally capped by the element budget."""
    MV = (1 << S) * V
    nd = int(mesh.devices.size) if mesh is not None else 1
    # with a mesh the per-step [G, MV, MV] working set shards over the
    # devices, so the element budget binds PER DEVICE — the key count a
    # single device must hold is ceil(B/nd) (the dispatch pads B up to a
    # device multiple for the key-sharded kernel)
    budget_keys = B if mesh is None else -(-B // nd)
    if budget_keys * MV * MV > MATRIX_MAX_ELEMS:
        # even C=1 would allocate over-budget [B, MV, MV] intermediates;
        # callers pre-gate with matrix_ok, so a direct caller this large
        # must hear "out of regime" rather than OOM the device
        raise ValueError(
            f"matrix_check_batch out of regime: keys/device * MV^2 = "
            f"{budget_keys * MV * MV} > {MATRIX_MAX_ELEMS}; split the "
            f"key batch or use the scan")
    rb = _bucket(R_max, floor=64)
    # chunk-count target, measured on-chip (r5 sweep, 64x1k keys):
    # G = B*C ≈ 2048 beats the old 256 target by ~9% on key BATCHES
    # (234k -> 254k ops/s; 4096 flat, 8192 degrades HBM-bound), while
    # single histories (B=1, incl. the segmented scale path) measured
    # best at the old 256 — padding past their return count buys
    # nothing. Per-key C stays capped at 256.
    target_g = 256 if B == 1 else 2048
    C = int(np.clip(target_g // B, 1, 256))
    C = max(1, min(C, MATRIX_MAX_ELEMS // (budget_keys * MV * MV)))
    if mesh is not None and B == 1:
        # the chunk axis shards over the mesh: pad C up to a device
        # multiple (identity chunks, visible in the
        # checker_mesh_padding_frac gauge) instead of the old silent
        # fall-back to an unsharded dispatch. Always within budget: the
        # per-device block C/nd * MV^2 never exceeds the unsharded
        # C * MV^2 the budget already admitted.
        C = -(-max(C, nd) // nd) * nd
    T = -(-rb // C)
    return C, T


def _matrix_grids(preps, S, V, B, C, T, mesh):
    """HOST side of one sub-batch dispatch: pads each key's return
    grids into the (T, G) chunk layout and interns the batch's distinct
    ops. Returns ([pend, ids, slots, valid] grids, uops) — everything
    the kernel call needs, so a pipeline can run this (and the H2D
    staging) while the previous sub-batch computes."""
    import jax

    def key_arrays(p):
        r_slot, r_pend, r_ops, s_k = p
        R = r_slot.shape[0]
        pad = C * T - R
        slot_p = np.concatenate([r_slot, np.zeros((pad,), np.int32)])
        pend_p = np.zeros((C * T, S), bool)
        pend_p[:R, :s_k] = r_pend
        ops_p = np.zeros((C * T, S, 3), np.int64)
        ops_p[:R, :s_k] = r_ops
        val_p = np.concatenate([np.ones((R,), bool), np.zeros((pad,), bool)])
        return slot_p, pend_p, ops_p, val_p

    slots, pends, opss, vals = zip(*[key_arrays(p) for p in preps])
    # Intern the batch's distinct (f, a, b) ops: the kernel receives small
    # int id grids plus one [U, 3] table instead of a [T, G, S, 3] int64
    # op tensor — an ~8x transfer cut that matters on tunneled devices,
    # and the per-op transition matrices get built once instead of per
    # scan step.
    all_ops = np.concatenate([o.reshape(-1, 3) for o in opss])
    # interning via packed scalar keys when fields fit 21 bits (the
    # in-regime case: f codes and interned value ids are tiny) — a 1-D
    # unique sorts ~10x faster than np.unique(axis=0)'s row view
    if all_ops.size and 0 <= all_ops.min() and all_ops.max() < (1 << 21):
        packed = ((all_ops[:, 0] << 42) | (all_ops[:, 1] << 21)
                  | all_ops[:, 2])
        keys, inv = np.unique(packed, return_inverse=True)
        uops = np.stack([keys >> 42, (keys >> 21) & 0x1FFFFF,
                         keys & 0x1FFFFF], axis=1)
    else:
        uops, inv = np.unique(all_ops, axis=0, return_inverse=True)
    # id/slot grids ride the narrowest exact dtype — the grids are the
    # bulk of host→device traffic and the tunnel is bandwidth-bound
    id_dtype = np.int16 if len(uops) < (1 << 15) else np.int32
    ids = inv.astype(id_dtype).reshape(B, C * T, S)
    ub = _bucket(len(uops), floor=16)
    uops = np.concatenate(
        [uops, np.zeros((ub - len(uops), 3), uops.dtype)]).astype(np.int32)

    def as_tg(x):
        # [B, C*T, ...] → [B, C, T, ...] → [T, B, C, ...] → [T, B*C, ...]
        x = np.asarray(x).reshape((B, C, T) + x.shape[2:])
        x = np.moveaxis(x, 2, 0)
        return x.reshape((T, B * C) + x.shape[3:])

    grids = [as_tg(np.stack(pends)), as_tg(ids),
             as_tg(np.stack(slots).astype(np.int8)), as_tg(np.stack(vals))]
    if mesh is not None:
        # the chunk axis G = B*C is a device multiple by construction
        # (_matrix_plan bumps C for B == 1, _matrix_dispatch pads the
        # key axis otherwise — the old path here silently DROPPED the
        # sharding on a non-divisible G): stage each device's block down
        # its own transfer lane
        from jepsen_tpu.parallel import shard_chunked
        grids = shard_chunked(mesh, grids, axis=1)
    return grids, uops


# empty key prep (R=0): its chunks are all-invalid, so its product is
# the identity — trivially alive, trivially exact. The key-axis pad for
# mesh divisibility, and the pipelined path's tail pad, both use it.
_EMPTY_PREP = (np.zeros(0, np.int32), np.zeros((0, 1), bool),
               np.zeros((0, 1, 3), np.int64), 1)


def _publish_mesh_padding(B_real, B_pad, S, R_max, V, C, T):
    """``checker_mesh_padding_frac``: the fraction of a sharded
    dispatch's chunk-step work (G * T) spent on mesh-divisibility
    padding — identity chunks from bumping C (B == 1) or padded keys.
    The cost of never silently dropping sharding, kept visible."""
    from jepsen_tpu import telemetry
    reg = telemetry.get_registry()
    if not reg.enabled:
        return
    try:
        c0, t0 = _matrix_plan(B_real, S, R_max, V, None)
        frac = max(0.0, 1.0 - (B_real * c0 * t0) / float(B_pad * C * T))
    except ValueError:
        # the unsharded plan can be out of budget where the per-device
        # sharded one is not: no meaningful baseline, skip the gauge
        return
    reg.gauge("checker_mesh_padding_frac",
              "fraction of sharded chunk-step work spent on mesh "
              "divisibility padding, last sharded dispatch").set(frac)


def _matrix_dispatch(preps, S, R_max, V, step_ids, init_state, mesh,
                     resume: bool = False, tot0=None, phases: dict | None
                     = None):
    """Builds one sub-batch's chunk grids and dispatches the kernel,
    returning UNSYNCED device arrays (alive[B], inexact[B]; plus the
    composed total[B, MV, MV] when ``resume``) so callers can pipeline
    several dispatches before reading any back. With a mesh the dispatch
    shards (chunk axis for B == 1, key axis otherwise — the key axis is
    padded HERE with empty keys to a device multiple; callers index only
    their real keys). ``phases`` (optional) collects the host
    grids/dispatch wall split for attribution."""
    B_real = len(preps)
    if mesh is not None and B_real > 1:
        nd = int(mesh.devices.size)
        if B_real % nd:
            preps = list(preps) + [_EMPTY_PREP] * ((-B_real) % nd)
    B = len(preps)
    C, T = _matrix_plan(B, S, R_max, V, mesh)
    if mesh is not None:
        _publish_mesh_padding(B_real, B, S, R_max, V, C, T)
        # the mesh twin runs the XLA scan + device-side tree combine by
        # construction (collectives pair with the tree — see
        # _build_matrix_kernel_mesh); label the routing accordingly
        _DISPATCH_INFO.value = {"variant": "scan", "combine": "tree"}
    t0 = time.perf_counter()
    grids, uops = _matrix_grids(preps, S, V, B, C, T, mesh)
    t1 = time.perf_counter()
    run = _matrix_cache(S, V, step_ids, init_state, T, C, B, mesh)
    if resume:
        if tot0 is None:
            tot0 = run.init_total()
        out = run.resume(grids[0], grids[1], uops, grids[2], grids[3],
                         tot0)
    else:
        out = run(grids[0], grids[1], uops, grids[2], grids[3])
    if phases is not None:
        phases["grids"] = phases.get("grids", 0.0) + (t1 - t0)
        phases["dispatch"] = (phases.get("dispatch", 0.0)
                              + time.perf_counter() - t1)
    return out


_MATRIX_CACHE: dict = {}
_DEFAULT_STEP_IDS = None


def _default_step_ids():
    """One shared default spec — a fresh object per call would defeat
    the id()-keyed compile cache."""
    global _DEFAULT_STEP_IDS
    if _DEFAULT_STEP_IDS is None:
        from jepsen_tpu.models import cas_register_spec
        _DEFAULT_STEP_IDS = cas_register_spec().step_ids
    return _DEFAULT_STEP_IDS


def _matrix_cache(S, V, step_ids, init_state, T, C, B=1, mesh=None):
    # the uop-table length is a runtime array shape — jax.jit retraces on
    # it, so it doesn't belong in this key. A mesh keys on its device ids
    # + axis names: parallel.auto_mesh caches one Mesh per device count,
    # so repeated sharded dispatches hit the same compiled kernel.
    mesh_key = (None if mesh is None else
                (tuple(int(d.id) for d in mesh.devices.flat),
                 tuple(mesh.axis_names)))
    key = (S, V, id(step_ids), init_state, T, C, B, mesh_key)
    fn = _MATRIX_CACHE.get(key)
    if fn is None:
        if mesh is not None:
            fn = _build_matrix_kernel_mesh(S, V, step_ids, init_state, T,
                                           C, n_keys=B, mesh=mesh)
        else:
            fn = _build_matrix_kernel(S, V, step_ids, init_state, T, C,
                                      n_keys=B)
        _MATRIX_CACHE[key] = fn
    return fn


# ---------------------------------------------------------------------------
# Anomaly forensics: device-side first-anomaly localization
# (checker/explain.py drives these — doc/observability.md "Anomaly
# forensics")
# ---------------------------------------------------------------------------

def _build_forensics_kernel(S: int, V: int, step_ids, T: int, C: int):
    """Device programs for localizing WHERE a transfer-matrix verdict
    went invalid, built from the same `_kernel_math` as the checking
    kernels so localization can never disagree with the verdict:

    * ``products`` — the chunk scan WITHOUT the final combine: every
      chunk's composed [MV, MV] operator product comes back instead of
      one verdict, so localization can bisect over them.
    * ``prefix_alive`` — an associative inclusive scan composing the
      chunk products into prefix products (log-depth on device; boolean
      matrix products are exact under any association, so the scan's
      re-pairing cannot change a verdict) and testing each prefix's
      frontier for survivors: the first dead prefix names the guilty
      chunk in O(log C) combine depth instead of a CPU re-scan.
    * ``vec_batch`` — a vmapped per-return re-scan of ONE chunk's
      operators applied to a [MV] frontier *vector* (not the [MV, MV]
      matrix — ~MV× cheaper per step), returning each candidate's first
      dead return: the within-chunk localization step AND the witness
      shrinker's candidate-mask evaluator (checker/explain.py ddmin).
    """
    import types

    import jax
    import jax.numpy as jnp
    from jax import lax

    math = _kernel_math(S, V, step_ids, C)
    MV, eye = math.MV, math.eye

    @jax.jit
    def products(pend, op_ids, uops, slots, valid):
        mt_tab, oob_tab = math.uop_tables(uops)
        P0 = jnp.broadcast_to(eye, (C, MV, MV))
        (P, inexact), _ = lax.scan(math.make_step(mt_tab, oob_tab),
                                   (P0, jnp.zeros((C,), bool)),
                                   (pend, op_ids, slots, valid))
        return P, inexact

    @jax.jit
    def prefix_alive(P, v0):
        def comb(a, b):
            # a holds earlier chunks' accumulated product, b later ones:
            # time order composes later-on-the-LEFT like chain_time
            out = jnp.einsum("...ij,...jk->...ik", b, a,
                             preferred_element_type=jnp.bfloat16)
            return (out > 0).astype(jnp.bfloat16)

        prefix = lax.associative_scan(comb, P)
        # frontier after chunk c = column init of prefix[c] @ tot0, i.e.
        # prefix[c] @ v0 with v0 the carry's init column
        w = jnp.einsum("cij,j->ci", prefix, v0.astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)
        return (w > 0).any(axis=1), prefix

    vmath = _kernel_math(S, V, step_ids, 1)

    def _vec_scan(pend, valid, op_ids, uops, slots, v0):
        """One candidate: the chunk's T return operators applied to the
        frontier vector ``v0``; returns (first dead return or -1,
        inexact)."""
        mt_tab, oob_tab = vmath.uop_tables(uops)
        base = vmath.make_step(mt_tab, oob_tab)

        def step(carry, inp):
            carry2, _ = base(carry, inp)
            vec, _ = carry2
            return carry2, (vec[0, :, 0] > 0).any()

        # ride make_step's [G=1, MV, MV] @ [G=1, MV, k] matmul with the
        # vector as a k=1 matrix — same operators, MV× less work
        P0 = v0.astype(jnp.bfloat16).reshape(1, MV, 1)
        (_, inexact), alive = lax.scan(
            step, (P0, jnp.zeros((1,), bool)),
            (pend[:, None, :], op_ids[:, None, :], slots[:, None],
             valid[:, None]))
        first = jnp.where(alive.all(), jnp.int32(-1),
                          jnp.argmax(~alive).astype(jnp.int32))
        return first, inexact.any()

    vec_batch = jax.jit(jax.vmap(_vec_scan,
                                 in_axes=(0, 0, None, None, None, None)))
    return types.SimpleNamespace(products=products,
                                 prefix_alive=prefix_alive,
                                 vec_batch=vec_batch)


_FORENSICS_CACHE: dict = {}


def _forensics_cache(S, V, step_ids, T, C):
    key = (S, V, id(step_ids), T, C)
    fk = _FORENSICS_CACHE.get(key)
    if fk is None:
        fk = _build_forensics_kernel(S, V, step_ids, T, C)
        _FORENSICS_CACHE[key] = fk
    return fk


class MatrixLocalization:
    """A settled device-side localization: WHERE the transfer-matrix
    frontier first died, plus the handles checker/explain.py needs to
    delta-debug a minimal witness over the guilty window (the chunk's
    host grids and the frontier vector at its entry)."""

    def __init__(self, failed_return, failed_event, failed_op_index,
                 bisect_steps, chunk, step, n_chunks, chunk_returns,
                 kernel, uops, window_pend, window_ids, window_slots,
                 window_valid, v_start, ret_idx):
        self.failed_return = failed_return      # global return index
        self.failed_event = failed_event        # stream event index
        self.failed_op_index = failed_op_index  # history op index
        self.bisect_steps = bisect_steps
        self.chunk = chunk                      # guilty chunk c*
        self.step = step                        # chunk-relative return t*
        self.n_chunks = n_chunks
        self.chunk_returns = chunk_returns      # T
        self.kernel = kernel                    # forensics kernel ns
        self.uops = uops
        self.window_pend = window_pend          # [T, S] guilty chunk grids
        self.window_ids = window_ids
        self.window_slots = window_slots
        self.window_valid = window_valid
        self.v_start = v_start                  # [MV] frontier at entry
        self.ret_idx = ret_idx                  # return -> event index map


def matrix_localize(stream, tot0=None, step_ids=None, init_state: int = 0,
                    num_states: int | None = None, n_slots: int | None = None):
    """Localizes the first anomaly of an INVALID matrix-family verdict
    entirely on device: re-derives the per-chunk operator products (one
    dispatch of the same cost as the check), bisects the composable
    prefix products for the first dead chunk (O(log C) combine depth —
    `prefix_alive`), then pinpoints the return within it with a cheap
    [MV]-vector re-scan. The result's ``failed_event`` is bit-identical
    to the exact CPU frontier's first rejection (the operators ARE the
    frontier transition — pinned by tests/test_explain.py across
    single-device, segmented, sharded-mesh, and live-screen backends).

    ``tot0`` carries a segmented chain's composed prior product
    (matrix_check_resume's output), so a failing segment localizes
    without re-scanning the chain; event/op indices are then relative to
    THIS segment's stream (its ``op_index`` column keeps them absolute).

    Returns a :class:`MatrixLocalization`, or None when the stream is
    alive, out of plan budget, or inexact (an oob transition proves
    nothing — the exact CPU frontier must settle it instead)."""
    import jax.numpy as jnp

    if step_ids is None:
        step_ids = _default_step_ids()
    if num_states is None:
        num_states = len(stream.intern)
    V = _bucket(num_states, floor=8)
    kind = np.asarray(stream.kind)
    prep = _returns_prepass(kind, np.asarray(stream.slot),
                            np.asarray(stream.f), np.asarray(stream.a),
                            np.asarray(stream.b))
    S = max(n_slots or 1, prep[3])
    R = prep[0].shape[0]
    if R == 0:
        return None
    MV = (1 << S) * V
    if tot0 is not None and np.asarray(tot0).shape[-1] != MV:
        raise ValueError(
            f"carry dimension {np.asarray(tot0).shape[-1]} != {MV}: "
            f"segments must share n_slots and num_states")
    try:
        C, T = _matrix_plan(1, S, R, V, None)
    except ValueError:
        return None  # out of element budget: the CPU frontier settles it
    grids, uops = _matrix_grids([prep], S, V, 1, C, T, None)
    fk = _forensics_cache(S, V, step_ids, T, C)
    P, inexact = fk.products(grids[0], grids[1], uops, grids[2], grids[3])
    if bool(np.asarray(inexact).any()):
        return None  # oob transition: localization would prove nothing
    if tot0 is not None:
        v0 = (jnp.asarray(tot0).reshape(-1, MV, MV)[0][:, init_state]
              > 0).astype(jnp.bfloat16)
    else:
        v0 = jnp.zeros((MV,), jnp.bfloat16).at[init_state].set(1)
    alive, prefix = fk.prefix_alive(P, v0)
    alive = np.asarray(alive)
    if alive.all():
        return None  # the (carried) history is alive: nothing to localize
    c_star = int(np.argmax(~alive))
    if c_star == 0:
        v_start = v0
    else:
        v_start = (jnp.einsum("ij,j->i", prefix[c_star - 1], v0,
                              preferred_element_type=jnp.float32)
                   > 0).astype(jnp.bfloat16)
    pend_c = np.asarray(grids[0])[:, c_star]
    ids_c = np.asarray(grids[1])[:, c_star]
    slots_c = np.asarray(grids[2])[:, c_star]
    valid_c = np.asarray(grids[3])[:, c_star]
    first, inexact2 = fk.vec_batch(pend_c[None], valid_c[None], ids_c,
                                   uops, slots_c, v_start)
    t_star = int(np.asarray(first)[0])
    if t_star < 0 or bool(np.asarray(inexact2).any()):
        # the chunk verdict and its per-return re-scan disagree — a bug
        # or an oob escape; never report a guessed position
        logger.warning("matrix localization inconsistency at chunk %d "
                       "(first=%d); declining", c_star, t_star)
        return None
    r_star = c_star * T + t_star
    ret_idx = np.nonzero(kind == EV_RETURN)[0]
    event = int(ret_idx[r_star])
    op_index = int(np.asarray(stream.op_index)[event])
    bisect_steps = max(1, int(np.ceil(np.log2(max(C, 2))))) + 1
    return MatrixLocalization(
        failed_return=r_star, failed_event=event, failed_op_index=op_index,
        bisect_steps=bisect_steps, chunk=c_star, step=t_star, n_chunks=C,
        chunk_returns=T, kernel=fk, uops=uops, window_pend=pend_c,
        window_ids=ids_c, window_slots=slots_c, window_valid=valid_c,
        v_start=v_start, ret_idx=ret_idx)


def matrix_window_rescan(loc: MatrixLocalization, pend_batch, valid_batch):
    """First dead return (chunk-relative; -1 = survives) for each
    candidate's masked (pend, valid) grids over the localized chunk,
    evaluated as ONE vmapped device dispatch — the witness shrinker's
    inner loop (checker/explain.py). Callers bucket the candidate count
    so the vmapped kernel compiles at a handful of batch shapes."""
    first, _ = loc.kernel.vec_batch(
        np.ascontiguousarray(pend_batch),
        np.ascontiguousarray(valid_batch),
        loc.window_ids, loc.uops, loc.window_slots, loc.v_start)
    return np.asarray(first)


# dense-table applicability bounds. Besides the per-axis caps, the closure
# materializes an [S, 2^S, V] f32 intermediate per batch element, so gate
# on the product too: S * 2^S * V elements (4 bytes each) must stay under
# a few MB or a vmapped batch of keys would blow device memory where the
# sparse kernel needs kilobytes.
DENSE_MAX_SLOTS = 12
DENSE_MAX_STATES = 512
DENSE_MAX_ELEMS = 1 << 21  # 2M elements ≈ 8 MB f32 per batch element


def _dense_ok(S: int, num_states: int | None) -> bool:
    if num_states is None:
        return False
    vb = _bucket(num_states, floor=16)
    return (S <= DENSE_MAX_SLOTS and num_states <= DENSE_MAX_STATES
            and S * (1 << S) * vb <= DENSE_MAX_ELEMS)


class _ResumeKernel:
    """A jitted resume-scan plus its initial-frontier constructor (jit
    wrappers don't take attributes, so the pair rides a tiny holder)."""

    def __init__(self, fn, init_carry):
        self.fn = fn
        self.init_carry = init_carry

    def __call__(self, *args):
        return self.fn(*args)


def quiescent_cuts(kind, max_segment: int) -> list[int]:
    """Cut positions for segmented verification: indices where no op is
    pending (every invoke has returned), at most ``max_segment`` events
    apart. Vectorized over the event-kind array; returns cumulative end
    positions including the final one."""
    kind = np.asarray(kind)
    delta = np.where(kind == EV_INVOKE, 1,
                     np.where(kind == EV_RETURN, -1, 0))
    pending = np.cumsum(delta)
    quiet = np.nonzero(pending == 0)[0] + 1  # cut AFTER these events
    cuts: list[int] = []
    pos = 0
    n = len(kind)
    while pos < n:
        limit = pos + max_segment
        if limit >= n:
            cuts.append(n)
            break
        j = np.searchsorted(quiet, limit, side="right") - 1
        if j >= 0 and quiet[j] > pos:
            nxt = int(quiet[j])
        else:
            # no quiescent point inside the window: a raw cut would DROP
            # pending-op state and could convict a valid history, so
            # extend to the next quiescent point (or the end) instead —
            # soundness beats the segment-size preference
            k = np.searchsorted(quiet, limit, side="right")
            nxt = int(quiet[k]) if k < len(quiet) else n
        cuts.append(nxt)
        pos = nxt
    return cuts


def segmented_check(stream, max_segment: int = 1 << 21, kernel=None,
                    capacity: int = 256, num_states: int | None = None,
                    ckpt=None):
    """Checks one long history as a chain of bounded segments, carrying
    the frontier on device between them — arbitrarily long histories in
    bounded device memory (and bounded single-dispatch size, which the
    tunneled backend needs: monolithic multi-million-event scans have
    crashed its worker).

    The stream is cut ONLY at quiescent points (no pending ops across a
    cut): the resume carry holds the frontier but not pending-op state,
    so a mid-operation cut would drop obligations and could convict a
    valid history. When a window has no quiescent point, the segment
    extends to the next one (or the end) — soundness beats the
    segment-size preference. Returns (alive, died_event, overflow, peak).

    ``ckpt`` (a :class:`jepsen_tpu.checker.checkpoint.CheckpointStore`)
    makes the chain crash-resumable: the frontier carry persists after
    each segment when the write interval elapses, and a valid
    ``frontier`` checkpoint (same cuts, same kernel config, matching
    consumed-prefix hash) resumes the chain at its cut instead of
    restarting — bit-identical, the carry IS the frontier the
    uninterrupted chain holds there (doc/robustness.md "Resumable
    checks and the elastic mesh")."""
    if kernel is None:
        kernel = JitLinKernel()
    if num_states is None and getattr(stream, "intern", None) is not None:
        num_states = len(stream.intern)
    S = max(1, stream.n_slots)
    run = kernel._get(S, capacity, batched=False, num_states=num_states,
                      resume=True)
    kind = np.asarray(stream.kind)
    cuts = quiescent_cuts(kind, max_segment)
    carry = run.init_carry()
    alive, died, ovf, peak = True, -1, False, 0
    base = 0
    config = ckpt_state = None
    if ckpt is not None:
        from jepsen_tpu.checker import checkpoint as ckpt_mod
        config = {"path": "segmented", "S": S, "capacity": capacity,
                  "num_states": num_states, "max_segment": max_segment,
                  "dense": bool(_dense_ok(S, num_states)),
                  "step": ckpt_mod.step_identity(kernel.step_ids)}
        ckpt_state = ckpt_mod.load_resume(ckpt, "frontier", config, stream)
        if ckpt_state is not None and ckpt_state["events_done"] in set(cuts):
            base = ckpt_state["events_done"]
            carry = tuple(ckpt_mod.decode_array(a).astype(d.dtype)
                          for a, d in zip(ckpt_state["carry"]["arrays"],
                                          (np.asarray(c) for c in carry)))
            ovf = bool(ckpt_state["carry"].get("overflow", False))
            peak = int(ckpt_state["carry"].get("peak", 0))
            ckpt_mod.count_resume("ckpt")
            logger.info("resuming segmented check from %s at event %d/%d",
                        ckpt.path, base, len(kind))
        elif ckpt_state is not None:
            logger.warning("segmented checkpoint's cut %d is not a "
                           "quiescent cut of this stream; restarting",
                           ckpt_state["events_done"])
            ckpt_state = None
    from jepsen_tpu.checker.linear_encode import pad_streams
    for end in cuts:
        if end <= base:
            continue  # already covered by the resumed carry
        seg = _slice_stream(stream, base, end)
        batch = pad_streams([seg], length=_bucket(len(seg)))
        out = run(batch["kind"][0], batch["slot"][0], batch["f"][0],
                  batch["a"][0], batch["b"][0], *carry)
        a, d, o, p = out[0], out[1], out[2], out[3]
        carry = out[4:]
        a, d, o, p = (bool(np.asarray(a)), int(np.asarray(d)),
                      bool(np.asarray(o)), int(np.asarray(p)))
        ovf |= o
        peak = max(peak, p)
        if not a:
            return False, base + d if d >= 0 else -1, ovf, peak
        base = end
        if ckpt is not None and base < len(kind):
            from jepsen_tpu.checker import checkpoint as ckpt_mod

            def make_state(carry=carry, base=base, ovf=ovf, peak=peak):
                return {
                    "kind": "frontier", "config": config,
                    "events_done": base, "segment": cuts.index(base),
                    "prefix_hash": ckpt_mod.stream_prefix_hash(stream,
                                                               base),
                    "carry": {
                        "arrays": [ckpt_mod.encode_array(np.asarray(c))
                                   for c in carry],
                        "overflow": ovf, "peak": peak,
                    },
                }
            ckpt.maybe_save(make_state, base)
    return True, -1, ovf, peak


def _slice_stream(stream, lo: int, hi: int):
    """A view-slice of an EventStream's arrays (shared intern/slots)."""
    import copy
    seg = copy.copy(stream)
    # op_index slices too: a segment's diagnostics (matrix_localize's
    # failed_op_index) must resolve through ITS events, not the full
    # stream's row numbering
    for field in ("kind", "slot", "f", "a", "b", "op_index"):
        setattr(seg, field, np.asarray(getattr(stream, field))[lo:hi])
    return seg


class JitLinKernel:
    """Compiled-kernel cache keyed by backend + (S, K|V, batched?)."""

    def __init__(self, step_ids=None, init_state: int = 0):
        # the shared default spec keeps id(step_ids)-keyed compile caches
        # (matrix kernels) warm across kernel instances
        self.step_ids = step_ids if step_ids is not None else _default_step_ids()
        self.init_state = init_state
        self._cache: dict = {}

    def _get(self, S: int, K: int, batched: bool, num_states: int | None = None,
             resume: bool = False):
        """Picks the dense exact kernel when the configuration space is
        small enough, else the capacity-K sort-based frontier. With
        ``resume`` the returned callable takes and returns the frontier
        carry (dense: +table; sparse: +mask,state) for segmented
        verification; it also exposes ``.init_carry()``."""
        import jax
        if _dense_ok(S, num_states):
            vb = _bucket(num_states, floor=16)
            key = ("dense", S, vb, batched, resume)
            fn = self._cache.get(key)
            if fn is None:
                run = _build_dense_step(S, vb, self.step_ids, self.init_state)
                if resume:
                    fn = _ResumeKernel(jax.jit(run.resume),
                                       lambda: (run.init_table(),))
                else:
                    fn = jax.jit(jax.vmap(run)) if batched else jax.jit(run)
                self._cache[key] = fn
            return fn
        key = ("sparse", S, K, batched, resume)
        fn = self._cache.get(key)
        if fn is None:
            run = _build_step(S, K, self.step_ids, self.init_state)
            if resume:
                fn = _ResumeKernel(jax.jit(run.resume),
                                   lambda: run.init_frontier())
            else:
                fn = jax.jit(jax.vmap(run)) if batched else jax.jit(run)
            self._cache[key] = fn
        return fn

    def check(self, stream, capacity: int = 256):
        """Single history. Returns (alive, died_event, overflow, peak).
        Delegates to parallel.batch_check (the one batching/sharding
        implementation)."""
        return self.check_batch([stream], capacity=capacity)[0]

    def check_batch(self, streams, capacity: int = 256, mesh=None):
        """vmapped per-key batch, sharded over a mesh when available.
        Returns [(alive, died, ovf, peak)] per stream."""
        from jepsen_tpu.parallel import batch_check
        return batch_check(streams, capacity=capacity, mesh=mesh, kernel=self)


def _bucket(n: int, floor: int = 64) -> int:
    """Round counts up to a power of two >= floor so jit caches hit
    (floor 64 for event lengths, 16 for state counts)."""
    b = floor
    while b < n:
        b *= 2
    return b


def verdict(alive: bool, overflow: bool):
    """Soundness rules: a surviving (possibly truncated) frontier proves
    linearizability; an empty frontier after overflow proves nothing."""
    if alive:
        return True
    return "unknown" if overflow else False
