"""TPU just-in-time-linearization kernel.

Replaces the reference's CPU-bound knossos linear/wgl searches (invoked at
jepsen/src/jepsen/checker.clj:199-203) with a fixed-shape XLA program:

* A *configuration* is (mask, state): ``mask`` = bitset over pending-op
  slots that have already been linearized; ``state`` = interned model state.
* The frontier of live configurations is a capacity-K array pair.
* Events stream through a ``lax.scan``: invokes update the per-slot op
  table; before consuming each return, the closure of the frontier under
  "linearize any pending, unlinearized op" is computed by masked batched
  expansion ([K, S] candidate grid through the model's int transition) and
  sort-based dedup (two lexicographic ``lax.sort`` passes), then configs
  that failed to linearize the returning op are killed.

The frontier is monotone within a closure, so convergence is detected by
count; overflow beyond K makes a False verdict "unknown" (a surviving
subset is still a sound witness for True). The whole kernel vmaps over a
batch of per-key histories — the jepsen.independent -> vmap mapping
(SURVEY.md §2.6, BASELINE config 3).

Shapes are static in (E, S, K): pad E via linear_encode.pad_streams and
bucket history lengths so XLA caches compilations.
"""
from __future__ import annotations

from functools import partial

import numpy as np

SENTINEL_MASK = np.uint32(0xFFFFFFFF)
SENTINEL_STATE = np.int32(0x7FFFFFFF)

EV_INVOKE, EV_RETURN, EV_NOOP = 0, 1, 2


def _build_step(num_slots: int, capacity: int, step_ids, init_state: int,
                max_closure_iters: int | None = None):
    import jax
    import jax.numpy as jnp
    from jax import lax

    S, K = num_slots, capacity
    closure_iters = max_closure_iters or S
    slot_bits = (jnp.uint32(1) << jnp.arange(S, dtype=jnp.uint32))

    def count_valid(mask):
        return jnp.sum((mask != SENTINEL_MASK).astype(jnp.int32))

    def dedup_compact(all_mask, all_state):
        """Sort, drop duplicates, move valid entries to the front, keep K."""
        m, st = lax.sort((all_mask, all_state), num_keys=2, is_stable=False)
        dup = jnp.concatenate([
            jnp.zeros((1,), dtype=bool),
            (m[1:] == m[:-1]) & (st[1:] == st[:-1]),
        ])
        m = jnp.where(dup, SENTINEL_MASK, m)
        st = jnp.where(dup, SENTINEL_STATE, st)
        m, st = lax.sort((m, st), num_keys=2, is_stable=False)
        overflow = m[K] != SENTINEL_MASK if m.shape[0] > K else jnp.bool_(False)
        return m[:K], st[:K], overflow

    def closure(mask, state, pend_mask, cur_f, cur_a, cur_b):
        """Expands the frontier to its closure under linearizing any pending,
        unlinearized op. Early-exits when the config count stops growing."""

        def body(carry):
            mask, state, _, count, overflow, it = carry
            valid = mask != SENTINEL_MASK
            can = (
                valid[:, None]
                & ((pend_mask & slot_bits) != 0)[None, :]
                & ((mask[:, None] & slot_bits[None, :]) == 0)
            )
            st2, ok = step_ids(state[:, None], cur_f[None, :], cur_a[None, :], cur_b[None, :])
            good = can & ok
            new_mask = jnp.where(good, mask[:, None] | slot_bits[None, :], SENTINEL_MASK)
            new_state = jnp.where(good, st2, SENTINEL_STATE)
            all_mask = jnp.concatenate([mask, new_mask.reshape(-1)])
            all_state = jnp.concatenate([state, new_state.reshape(-1)])
            m, st, ovf = dedup_compact(all_mask, all_state)
            c2 = count_valid(m)
            return m, st, c2 > count, c2, overflow | ovf, it + 1

        def cond(carry):
            _, _, changed, _, _, it = carry
            return changed & (it < closure_iters)

        init = (mask, state, jnp.bool_(True), count_valid(mask), jnp.bool_(False),
                jnp.int32(0))
        mask, state, _, count, overflow, _ = lax.while_loop(cond, body, init)
        return mask, state, count, overflow

    def step_event(carry, ev):
        (mask, state, cur_f, cur_a, cur_b, pend_mask, alive, died_at,
         overflow, peak, eidx) = carry
        kind, slot, f, a, b = ev
        slot_bit = jnp.uint32(1) << slot.astype(jnp.uint32)

        def on_invoke(_):
            return (mask, state, cur_f.at[slot].set(f), cur_a.at[slot].set(a),
                    cur_b.at[slot].set(b), pend_mask | slot_bit, alive,
                    died_at, overflow, peak, eidx + 1)

        def on_return(_):
            m, st, count, ovf = closure(mask, state, pend_mask, cur_f, cur_a, cur_b)
            # keep configs that linearized the returning op; clear its bit
            # (sentinel entries have all bits set — exclude them explicitly)
            has = (m != SENTINEL_MASK) & ((m & slot_bit) != 0)
            m2 = jnp.where(has, m & ~slot_bit, SENTINEL_MASK)
            st2 = jnp.where(has, st, SENTINEL_STATE)
            m2, st2, _ = dedup_compact(
                jnp.concatenate([m2, jnp.full((S,), SENTINEL_MASK, jnp.uint32)]),
                jnp.concatenate([st2, jnp.full((S,), SENTINEL_STATE, jnp.int32)]),
            )
            now_alive = count_valid(m2) > 0
            new_died = jnp.where(alive & ~now_alive, eidx, died_at)
            return (m2, st2, cur_f, cur_a, cur_b, pend_mask & ~slot_bit,
                    alive & now_alive, new_died, overflow | ovf,
                    jnp.maximum(peak, count), eidx + 1)

        def on_noop(_):
            return (mask, state, cur_f, cur_a, cur_b, pend_mask, alive,
                    died_at, overflow, peak, eidx + 1)

        new_carry = lax.switch(kind, [on_invoke, on_return, on_noop], None)
        return new_carry, None

    def run(kind, slot, f, a, b):
        mask0 = jnp.full((K,), SENTINEL_MASK, dtype=jnp.uint32)
        mask0 = mask0.at[0].set(jnp.uint32(0))
        state0 = jnp.full((K,), SENTINEL_STATE, dtype=jnp.int32)
        state0 = state0.at[0].set(jnp.int32(init_state))
        carry = (
            mask0, state0,
            jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
            jnp.zeros((S,), jnp.int32),
            jnp.uint32(0), jnp.bool_(True), jnp.int32(-1), jnp.bool_(False),
            jnp.int32(1), jnp.int32(0),
        )
        events = (kind.astype(jnp.int32), slot.astype(jnp.int32),
                  f.astype(jnp.int32), a.astype(jnp.int32), b.astype(jnp.int32))
        carry, _ = lax.scan(step_event, carry, events)
        (_, _, _, _, _, _, alive, died_at, overflow, peak, _) = carry
        return alive, died_at, overflow, peak

    return run


class JitLinKernel:
    """Compiled-kernel cache keyed by (S, K, E-bucket, batched?)."""

    def __init__(self, step_ids=None, init_state: int = 0):
        if step_ids is None:
            from jepsen_tpu.models import cas_register_spec
            step_ids = cas_register_spec().step_ids
        self.step_ids = step_ids
        self.init_state = init_state
        self._cache: dict = {}

    def _get(self, S: int, K: int, batched: bool):
        import jax
        key = (S, K, batched)
        fn = self._cache.get(key)
        if fn is None:
            run = _build_step(S, K, self.step_ids, self.init_state)
            fn = jax.jit(jax.vmap(run)) if batched else jax.jit(run)
            self._cache[key] = fn
        return fn

    def check(self, stream, capacity: int = 256):
        """Single history. Returns (alive, died_event, overflow, peak).
        Delegates to parallel.batch_check (the one batching/sharding
        implementation)."""
        return self.check_batch([stream], capacity=capacity)[0]

    def check_batch(self, streams, capacity: int = 256, mesh=None):
        """vmapped per-key batch, sharded over a mesh when available.
        Returns [(alive, died, ovf, peak)] per stream."""
        from jepsen_tpu.parallel import batch_check
        return batch_check(streams, capacity=capacity, mesh=mesh, kernel=self)


def _bucket(n: int) -> int:
    """Round event counts up to a power of two >= 64 so jit caches hit."""
    b = 64
    while b < n:
        b *= 2
    return b


def verdict(alive: bool, overflow: bool):
    """Soundness rules: a surviving (possibly truncated) frontier proves
    linearizability; an empty frontier after overflow proves nothing."""
    if alive:
        return True
    return "unknown" if overflow else False
