"""Cycle detection over dependency graphs: the Elle core, device-first.

The reference's Elle searches dependency graphs of up to ~100k txns for
cycles (SURVEY.md §2.4). The device kernel here is *iterative trimming*
(Karp-style 2-core peeling): repeatedly drop nodes with no active in-edge
or no active out-edge, entirely with ``segment_sum`` over edge lists under
``lax.while_loop``. After convergence:

* residue empty  => the graph is acyclic (serializable: no anomaly).
* otherwise the residue — every cycle lives inside it, but long-diameter
  graphs may leave acyclic chains when the peel hits its iteration cap —
  is handed to an exact host-side Tarjan for SCC extraction and cycle
  classification. The residue is always a *superset* of the cycle nodes;
  only the exact pass's verdict counts.

The trim is O(E) per iteration with ~diameter iterations, fully
data-parallel, and edge arrays shard cleanly over a device mesh (segment
sums become psum-reduced partials). Running it per edge-type-filtered
subgraph (ww-only, ww+wr) answers G0/G1c directly.
"""
from __future__ import annotations

from functools import partial

import numpy as np


_TRIM_CACHE: dict = {}


def _trim_kernel(n_nodes: int, n_edges: int, max_iters: int):
    """Compiled trim kernel for bucketed (n_nodes, n_edges) shapes. Edge
    arrays are runtime arguments (with a validity mask for padding), NOT
    trace-time constants — so one compilation serves every graph in the
    same shape bucket instead of re-jitting per call."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    key = (n_nodes, n_edges, max_iters)
    fn = _TRIM_CACHE.get(key)
    if fn is not None:
        return fn

    @jax.jit
    def run(src_j, dst_j, valid):
        def body(carry):
            active, _, it = carry
            edge_active = valid & active[src_j] & active[dst_j]
            indeg = jax.ops.segment_sum(edge_active.astype(jnp.int32), dst_j,
                                        num_segments=n_nodes)
            outdeg = jax.ops.segment_sum(edge_active.astype(jnp.int32), src_j,
                                         num_segments=n_nodes)
            new_active = active & (indeg > 0) & (outdeg > 0)
            changed = jnp.any(new_active != active)
            return new_active, changed, it + 1

        def cond(carry):
            _, changed, it = carry
            return changed & (it < max_iters)

        active0 = jnp.ones((n_nodes,), dtype=bool)
        active, _, _ = lax.while_loop(cond, body, (active0, jnp.bool_(True),
                                                   jnp.int32(0)))
        return active

    _TRIM_CACHE[key] = run
    return run


def trim_to_cycles(n_nodes: int, src: np.ndarray, dst: np.ndarray,
                   max_iters: int = 512):
    """Device trim: returns a bool[n_nodes] mask of nodes surviving 2-core
    peeling (empty => acyclic; every cycle is inside the residue). Peeling
    removes one fringe layer per iteration, so a near-serial history (a
    ~n-long dependency chain) would need ~n iterations to fully converge;
    the cap keeps device time bounded and leaves a conservative residue
    that the exact host pass classifies.

    Node and edge counts are bucketed to powers of two (padding nodes have
    no edges and peel away in the first iteration; padding edges carry a
    False validity bit), so nearby graph sizes share one compilation."""
    from jepsen_tpu.ops.jitlin import _bucket

    if len(src) == 0 or n_nodes == 0:
        return np.zeros(n_nodes, dtype=bool)

    nb = _bucket(n_nodes, floor=64)
    eb = _bucket(len(src), floor=64)
    pad = eb - len(src)
    src_p = np.concatenate([np.asarray(src, np.int32),
                            np.zeros(pad, np.int32)])
    dst_p = np.concatenate([np.asarray(dst, np.int32),
                            np.zeros(pad, np.int32)])
    valid = np.concatenate([np.ones(len(src), bool), np.zeros(pad, bool)])
    run = _trim_kernel(nb, eb, max_iters)
    return np.asarray(run(src_p, dst_p, valid))[:n_nodes]


def has_cycle(n_nodes: int, src, dst) -> bool:
    """Exact cycle test: device trim narrows, host Tarjan confirms (a
    capped trim's residue may contain acyclic chains)."""
    src = np.asarray(src)
    dst = np.asarray(dst)
    mask = trim_to_cycles(n_nodes, src, dst)
    if not mask.any():
        return False
    kept = set(np.nonzero(mask)[0].tolist())
    edges = [(int(s), int(d)) for s, d in zip(src, dst)
             if s in kept and d in kept]
    return bool(tarjan_scc(n_nodes, edges))


def trim_to_cycles_sharded(n_nodes: int, src: np.ndarray, dst: np.ndarray,
                           mesh, max_iters: int = 512):
    """Edge-sharded device trim: the same capped 2-core peeling as
    :func:`trim_to_cycles` (same loose-superset residue contract — the
    exact host pass is authoritative), but with the edge list sharded over
    the mesh's first axis under ``shard_map``. Each device computes partial in/out
    degrees for its edge shard with ``segment_sum``; partials are reduced
    with ``psum`` (ICI all-reduce on a pod), so the node-activity vector is
    replicated while edge traffic stays device-local. This is the 50k-txn
    Elle-graph scaling path (BASELINE config 5, SURVEY.md §5.8)."""
    import jax

    if len(src) == 0 or n_nodes == 0:
        return np.zeros(n_nodes, dtype=bool)

    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = mesh.devices.size
    E = len(src)
    pad = (-E) % n_dev
    # Padding edges carry weight 0 so they contribute no degree.
    src_p = np.concatenate([np.asarray(src, np.int32), np.zeros(pad, np.int32)])
    dst_p = np.concatenate([np.asarray(dst, np.int32), np.zeros(pad, np.int32)])
    w_p = np.concatenate([np.ones(E, np.int32), np.zeros(pad, np.int32)])

    esh = NamedSharding(mesh, P(mesh.axis_names[0]))
    sj = jax.device_put(src_p, esh)
    dj = jax.device_put(dst_p, esh)
    wj = jax.device_put(w_p, esh)
    return np.asarray(run_sharded_trim(mesh, n_nodes, sj, dj, wj, max_iters))


def run_sharded_trim(mesh, n_nodes: int, sj, dj, wj, max_iters: int = 512):
    """The compute half of the sharded trim, over ALREADY-PLACED edge
    arrays (sharded on the mesh's first axis with weight 0 padding).
    Split out so the multi-process (DCN) path can place per-process
    local shards with make_array_from_process_local_data and run the
    identical kernel (jepsen_tpu.parallel.distributed)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]

    def degrees(active, s, d, w):
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P(axis), P(axis), P(axis)), out_specs=P())
        def go(active, s, d, w):
            ew = w * (active[s] & active[d]).astype(jnp.int32)
            indeg = jax.ops.segment_sum(ew, d, num_segments=n_nodes)
            outdeg = jax.ops.segment_sum(ew, s, num_segments=n_nodes)
            return lax.psum(jnp.stack([indeg, outdeg]), axis)

        return go(active, s, d, w)

    @jax.jit
    def run(s, d, w):
        def body(carry):
            active, _, it = carry
            deg = degrees(active, s, d, w)
            new_active = active & (deg[0] > 0) & (deg[1] > 0)
            changed = jnp.any(new_active != active)
            return new_active, changed, it + 1

        def cond(carry):
            _, changed, it = carry
            return changed & (it < max_iters)

        active0 = jnp.ones((n_nodes,), dtype=bool)
        active, _, _ = lax.while_loop(
            cond, body, (active0, jnp.bool_(True), jnp.int32(0)))
        return active

    return run(sj, dj, wj)


_SCREEN_CACHE: dict = {}


def _screen_kernel(n_clusters: int, n_local: int, n_edges: int):
    """Compiled batched-closure screen for bucketed (B, V, E) shapes.

    One boolean adjacency matrix per cluster, [B, V, V]; transitive
    closure by repeated squaring — ``ceil(log2(V))`` batched bf16
    matmuls on the MXU (R := R ∨ R·R doubles the covered path length
    each step, so it has fully converged once 2^steps >= V; the result
    is EXACT, unlike the capped peeling trim). A cluster contains a
    cycle iff its closure has a nonzero diagonal.

    bf16 operands with float32 accumulation (`preferred_element_type`)
    keep the MXU path while making the >0 threshold exact: entries are
    0/1, so any true sum is >= 1 and cannot round to 0."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    key = (n_clusters, n_local, n_edges)
    fn = _SCREEN_CACHE.get(key)
    if fn is not None:
        return fn

    n_steps = max(1, int(np.ceil(np.log2(max(2, n_local)))))

    @jax.jit
    def run(cid, src_l, dst_l, valid):
        adj = jnp.zeros((n_clusters, n_local, n_local), jnp.bfloat16)
        adj = adj.at[cid, src_l, dst_l].max(
            jnp.where(valid, jnp.bfloat16(1), jnp.bfloat16(0)))

        def body(_, r):
            sq = jax.lax.dot_general(
                r, r,
                dimension_numbers=(((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            return jnp.maximum(r, (sq > 0).astype(jnp.bfloat16))

        closure = lax.fori_loop(0, n_steps, body, adj)
        diag = jnp.diagonal(closure, axis1=1, axis2=2)
        return jnp.any(diag > 0, axis=1)

    _SCREEN_CACHE[key] = run
    return run


# ceiling on one screen dispatch's [B, V, V] element count: bf16
# adjacency ~64 MB and the f32 dot_general intermediate ~128 MB at this
# size — batches beyond it are chunked along the cluster axis
SCREEN_MAX_ELEMS = 1 << 25


def batch_cluster_screen(cid: np.ndarray, src_l: np.ndarray,
                         dst_l: np.ndarray, n_clusters: int,
                         max_local: int) -> np.ndarray:
    """Exact per-cluster cycle screen on device: returns bool[n_clusters],
    True iff cluster ``c`` (edges where ``cid == c``, node ids already
    LOCAL to the cluster) contains a directed cycle.

    This is the device half of the φ-interval Elle path (see
    jepsen_tpu.elle.check_cycles): the host localizes all possible cycle
    nodes into small clusters, and this kernel settles every cluster's
    has-a-cycle question in ONE dispatch — batched [B, V, V] boolean
    matrix squaring instead of the reference's per-graph host Tarjan
    (jepsen/src/jepsen/tests/cycle.clj's SCC search). Transfers are edge
    lists (KBs), not matrices; shapes are bucketed so compilations cache."""
    from jepsen_tpu.ops.jitlin import _bucket

    if n_clusters == 0:
        return np.zeros(0, dtype=bool)
    if len(cid) == 0:
        return np.zeros(n_clusters, dtype=bool)

    vb = _bucket(max_local, floor=8)
    # element budget: chunk the cluster axis when B*V^2 would exceed it
    # (callers bucket clusters by size, so V is tight for every chunk)
    b_max = max(1, SCREEN_MAX_ELEMS // (vb * vb))
    if n_clusters > b_max:
        cid = np.asarray(cid, np.int64)
        out = np.zeros(n_clusters, dtype=bool)
        for b0 in range(0, n_clusters, b_max):
            b1 = min(b0 + b_max, n_clusters)
            m = (cid >= b0) & (cid < b1)
            out[b0:b1] = batch_cluster_screen(
                (cid[m] - b0).astype(np.int32), src_l[m], dst_l[m],
                b1 - b0, max_local)
        return out

    bb = _bucket(n_clusters, floor=8)
    eb = _bucket(len(cid), floor=64)
    pad = eb - len(cid)
    cid_p = np.concatenate([np.asarray(cid, np.int32),
                            np.zeros(pad, np.int32)])
    src_p = np.concatenate([np.asarray(src_l, np.int32),
                            np.zeros(pad, np.int32)])
    dst_p = np.concatenate([np.asarray(dst_l, np.int32),
                            np.zeros(pad, np.int32)])
    valid = np.concatenate([np.ones(len(cid), bool), np.zeros(pad, bool)])
    run = _screen_kernel(bb, vb, eb)
    return np.asarray(run(cid_p, src_p, dst_p, valid))[:n_clusters]


def tarjan_scc(n_nodes: int, edges: list[tuple[int, int]]) -> list[list[int]]:
    """Exact SCCs, iterative Tarjan (host-side; used on the trimmed
    residue). Returns SCCs with >1 node or a self-loop."""
    adj: list[list[int]] = [[] for _ in range(n_nodes)]
    self_loop = set()
    for s, d in edges:
        if s == d:
            self_loop.add(s)
        adj[s].append(d)
    index = [-1] * n_nodes
    low = [0] * n_nodes
    on_stack = [False] * n_nodes
    stack: list[int] = []
    sccs: list[list[int]] = []
    counter = [0]

    for root in range(n_nodes):
        if index[root] != -1:
            continue
        work = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack[v] = True
            recurse = False
            for i in range(pi, len(adj[v])):
                w = adj[v][i]
                if index[w] == -1:
                    work[-1] = (v, i + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                elif on_stack[w]:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1 or v in self_loop:
                    sccs.append(scc)
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
    return sccs


def find_cycle_in_scc(scc: list[int], edges: list[tuple[int, int, str]],
                      prefer_fewest: str | None = None):
    """Finds one cycle within an SCC as [(src, dst, type), ...].
    With prefer_fewest='rw', tries to find a cycle using as few edges of
    that type as possible (distinguishes G-single from G2, mirroring
    Elle's typed cycle searches)."""
    in_scc = set(scc)
    adj: dict[int, list[tuple[int, str]]] = {v: [] for v in scc}
    for s, d, t in edges:
        if s in in_scc and d in in_scc:
            adj[s].append((d, t))

    def bfs_cycle(allowed):
        """Shortest cycle through each start using only allowed edge types,
        then one optional non-allowed edge... simple variant: BFS from each
        node back to itself."""
        for start in scc:
            # BFS over (node) with parent tracking
            prev: dict[int, tuple[int, str]] = {}
            frontier = [start]
            seen = {start}
            found = None
            while frontier and found is None:
                nxt = []
                for u in frontier:
                    for (w, t) in adj[u]:
                        if allowed is not None and t not in allowed:
                            continue
                        if w == start:
                            prev[("end",)] = (u, t)
                            found = True
                            break
                        if w not in seen:
                            seen.add(w)
                            prev[w] = (u, t)
                            nxt.append(w)
                    if found:
                        break
                frontier = nxt
            if found:
                cycle = []
                node, t = prev[("end",)]
                cycle.append((node, start, t))
                while node != start:
                    pnode, pt = prev[node]
                    cycle.append((pnode, node, pt))
                    node = pnode
                cycle.reverse()
                return cycle
        return None

    if prefer_fewest is not None:
        others = {t for _, _, t in edges if t != prefer_fewest}
        c = bfs_cycle(others)  # zero rw edges
        if c is not None:
            return c
        # allow exactly one rw: BFS where the rw edge is taken first
        for s, d, t in edges:
            if t != prefer_fewest or s not in in_scc or d not in in_scc:
                continue
            path = _bfs_path(adj, d, s, others)
            if path is not None:
                return [(s, d, t)] + path
    return bfs_cycle(None)


def _bfs_path(adj, start, goal, allowed):
    """Shortest path start->goal using allowed edge types, as
    [(src, dst, type), ...]; None if unreachable."""
    if start == goal:
        return []
    prev: dict[int, tuple[int, str]] = {}
    frontier = [start]
    seen = {start}
    while frontier:
        nxt = []
        for u in frontier:
            for (w, t) in adj.get(u, []):
                if t not in allowed or w in seen:
                    continue
                seen.add(w)
                prev[w] = (u, t)
                if w == goal:
                    path = []
                    node = w
                    while node != start:
                        p, pt = prev[node]
                        path.append((p, node, pt))
                        node = p
                    path.reverse()
                    return path
                nxt.append(w)
        frontier = nxt
    return None
