"""Network manipulation (reference: jepsen/src/jepsen/net.clj +
net/proto.clj + control/net.clj).

The Net protocol cuts/heals/degrades links between db nodes — it breaks the
*system under test's* network, not the control plane. The iptables
implementation mirrors net.clj:58-111 (tc netem for slow/flaky, batch
PartitionAll application); ipfilter is available for BSD-ish targets
(net.clj:113-145).
"""
from __future__ import annotations

import logging
from typing import Iterable

from jepsen_tpu import control
from jepsen_tpu.utils import real_pmap

logger = logging.getLogger("jepsen.net")


class Net:
    """net/proto.clj:5-12"""

    def drop(self, test: dict, src: str, dest: str) -> None:
        """Cuts the link src -> dest."""
        raise NotImplementedError

    def heal(self, test: dict) -> None:
        raise NotImplementedError

    def slow(self, test: dict, mean_ms: float = 50, variance_ms: float = 10) -> None:
        raise NotImplementedError

    def flaky(self, test: dict) -> None:
        raise NotImplementedError

    def fast(self, test: dict) -> None:
        raise NotImplementedError

    # PartitionAll extension (net.clj:101-111): apply a whole grudge at once
    def drop_all(self, test: dict, grudge: dict) -> None:
        """grudge: {node: iterable-of-nodes-to-snub}. Default: per-link."""
        for node, snubbed in grudge.items():
            for other in snubbed:
                self.drop(test, other, node)


def resolve_ip(test: dict, node: str) -> str:
    """Resolves a node name to an IP on the control node or via getent on
    the node itself (control/net.clj:19-40). Cached on the test map."""
    cache = test.setdefault("_ip_cache", {})
    if node in cache:
        return cache[node]
    import socket
    try:
        ip = socket.gethostbyname(node)
    except OSError:
        ip = node
    cache[node] = ip
    return ip


class IPTables(Net):
    """Default partitioner: `iptables -A INPUT -s <ips> -j DROP -w`
    (net.clj:58-111)."""

    def drop(self, test, src, dest):
        ip = resolve_ip(test, src)
        control.on(dest, test, lambda: _iptables_drop([ip]))

    def drop_all(self, test, grudge):
        def apply_node(node):
            snubbed = grudge.get(node) or []
            if not snubbed:
                return
            ips = [resolve_ip(test, s) for s in snubbed]
            control.on(node, test, lambda: _iptables_drop(ips))
        real_pmap(apply_node, [n for n, s in grudge.items() if s])

    def heal(self, test):
        def heal_node(node):
            control.on(node, test, lambda: _iptables_heal())
        real_pmap(heal_node, list(test.get("nodes") or []))

    def slow(self, test, mean_ms=50, variance_ms=10):
        def slow_node(node):
            control.on(node, test, lambda: _tc_netem(
                f"delay {mean_ms}ms {variance_ms}ms distribution normal"))
        real_pmap(slow_node, list(test.get("nodes") or []))

    def flaky(self, test):
        def flaky_node(node):
            control.on(node, test, lambda: _tc_netem(
                "loss 20% 75% corrupt 1%"))
        real_pmap(flaky_node, list(test.get("nodes") or []))

    def fast(self, test):
        def fast_node(node):
            control.on(node, test, lambda: _tc_del())
        real_pmap(fast_node, list(test.get("nodes") or []))


def _iptables_drop(ips: Iterable[str]) -> None:
    with control.su():
        control.exec_("iptables", "-A", "INPUT", "-s", ",".join(ips),
                      "-j", "DROP", "-w")


def _iptables_heal() -> None:
    with control.su():
        control.exec_("iptables", "-F", "-w")
        control.exec_("iptables", "-X", "-w")


def _tc_netem(spec: str) -> None:
    from jepsen_tpu.control.core import lit
    with control.su():
        control.exec_("tc", "qdisc", "replace", "dev", "eth0", "root",
                      "netem", lit(spec))


def _tc_del() -> None:
    with control.su():
        r = control.exec_star("tc", "qdisc", "del", "dev", "eth0", "root")
        # no qdisc installed is fine
        _ = r


class IPFilter(Net):
    """ipfilter-based variant for SmartOS/BSD targets (net.clj:113-145)."""

    def drop(self, test, src, dest):
        ip = resolve_ip(test, src)
        control.on(dest, test, lambda: control.exec_(
            "sh", "-c", f"echo 'block in quick from {ip}/32' | ipf -f -"))

    def heal(self, test):
        def heal_node(node):
            control.on(node, test, lambda: control.exec_("ipf", "-Fa"))
        real_pmap(heal_node, list(test.get("nodes") or []))

    def slow(self, test, mean_ms=50, variance_ms=10):
        raise NotImplementedError("ipfilter has no netem equivalent")

    def flaky(self, test):
        raise NotImplementedError("ipfilter has no netem equivalent")

    def fast(self, test):
        pass


class NoopNet(Net):
    """For dummy-remote runs: records grudges on the test map."""

    def drop(self, test, src, dest):
        test.setdefault("_net_log", []).append(("drop", src, dest))

    def drop_all(self, test, grudge):
        test.setdefault("_net_log", []).append(("drop-all", grudge))

    def heal(self, test):
        test.setdefault("_net_log", []).append(("heal",))

    def slow(self, test, mean_ms=50, variance_ms=10):
        test.setdefault("_net_log", []).append(("slow",))

    def flaky(self, test):
        test.setdefault("_net_log", []).append(("flaky",))

    def fast(self, test):
        test.setdefault("_net_log", []).append(("fast",))


iptables = IPTables
ipfilter = IPFilter
