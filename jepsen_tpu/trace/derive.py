"""Offline trace derivation: old runs become traceable retroactively.

A live ``--trace`` run streams its trace as it happens. But every run —
traced or not — already persists the raw material: the WAL / history
(per-op process + invoke time, which is all :func:`trace_id_for`
needs), the durable fault registry (``faults.jsonl``), the quarantine
log (``late.jsonl``), and the exported telemetry events + checker phase
timers (``metrics.json``). ``jepsen-tpu trace <run-dir>`` re-derives a
merged Perfetto trace from those artifacts, with op trace ids
IDENTICAL to what a live trace would have minted (pinned by
tests/test_trace.py's live-vs-derived differential).

Timebase: wall-clock microseconds. History op times are nanoseconds
relative to the run origin; the origin is recovered from the run's
``start_time`` (test.json), so fault-registry rows and telemetry
events — which carry epoch timestamps — land on the same axis to
within the run's setup time (the origin is stamped slightly before the
interpreter starts; documented in doc/observability.md).

Worker-track mapping mirrors the interpreter: thread =
``process % concurrency`` (process renumbering adds the client-thread
count, so the residue is stable), nemesis ops on the ``nemesis``
track.
"""
from __future__ import annotations

import datetime
import json
import logging
from pathlib import Path

from jepsen_tpu.trace import (
    TRACK_CHECKER, TRACK_LADDER, TRACK_NEMESIS, TRACK_SCHEDULER,
    RunTracer, trace_id_for, worker_track,
)
from jepsen_tpu.trace.perfetto import PerfettoSink, read_trace_events

logger = logging.getLogger("jepsen.trace.derive")

DERIVED_NAME = "trace-derived.json"

# telemetry event name -> track for the offline instants
_EVENT_TRACKS = {
    "nemesis-fault": TRACK_NEMESIS,
    "interpreter-stall": TRACK_SCHEDULER,
    "checker-circuit-open": TRACK_LADDER,
}


def _origin_us(test: dict) -> int:
    """Epoch microseconds of the run's start_time, or 0 (pure-relative
    timebase) when it doesn't parse."""
    ts = str(test.get("start_time") or "")
    try:
        dt = datetime.datetime.strptime(ts, "%Y%m%dT%H%M%S.%f")
        return int(dt.timestamp() * 1e6)
    except ValueError:
        return 0


def _load_jsonl(path: Path) -> list[dict]:
    if not path.is_file():
        return []
    from jepsen_tpu.journal import read_jsonl_tolerant
    rows, _ = read_jsonl_tolerant(path)
    return [r for r in rows if isinstance(r, dict)]


def _load_ops(run_dir: Path) -> list[dict]:
    """history.jsonl when the run completed, else the surviving WAL —
    a crashed run's trace covers exactly the journaled prefix."""
    from jepsen_tpu.journal import WAL_NAME
    ops = _load_jsonl(run_dir / "history.jsonl")
    if ops:
        return ops
    return _load_jsonl(run_dir / WAL_NAME)


def _concurrency(test: dict, ops: list[dict]) -> int:
    c = test.get("concurrency")
    if isinstance(c, int) and c >= 1:
        return c
    # fallback for a run with no readable test.json: the peak number of
    # concurrently-open client invocations. Every worker holds at most
    # one op in flight, so the peak is the busiest-moment worker count
    # — a heuristic (an always-idle worker is invisible), but unlike
    # counting distinct process ids it is immune to crash renumbering
    # (a renumbered process is never in flight alongside its
    # predecessor)
    open_p: set = set()
    peak = 1
    for op in ops:
        p, typ = op.get("process"), op.get("type")
        if not isinstance(p, int):
            continue
        if typ == "invoke":
            open_p.add(p)
            if len(open_p) > peak:
                peak = len(open_p)
        elif typ in ("ok", "fail", "info"):
            open_p.discard(p)
    return peak


def _op_track(process, concurrency: int) -> str:
    if isinstance(process, int) and process >= 0:
        return worker_track(process % concurrency)
    return TRACK_NEMESIS


def derive_run_trace(run_dir, out=None) -> Path | None:
    """Writes the merged offline trace for a stored run; returns the
    written path, or None when the run has no usable op artifact.
    ``out`` overrides the target; by default the trace lands at
    ``trace.json``, or ``trace-derived.json`` when a live-written
    trace.json already exists (a derived trace must never clobber the
    richer live one)."""
    run_dir = Path(run_dir)
    ops = _load_ops(run_dir)
    if not ops:
        return None
    test: dict = {}
    try:
        with open(run_dir / "test.json", encoding="utf-8") as f:
            test = json.load(f)
    except (OSError, ValueError):
        logger.warning("no readable test.json in %s; deriving with "
                       "defaults", run_dir)
    if out is None:
        live = run_dir / "trace.json"
        out = run_dir / (DERIVED_NAME if live.exists() else "trace.json")
    origin = _origin_us(test)
    conc = _concurrency(test, ops)
    sink = PerfettoSink(out)
    tracer = RunTracer(perfetto=sink)
    try:
        last_ts = origin
        open_inv: dict = {}  # process -> (ts_us, invoke op)
        for op in ops:
            t = op.get("time")
            if not isinstance(t, (int, float)):
                continue
            ts = origin + int(t / 1e3)
            last_ts = max(last_ts, ts)
            typ = op.get("type")
            if typ == "invoke":
                open_inv[op.get("process")] = (ts, op)
            elif typ in ("ok", "fail", "info"):
                inv = open_inv.pop(op.get("process"), None)
                if inv is None:
                    continue  # a completion with no journaled invoke
                inv_ts, inv_op = inv
                args = {"process": inv_op.get("process"),
                        "f": str(inv_op.get("f")), "type": typ,
                        "trace_id": trace_id_for(inv_op.get("process"),
                                                 inv_op.get("time"))}
                if op.get("error") is not None:
                    args["error"] = str(op.get("error"))
                tracer.complete(
                    _op_track(inv_op.get("process"), conc),
                    str(inv_op.get("f")), inv_ts,
                    max(ts - inv_ts, 1), args=args)
        # ops still in flight when the run died: open B slices, exactly
        # the live sinks' in-flight semantics (flight dump / SIGKILL)
        for process, (ts, inv_op) in sorted(open_inv.items(), key=str):
            tracer.begin(_op_track(process, conc), str(inv_op.get("f")),
                         ts_us=ts,
                         args={"process": process,
                               "f": str(inv_op.get("f")),
                               "trace_id": trace_id_for(
                                   process, inv_op.get("time"))})
        _derive_faults(tracer, run_dir)
        _derive_late(tracer, run_dir, origin)
        _derive_metrics(tracer, run_dir, last_ts)
    finally:
        tracer.close()
    return Path(out)


def _derive_faults(tracer: RunTracer, run_dir: Path) -> None:
    """Fault windows from the durable registry: inject rows open an
    async slice keyed by fault id, heal rows close it; an unhealed
    entry stays open — exactly the crash evidence the registry exists
    for."""
    from jepsen_tpu.nemesis.faults import FAULTS_NAME
    injects: dict[int, dict] = {}
    for row in _load_jsonl(run_dir / FAULTS_NAME):
        rid = row.get("id")
        t = row.get("time")
        if not isinstance(rid, int) or not isinstance(t, (int, float)):
            continue
        ts = int(t * 1e6)
        if row.get("op") == "inject":
            injects[rid] = row
            tracer.window_begin(TRACK_NEMESIS, str(row.get("kind")),
                                wid=f"fault-{rid}", ts_us=ts,
                                args={"f": row.get("f"), "id": rid})
        elif row.get("op") == "heal" and rid in injects:
            tracer.window_end(TRACK_NEMESIS,
                              str(injects[rid].get("kind")),
                              wid=f"fault-{rid}", ts_us=ts,
                              args={"via": row.get("via")})


def _derive_late(tracer: RunTracer, run_dir: Path, origin: int) -> None:
    from jepsen_tpu.journal import LATE_NAME
    for row in _load_jsonl(run_dir / LATE_NAME):
        t = row.get("time")  # the quarantine stamp (when it surfaced)
        ts = origin + int(t / 1e3) if isinstance(t, (int, float)) else None
        # the id joins on the op's DISPATCH time — quarantine preserves
        # it as invoke_time because it re-stamps "time" (rows from runs
        # predating that field get no id rather than a wrong one)
        inv_t = row.get("invoke_time")
        tracer.instant(TRACK_SCHEDULER, "late-completion", ts_us=ts,
                       args={"worker": row.get("worker"),
                             "f": row.get("f"),
                             "trace_id": trace_id_for(row.get("process"),
                                                      inv_t)
                             if isinstance(inv_t, (int, float))
                             else None})


def _derive_metrics(tracer: RunTracer, run_dir: Path,
                    end_ts: int) -> None:
    """Telemetry events become instants; the checker's measured phase
    split (``checker_matrix_phase_seconds{phase}``) becomes synthetic
    slices anchored at the end of the history — durations are real,
    placement is approximate (the export records no start times)."""
    rows = _load_jsonl(run_dir / "metrics.json") \
        or _load_jsonl(run_dir / "metrics-analyze.json")
    for row in rows:
        if row.get("type") == "event":
            t = row.get("time")
            if not isinstance(t, (int, float)):
                continue
            track = _EVENT_TRACKS.get(str(row.get("name")), TRACK_CHECKER)
            tracer.instant(track, str(row.get("name")),
                           ts_us=int(t * 1e6),
                           args=row.get("fields") or {})
        elif (row.get("name") == "checker_matrix_phase_seconds"
              and isinstance(row.get("value"), (int, float))
              and row.get("value") > 0):
            phase = (row.get("labels") or {}).get("phase", "?")
            tracer.complete(TRACK_CHECKER, "phase", end_ts,
                            int(row["value"] * 1e6),
                            args={"phase": phase,
                                  "seconds": row["value"]})


# ---------------------------------------------------------------------------
# Summary (shared by `jepsen-tpu trace` and the web run page)
# ---------------------------------------------------------------------------

def summarize_trace(path, max_bytes: int = 8 << 20) -> dict | None:
    """{tracks: {name: count}, slowest_ops: [...], demotions: [...],
    events: n} for a trace.json — reading at most ``max_bytes`` so a
    huge trace can't wedge a page render."""
    try:
        events = read_trace_events(path, max_bytes=max_bytes)
    except OSError:
        return None
    if not events:
        return None
    names: dict[int, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            names[ev.get("tid")] = (ev.get("args") or {}).get("name", "?")
    tracks: dict[str, int] = {}
    spans: list[tuple[float, str, str]] = []  # (dur_us, track, name)
    open_b: dict[int, dict] = {}
    demotions: list[str] = []
    for ev in events:
        ph = ev.get("ph")
        if ph == "M":
            continue
        track = names.get(ev.get("tid"), "?")
        tracks[track] = tracks.get(track, 0) + 1
        if ph == "B":
            open_b[ev.get("tid")] = ev
        elif ph == "E":
            b = open_b.pop(ev.get("tid"), None)
            if b is not None and isinstance(ev.get("ts"), (int, float)) \
                    and isinstance(b.get("ts"), (int, float)):
                spans.append((ev["ts"] - b["ts"], track,
                              str(b.get("name"))))
        elif ph == "X" and isinstance(ev.get("dur"), (int, float)):
            spans.append((ev["dur"], track, str(ev.get("name"))))
        elif ph == "i" and ev.get("name") == "demote":
            args = ev.get("args") or {}
            demotions.append(f"{args.get('backend')} "
                             f"({args.get('reason')})")
    spans.sort(reverse=True)
    return {
        "events": sum(tracks.values()),
        "tracks": dict(sorted(tracks.items())),
        "slowest_ops": [
            {"track": t, "name": n, "dur_ms": round(d / 1000.0, 3)}
            for d, t, n in spans[:5]],
        "demotions": demotions,
        "open_spans": len(open_b),
    }
